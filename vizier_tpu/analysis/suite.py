"""Suite runner: all passes over the configured paths, baseline applied.

Configuration lives in ``pyproject.toml``::

    [tool.vizier_analysis]
    paths = ["vizier_tpu", "bench.py", "tools"]
    baseline = "vizier_tpu/analysis/baseline.toml"
    passes = ["lock_order", "jax_discipline", "env_registry"]
    critical_locks = [...]   # optional override

The CLI (``tools/check_analysis.py``) and the tier-1 tests
(``tests/analysis/``) both run through :func:`run_suite`, so they cannot
disagree about what a violation is.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Sequence

from vizier_tpu.analysis import baseline as baseline_lib
from vizier_tpu.analysis import common
from vizier_tpu.analysis import compute_ir
from vizier_tpu.analysis import env_registry
from vizier_tpu.analysis import jax_discipline
from vizier_tpu.analysis import lock_order

ALL_PASSES = (
    "lock_order",
    "jax_discipline",
    "env_registry",
    "compute_ir",
    "debug_locks",
)

DEFAULT_PATHS = ("vizier_tpu", "bench.py", "tools")
DEFAULT_BASELINE = "vizier_tpu/analysis/baseline.toml"


@dataclasses.dataclass
class SuiteConfig:
    paths: List[str] = dataclasses.field(default_factory=lambda: list(DEFAULT_PATHS))
    baseline: str = DEFAULT_BASELINE
    passes: List[str] = dataclasses.field(default_factory=lambda: list(ALL_PASSES))
    critical_locks: List[str] = dataclasses.field(
        default_factory=lambda: list(lock_order.DEFAULT_CRITICAL_LOCKS)
    )


def load_config(repo_root: str) -> SuiteConfig:
    """The ``[tool.vizier_analysis]`` pyproject section, with defaults."""
    config = SuiteConfig()
    pyproject = os.path.join(repo_root, "pyproject.toml")
    try:
        with open(pyproject, "r", encoding="utf-8") as f:
            data = baseline_lib.parse_toml_subset(f.read(), source=pyproject)
    except OSError:
        return config
    section = data.get("tool", {}).get("vizier_analysis", {})
    if isinstance(section, dict):
        if isinstance(section.get("paths"), list):
            config.paths = [str(p) for p in section["paths"]]
        if isinstance(section.get("baseline"), str):
            config.baseline = section["baseline"]
        if isinstance(section.get("passes"), list):
            config.passes = [str(p) for p in section["passes"]]
        if isinstance(section.get("critical_locks"), list):
            config.critical_locks = [str(p) for p in section["critical_locks"]]
    return config


@dataclasses.dataclass
class PassResult:
    name: str
    findings: List[common.Finding]
    new: List[common.Finding]
    accepted: List[common.Finding]


@dataclasses.dataclass
class SuiteResult:
    passes: Dict[str, PassResult]
    stale_baseline: List[baseline_lib.BaselineEntry]
    lock_result: Optional[lock_order.LockOrderResult] = None
    jax_result: Optional[jax_discipline.JaxDisciplineResult] = None
    env_result: Optional[env_registry.EnvRegistryResult] = None
    compute_ir_result: Optional[compute_ir.ComputeIrResult] = None
    # (confirmed_edge_count, unmapped_site_count) from the runtime check.
    debug_locks_stats: Optional[tuple] = None
    parse_errors: List = dataclasses.field(default_factory=list)

    @property
    def new_findings(self) -> List[common.Finding]:
        out: List[common.Finding] = []
        for result in self.passes.values():
            out.extend(result.new)
        return out

    @property
    def ok(self) -> bool:
        return not self.new_findings and not self.parse_errors


def run_suite(
    repo_root: str,
    config: Optional[SuiteConfig] = None,
    passes: Optional[Sequence[str]] = None,
) -> SuiteResult:
    config = config or load_config(repo_root)
    selected = list(passes or config.passes)
    unknown = set(selected) - set(ALL_PASSES)
    if unknown:
        raise ValueError(
            f"Unknown analysis pass(es) {sorted(unknown)}; "
            f"known: {list(ALL_PASSES)}"
        )
    roots = [os.path.join(repo_root, p) for p in config.paths]
    project = common.Project(roots, rel_to=repo_root)
    bl = baseline_lib.load_baseline(os.path.join(repo_root, config.baseline))

    all_findings: List[common.Finding] = []
    result = SuiteResult(passes={}, stale_baseline=[], parse_errors=list(project.parse_errors))

    if "lock_order" in selected:
        result.lock_result = lock_order.run(
            project, critical_locks=config.critical_locks
        )
        all_findings.extend(result.lock_result.findings)
    if "jax_discipline" in selected:
        result.jax_result = jax_discipline.run(project)
        all_findings.extend(result.jax_result.findings)
    if "env_registry" in selected:
        result.env_result = env_registry.run(project, repo_root)
        all_findings.extend(result.env_result.findings)
    if "compute_ir" in selected:
        result.compute_ir_result = compute_ir.run(project, repo_root)
        all_findings.extend(result.compute_ir_result.findings)
    if "debug_locks" in selected:
        lock_result = result.lock_result or lock_order.run(
            project, critical_locks=config.critical_locks
        )
        dl_findings, result.debug_locks_stats = _run_debug_locks(
            lock_result, repo_root
        )
        all_findings.extend(dl_findings)

    new, accepted, stale = bl.apply(all_findings)
    # A partial run (--pass X) cannot judge other passes' baseline entries.
    result.stale_baseline = [e for e in stale if e.pass_name in selected]
    new_keys = {(f.pass_name, f.key) for f in new}
    for name in selected:
        pass_findings = [f for f in all_findings if f.pass_name == name]
        result.passes[name] = PassResult(
            name=name,
            findings=pass_findings,
            new=[f for f in pass_findings if (f.pass_name, f.key) in new_keys],
            accepted=[
                f for f in pass_findings if (f.pass_name, f.key) not in new_keys
            ],
        )
    return result


def _run_debug_locks(lock_result, repo_root: str):
    """Pass 4: record RUNTIME acquisition order and diff it against the
    static graph.

    Drives the real serving designer-cache + coalescer through a seeded
    threaded workload (happy path AND the invalidate-under-entry-lock
    error path) with every lock instrumented; any observed edge the static
    graph does not predict is a finding — a resolution gap in the static
    pass, not an acceptable exception. The richer chaos-harness variant
    runs in tests/analysis/test_debug_locks.py; this one stays jax-free so
    the CLI works in bare CI images.
    """
    import random
    import threading

    from vizier_tpu.analysis import common as common_lib
    from vizier_tpu.analysis import debug_locks as debug_locks_lib

    with debug_locks_lib.instrument() as obs:
        from vizier_tpu.serving.coalescer import RequestCoalescer
        from vizier_tpu.serving.designer_cache import DesignerStateCache

        cache = DesignerStateCache(max_entries=3, observe_latency=False)
        coalescer = RequestCoalescer(observe_latency=False)

        def worker(tid: int):
            rng = random.Random(1000 + tid)
            for step in range(8):
                name = f"s{(tid + step) % 4}"
                entry = cache.get_or_create(name, lambda: object())
                with entry.lock:
                    if rng.random() < 0.4:  # the policy's error path
                        cache.invalidate(name)
                coalescer.coalesce((name, step), lambda: step)

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)

    check = debug_locks_lib.check_against_static(obs, lock_result, repo_root)
    findings = []
    seen = set()
    for src, dst, edge in check.missing_static:
        key = f"runtime-edge-not-in-static-graph:{src}->{dst}"
        if key in seen:
            continue
        seen.add(key)
        findings.append(
            common_lib.Finding(
                pass_name="debug_locks",
                rule="runtime-order-not-in-static-graph",
                key=key,
                message=(
                    f"runtime acquisition order {src} -> {dst} (thread "
                    f"{edge.thread}) is absent from the static lock graph — "
                    "fix the lock_order pass's resolution, don't baseline"
                ),
                path="vizier_tpu/analysis/lock_order.py",
                line=0,
            )
        )
    return findings, (len(check.confirmed), len(check.unmapped_sites))


def format_report(result: SuiteResult, verbose: bool = False) -> str:
    lines: List[str] = []
    for path, err in result.parse_errors:
        lines.append(f"PARSE ERROR {path}: {err}")
    for name, pass_result in result.passes.items():
        status = "FAIL" if pass_result.new else "ok"
        extra = ""
        if name == "lock_order" and result.lock_result is not None:
            extra = (
                f" ({len(result.lock_result.sites)} lock sites, "
                f"{len(result.lock_result.edges)} edges)"
            )
        elif name == "jax_discipline" and result.jax_result is not None:
            extra = (
                f" ({len(result.jax_result.roots)} jit roots, "
                f"{len(result.jax_result.traced)} traced fns)"
            )
        elif name == "env_registry" and result.env_result is not None:
            extra = f" ({len(result.env_result.references)} VIZIER_* names seen)"
        elif name == "compute_ir" and result.compute_ir_result is not None:
            kinds = sorted(
                r.kind or "?" for r in result.compute_ir_result.registered
            )
            extra = f" ({len(kinds)} registered programs: {', '.join(kinds)})"
        elif name == "debug_locks" and result.debug_locks_stats is not None:
            confirmed, unmapped = result.debug_locks_stats
            extra = (
                f" ({confirmed} runtime edges confirmed static, "
                f"{unmapped} unmapped sites)"
            )
        lines.append(
            f"[{name}] {status}: {len(pass_result.new)} new, "
            f"{len(pass_result.accepted)} baselined{extra}"
        )
        for f in pass_result.new:
            lines.append(f"  NEW {f.format()}")
            lines.append(f"      baseline key: {f.key}")
        if verbose:
            for f in pass_result.accepted:
                lines.append(f"  baselined {f.format()}")
    for entry in result.stale_baseline:
        lines.append(
            f"STALE baseline entry [{entry.pass_name}] {entry.key} "
            "(no longer matches anything — remove it)"
        )
    lines.append("ANALYSIS " + ("OK" if result.ok else "FAILED"))
    return "\n".join(lines)
