"""Pass 4: runtime lock-order recording that cross-checks the static graph.

:func:`instrument` monkeypatches ``threading.Lock`` / ``RLock`` /
``Condition`` with wrappers that record, per thread, which locks are held
when another is acquired — the *observed* acquisition graph. Each wrapper
remembers the first non-library frame of its creation stack, so an
observed lock maps back to the static
:class:`~vizier_tpu.analysis.lock_order.LockSite` created at the same
``(file, line)``; locks built through factories (``defaultdict(
threading.Lock)`` creates at the access site, not the declaration site)
fall back to the file's unique static site.

The chaos/serving tests run a threaded workload under ``instrument()``
and then call :func:`check_against_static`: every observed edge must
already be in the static graph (or the baseline) — an edge the static
pass missed is a resolution gap to fix, not a test flake to retry.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import sys
import threading
from typing import Dict, Iterator, List, Optional, Set, Tuple

from vizier_tpu.analysis import lock_order

_LIBRARY_HINTS = ("analysis/debug_locks.py", "threading.py", "importlib")


@dataclasses.dataclass(frozen=True)
class CreationSite:
    path: str  # absolute file of the creating frame
    line: int

    def short(self) -> str:
        return f"{os.path.basename(self.path)}:{self.line}"


@dataclasses.dataclass(frozen=True)
class ObservedEdge:
    src: CreationSite
    dst: CreationSite
    thread: str


class LockObservatory:
    """Shared sink for every instrumented lock's acquisition events."""

    def __init__(self):
        self._mutex = threading.Lock()  # guards the edge/site tables only
        self._held = threading.local()
        self.edges: Set[ObservedEdge] = set()
        self.sites: Set[CreationSite] = set()
        self.acquisitions = 0

    def _stack(self) -> List["_InstrumentedBase"]:
        stack = getattr(self._held, "stack", None)
        if stack is None:
            stack = []
            self._held.stack = stack
        return stack

    def record_site(self, site: CreationSite) -> None:
        with self._mutex:
            self.sites.add(site)

    def on_acquired(self, lock: "_InstrumentedBase") -> None:
        stack = self._stack()
        with self._mutex:
            self.acquisitions += 1
            for held in stack:
                if held is lock or held.site == lock.site:
                    continue  # reentrancy / sibling instances of one site
                self.edges.add(
                    ObservedEdge(
                        held.site, lock.site, threading.current_thread().name
                    )
                )
        stack.append(lock)

    def on_released(self, lock: "_InstrumentedBase") -> None:
        stack = self._stack()
        # Release order need not be LIFO (c.f. explicit acquire/release);
        # drop the most recent matching entry.
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is lock:
                del stack[i]
                return

    def edge_pairs(self) -> Set[Tuple[CreationSite, CreationSite]]:
        with self._mutex:
            return {(e.src, e.dst) for e in self.edges}


def _creation_site() -> CreationSite:
    frame = sys._getframe(2)
    while frame is not None:
        path = frame.f_code.co_filename.replace("\\", "/")
        if not any(hint in path for hint in _LIBRARY_HINTS):
            return CreationSite(path=path, line=frame.f_lineno)
        frame = frame.f_back
    return CreationSite(path="<unknown>", line=0)


class _InstrumentedBase:
    def __init__(self, inner, observatory: LockObservatory):
        self._inner = inner
        self.observatory = observatory
        self.site = _creation_site()
        observatory.record_site(self.site)

    def acquire(self, *args, **kwargs):
        got = self._inner.acquire(*args, **kwargs)
        if got:
            self.observatory.on_acquired(self)
        return got

    def release(self):
        self._inner.release()
        self.observatory.on_released(self)

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class InstrumentedLock(_InstrumentedBase):
    pass


class InstrumentedRLock(_InstrumentedBase):
    pass


class InstrumentedCondition(_InstrumentedBase):
    """Condition wrapper: the underlying lock IS the condition's lock, so
    wait() releasing and re-acquiring it is tracked coherently."""

    def __init__(self, real_condition_factory, observatory: LockObservatory):
        super().__init__(real_condition_factory(), observatory)

    def wait(self, timeout: Optional[float] = None):
        # wait() atomically releases the condition lock; mirror that in the
        # held stack so waiting does not manufacture false edges.
        self.observatory.on_released(self)
        try:
            return self._inner.wait(timeout)
        finally:
            self.observatory.on_acquired(self)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        self.observatory.on_released(self)
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            self.observatory.on_acquired(self)

    def notify(self, n: int = 1):
        return self._inner.notify(n)

    def notify_all(self):
        return self._inner.notify_all()

    def locked(self):  # Condition has no locked(); keep the wrapper honest
        raise AttributeError("Condition has no locked()")


@contextlib.contextmanager
def instrument(
    observatory: Optional[LockObservatory] = None,
) -> Iterator[LockObservatory]:
    """Patches ``threading.Lock/RLock/Condition`` inside the block.

    Only locks *constructed* inside the block are instrumented; existing
    locks keep running untouched (their acquisitions are simply not
    observed). Nesting instrument() is not supported.
    """
    obs = observatory or LockObservatory()
    real_lock, real_rlock = threading.Lock, threading.RLock
    real_condition = threading.Condition

    def make_lock():
        return InstrumentedLock(real_lock(), obs)

    def make_rlock():
        return InstrumentedRLock(real_rlock(), obs)

    def make_condition(lock=None):
        # The real Condition must wrap a REAL lock: handing it an
        # instrumented wrapper breaks its _is_owned() probe (a reentrant
        # acquire(0) on a wrapper succeeds, so the probe concludes "not
        # owned" and wait() raises). Unwrap caller-supplied instrumented
        # locks; default to an unpatched RLock.
        if isinstance(lock, _InstrumentedBase):
            inner_lock = lock._inner
        elif lock is not None:
            inner_lock = lock
        else:
            inner_lock = real_rlock()
        return InstrumentedCondition(lambda: real_condition(inner_lock), obs)

    threading.Lock = make_lock  # type: ignore[assignment]
    threading.RLock = make_rlock  # type: ignore[assignment]
    threading.Condition = make_condition  # type: ignore[assignment]
    try:
        yield obs
    finally:
        threading.Lock = real_lock  # type: ignore[assignment]
        threading.RLock = real_rlock  # type: ignore[assignment]
        threading.Condition = real_condition  # type: ignore[assignment]


def map_site(
    site: CreationSite,
    static_sites: List[lock_order.LockSite],
    repo_root: str,
) -> Optional[str]:
    """The static lock id created at ``site``, or None.

    Exact ``(file, line)`` match first; for factory-created locks (whose
    creation frame is the *access* site) fall back to the file's static
    site when the file declares exactly one.
    """
    norm = site.path.replace("\\", "/")
    in_file: List[lock_order.LockSite] = []
    for s in static_sites:
        static_abs = os.path.join(repo_root, s.path).replace("\\", "/")
        if norm.endswith(s.path) or norm == static_abs:
            in_file.append(s)
            if s.line == site.line:
                return s.lock_id
    if len(in_file) == 1:
        return in_file[0].lock_id
    factories = [s for s in in_file if s.factory]
    if len(factories) == 1:
        return factories[0].lock_id
    return None


@dataclasses.dataclass
class CrossCheckResult:
    # Observed edges whose endpoints both mapped to static sites but which
    # the static graph does not contain: static-analysis gaps.
    missing_static: List[Tuple[str, str, ObservedEdge]]
    # Observed edges fully mapped AND statically predicted (the good case).
    confirmed: List[Tuple[str, str]]
    # Creation sites that could not be joined to any static site (locks
    # created by code outside the scanned tree, e.g. test scaffolding).
    unmapped_sites: List[CreationSite]


def check_against_static(
    observatory: LockObservatory,
    static_result: lock_order.LockOrderResult,
    repo_root: str,
    allowed_extra: Optional[Set[Tuple[str, str]]] = None,
) -> CrossCheckResult:
    mapping: Dict[CreationSite, Optional[str]] = {}
    for site in observatory.sites:
        mapping[site] = map_site(site, static_result.sites, repo_root)
    static_edges = static_result.edge_pairs()
    allowed = allowed_extra or set()
    missing: List[Tuple[str, str, ObservedEdge]] = []
    confirmed: List[Tuple[str, str]] = []
    for edge in sorted(
        observatory.edges, key=lambda e: (e.src.short(), e.dst.short())
    ):
        src_id, dst_id = mapping.get(edge.src), mapping.get(edge.dst)
        if src_id is None or dst_id is None or src_id == dst_id:
            continue
        if (src_id, dst_id) in static_edges or (src_id, dst_id) in allowed:
            confirmed.append((src_id, dst_id))
        else:
            missing.append((src_id, dst_id, edge))
    unmapped = sorted(
        (s for s, lock_id in mapping.items() if lock_id is None),
        key=lambda s: (s.path, s.line),
    )
    return CrossCheckResult(
        missing_static=missing, confirmed=confirmed, unmapped_sites=unmapped
    )
