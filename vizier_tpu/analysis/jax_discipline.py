"""Pass 2: JAX tracing discipline inside jit/vmap-reachable code.

The GP-bandit hot path keeps one compiled program per padding bucket;
that contract only holds while jitted code stays free of host syncs and
per-call retrace hazards. This pass finds the functions that jit/vmap
will trace — decorator roots (``@jax.jit``, ``@functools.partial(jax.jit,
...)``), call-site roots (``jax.jit(f)``), ``jax.vmap`` targets and
``lax.scan/cond/while_loop`` body functions, plus everything reachable
from them through the project call graph — and flags, inside that traced
set:

- **host syncs**: ``.block_until_ready()``, ``jax.device_get``,
  ``np.asarray``/``np.array`` on traced values, ``.item()``, and
  ``float()``/``int()`` coercions of non-literal expressions — each forces
  the device to flush mid-program;
- **tracer branching**: Python ``if``/``while`` whose condition involves a
  non-static parameter of a jit root or a value produced by ``jnp``/``jax``
  ops (shape/ndim/dtype/len and ``is None`` tests are static and exempt);
- **retrace hazards**: ``jax.jit(...)`` created inside a loop, static
  arguments that are unhashable literals (list/dict/set), and ``len(...)``
  passed directly as a jit-static (a per-size recompile outside the
  padding-bucket grid).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from vizier_tpu.analysis import common

PASS_NAME = "jax_discipline"

_TRACE_ENTRY_TAILS = {"jit", "vmap", "pmap"}
_LAX_BODY_FUNCS = {"scan", "cond", "while_loop", "fori_loop", "map", "switch"}
_NUMPY_ROOTS = {"np", "numpy", "onp"}
_JAX_VALUE_ROOTS = {"jnp", "jax", "lax"}


@dataclasses.dataclass
class JitRoot:
    fn: common.FunctionInfo
    static_names: Set[str]
    line: int


@dataclasses.dataclass
class JaxDisciplineResult:
    roots: List[JitRoot]
    traced: Set[str]  # qualnames
    findings: List[common.Finding]


def _param_names(fn_node: ast.AST) -> List[str]:
    args = fn_node.args
    return [a.arg for a in list(args.posonlyargs) + list(args.args)]


def _static_names_from_call(call: ast.Call, fn_node: Optional[ast.AST]) -> Set[str]:
    """static_argnames/static_argnums keywords -> parameter-name set."""
    names: Set[str] = set()
    params = _param_names(fn_node) if fn_node is not None else []
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for elt in _iter_const(kw.value):
                if isinstance(elt, str):
                    names.add(elt)
        elif kw.arg == "static_argnums":
            for elt in _iter_const(kw.value):
                if isinstance(elt, int) and 0 <= elt < len(params):
                    names.add(params[elt])
    return names


def _iter_const(node: ast.AST):
    if isinstance(node, ast.Constant):
        yield node.value
    elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for elt in node.elts:
            if isinstance(elt, ast.Constant):
                yield elt.value


def _jit_call_of(node: ast.AST) -> Optional[ast.Call]:
    """The jit/partial(jit, ...) Call if ``node`` is a jit decorator/expr."""
    if isinstance(node, ast.Call):
        tail = common._tail_name(node.func)
        if tail == "jit":
            return node
        if tail == "partial" and node.args:
            if common._tail_name(node.args[0]) == "jit":
                return node
    elif common._tail_name(node) == "jit":
        # Bare `@jax.jit` / `@jit` decorator with no arguments.
        return ast.Call(func=node, args=[], keywords=[])
    return None


class JaxDisciplineAnalyzer:
    def __init__(self, project: common.Project):
        self.project = project
        self.roots: Dict[str, JitRoot] = {}
        self.findings: List[common.Finding] = []

    # -- root discovery -----------------------------------------------------

    def _discover_roots(self) -> None:
        for qualname, fn in self.project.functions.items():
            node = fn.node
            for dec in getattr(node, "decorator_list", []):
                jit_call = _jit_call_of(dec)
                if jit_call is not None:
                    self.roots[qualname] = JitRoot(
                        fn=fn,
                        static_names=_static_names_from_call(jit_call, node),
                        line=node.lineno,
                    )
        # Call-site roots and lax body functions.
        for qualname, fn in self.project.functions.items():
            local_types = self.project.local_types(fn)
            for call in ast.walk(fn.node):
                if not isinstance(call, ast.Call):
                    continue
                tail = common._tail_name(call.func)
                if tail in _TRACE_ENTRY_TAILS and call.args:
                    self._add_callable_root(call.args[0], call, fn, local_types)
                elif tail in _LAX_BODY_FUNCS:
                    for arg in call.args:
                        self._add_callable_root(arg, None, fn, local_types)

    def _add_callable_root(
        self,
        target: ast.AST,
        jit_call: Optional[ast.Call],
        fn: common.FunctionInfo,
        local_types: Dict[str, str],
    ) -> None:
        if isinstance(target, ast.Lambda):
            # Trace the lambda body's resolvable callees directly.
            for sub in ast.walk(target.body):
                if isinstance(sub, ast.Call):
                    for callee in self.project.resolve_call(sub, fn, local_types):
                        self.roots.setdefault(
                            callee.qualname,
                            JitRoot(fn=callee, static_names=set(), line=target.lineno),
                        )
            return
        if isinstance(target, ast.Name):
            info = self.project.module_functions.get(fn.path, {}).get(target.id)
            if info is not None:
                statics = (
                    _static_names_from_call(jit_call, info.node)
                    if jit_call is not None
                    else set()
                )
                root = self.roots.setdefault(
                    info.qualname, JitRoot(fn=info, static_names=set(), line=target.lineno)
                )
                root.static_names |= statics

    # -- reachability --------------------------------------------------------

    def _traced_closure(self) -> Set[str]:
        traced: Set[str] = set(self.roots)
        queue = list(self.roots)
        while queue:
            qualname = queue.pop()
            fn = self.project.functions.get(qualname)
            if fn is None:
                continue
            local_types = self.project.local_types(fn)
            for call in ast.walk(fn.node):
                if not isinstance(call, ast.Call):
                    continue
                for callee in self.project.resolve_call(call, fn, local_types):
                    if callee.qualname not in traced:
                        traced.add(callee.qualname)
                        queue.append(callee.qualname)
        return traced

    # -- checks inside traced functions --------------------------------------

    def _tainted_locals(self, fn: common.FunctionInfo) -> Set[str]:
        """Names assigned from jnp/jax computations in ``fn``'s body."""
        tainted: Set[str] = set()
        for _ in range(2):
            for node in ast.walk(fn.node):
                if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                    continue
                tgt = node.targets[0]
                names: List[str] = []
                if isinstance(tgt, ast.Name):
                    names = [tgt.id]
                elif isinstance(tgt, ast.Tuple):
                    names = [e.id for e in tgt.elts if isinstance(e, ast.Name)]
                if not names:
                    continue
                if self._is_jax_valued(node.value, tainted):
                    tainted.update(names)
        return tainted

    def _is_jax_valued(self, node: ast.AST, tainted: Set[str]) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                dotted_name = common.dotted(sub.func)
                if dotted_name and dotted_name.split(".", 1)[0] in _JAX_VALUE_ROOTS:
                    return True
            elif isinstance(sub, ast.Name) and sub.id in tainted:
                return True
        return False

    def _check_function(self, qualname: str) -> None:
        fn = self.project.functions.get(qualname)
        if fn is None:
            return
        root = self.roots.get(qualname)
        nonstatic_params: Set[str] = set()
        if root is not None:
            nonstatic_params = set(_param_names(fn.node)) - root.static_names
            nonstatic_params.discard("self")
        tainted = self._tainted_locals(fn)
        fn_label = qualname.split("::", 1)[1]

        for node in ast.walk(fn.node):
            if isinstance(node, ast.Call):
                self._check_host_sync(node, fn, fn_label)
            elif isinstance(node, (ast.If, ast.While)):
                bad = self._tracer_names_in_test(
                    node.test, nonstatic_params, tainted
                )
                if bad:
                    self.findings.append(
                        common.Finding(
                            pass_name=PASS_NAME,
                            rule="tracer-branch",
                            key=f"tracer-branch@{fn.path}::{fn_label}:{sorted(bad)[0]}",
                            message=(
                                f"Python branch on traced value(s) "
                                f"{sorted(bad)} inside jitted {fn_label}; "
                                "use lax.cond/jnp.where"
                            ),
                            path=fn.path,
                            line=node.lineno,
                        )
                    )

    def _check_host_sync(
        self, call: ast.Call, fn: common.FunctionInfo, fn_label: str
    ) -> None:
        func = call.func
        attr = func.attr if isinstance(func, ast.Attribute) else None
        dotted_name = common.dotted(func)
        sync: Optional[str] = None
        if attr == "block_until_ready":
            sync = "block_until_ready"
        elif attr == "item" and not call.args:
            sync = ".item()"
        elif dotted_name in ("jax.device_get",):
            sync = "jax.device_get"
        elif (
            dotted_name
            and dotted_name.split(".", 1)[0] in _NUMPY_ROOTS
            and dotted_name.split(".")[-1] in ("asarray", "array")
        ):
            sync = dotted_name
        elif (
            isinstance(func, ast.Name)
            and func.id in ("float", "int")
            and call.args
            and not isinstance(call.args[0], ast.Constant)
            and not self._static_value(call.args[0])
            and self._has_bare_name_load(call.args[0])
        ):
            sync = f"{func.id}()"
        if sync is not None:
            self.findings.append(
                common.Finding(
                    pass_name=PASS_NAME,
                    rule="host-sync-in-jit",
                    key=f"host-sync@{fn.path}::{fn_label}:{sync}",
                    message=(
                        f"host sync {sync} inside jit-traced {fn_label} "
                        "(forces a device flush / retrace hazard)"
                    ),
                    path=fn.path,
                    line=call.lineno,
                )
            )

    @staticmethod
    def _has_bare_name_load(node: ast.AST) -> bool:
        """True when the expression reads any plain variable.

        ``float(np.log(1e-2))`` is a host *constant* — every Name in it is
        the root of a module-attribute chain (``np``), not a value — while
        ``float(x)`` coerces a runtime value and would sync a tracer.
        """
        # Names that are roots of attribute chains (np.log, math.pi) are
        # module references, not runtime values.
        roots = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute) and isinstance(sub.value, ast.Name):
                roots.add(id(sub.value))
        for sub in ast.walk(node):
            if (
                isinstance(sub, ast.Name)
                and isinstance(sub.ctx, ast.Load)
                and id(sub) not in roots
            ):
                return True
        return False

    @staticmethod
    def _static_value(node: ast.AST) -> bool:
        """Expressions whose value is static under tracing (shape-derived)."""
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute) and sub.attr in (
                "shape", "ndim", "size", "dtype",
            ):
                return True
            if isinstance(sub, ast.Call) and common._tail_name(sub.func) == "len":
                return True
        return False

    def _tracer_names_in_test(
        self, test: ast.AST, nonstatic_params: Set[str], tainted: Set[str]
    ) -> Set[str]:
        # Static/exempt shapes: `x is None`, isinstance, shape/ndim/dtype
        # comparisons, len() — all concrete at trace time.
        if isinstance(test, ast.Compare) and any(
            isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
        ):
            return set()
        if self._static_value(test):
            return set()
        bad: Set[str] = set()
        for sub in ast.walk(test):
            if isinstance(sub, ast.Call):
                tail = common._tail_name(sub.func)
                if tail in ("isinstance", "len", "hasattr", "getattr"):
                    return set()
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
                if sub.id in nonstatic_params or sub.id in tainted:
                    bad.add(sub.id)
        return bad

    # -- call-site checks -----------------------------------------------------

    def _check_call_sites(self) -> None:
        root_by_name: Dict[Tuple[str, str], JitRoot] = {}
        for root in self.roots.values():
            root_by_name[(root.fn.path, root.fn.name)] = root
        for qualname, fn in self.project.functions.items():
            loop_depth_nodes = self._loop_nodes(fn.node)
            for call in ast.walk(fn.node):
                if not isinstance(call, ast.Call):
                    continue
                tail = common._tail_name(call.func)
                # jax.jit(...) constructed inside a loop: a fresh callable
                # (and compile cache) per iteration.
                if tail == "jit" and call in loop_depth_nodes:
                    fn_label = qualname.split("::", 1)[1]
                    self.findings.append(
                        common.Finding(
                            pass_name=PASS_NAME,
                            rule="jit-in-loop",
                            key=f"jit-in-loop@{fn.path}::{fn_label}",
                            message=(
                                f"jax.jit(...) constructed inside a loop in "
                                f"{fn_label}: hoist it, or every iteration "
                                "retraces"
                            ),
                            path=fn.path,
                            line=call.lineno,
                        )
                    )
                # Static args at direct calls of known roots. Values derived
                # from the CALLER's own jit-statics are stable and exempt
                # (e.g. len(mesh.devices.flat) where mesh is the caller's
                # static param).
                root = None
                if isinstance(call.func, ast.Name):
                    root = root_by_name.get((fn.path, call.func.id))
                if root is None or not root.static_names:
                    continue
                caller_root = self.roots.get(qualname)
                caller_statics = (
                    caller_root.static_names if caller_root else set()
                )
                params = _param_names(root.fn.node)
                fn_label = qualname.split("::", 1)[1]
                for i, arg in enumerate(call.args):
                    if i >= len(params) or params[i] not in root.static_names:
                        continue
                    self._check_static_arg(
                        arg, params[i], root, fn, fn_label, caller_statics
                    )
                for kw in call.keywords:
                    if kw.arg in root.static_names:
                        self._check_static_arg(
                            kw.value, kw.arg, root, fn, fn_label, caller_statics
                        )

    def _check_static_arg(
        self,
        arg: ast.AST,
        param: str,
        root: JitRoot,
        fn: common.FunctionInfo,
        fn_label: str,
        caller_statics: Set[str] = frozenset(),
    ) -> None:
        if isinstance(arg, (ast.List, ast.Dict, ast.Set)):
            self.findings.append(
                common.Finding(
                    pass_name=PASS_NAME,
                    rule="unhashable-static",
                    key=(
                        f"unhashable-static@{fn.path}::{fn_label}:"
                        f"{root.fn.name}.{param}"
                    ),
                    message=(
                        f"unhashable literal passed as jit-static "
                        f"{param!r} of {root.fn.name} (TypeError at trace "
                        "time; use a tuple)"
                    ),
                    path=fn.path,
                    line=arg.lineno,
                )
            )
        elif (
            isinstance(arg, ast.Call)
            and common._tail_name(arg.func) == "len"
            and not self._rooted_in(arg, caller_statics)
        ):
            self.findings.append(
                common.Finding(
                    pass_name=PASS_NAME,
                    rule="shape-unstable-static",
                    key=(
                        f"shape-unstable-static@{fn.path}::{fn_label}:"
                        f"{root.fn.name}.{param}"
                    ),
                    message=(
                        f"len(...) passed directly as jit-static {param!r} "
                        f"of {root.fn.name}: recompiles per size — route "
                        "through the padding-bucket grid"
                    ),
                    path=fn.path,
                    line=arg.lineno,
                )
            )

    @staticmethod
    def _rooted_in(node: ast.AST, names: Set[str]) -> bool:
        """Whether every Name the expression reads is one of ``names``."""
        if not names:
            return False
        loads = [
            sub.id
            for sub in ast.walk(node)
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load)
        ]
        return bool(loads) and all(name in names or name == "len" for name in loads)

    @staticmethod
    def _loop_nodes(fn_node: ast.AST) -> Set[ast.AST]:
        """All Call nodes lexically inside a for/while body."""
        out: Set[ast.AST] = set()
        for node in ast.walk(fn_node):
            if isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call):
                        out.add(sub)
        return out

    def run(self) -> JaxDisciplineResult:
        self._discover_roots()
        traced = self._traced_closure()
        for qualname in sorted(traced):
            self._check_function(qualname)
        self._check_call_sites()
        seen: Set[str] = set()
        unique: List[common.Finding] = []
        for f in sorted(self.findings, key=lambda f: (f.path, f.line, f.key)):
            if f.key not in seen:
                seen.add(f.key)
                unique.append(f)
        return JaxDisciplineResult(
            roots=sorted(self.roots.values(), key=lambda r: r.fn.qualname),
            traced=traced,
            findings=unique,
        )


def run(project: common.Project) -> JaxDisciplineResult:
    return JaxDisciplineAnalyzer(project).run()
