"""Shared infrastructure for the static-analysis passes.

Everything here is stdlib-only (``ast`` + dataclasses): the analysis suite
must run in any environment that can parse the tree, including CI images
without jax. The central object is :class:`Project` — a parsed-AST index
over a set of Python files with just enough *lightweight* type inference
to resolve ``obj.method()`` calls across modules:

- every class definition, its bases and its methods;
- per-class attribute types, inferred from ``self.x = SomeClass(...)``,
  ``self.x = typed_param`` and annotated assignments;
- per-function local-variable types from parameter annotations and
  assignments (``x = SomeClass(...)``, ``x = self.typed_attr``,
  ``x = getattr(obj, "literal")``).

Resolution is deliberately conservative: an unresolvable call is simply
not followed (passes may count them), never guessed. Precision comes from
the codebase's own discipline — constructor injection and annotated
parameters — which is exactly what the passes are meant to protect.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple


@dataclasses.dataclass(frozen=True)
class Finding:
    """One violation reported by a pass.

    ``key`` is the stable identity used for baseline matching: it is built
    from qualified names (never line numbers), so ordinary edits do not
    churn the baseline.
    """

    pass_name: str
    rule: str
    key: str
    message: str
    path: str
    line: int

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.pass_name}/{self.rule}] {self.message}"


@dataclasses.dataclass
class FunctionInfo:
    """One function or method definition."""

    qualname: str  # "module/path.py::Class.method" or "module/path.py::func"
    name: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    path: str
    class_name: Optional[str] = None


@dataclasses.dataclass
class ClassInfo:
    name: str
    node: ast.ClassDef
    path: str
    bases: List[str] = dataclasses.field(default_factory=list)
    methods: Dict[str, FunctionInfo] = dataclasses.field(default_factory=dict)
    # attr name -> inferred class name (project classes only)
    attr_types: Dict[str, str] = dataclasses.field(default_factory=dict)


def iter_python_files(roots: Sequence[str]) -> List[str]:
    """All .py files under ``roots`` (files accepted verbatim), sorted."""
    out: Set[str] = set()
    for root in roots:
        if os.path.isfile(root) and root.endswith(".py"):
            out.add(root)
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in filenames:
                if fn.endswith(".py"):
                    out.add(os.path.join(dirpath, fn))
    return sorted(out)


def _annotation_class(node: Optional[ast.AST]) -> Optional[str]:
    """The class name an annotation refers to, unwrapping Optional/quotes."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        try:
            node = ast.parse(node.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(node, ast.Subscript):
        # Optional[X] / List[X] / "collections.OrderedDict[str, X]": take the
        # innermost project-class-looking name on a best-effort basis.
        inner = node.slice
        if isinstance(inner, ast.Tuple):
            for elt in reversed(inner.elts):
                name = _annotation_class(elt)
                if name is not None:
                    return name
            return None
        return _annotation_class(inner)
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _tail_name(node: ast.AST) -> Optional[str]:
    """The final identifier of a Name/Attribute chain (``a.b.C`` -> ``C``)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` as a string, or None for non-trivial expressions."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class Project:
    """Parsed-AST index with lightweight cross-module type resolution."""

    def __init__(self, roots: Sequence[str], rel_to: Optional[str] = None):
        self.rel_to = rel_to or os.getcwd()
        self.trees: Dict[str, ast.Module] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}  # qualname -> info
        self.module_functions: Dict[str, Dict[str, FunctionInfo]] = {}
        self.subclasses: Dict[str, Set[str]] = {}
        self.parse_errors: List[Tuple[str, str]] = []
        for abspath in iter_python_files(roots):
            rel = os.path.relpath(abspath, self.rel_to)
            try:
                with open(abspath, "r", encoding="utf-8") as f:
                    tree = ast.parse(f.read(), filename=rel)
            except (SyntaxError, UnicodeDecodeError, OSError) as e:
                self.parse_errors.append((rel, str(e)))
                continue
            self.trees[rel] = tree
            self._index_module(rel, tree)
        self._infer_attr_types()
        self._build_subclasses()

    # -- indexing -----------------------------------------------------------

    def _index_module(self, path: str, tree: ast.Module) -> None:
        mod_funcs: Dict[str, FunctionInfo] = {}
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info = FunctionInfo(
                    qualname=f"{path}::{node.name}",
                    name=node.name,
                    node=node,
                    path=path,
                )
                mod_funcs[node.name] = info
                self.functions[info.qualname] = info
            elif isinstance(node, ast.ClassDef):
                cls = ClassInfo(name=node.name, node=node, path=path)
                for base in node.bases:
                    base_name = _tail_name(base)
                    if base_name is not None:
                        cls.bases.append(base_name)
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        info = FunctionInfo(
                            qualname=f"{path}::{node.name}.{item.name}",
                            name=item.name,
                            node=item,
                            path=path,
                            class_name=node.name,
                        )
                        cls.methods[item.name] = info
                        self.functions[info.qualname] = info
                # Last definition of a class name wins (names are unique in
                # this tree; fixtures keep their own Project instances).
                self.classes[node.name] = cls
        self.module_functions[path] = mod_funcs

    def _build_subclasses(self) -> None:
        for cls in self.classes.values():
            for base in cls.bases:
                if base in self.classes:
                    self.subclasses.setdefault(base, set()).add(cls.name)

    def _infer_attr_types(self) -> None:
        for cls in self.classes.values():
            # Class-level annotated attributes.
            for item in cls.node.body:
                if isinstance(item, ast.AnnAssign) and isinstance(
                    item.target, ast.Name
                ):
                    ann = _annotation_class(item.annotation)
                    if ann in self.classes:
                        cls.attr_types[item.target.id] = ann
            for method in cls.methods.values():
                params = self._param_types(method.node)
                for stmt in ast.walk(method.node):
                    target: Optional[str] = None
                    value: Optional[ast.AST] = None
                    if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                        target = self._self_attr(stmt.targets[0])
                        value = stmt.value
                    elif isinstance(stmt, ast.AnnAssign):
                        target = self._self_attr(stmt.target)
                        ann = _annotation_class(stmt.annotation)
                        if target is not None and ann in self.classes:
                            cls.attr_types.setdefault(target, ann)
                            continue
                        value = stmt.value
                    if target is None or value is None:
                        continue
                    inferred = self._expr_class(value, params, cls)
                    if inferred is not None:
                        cls.attr_types.setdefault(target, inferred)

    @staticmethod
    def _self_attr(node: ast.AST) -> Optional[str]:
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            return node.attr
        return None

    def _param_types(self, fn_node: ast.AST) -> Dict[str, str]:
        out: Dict[str, str] = {}
        args = fn_node.args
        for arg in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
            ann = _annotation_class(arg.annotation)
            if ann in self.classes:
                out[arg.arg] = ann
        return out

    def _expr_class(
        self,
        node: ast.AST,
        local_types: Dict[str, str],
        cls: Optional[ClassInfo],
    ) -> Optional[str]:
        """The project class an expression evaluates to, if inferable."""
        if isinstance(node, ast.Call):
            # getattr(obj, "literal"[, default]) reads an attribute.
            if (
                isinstance(node.func, ast.Name)
                and node.func.id == "getattr"
                and len(node.args) >= 2
                and isinstance(node.args[1], ast.Constant)
                and isinstance(node.args[1].value, str)
            ):
                owner = self._expr_class(node.args[0], local_types, cls)
                if owner is not None:
                    return self.classes[owner].attr_types.get(node.args[1].value)
                return None
            callee = _tail_name(node.func)
            if callee in self.classes:
                return callee
            # Annotated return type of a resolvable method call
            # (e.g. registry.counter(...) -> Counter).
            if isinstance(node.func, ast.Attribute):
                owner = self._expr_class(node.func.value, local_types, cls)
                if owner is not None:
                    method = self._lookup_method(owner, node.func.attr)
                    if method is not None:
                        ret = _annotation_class(
                            getattr(method.node, "returns", None)
                        )
                        if ret in self.classes:
                            return ret
                    elif node.func.attr in ("get", "pop", "setdefault"):
                        # Container-of-X convention: dict-style access on a
                        # container typed by its element class yields X
                        # (the class defines no such method itself).
                        return owner
            return None
        if isinstance(node, ast.Subscript):
            # Container-of-X convention: a dict/list attr typed as X (via
            # Dict[str, X] annotations or comprehension values) yields X
            # when subscripted.
            return self._expr_class(node.value, local_types, cls)
        if isinstance(node, ast.DictComp):
            return self._expr_class(node.value, local_types, cls)
        if isinstance(node, (ast.ListComp, ast.SetComp)):
            return self._expr_class(node.elt, local_types, cls)
        if isinstance(node, ast.Name):
            if node.id == "self" and cls is not None:
                return cls.name
            return local_types.get(node.id)
        if isinstance(node, ast.Attribute):
            owner = self._expr_class(node.value, local_types, cls)
            if owner is not None:
                return self.classes[owner].attr_types.get(node.attr)
            return None
        if isinstance(node, ast.IfExp):
            return self._expr_class(
                node.body, local_types, cls
            ) or self._expr_class(node.orelse, local_types, cls)
        if isinstance(node, ast.BoolOp):
            for v in node.values:
                got = self._expr_class(v, local_types, cls)
                if got is not None:
                    return got
        return None

    # -- per-function local type environments -------------------------------

    def local_types(self, fn: FunctionInfo) -> Dict[str, str]:
        """Variable name -> project class, for ``fn``'s body."""
        cls = self.classes.get(fn.class_name) if fn.class_name else None
        env = self._param_types(fn.node)
        # Two sweeps so a name assigned before its source attr was seen
        # still resolves (assignment order in a straight-line body).
        for _ in range(2):
            for stmt in ast.walk(fn.node):
                if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                    tgt = stmt.targets[0]
                    if isinstance(tgt, ast.Name):
                        inferred = self._expr_class(stmt.value, env, cls)
                        if inferred is not None:
                            env[tgt.id] = inferred
                elif isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name
                ):
                    ann = _annotation_class(stmt.annotation)
                    if ann in self.classes:
                        env[stmt.target.id] = ann
        return env

    # -- call resolution -----------------------------------------------------

    def _lookup_method(self, class_name: str, meth: str) -> Optional[FunctionInfo]:
        seen: Set[str] = set()
        queue = [class_name]
        while queue:
            name = queue.pop(0)
            if name in seen or name not in self.classes:
                continue
            seen.add(name)
            cls = self.classes[name]
            if meth in cls.methods:
                return cls.methods[meth]
            queue.extend(cls.bases)
        return None

    def _method_candidates(self, class_name: str, meth: str) -> List[FunctionInfo]:
        """``cls.meth`` plus overrides in project subclasses (ABC dispatch)."""
        out: List[FunctionInfo] = []
        base = self._lookup_method(class_name, meth)
        if base is not None:
            out.append(base)
        for sub in sorted(self._all_subclasses(class_name)):
            sub_cls = self.classes.get(sub)
            if sub_cls is not None and meth in sub_cls.methods:
                info = sub_cls.methods[meth]
                if info not in out:
                    out.append(info)
        return out

    def _all_subclasses(self, class_name: str) -> Set[str]:
        out: Set[str] = set()
        queue = list(self.subclasses.get(class_name, ()))
        while queue:
            name = queue.pop()
            if name in out:
                continue
            out.add(name)
            queue.extend(self.subclasses.get(name, ()))
        return out

    def resolve_call(
        self,
        call: ast.Call,
        fn: FunctionInfo,
        local_types: Dict[str, str],
    ) -> List[FunctionInfo]:
        """Project functions a call may dispatch to ([] when unresolvable)."""
        func = call.func
        cls = self.classes.get(fn.class_name) if fn.class_name else None
        if isinstance(func, ast.Name):
            # Constructor or module-level function in the same module.
            if func.id in self.classes:
                ctor = self._lookup_method(func.id, "__init__")
                return [ctor] if ctor is not None else []
            local = self.module_functions.get(fn.path, {}).get(func.id)
            return [local] if local is not None else []
        if isinstance(func, ast.Attribute):
            meth = func.attr
            # self.meth() / typed_receiver.meth()
            owner = self._expr_class(func.value, local_types, cls)
            if owner is not None:
                candidates = self._method_candidates(owner, meth)
                if candidates:
                    return candidates
            # module_alias.func() / module_alias.Class() — match by tail name
            # against project classes, then module-level functions anywhere
            # with a unique name.
            if meth in self.classes:
                ctor = self._lookup_method(meth, "__init__")
                return [ctor] if ctor is not None else []
            matches = [
                funcs[meth]
                for funcs in self.module_functions.values()
                if meth in funcs
            ]
            if len(matches) == 1:
                return matches
        return []
