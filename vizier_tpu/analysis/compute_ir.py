"""Pass 5: compute-IR conformance — every registered DesignerProgram is a
full citizen of the serving stack.

The batched designer-compute IR (:mod:`vizier_tpu.compute`) only pays off
if every registered program actually carries the cross-cutting features
the seam promises. This pass AST-scans the configured paths for
``compute`` registry ``register(DesignerType, Program())`` sites and fails
on:

- ``unresolvable-program-class`` — a registration whose program class
  definition the scan cannot find (dynamic construction hides the
  contract from every other rule);
- ``program-missing-hook`` — the class (or a scanned non-ABC base) does
  not define one of the four IR hooks (``bucket_key`` / ``prepare`` /
  ``device_program`` / ``finalize``); the abstract definitions on
  ``DesignerProgram`` itself do not count;
- ``program-missing-prewarm-coverage`` — no ``prewarm_factory``
  implementation: the program would be invisible to the compile-prewarm
  walker and first-request latency pays its XLA compile;
- ``program-missing-kind`` / ``program-missing-device-phase`` — the
  ``kind`` / ``device_phase`` class attributes are absent or not string
  literals, so registry lookup / ``vizier_jax_phase_seconds`` tracing
  cannot name the program;
- ``program-missing-shard-axis`` — no literal ``shardable_batch_axis``
  declaration: the mesh execution plane (``parallel.mesh``) needs every
  program to state explicitly whether its ``device_program`` may be
  sharded over a device placement (``"study"`` for the stacked
  leading-axis programs, ``""`` for an unshardable one) — an inherited
  silent default would let a program that never audited its batch axis
  ride the single-device path forever, or worse, a copied program claim
  shardability it never implements;
- ``missing-chaos-program-hook`` — ``vizier_tpu/testing/chaos.py`` no
  longer defines the generic ``ChaosProgram`` wrapper (the IR-level chaos
  slot-isolation seam) with the per-slot and device hooks;
- ``program-missing-chaos-coverage`` — the program's ``kind`` literal
  appears in no test file that exercises the chaos harness: a program
  nobody chaos-tests has unproven slot isolation. (Like the env pass's
  doc rule, this reads ``tests/`` directly — the suite's scan roots stay
  production code.)
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Set, Tuple

from vizier_tpu.analysis import common

PASS_NAME = "compute_ir"

REQUIRED_HOOKS = ("bucket_key", "prepare", "device_program", "finalize")

# The abstract contract class: its (abstract) hook defs never count as
# implementations, and it is skipped when walking scanned bases.
_ABC_NAMES = ("DesignerProgram",)

_CHAOS_MODULE = os.path.join("vizier_tpu", "testing", "chaos.py")
_CHAOS_WRAPPER = "ChaosProgram"
_CHAOS_HOOKS = ("prepare", "device_program", "finalize")


@dataclasses.dataclass(frozen=True)
class RegisteredProgram:
    """One ``register(DesignerType, ProgramClass())`` site."""

    designer_type: str
    program_class: str
    kind: Optional[str]  # the class's literal kind, if resolvable
    path: str
    line: int


@dataclasses.dataclass
class ComputeIrResult:
    findings: List[common.Finding]
    registered: List[RegisteredProgram] = dataclasses.field(
        default_factory=list
    )


def _is_registry_register(call: ast.Call, path_imports: Set[str]) -> bool:
    """Whether ``call`` is a compute-registry ``register(...)`` call."""
    name = common.dotted(call.func)
    if name is None or not name.endswith("register"):
        return False
    # compute_registry.register(...) / registry.register(...) where the
    # module was imported from vizier_tpu.compute.
    parts = name.split(".")
    if len(parts) != 2:
        return False
    return parts[0] in path_imports and len(call.args) >= 2


def _compute_registry_aliases(tree: ast.Module) -> Set[str]:
    """Local names bound to ``vizier_tpu.compute.registry`` in a module."""
    aliases: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            module = node.module or ""
            for alias in node.names:
                if module == "vizier_tpu.compute" and alias.name == "registry":
                    aliases.add(alias.asname or alias.name)
                elif module == "vizier_tpu.compute.registry":
                    continue  # from-imports of members, not the module
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "vizier_tpu.compute.registry":
                    aliases.add(
                        alias.asname or "vizier_tpu.compute.registry"
                    )
    return aliases


def _class_attr_literal(cls: ast.ClassDef, attr: str) -> Optional[str]:
    """The string literal bound to a class attribute, or None."""
    for item in cls.body:
        targets = []
        value = None
        if isinstance(item, ast.Assign):
            targets = [
                t.id for t in item.targets if isinstance(t, ast.Name)
            ]
            value = item.value
        elif isinstance(item, ast.AnnAssign) and isinstance(
            item.target, ast.Name
        ):
            targets = [item.target.id]
            value = item.value
        if attr in targets and isinstance(value, ast.Constant):
            if isinstance(value.value, str):
                return value.value
    return None


def _methods_with_bases(
    project: common.Project, class_name: str
) -> Dict[str, common.FunctionInfo]:
    """Methods defined on ``class_name`` or scanned non-ABC bases."""
    out: Dict[str, common.FunctionInfo] = {}
    seen: Set[str] = set()
    stack = [class_name]
    while stack:
        name = stack.pop()
        if name in seen or name in _ABC_NAMES:
            continue
        seen.add(name)
        info = project.classes.get(name)
        if info is None:
            continue
        for method, finfo in info.methods.items():
            out.setdefault(method, finfo)
        stack.extend(info.bases)
    return out


def _inherited_attr_literal(
    project: common.Project, class_name: str, attr: str
) -> Optional[str]:
    seen: Set[str] = set()
    stack = [class_name]
    while stack:
        name = stack.pop()
        if name in seen or name in _ABC_NAMES:
            continue
        seen.add(name)
        info = project.classes.get(name)
        if info is None:
            continue
        literal = _class_attr_literal(info.node, attr)
        if literal is not None:
            return literal
        stack.extend(info.bases)
    return None


def run(project: common.Project, repo_root: str) -> ComputeIrResult:
    findings: List[common.Finding] = []
    registered: List[RegisteredProgram] = []

    # 1. Registration sites.
    for path, tree in project.trees.items():
        aliases = _compute_registry_aliases(tree)
        if not aliases:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if not _is_registry_register(node, aliases):
                continue
            designer = common.dotted(node.args[0]) or "<dynamic>"
            program_arg = node.args[1]
            program_class: Optional[str] = None
            if isinstance(program_arg, ast.Call):
                program_class = common.dotted(program_arg.func)
            elif isinstance(program_arg, ast.Name):
                # register(T, PROGRAM_SINGLETON) — resolve via assignment?
                program_class = None
            if program_class is None:
                findings.append(
                    common.Finding(
                        pass_name=PASS_NAME,
                        rule="unresolvable-program-class",
                        key=f"unresolvable-program-class@{path}:{designer}",
                        message=(
                            "compute-registry register() with a program "
                            "whose class the scan cannot resolve; register "
                            "a direct ProgramClass() instantiation"
                        ),
                        path=path,
                        line=node.lineno,
                    )
                )
                continue
            program_class = program_class.split(".")[-1]
            kind = _inherited_attr_literal(project, program_class, "kind")
            registered.append(
                RegisteredProgram(
                    designer_type=designer,
                    program_class=program_class,
                    kind=kind,
                    path=path,
                    line=node.lineno,
                )
            )

    # 2. Per-program contract checks.
    for reg in registered:
        info = project.classes.get(reg.program_class)
        if info is None:
            findings.append(
                common.Finding(
                    pass_name=PASS_NAME,
                    rule="unresolvable-program-class",
                    key=f"unresolvable-program-class:{reg.program_class}",
                    message=(
                        f"registered program class {reg.program_class} has "
                        "no scanned definition"
                    ),
                    path=reg.path,
                    line=reg.line,
                )
            )
            continue
        methods = _methods_with_bases(project, reg.program_class)
        for hook in REQUIRED_HOOKS:
            if hook not in methods:
                findings.append(
                    common.Finding(
                        pass_name=PASS_NAME,
                        rule="program-missing-hook",
                        key=f"program-missing-hook:{reg.program_class}.{hook}",
                        message=(
                            f"DesignerProgram {reg.program_class} does not "
                            f"implement the IR hook {hook}()"
                        ),
                        path=info.path,
                        line=info.node.lineno,
                    )
                )
        if "prewarm_factory" not in methods:
            findings.append(
                common.Finding(
                    pass_name=PASS_NAME,
                    rule="program-missing-prewarm-coverage",
                    key=f"program-missing-prewarm-coverage:{reg.program_class}",
                    message=(
                        f"DesignerProgram {reg.program_class} has no "
                        "prewarm_factory — the compile-prewarm walker "
                        "cannot cover it and first requests pay its XLA "
                        "compile"
                    ),
                    path=info.path,
                    line=info.node.lineno,
                )
            )
        if reg.kind is None:
            findings.append(
                common.Finding(
                    pass_name=PASS_NAME,
                    rule="program-missing-kind",
                    key=f"program-missing-kind:{reg.program_class}",
                    message=(
                        f"DesignerProgram {reg.program_class} does not "
                        "declare a literal `kind` class attribute"
                    ),
                    path=info.path,
                    line=info.node.lineno,
                )
            )
        if _inherited_attr_literal(
            project, reg.program_class, "device_phase"
        ) is None:
            findings.append(
                common.Finding(
                    pass_name=PASS_NAME,
                    rule="program-missing-device-phase",
                    key=f"program-missing-device-phase:{reg.program_class}",
                    message=(
                        f"DesignerProgram {reg.program_class} does not "
                        "declare a literal `device_phase` — its flushes "
                        "would be invisible to vizier_jax_phase_seconds"
                    ),
                    path=info.path,
                    line=info.node.lineno,
                )
            )
        if _inherited_attr_literal(
            project, reg.program_class, "shardable_batch_axis"
        ) is None:
            findings.append(
                common.Finding(
                    pass_name=PASS_NAME,
                    rule="program-missing-shard-axis",
                    key=f"program-missing-shard-axis:{reg.program_class}",
                    message=(
                        f"DesignerProgram {reg.program_class} does not "
                        "declare a literal `shardable_batch_axis` — the "
                        "mesh execution plane needs an explicit statement "
                        "of whether device_program may shard over a "
                        'placement ("study") or must stay single-device '
                        '("")'
                    ),
                    path=info.path,
                    line=info.node.lineno,
                )
            )

    # 3. The generic chaos hook must exist and cover the IR surface. Like
    # env_registry's registry-wide rules, the whole-tree checks only run
    # when the scan actually saw registrations — a partial scan (fixtures,
    # one subpackage) cannot judge tree-wide coverage.
    if not registered:
        return ComputeIrResult(findings=_dedupe(findings), registered=[])
    chaos_info = project.classes.get(_CHAOS_WRAPPER)
    chaos_path_ok = chaos_info is not None and chaos_info.path.replace(
        "\\", "/"
    ).endswith("testing/chaos.py")
    if not chaos_path_ok:
        findings.append(
            common.Finding(
                pass_name=PASS_NAME,
                rule="missing-chaos-program-hook",
                key="missing-chaos-program-hook",
                message=(
                    "vizier_tpu/testing/chaos.py must define the generic "
                    f"{_CHAOS_WRAPPER} wrapper (IR-level chaos slot "
                    "isolation)"
                ),
                path=_CHAOS_MODULE.replace(os.sep, "/"),
                line=0,
            )
        )
    else:
        for hook in _CHAOS_HOOKS:
            if hook not in chaos_info.methods:
                findings.append(
                    common.Finding(
                        pass_name=PASS_NAME,
                        rule="missing-chaos-program-hook",
                        key=f"missing-chaos-program-hook:{hook}",
                        message=(
                            f"{_CHAOS_WRAPPER} does not wrap the IR hook "
                            f"{hook}()"
                        ),
                        path=chaos_info.path,
                        line=chaos_info.node.lineno,
                    )
                )

    # 4. Per-kind chaos coverage in tests/.
    chaos_texts: List[str] = []
    tests_root = os.path.join(repo_root, "tests")
    for dirpath, dirnames, filenames in os.walk(tests_root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for filename in filenames:
            if not filename.endswith(".py"):
                continue
            try:
                with open(
                    os.path.join(dirpath, filename), "r", encoding="utf-8"
                ) as f:
                    text = f.read()
            except OSError:
                continue
            if any(
                marker in text
                for marker in (
                    "testing import chaos",
                    "testing.chaos",
                    "ChaosDesigner",
                    "ChaosProgram",
                    "ChaosMonkey",
                )
            ):
                chaos_texts.append(text)
    for reg in registered:
        if reg.kind is None:
            continue  # already reported above
        if not any(reg.kind in text for text in chaos_texts):
            findings.append(
                common.Finding(
                    pass_name=PASS_NAME,
                    rule="program-missing-chaos-coverage",
                    key=f"program-missing-chaos-coverage:{reg.kind}",
                    message=(
                        f"registered program kind {reg.kind!r} appears in "
                        "no chaos-exercising test under tests/ — its "
                        "slot-isolation contract is untested"
                    ),
                    path=reg.path,
                    line=reg.line,
                )
            )

    return ComputeIrResult(findings=_dedupe(findings), registered=registered)


def _dedupe(findings: List[common.Finding]) -> List[common.Finding]:
    seen: Set[str] = set()
    unique: List[common.Finding] = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.key)):
        if f.key not in seen:
            seen.add(f.key)
            unique.append(f)
    return unique
