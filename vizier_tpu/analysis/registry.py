"""The single source of truth for every ``VIZIER_*`` switch.

Every environment variable the tree reads (and every reserved ``VIZIER_*``
constant that is *not* an environment variable) is declared here with its
owner and documentation link. The ``env_registry`` analysis pass fails any
``os.environ`` read — direct or through the helpers below — of a name that
is missing from this table, and any declared switch whose doc file does
not mention it.

Runtime code reads switches through :func:`env_on` / :func:`env_int` /
:func:`env_float` / :func:`env_str`, which raise on undeclared names — so
a typo'd switch fails loudly at import time instead of silently reading an
always-unset variable.

Stdlib-only on purpose: config modules all over the tree import this, and
the analysis pass must be runnable without jax installed.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional, Tuple

# Declaration kinds:
#   "flag"     — boolean-ish on/off switch ("0"/"false"/"" = off);
#   "int"      — integer-valued;
#   "float"    — float-valued;
#   "str"      — free-form string (paths, names);
#   "constant" — a reserved VIZIER_* Python constant that is NOT an
#                environment variable (reading it from os.environ is a
#                violation; declaring it here keeps the literal scan and
#                naive greps honest about what is and is not a switch).
_KINDS = ("flag", "int", "float", "str", "constant")


@dataclasses.dataclass(frozen=True)
class EnvSwitch:
    """One declared ``VIZIER_*`` name."""

    name: str
    kind: str
    owner: str  # owning config class or module
    doc: str  # repo-relative doc path that describes the switch
    description: str
    # Default *as read* ("1" = on unless explicitly disabled). Only
    # meaningful for env kinds; constants have no runtime default.
    default: str = ""

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(f"Unknown switch kind {self.kind!r} for {self.name}.")
        if not self.name.startswith("VIZIER_"):
            raise ValueError(f"Switch {self.name!r} must start with VIZIER_.")


def _switch(name, kind, owner, doc, description, default=""):
    return EnvSwitch(name, kind, owner, doc, description, default)


_OBS_DOC = "docs/guides/observability.md"
_REL_DOC = "docs/guides/reliability.md"
_SRV_DOC = "docs/guides/serving.md"
_PERF_DOC = "docs/guides/performance.md"
_SWITCH_DOC = "docs/guides/switching_from_oss_vizier.md"
_RUN_DOC = "docs/guides/running_the_service.md"
_LOAD_DOC = "docs/guides/loadtest.md"

SWITCHES: Tuple[EnvSwitch, ...] = (
    # -- observability (ObservabilityConfig) -------------------------------
    _switch("VIZIER_OBSERVABILITY", "flag", "ObservabilityConfig", _OBS_DOC,
            "Master switch for tracing/metrics/JAX profiling.", "1"),
    _switch("VIZIER_OBSERVABILITY_TRACING", "flag", "ObservabilityConfig",
            _OBS_DOC, "Span tracing on/off (counters stay).", "1"),
    _switch("VIZIER_OBSERVABILITY_METRICS", "flag", "ObservabilityConfig",
            _OBS_DOC, "Latency histograms on/off.", "1"),
    _switch("VIZIER_OBSERVABILITY_JAX", "flag", "ObservabilityConfig",
            _OBS_DOC, "Designer device-phase timers (forces syncs).", "1"),
    _switch("VIZIER_OBSERVABILITY_SPAN_BUFFER", "int", "ObservabilityConfig",
            _OBS_DOC, "Finished-span ring-buffer size.", "4096"),
    _switch("VIZIER_OBSERVABILITY_SPAN_LOG", "str", "ObservabilityConfig",
            _OBS_DOC, "JSON-lines span sink path ('' = ring only)."),
    # -- SLO engine (SloConfig) --------------------------------------------
    _switch("VIZIER_SLO", "flag", "SloConfig", _OBS_DOC,
            "Arm the SLO engine: sliding-window error-budget burn rates "
            "+ breach handling (opt-in; unset/0 = no engine, no sampler).",
            "0"),
    _switch("VIZIER_SLO_WINDOWS", "str", "SloConfig", _OBS_DOC,
            "Comma-separated sliding windows in seconds.", "60,300"),
    _switch("VIZIER_SLO_EVAL_INTERVAL_S", "float", "SloConfig", _OBS_DOC,
            "Background evaluation cadence (0 = manual evaluate() only).",
            "1.0"),
    _switch("VIZIER_SLO_SUGGEST_P99_MS", "float", "SloConfig", _OBS_DOC,
            "Objective: 99% of suggests per hop under this many ms.",
            "5000.0"),
    _switch("VIZIER_SLO_SPECULATIVE_HIT_RATE", "float", "SloConfig",
            _OBS_DOC,
            "Objective: minimum speculative serve hit rate (evaluated "
            "only when the window saw speculative traffic).", "0.8"),
    _switch("VIZIER_SLO_FALLBACK_RATE", "float", "SloConfig", _OBS_DOC,
            "Objective: maximum quasi-random fallback fraction.", "0.05"),
    _switch("VIZIER_SLO_SHED_RATE", "float", "SloConfig", _OBS_DOC,
            "Objective: maximum admission-shed fraction of suggests.",
            "0.05"),
    _switch("VIZIER_SLO_DUMP_DIR", "str", "SloConfig", _OBS_DOC,
            "Black-box dump directory for SLO breaches ('' = no dumps)."),
    # -- flight recorder (FlightRecorderConfig) ----------------------------
    _switch("VIZIER_FLIGHT_RECORDER", "flag", "FlightRecorderConfig",
            _OBS_DOC,
            "Per-study flight recorder of structured lifecycle events "
            "(opt-in; unset/0 = the stateless no-op recorder).", "0"),
    _switch("VIZIER_FLIGHT_RECORDER_RING", "int", "FlightRecorderConfig",
            _OBS_DOC, "Events kept per study ring.", "256"),
    _switch("VIZIER_FLIGHT_RECORDER_STUDIES", "int", "FlightRecorderConfig",
            _OBS_DOC, "Study rings kept (LRU-evicted past this).", "1024"),
    # -- fleet aggregation (observability.fleet) ---------------------------
    _switch("VIZIER_OBS_DUMP_DIR", "str", "replica_main", _OBS_DOC,
            "Per-replica observability dump directory: span/metric/"
            "recorder files written on shutdown for fleet merging."),
    # -- reliability (ReliabilityConfig) -----------------------------------
    _switch("VIZIER_RELIABILITY", "flag", "ReliabilityConfig", _REL_DOC,
            "Master switch for retries/deadlines/breaker/fallback.", "1"),
    _switch("VIZIER_RELIABILITY_RETRIES", "flag", "ReliabilityConfig",
            _REL_DOC, "Retry transient RPC/op failures.", "1"),
    _switch("VIZIER_RELIABILITY_DEADLINE", "flag", "ReliabilityConfig",
            _REL_DOC, "Deadline attachment and propagation.", "1"),
    _switch("VIZIER_RELIABILITY_BREAKER", "flag", "ReliabilityConfig",
            _REL_DOC, "Per-study circuit breaker.", "1"),
    _switch("VIZIER_RELIABILITY_FALLBACK", "flag", "ReliabilityConfig",
            _REL_DOC, "Quasi-random fallback on designer failure.", "1"),
    # -- multi-tenant admission (serving.admission.AdmissionConfig) --------
    _switch("VIZIER_ADMISSION", "flag", "AdmissionConfig", _REL_DOC,
            "Multi-tenant overload protection: fair-share admission, "
            "load shedding, deadline-aware rejection, graceful "
            "degradation (opt-in; unset/0 = the bit-identical "
            "pre-admission path).", "0"),
    _switch("VIZIER_ADMISSION_MAX_INFLIGHT", "int", "AdmissionConfig",
            _REL_DOC,
            "Fleet-wide cap on concurrent designer computations.", "16"),
    _switch("VIZIER_ADMISSION_TENANT_INFLIGHT", "int", "AdmissionConfig",
            _REL_DOC,
            "Per-tenant cap on concurrent designer computations.", "8"),
    _switch("VIZIER_ADMISSION_WEIGHTS", "str", "AdmissionConfig", _REL_DOC,
            "Fair-share weights, 'tenant:w,...' (unlisted tenants = 1.0); "
            "drives the DRR quantum and the degraded-mode priority split."),
    _switch("VIZIER_ADMISSION_RETRY_AFTER_MS", "float", "AdmissionConfig",
            _REL_DOC,
            "Backoff-floor hint stamped into shed errors.", "50"),
    _switch("VIZIER_ADMISSION_DEADLINE", "flag", "AdmissionConfig", _REL_DOC,
            "Deadline-aware rejection: shed when the remaining budget "
            "cannot cover estimated queue wait + compute p50.", "1"),
    _switch("VIZIER_ADMISSION_DEGRADED", "flag", "AdmissionConfig", _REL_DOC,
            "Graceful degradation under sustained saturation (the "
            "healthy/shedding/degraded state machine's last stage).", "1"),
    _switch("VIZIER_ADMISSION_DEGRADED_FLOOR", "float", "AdmissionConfig",
            _REL_DOC,
            "Tenants with weight below this serve quasi-random in "
            "degraded mode; others keep GP compute.", "1.0"),
    _switch("VIZIER_ADMISSION_DEGRADE_RATE", "float", "AdmissionConfig",
            _REL_DOC,
            "Windowed shed rate at which SHEDDING escalates to DEGRADED.",
            "0.5"),
    _switch("VIZIER_ADMISSION_RECOVER_RATE", "float", "AdmissionConfig",
            _REL_DOC,
            "Windowed shed rate below which DEGRADED may recover "
            "(hysteretic: must hold for a full window).", "0.1"),
    _switch("VIZIER_ADMISSION_WINDOW_S", "float", "AdmissionConfig",
            _REL_DOC,
            "Sliding decision window for the overload state machine.",
            "5.0"),
    # -- serving (ServingConfig) -------------------------------------------
    _switch("VIZIER_SERVING_CACHE", "flag", "ServingConfig", _SRV_DOC,
            "Per-study designer-state cache.", "1"),
    _switch("VIZIER_SERVING_WARM_START", "flag", "ServingConfig", _SRV_DOC,
            "Warm-started ARD training.", "1"),
    _switch("VIZIER_SERVING_COALESCING", "flag", "ServingConfig", _SRV_DOC,
            "Compute-level request coalescing.", "1"),
    _switch("VIZIER_BATCHING", "flag", "ServingConfig", _PERF_DOC,
            "Cross-study batch executor.", "1"),
    _switch("VIZIER_BATCH_MAX_SIZE", "int", "ServingConfig", _PERF_DOC,
            "Micro-batch flush size.", "8"),
    _switch("VIZIER_BATCH_MAX_WAIT_MS", "float", "ServingConfig", _PERF_DOC,
            "Micro-batch flush window (ms).", "4.0"),
    _switch("VIZIER_BATCHING_PREWARM", "flag", "ServingConfig", _PERF_DOC,
            "Background AOT compile of batched programs.", "0"),
    _switch("VIZIER_COMPILE_CACHE_DIR", "str", "ServingConfig", _PERF_DOC,
            "JAX persistent compilation cache directory."),
    # -- distributed (DistributedConfig) -----------------------------------
    _switch("VIZIER_DISTRIBUTED", "flag", "DistributedConfig", _RUN_DOC,
            "Study-affinity router (off = first replica serves all).", "1"),
    _switch("VIZIER_DISTRIBUTED_REPLICAS", "int", "DistributedConfig",
            _RUN_DOC, "Replica count for env-built sharded tiers.", "4"),
    _switch("VIZIER_DISTRIBUTED_WAL_DIR", "str", "DistributedConfig",
            _RUN_DOC, "Snapshot+WAL root ('' = RAM only, no restart warmth)."),
    _switch("VIZIER_DISTRIBUTED_SNAPSHOT_INTERVAL", "int", "DistributedConfig",
            _RUN_DOC, "Mutations per shard between WAL compactions.", "256"),
    _switch("VIZIER_DISTRIBUTED_WAL_FSYNC", "flag", "DistributedConfig",
            _RUN_DOC, "fsync the WAL per append (power-loss durability).", "0"),
    _switch("VIZIER_DISTRIBUTED_ROUTE_CACHE_SIZE", "int", "StudyRouter",
            _RUN_DOC, "LRU cap on the router's placement cache.", "65536"),
    _switch("VIZIER_DISTRIBUTED_REPLICATION", "flag", "DistributedConfig",
            _RUN_DOC,
            "Stream WAL appends to each study's rendezvous successors' "
            "standby logs so failover needs no shared filesystem "
            "(0 = local-disk-only failover, the pre-replication path).",
            "1"),
    _switch("VIZIER_DISTRIBUTED_REPLICATION_FACTOR", "int",
            "DistributedConfig", _RUN_DOC,
            "Standby copies per study (K rendezvous successors).", "2"),
    _switch("VIZIER_DISTRIBUTED_REPLICATION_QUEUE", "int",
            "DistributedConfig", _RUN_DOC,
            "Per-origin replication streamer queue bound; overflow drops "
            "and re-baselines rather than blocking the write path.",
            "4096"),
    _switch("VIZIER_DISTRIBUTED_REPLICATION_BATCH", "int",
            "DistributedConfig", _RUN_DOC,
            "Records per streamed replication batch.", "64"),
    _switch("VIZIER_DISTRIBUTED_LEASE_TIMEOUT_S", "float",
            "DistributedConfig", _RUN_DOC,
            "Seconds without a renewed heartbeat before the fleet manager "
            "declares a subprocess replica dead and fails it over.", "3.0"),
    _switch("VIZIER_DISTRIBUTED_HEARTBEAT_INTERVAL_S", "float",
            "DistributedConfig", _RUN_DOC,
            "Cadence of the manager's lease-renewal Heartbeat probes to "
            "subprocess replicas.", "1.0"),
    _switch("VIZIER_NETCHAOS", "str", "replica_main", _RUN_DOC,
            "Seeded network fault-injection schedule for a replica's "
            "outbound replication links (testing.netchaos spec string; "
            "'' = no injection)."),
    # -- disaggregated compute tier (ComputeTierConfig) --------------------
    _switch("VIZIER_COMPUTE_TIER", "flag", "ComputeTierConfig", _RUN_DOC,
            "Disaggregated compute tier: frontends dispatch Pythia "
            "suggest/early-stop to one shared standalone compute server "
            "(opt-in; unset/0 = the bit-identical self-contained path).",
            "0"),
    _switch("VIZIER_COMPUTE_TIER_ENDPOINT", "str", "ComputeTierConfig",
            _RUN_DOC,
            "host:port of the shared Pythia compute server ('' with the "
            "tier enabled behaves as tier-down: every request takes the "
            "fallback path)."),
    _switch("VIZIER_COMPUTE_TIER_FALLBACK", "str", "ComputeTierConfig",
            _RUN_DOC,
            "Degradation mode when the tier is unreachable: 'local' "
            "serves from the frontend's own minimal Pythia; 'fail' "
            "surfaces the transport error to the client.", "local"),
    _switch("VIZIER_COMPUTE_TIER_HEALTH_INTERVAL_S", "float",
            "ComputeTierConfig", _RUN_DOC,
            "Cooldown after a compute-tier failure before a frontend "
            "re-probes the remote endpoint (the fallback serves "
            "meanwhile).", "1.0"),
    # -- speculative pre-compute (SpeculativeConfig) -----------------------
    _switch("VIZIER_SPECULATIVE", "flag", "SpeculativeConfig", _SRV_DOC,
            "Background pre-compute of the next suggestion batch after "
            "each completion (opt-in; unset/0 = the exact request path).",
            "0"),
    _switch("VIZIER_SPECULATIVE_WORKERS", "int", "SpeculativeConfig",
            _SRV_DOC, "Speculative worker-pool size.", "1"),
    _switch("VIZIER_SPECULATIVE_MAX_AGE_S", "float", "SpeculativeConfig",
            _SRV_DOC,
            "Staleness deadline: a parked batch older than this is never "
            "served.", "300.0"),
    _switch("VIZIER_SPECULATIVE_ON_FILL", "flag", "SpeculativeConfig",
            _SRV_DOC,
            "Also pre-compute after each live suggest (for a second "
            "client at the post-suggest frontier).", "0"),
    _switch("VIZIER_SPECULATIVE_COUNT_MEMORY", "int", "SpeculativeConfig",
            _SRV_DOC,
            "Distinct recent request counts remembered per study; jobs "
            "speculate the largest so bigger requests stop missing.", "4"),
    _switch("VIZIER_SPECULATIVE_DEBOUNCE_MS", "float", "SpeculativeConfig",
            _SRV_DOC,
            "Trigger debounce: a completion burst coalesces into one "
            "pre-compute after this quiet window (0 = immediate).", "0"),
    # -- surrogates (SurrogateConfig) --------------------------------------
    _switch("VIZIER_SPARSE", "flag", "SurrogateConfig", _PERF_DOC,
            "Sparse-GP surrogate auto-switch (off = exact GP always).", "1"),
    _switch("VIZIER_SPARSE_THRESHOLD", "int", "SurrogateConfig", _PERF_DOC,
            "Completed trials at which a study turns sparse.", "512"),
    _switch("VIZIER_SPARSE_HYSTERESIS", "int", "SurrogateConfig", _PERF_DOC,
            "Trial hysteresis before a sparse study returns to exact.", "64"),
    _switch("VIZIER_SPARSE_INDUCING", "int", "SurrogateConfig", _PERF_DOC,
            "Inducing-point budget m (padded to the trial bucket grid).",
            "128"),
    _switch("VIZIER_SPARSE_UCB_PE", "flag", "SurrogateConfig", _PERF_DOC,
            "Extend the sparse auto-switch to the UCB-PE DEFAULT "
            "(0 = UCB-PE studies stay exact at every size).", "1"),
    # -- mesh execution plane (parallel.mesh.MeshConfig) -------------------
    _switch("VIZIER_MESH", "flag", "MeshConfig", _PERF_DOC,
            "Mesh-sharded batch execution: carve devices into placements "
            "and dispatch buckets concurrently (opt-in; unset/0 = the "
            "bit-identical single-device executor).", "0"),
    _switch("VIZIER_MESH_DEVICES", "int", "MeshConfig", _PERF_DOC,
            "Devices the mesh plane may use (0 = all).", "0"),
    _switch("VIZIER_MESH_SHARD_DEVICES", "int", "MeshConfig", _PERF_DOC,
            "Devices per placement submesh; >1 shards each flush's study "
            "axis over the placement.", "1"),
    _switch("VIZIER_MESH_COORDINATOR", "str", "MeshConfig", _PERF_DOC,
            "jax.distributed coordinator address for a multi-host mesh "
            "('' = single host)."),
    _switch("VIZIER_MESH_PROCESSES", "int", "MeshConfig", _PERF_DOC,
            "Process count for the multi-host mesh (0 = auto).", "0"),
    _switch("VIZIER_MESH_PROCESS_ID", "int", "MeshConfig", _PERF_DOC,
            "This process's id in the multi-host mesh (-1 = auto).", "-1"),
    # -- loadgen traffic engine (loadgen.models.ScenarioConfig) ------------
    _switch("VIZIER_LOADGEN_SEED", "int", "ScenarioConfig", _LOAD_DOC,
            "Scenario seed: the whole workload expansion (arrivals, "
            "sizes, mixes, events) is a pure function of it.", "0"),
    _switch("VIZIER_LOADGEN_SCALE", "float", "ScenarioConfig", _LOAD_DOC,
            "Study-count multiplier for the configured scenario.", "1.0"),
    _switch("VIZIER_LOADGEN_STUDIES", "int", "ScenarioConfig", _LOAD_DOC,
            "Base study count before scaling.", "64"),
    _switch("VIZIER_LOADGEN_TARGET", "str", "ScenarioConfig", _LOAD_DOC,
            "Serving target the driver runs against: inprocess | replicas "
            "| subprocess (real replica_main processes).",
            "replicas"),
    _switch("VIZIER_LOADGEN_EVENTS", "str", "ScenarioConfig", _LOAD_DOC,
            "Scripted event track, kind[:arg]@fraction entries ('' = the "
            "scenario's built-in kill/revive + chaos track)."),
    # -- designers ---------------------------------------------------------
    _switch("VIZIER_DISABLE_MESH", "flag", "GPBanditDesigner", _SWITCH_DOC,
            "Opt out of the multi-device auto-mesh (set = disabled).", "0"),
    # -- bench.py (repo-root benchmark harness) ----------------------------
    _switch("VIZIER_BENCH_SCALE", "float", "bench.py", _PERF_DOC,
            "Global workload scale factor for bench.py.", "1.0"),
    _switch("VIZIER_BENCH_WATCHDOG_S", "float", "bench.py", _PERF_DOC,
            "bench.py watchdog timeout in seconds."),
    _switch("VIZIER_PEAK_FLOPS", "float", "bench.py", _PERF_DOC,
            "Hardware peak FLOP/s override for MFU accounting."),
    # -- reserved constants (NOT environment variables) --------------------
    _switch("VIZIER_METHODS", "constant", "service.grpc_stubs",
            "docs/guides/running_the_service.md",
            "gRPC method table constant in grpc_stubs; never an env var."),
    _switch("VIZIER_SERVICE_NAME", "constant", "service.grpc_stubs",
            "docs/guides/running_the_service.md",
            "gRPC service name constant in grpc_stubs; never an env var."),
)

BY_NAME: Dict[str, EnvSwitch] = {s.name: s for s in SWITCHES}
if len(BY_NAME) != len(SWITCHES):  # pragma: no cover - declaration bug
    raise RuntimeError("Duplicate VIZIER_* switch declaration.")


def declared(name: str) -> bool:
    return name in BY_NAME


def env_switch_names() -> Tuple[str, ...]:
    """Declared names that are real environment switches (not constants)."""
    return tuple(s.name for s in SWITCHES if s.kind != "constant")


def _require(name: str) -> EnvSwitch:
    switch = BY_NAME.get(name)
    if switch is None:
        raise KeyError(
            f"Undeclared environment switch {name!r}: declare it in "
            "vizier_tpu/analysis/registry.py (and document it) first."
        )
    if switch.kind == "constant":
        raise KeyError(
            f"{name!r} is a reserved constant, not an environment switch."
        )
    return switch


def env_on(name: str, default: Optional[str] = None) -> bool:
    """Boolean switch read: unset -> declared default; "0"/"false"/"" = off."""
    switch = _require(name)
    base = switch.default if default is None else default
    return os.environ.get(name, base) not in ("0", "false", "False", "")


def env_set(name: str) -> bool:
    """True when the switch is set to a truthy value (unset -> False).

    The read shape for opt-*out* flags like ``VIZIER_DISABLE_MESH`` whose
    absence means "feature on".
    """
    return env_on(name, default="0")


def env_int(name: str, default: int) -> int:
    _require(name)
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def env_float(name: str, default: float) -> float:
    _require(name)
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def env_str(name: str, default: str = "") -> str:
    _require(name)
    return os.environ.get(name, default)
