"""Baseline handling: intentional violations are explicit, new ones fail.

``baseline.toml`` is a checked-in list of findings (by stable key) that
are *accepted with a reason* — e.g. the per-study designer serialization
that deliberately holds one study's entry lock across device compute.
The suite subtracts baselined findings from each pass's output; anything
left fails the build, and baseline entries that no longer match anything
are reported as stale so the file cannot rot.

Python 3.10 has no ``tomllib``, and the analysis suite is stdlib-only by
contract, so this module carries a small reader for the TOML subset the
baseline and the ``[tool.vizier_analysis]`` pyproject section actually
use: top-level keys, ``[table]`` headers, ``[[array-of-table]]`` headers,
and string / integer / float / boolean / string-array values. Anything
fancier is a parse error, not a silent skip.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from vizier_tpu.analysis import common

_KEY_RE = re.compile(r"^[A-Za-z0-9_.-]+$")


class TomlSubsetError(ValueError):
    pass


def _parse_scalar(text: str, where: str) -> Any:
    text = text.strip()
    if text.startswith('"') and text.endswith('"') and len(text) >= 2:
        body = text[1:-1]
        return body.replace('\\"', '"').replace("\\\\", "\\")
    if text in ("true", "false"):
        return text == "true"
    if re.fullmatch(r"[+-]?\d+", text):
        return int(text)
    if re.fullmatch(r"[+-]?\d*\.\d+([eE][+-]?\d+)?", text):
        return float(text)
    raise TomlSubsetError(f"Unsupported TOML value {text!r} at {where}.")


def _split_array_items(body: str, where: str) -> List[str]:
    items: List[str] = []
    depth = 0
    in_str = False
    current = ""
    i = 0
    while i < len(body):
        ch = body[i]
        if in_str:
            current += ch
            if ch == '"' and (i == 0 or body[i - 1] != "\\"):
                in_str = False
        elif ch == '"':
            in_str = True
            current += ch
        elif ch == "[":
            depth += 1
            current += ch
        elif ch == "]":
            depth -= 1
            current += ch
        elif ch == "," and depth == 0:
            if current.strip():
                items.append(current.strip())
            current = ""
        else:
            current += ch
        i += 1
    if in_str:
        raise TomlSubsetError(f"Unterminated string in array at {where}.")
    if current.strip():
        items.append(current.strip())
    return items


def parse_toml_subset(text: str, source: str = "<toml>") -> Dict[str, Any]:
    """Parses the TOML subset documented in the module docstring.

    Array-of-table sections come back as lists of dicts; dotted table
    headers (``[tool.vizier_analysis]``) become nested dicts.
    """
    root: Dict[str, Any] = {}
    current: Dict[str, Any] = root
    pending: Optional[Tuple[str, str]] = None  # (key, accumulated) multiline

    def target_for(path: List[str], make_list_leaf: bool) -> Dict[str, Any]:
        node = root
        for part in path[:-1]:
            node = node.setdefault(part, {})
            if isinstance(node, list):
                node = node[-1]
            if not isinstance(node, dict):
                raise TomlSubsetError(
                    f"Conflicting table path {'.'.join(path)} in {source}."
                )
        leaf = path[-1]
        if make_list_leaf:
            arr = node.setdefault(leaf, [])
            if not isinstance(arr, list):
                raise TomlSubsetError(
                    f"{'.'.join(path)} is both a table and an array in {source}."
                )
            arr.append({})
            return arr[-1]
        sub = node.setdefault(leaf, {})
        if isinstance(sub, list):
            return sub[-1]
        if not isinstance(sub, dict):
            raise TomlSubsetError(
                f"Conflicting table path {'.'.join(path)} in {source}."
            )
        return sub

    for lineno, raw in enumerate(text.splitlines(), 1):
        where = f"{source}:{lineno}"
        line = raw.strip()
        if pending is not None:
            key, acc = pending
            acc += " " + line
            if acc.count("[") == acc.count("]") and not acc.rstrip().endswith(","):
                pending = None
                current[key] = _finish_value(acc, where)
            else:
                pending = (key, acc)
            continue
        if not line or line.startswith("#"):
            continue
        if line.startswith("[[") and line.endswith("]]"):
            path = [p.strip() for p in line[2:-2].split(".")]
            current = target_for(path, make_list_leaf=True)
            continue
        if line.startswith("[") and line.endswith("]"):
            path = [p.strip() for p in line[1:-1].split(".")]
            current = target_for(path, make_list_leaf=False)
            continue
        if "=" not in line:
            raise TomlSubsetError(f"Unparseable line at {where}: {raw!r}")
        key, _, value = line.partition("=")
        key = key.strip().strip('"')
        if not _KEY_RE.match(key):
            raise TomlSubsetError(f"Unsupported key {key!r} at {where}.")
        value = value.strip()
        # Strip trailing comments outside strings.
        value = _strip_comment(value)
        if value.startswith("[") and value.count("[") != value.count("]"):
            pending = (key, value)
            continue
        current[key] = _finish_value(value, where)
    if pending is not None:
        raise TomlSubsetError(f"Unterminated array for {pending[0]} in {source}.")
    return root


def _strip_comment(value: str) -> str:
    out = ""
    in_str = False
    for i, ch in enumerate(value):
        if ch == '"' and (i == 0 or value[i - 1] != "\\"):
            in_str = not in_str
        if ch == "#" and not in_str:
            break
        out += ch
    return out.strip()


def _finish_value(value: str, where: str) -> Any:
    value = value.strip()
    if value.startswith("[") and value.endswith("]"):
        return [
            _parse_scalar(item, where)
            for item in _split_array_items(value[1:-1], where)
        ]
    return _parse_scalar(value, where)


@dataclasses.dataclass(frozen=True)
class BaselineEntry:
    pass_name: str
    rule: str
    key: str
    reason: str


@dataclasses.dataclass
class Baseline:
    entries: List[BaselineEntry]
    source: str = ""

    def __post_init__(self):
        self._by_key = {(e.pass_name, e.key): e for e in self.entries}

    def match(self, finding: common.Finding) -> Optional[BaselineEntry]:
        return self._by_key.get((finding.pass_name, finding.key))

    def apply(
        self, findings: Sequence[common.Finding]
    ) -> Tuple[List[common.Finding], List[common.Finding], List[BaselineEntry]]:
        """(new, accepted, stale_entries) for one suite run's findings."""
        new: List[common.Finding] = []
        accepted: List[common.Finding] = []
        matched: Set[Tuple[str, str]] = set()
        for f in findings:
            entry = self.match(f)
            if entry is None:
                new.append(f)
            else:
                accepted.append(f)
                matched.add((entry.pass_name, entry.key))
        stale = [
            e for e in self.entries if (e.pass_name, e.key) not in matched
        ]
        return new, accepted, stale


def load_baseline(path: str) -> Baseline:
    try:
        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return Baseline(entries=[], source=path)
    data = parse_toml_subset(text, source=path)
    entries: List[BaselineEntry] = []
    for raw in data.get("finding", []):
        missing = {"pass", "key", "reason"} - set(raw)
        if missing:
            raise TomlSubsetError(
                f"Baseline entry in {path} is missing {sorted(missing)}: {raw}"
            )
        if not str(raw["reason"]).strip():
            raise TomlSubsetError(
                f"Baseline entry {raw['key']!r} in {path} has an empty "
                "reason; intentional exceptions must say why."
            )
        entries.append(
            BaselineEntry(
                pass_name=str(raw["pass"]),
                rule=str(raw.get("rule", "")),
                key=str(raw["key"]),
                reason=str(raw["reason"]),
            )
        )
    return Baseline(entries=entries, source=path)
