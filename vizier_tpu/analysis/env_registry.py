"""Pass 3: every ``VIZIER_*`` environment read must be declared.

The registry (:mod:`vizier_tpu.analysis.registry`) is the single source of
truth for the tree's environment switches. This pass AST-scans the
configured paths and fails on:

- ``undeclared-env-read`` — ``os.environ.get/[]/setdefault`` or
  ``os.getenv`` of a literal ``VIZIER_*`` name missing from the registry;
- ``environ-read-of-constant`` — an env read of a name declared as a
  reserved *constant* (``VIZIER_METHODS`` / ``VIZIER_SERVICE_NAME`` are
  gRPC tables, not switches);
- ``dynamic-env-read`` — an ``os.environ`` read whose name is not a string
  literal. Only :mod:`vizier_tpu.analysis.registry` itself may do this
  (its helpers validate names at runtime); ad-hoc ``_env_on(name)``
  helpers elsewhere hide reads from this scan and must go through the
  registry;
- ``undeclared-literal`` — any other ``VIZIER_*`` string literal not in
  the registry (catches reads routed through helpers and doc drift);
- ``undocumented-switch`` — a declared switch whose ``doc`` file is
  missing or never mentions the switch name;
- ``unreferenced-switch`` — a declared env switch no scanned file
  mentions (a stale declaration).
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, List, Optional, Set

from vizier_tpu.analysis import common
from vizier_tpu.analysis import registry

PASS_NAME = "env_registry"

_VIZIER_NAME = re.compile(r"^VIZIER_[A-Z0-9_]+$")

# The registry module itself reads the environment with validated
# non-literal names; that is the one sanctioned dynamic read site.
_DYNAMIC_READ_ALLOWED = ("analysis/registry.py",)


@dataclasses.dataclass
class EnvRegistryResult:
    findings: List[common.Finding]
    # literal VIZIER_* name -> paths referencing it (for coverage checks)
    references: Dict[str, Set[str]] = dataclasses.field(default_factory=dict)


def _env_read_name(call: ast.Call) -> Optional[ast.AST]:
    """The name expression of an env read call, or None if not one."""
    func = call.func
    dotted_name = common.dotted(func)
    if dotted_name in ("os.getenv",) and call.args:
        return call.args[0]
    if isinstance(func, ast.Attribute) and func.attr in ("get", "setdefault"):
        base = common.dotted(func.value)
        if base in ("os.environ", "environ") and call.args:
            return call.args[0]
    return None


def _environ_subscript(node: ast.Subscript) -> Optional[ast.AST]:
    base = common.dotted(node.value)
    if base in ("os.environ", "environ"):
        return node.slice
    return None


def run(
    project: common.Project,
    repo_root: str,
    check_registry_coverage: Optional[bool] = None,
) -> EnvRegistryResult:
    """Scans ``project`` for env-read violations.

    ``check_registry_coverage`` controls the registry-wide rules
    (undocumented-switch / unreferenced-switch); by default they run only
    when the scan actually includes the registry module — a partial scan
    (a fixtures directory, one subpackage) cannot judge whole-tree
    coverage.
    """
    if check_registry_coverage is None:
        check_registry_coverage = any(
            p.replace("\\", "/").endswith(_DYNAMIC_READ_ALLOWED[0])
            for p in project.trees
        )
    findings: List[common.Finding] = []
    references: Dict[str, Set[str]] = {}

    def check_read(name_node: ast.AST, path: str) -> None:
        if isinstance(name_node, ast.Constant) and isinstance(
            name_node.value, str
        ):
            name = name_node.value
            if not _VIZIER_NAME.match(name):
                return  # non-VIZIER env reads are out of scope
            switch = registry.BY_NAME.get(name)
            if switch is None:
                findings.append(
                    common.Finding(
                        pass_name=PASS_NAME,
                        rule="undeclared-env-read",
                        key=f"undeclared-env-read:{name}@{path}",
                        message=(
                            f"environment read of undeclared switch {name}; "
                            "declare it in vizier_tpu/analysis/registry.py"
                        ),
                        path=path,
                        line=name_node.lineno,
                    )
                )
            elif switch.kind == "constant":
                findings.append(
                    common.Finding(
                        pass_name=PASS_NAME,
                        rule="environ-read-of-constant",
                        key=f"environ-read-of-constant:{name}@{path}",
                        message=(
                            f"{name} is a reserved constant "
                            f"(owner {switch.owner}), not an environment "
                            "switch; reading it from os.environ is a bug"
                        ),
                        path=path,
                        line=name_node.lineno,
                    )
                )
            return
        # Non-literal name.
        norm = path.replace("\\", "/")
        if any(norm.endswith(suffix) for suffix in _DYNAMIC_READ_ALLOWED):
            return
        findings.append(
            common.Finding(
                pass_name=PASS_NAME,
                rule="dynamic-env-read",
                key=f"dynamic-env-read@{path}:{getattr(name_node, 'lineno', 0)}",
                message=(
                    "os.environ read with a non-literal name; route it "
                    "through vizier_tpu.analysis.registry helpers so the "
                    "switch is declared and validated"
                ),
                path=path,
                line=getattr(name_node, "lineno", 0),
            )
        )

    for path, tree in project.trees.items():
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                name_node = _env_read_name(node)
                if name_node is not None:
                    check_read(name_node, path)
            elif isinstance(node, ast.Subscript):
                name_node = _environ_subscript(node)
                if name_node is not None:
                    check_read(name_node, path)
            elif isinstance(node, ast.Constant) and isinstance(node.value, str):
                if _VIZIER_NAME.match(node.value):
                    references.setdefault(node.value, set()).add(path)
                    if node.value not in registry.BY_NAME:
                        findings.append(
                            common.Finding(
                                pass_name=PASS_NAME,
                                rule="undeclared-literal",
                                key=f"undeclared-literal:{node.value}@{path}",
                                message=(
                                    f"VIZIER_* literal {node.value!r} is not "
                                    "declared in the switch registry"
                                ),
                                path=path,
                                line=node.lineno,
                            )
                        )

    # Declared switches must be documented where they claim to be...
    for switch in registry.SWITCHES if check_registry_coverage else ():
        doc_path = os.path.join(repo_root, switch.doc)
        documented = False
        try:
            with open(doc_path, "r", encoding="utf-8") as f:
                documented = switch.name in f.read()
        except OSError:
            documented = False
        if not documented:
            findings.append(
                common.Finding(
                    pass_name=PASS_NAME,
                    rule="undocumented-switch",
                    key=f"undocumented-switch:{switch.name}",
                    message=(
                        f"declared switch {switch.name} is not mentioned in "
                        f"its doc file {switch.doc}"
                    ),
                    path="vizier_tpu/analysis/registry.py",
                    line=0,
                )
            )
        # ... and real env switches must actually be referenced somewhere
        # beyond their own registry declaration.
        outside_refs = {
            p
            for p in references.get(switch.name, ())
            if not p.replace("\\", "/").endswith(_DYNAMIC_READ_ALLOWED[0])
        }
        if switch.kind != "constant" and not outside_refs:
            findings.append(
                common.Finding(
                    pass_name=PASS_NAME,
                    rule="unreferenced-switch",
                    key=f"unreferenced-switch:{switch.name}",
                    message=(
                        f"declared switch {switch.name} is never referenced "
                        "by any scanned file (stale declaration?)"
                    ),
                    path="vizier_tpu/analysis/registry.py",
                    line=0,
                )
            )

    seen: Set[str] = set()
    unique: List[common.Finding] = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.key)):
        if f.key not in seen:
            seen.add(f.key)
            unique.append(f)
    return EnvRegistryResult(findings=unique, references=references)
