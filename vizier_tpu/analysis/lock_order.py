"""Pass 1: static lock-order analysis over the serving stack.

Three things are extracted from the AST, with no code imported or run:

1. **Lock sites** — every ``threading.Lock`` / ``RLock`` / ``Condition``
   construction, including factory forms like
   ``collections.defaultdict(threading.Lock)``. A site is identified by
   ``Class.attr`` (or ``module.NAME`` for module-level locks); the file
   and line are kept so :mod:`vizier_tpu.analysis.debug_locks` can join
   runtime-created locks back to static nodes.

2. **The acquisition graph** — an edge ``A -> B`` means B is (possibly)
   acquired while A is held. Direct ``with a: with b:`` nesting is exact;
   cross-module edges come from resolving calls made under a lock through
   :class:`~vizier_tpu.analysis.common.Project`'s type index and
   propagating each callee's transitive lock set to a fixpoint
   (e.g. ServingRuntime -> designer_cache -> coalescer, and the
   vizier_service study locks -> datastore locks).

3. **Hazards under critical locks** — the rule "no device compute,
   blocking RPC, or ``Condition.wait`` while holding a study/cache lock".
   Blocking markers are ``.wait()`` (except a condition waiting on
   itself), ``WaitForResponse``, ``time.sleep``, thread ``.join``,
   future ``.result``; device compute is any call that reaches a module
   under ``designers/ models/ optimizers/ ops/ parallel/`` or a
   duck-typed ``designer.*`` receiver; RPC is a duck-typed
   ``_pythia/stub/channel`` receiver or a ``grpc.*`` call.

Violations fail unless listed in ``baseline.toml`` with a reason — the
intentional per-study serialization (device compute under one study's
``CachedDesignerEntry.lock``) is the canonical baselined exception.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from vizier_tpu.analysis import common

PASS_NAME = "lock_order"

_LOCK_CTORS = {"Lock", "RLock", "Condition"}

# Locks whose critical sections must stay free of blocking work. Matched
# by site id; the list mirrors the serving stack's contention points.
DEFAULT_CRITICAL_LOCKS = (
    "VizierServicer._study_locks",
    "DesignerStateCache._lock",
    "CachedDesignerEntry.lock",
    "RequestCoalescer._lock",
    "grpc_stubs._CHANNEL_LOCK",
    # Sharded service tier (vizier_tpu.distributed): the router/WAL locks
    # sit UNDER the study locks on the hot path and must stay leaf-ward
    # (bookkeeping + local file I/O only — no RPC, no device compute).
    "StudyRouter._lock",
    "RoutedVizierStub._lock",
    "PersistentDataStore._lock",
    "ReplicaManager._lock",
)

# Any resolved call landing in these subtrees counts as device compute.
DEVICE_MODULE_PARTS = (
    "designers/",
    "models/",
    "optimizers/",
    "ops/",
    "parallel/",
)

# Receiver names that imply a hazard even when the call target cannot be
# resolved (duck-typed seams: the designer protocol, the Pythia endpoint).
# "channel" is deliberately absent: channel-object methods (subscribe,
# unary_unary, close) register/construct without network round-trips; real
# RPCs go through stubs.
DUCK_DEVICE_RECEIVERS = frozenset({"designer"})
DUCK_RPC_RECEIVERS = frozenset({"_pythia", "stub"})

_WAIT_METHODS = frozenset({"wait", "wait_for", "WaitForResponse"})

# grpc entry points that only CONSTRUCT objects (no network activity —
# channels connect lazily); calling these under a lock is not an RPC.
_NONBLOCKING_GRPC = frozenset(
    {
        "grpc.insecure_channel",
        "grpc.secure_channel",
        "grpc.server",
        "grpc.method_handlers_generic_handler",
        "grpc.unary_unary_rpc_method_handler",
    }
)


@dataclasses.dataclass(frozen=True)
class LockSite:
    lock_id: str  # "Class.attr" or "module.NAME"
    kind: str  # "Lock" | "RLock" | "Condition"
    path: str
    line: int
    factory: bool = False  # constructed via a factory (defaultdict etc.)


@dataclasses.dataclass(frozen=True)
class Edge:
    src: str
    dst: str
    via: str  # "path::qualname" of the function holding src
    line: int


@dataclasses.dataclass
class LockOrderResult:
    sites: List[LockSite]
    edges: List[Edge]
    findings: List[common.Finding]
    # functions whose calls could not be resolved while a lock was held
    unresolved_calls: int = 0

    def site_ids(self) -> Set[str]:
        return {s.lock_id for s in self.sites}

    def edge_pairs(self) -> Set[Tuple[str, str]]:
        return {(e.src, e.dst) for e in self.edges}


def _is_lock_ctor(node: ast.AST) -> Optional[str]:
    """'Lock'/'RLock'/'Condition' when node constructs one, else None."""
    if isinstance(node, ast.Call):
        tail = common._tail_name(node.func)
        if tail in _LOCK_CTORS:
            return tail
        # Factory forms: defaultdict(threading.Lock), partial(Condition).
        for arg in node.args:
            tail = common._tail_name(arg)
            if tail in _LOCK_CTORS:
                return tail
    return None


def find_lock_sites(project: common.Project) -> List[LockSite]:
    sites: Dict[str, LockSite] = {}

    def add(lock_id: str, kind: str, path: str, line: int, factory: bool):
        # First construction site wins; re-assignments (e.g. in reset
        # helpers) refer to the same logical lock.
        sites.setdefault(
            lock_id, LockSite(lock_id, kind, path, line, factory)
        )

    for path, tree in project.trees.items():
        module = _module_base(path)
        # Module-level locks.
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                kind = _is_lock_ctor(node.value)
                if kind and isinstance(node.targets[0], ast.Name):
                    factory = common._tail_name(node.value.func) not in _LOCK_CTORS
                    add(
                        f"{module}.{node.targets[0].id}",
                        kind,
                        path,
                        node.lineno,
                        factory,
                    )
        # self.attr locks anywhere inside class methods.
        for cls_name, cls in project.classes.items():
            if cls.path != path:
                continue
            for method in cls.methods.values():
                for node in ast.walk(method.node):
                    value = None
                    target = None
                    if isinstance(node, ast.Assign) and len(node.targets) == 1:
                        target, value = node.targets[0], node.value
                    elif isinstance(node, ast.AnnAssign) and node.value is not None:
                        target, value = node.target, node.value
                    if value is None:
                        continue
                    kind = _is_lock_ctor(value)
                    if not kind:
                        continue
                    attr = common.Project._self_attr(target)
                    if attr is not None:
                        factory = (
                            common._tail_name(value.func) not in _LOCK_CTORS
                        )
                        add(
                            f"{cls_name}.{attr}", kind, path, node.lineno, factory
                        )
    return sorted(sites.values(), key=lambda s: s.lock_id)


def _module_base(path: str) -> str:
    base = path.rsplit("/", 1)[-1]
    return base[:-3] if base.endswith(".py") else base


class _FunctionSummary:
    def __init__(self):
        # (held_tuple, lock_id, line): direct acquisitions with held context
        self.acquisitions: List[Tuple[Tuple[str, ...], str, int]] = []
        # (held_tuple, callee_qualnames, receiver_tail, attr_name, line)
        self.calls: List[
            Tuple[Tuple[str, ...], Tuple[str, ...], Optional[str], Optional[str], int]
        ] = []
        # Hazard tags triggered directly in this function body with no lock
        # requirement (used for transitive propagation).
        self.direct_tags: Set[str] = set()
        # (held_tuple, tag, detail, line) — hazards observed under a lock.
        self.held_hazards: List[Tuple[Tuple[str, ...], str, str, int]] = []
        self.unresolved_under_lock = 0


class LockOrderAnalyzer:
    def __init__(
        self,
        project: common.Project,
        critical_locks: Sequence[str] = DEFAULT_CRITICAL_LOCKS,
        duck_device: FrozenSet[str] = DUCK_DEVICE_RECEIVERS,
        duck_rpc: FrozenSet[str] = DUCK_RPC_RECEIVERS,
    ):
        self.project = project
        self.critical = set(critical_locks)
        self.duck_device = duck_device
        self.duck_rpc = duck_rpc
        self.sites = find_lock_sites(project)
        self._by_id = {s.lock_id: s for s in self.sites}
        self._by_attr: Dict[str, List[LockSite]] = {}
        for s in self.sites:
            self._by_attr.setdefault(s.lock_id.split(".", 1)[1], []).append(s)
        self.summaries: Dict[str, _FunctionSummary] = {}

    # -- lock expression resolution ----------------------------------------

    def _resolve_lock_expr(
        self,
        node: ast.AST,
        fn: common.FunctionInfo,
        local_types: Dict[str, str],
    ) -> Optional[str]:
        # `with self._study_locks[name]:` — the dict values are the locks.
        if isinstance(node, ast.Subscript):
            node = node.value
        if isinstance(node, ast.Attribute):
            attr = node.attr
            # self.attr: the enclosing class (or a base) owns the site.
            if isinstance(node.value, ast.Name) and node.value.id == "self":
                cls_name = fn.class_name
                while cls_name:
                    if f"{cls_name}.{attr}" in self._by_id:
                        return f"{cls_name}.{attr}"
                    cls = self.project.classes.get(cls_name)
                    cls_name = cls.bases[0] if cls and cls.bases else None
            # typed receiver
            owner = self.project._expr_class(
                node.value,
                local_types,
                self.project.classes.get(fn.class_name) if fn.class_name else None,
            )
            if owner and f"{owner}.{attr}" in self._by_id:
                return f"{owner}.{attr}"
            # unique attribute name across all sites
            candidates = self._by_attr.get(attr, [])
            if len(candidates) == 1:
                return candidates[0].lock_id
            return None
        if isinstance(node, ast.Name):
            lock_id = f"{_module_base(fn.path)}.{node.id}"
            if lock_id in self._by_id:
                return lock_id
            candidates = self._by_attr.get(node.id, [])
            if len(candidates) == 1:
                return candidates[0].lock_id
        return None

    # -- per-function walk ---------------------------------------------------

    def _summarize(self, fn: common.FunctionInfo) -> _FunctionSummary:
        summary = _FunctionSummary()
        local_types = self.project.local_types(fn)
        nested: List[ast.AST] = []

        def visit(node: ast.AST, held: Tuple[str, ...]):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                # Nested defs run later (threads, callbacks): analyzed as
                # their own functions with an empty held stack.
                nested.append(node)
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                new_held = held
                for item in node.items:
                    visit(item.context_expr, held)
                    lock_id = self._resolve_lock_expr(
                        item.context_expr, fn, local_types
                    )
                    if lock_id is not None:
                        summary.acquisitions.append(
                            (new_held, lock_id, node.lineno)
                        )
                        new_held = new_held + (lock_id,)
                for child in node.body:
                    visit(child, new_held)
                return
            if isinstance(node, ast.Call):
                self._record_call(node, fn, local_types, held, summary)
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in fn.node.body:
            visit(stmt, ())
        # Nested defs: separate summaries folded into this pass run.
        for i, sub in enumerate(nested):
            if isinstance(sub, ast.Lambda):
                continue
            sub_fn = common.FunctionInfo(
                qualname=f"{fn.qualname}.<{sub.name}>",
                name=sub.name,
                node=sub,
                path=fn.path,
                class_name=fn.class_name,
            )
            self.summaries[sub_fn.qualname] = self._summarize(sub_fn)
        return summary

    def _record_call(
        self,
        call: ast.Call,
        fn: common.FunctionInfo,
        local_types: Dict[str, str],
        held: Tuple[str, ...],
        summary: _FunctionSummary,
    ) -> None:
        func = call.func
        attr = func.attr if isinstance(func, ast.Attribute) else None
        receiver_tail = (
            common._tail_name(func.value)
            if isinstance(func, ast.Attribute)
            else None
        )
        dotted_name = common.dotted(func)

        # Direct blocking markers.
        tag: Optional[str] = None
        detail = dotted_name or attr or "?"
        if attr in _WAIT_METHODS:
            # A condition waiting on itself releases the lock: exempt.
            waited = self._resolve_lock_expr(func.value, fn, local_types)
            if not (waited is not None and waited in held):
                tag = "wait"
        elif dotted_name == "time.sleep":
            tag = "wait"
        elif attr == "join" and receiver_tail and "thread" in receiver_tail.lower():
            tag = "wait"
        elif attr == "result" and receiver_tail and "future" in receiver_tail.lower():
            tag = "wait"
        elif attr in ("block_until_ready", "device_get"):
            tag = "device_compute"
        elif receiver_tail in self.duck_rpc or (
            dotted_name
            and dotted_name.startswith("grpc.")
            and dotted_name not in _NONBLOCKING_GRPC
        ):
            tag = "rpc"
        elif receiver_tail in self.duck_device and attr is not None:
            tag = "device_compute"
        if tag is not None:
            summary.direct_tags.add(tag)
            if held:
                summary.held_hazards.append((held, tag, detail, call.lineno))

        # Resolved project callees (for transitive locks/hazards).
        callees = self.project.resolve_call(call, fn, local_types)
        if callees:
            summary.calls.append(
                (
                    held,
                    tuple(c.qualname for c in callees),
                    receiver_tail,
                    attr,
                    call.lineno,
                )
            )
        elif held and isinstance(func, ast.Attribute) and tag is None:
            summary.unresolved_under_lock += 1

    # -- fixpoint propagation -----------------------------------------------

    def run(self) -> LockOrderResult:
        for qualname, fn in list(self.project.functions.items()):
            self.summaries[qualname] = self._summarize(fn)

        # Transitive lock sets and hazard tags per function.
        locks_t: Dict[str, Set[str]] = {}
        tags_t: Dict[str, Set[str]] = {}
        for qualname, summary in self.summaries.items():
            locks_t[qualname] = {a[1] for a in summary.acquisitions}
            tags_t[qualname] = set(summary.direct_tags)
        changed = True
        iterations = 0
        while changed and iterations < 50:
            changed = False
            iterations += 1
            for qualname, summary in self.summaries.items():
                for _, callees, _, _, _ in summary.calls:
                    for callee in callees:
                        if callee == qualname:
                            continue
                        extra_locks = locks_t.get(callee, set()) - locks_t[qualname]
                        if extra_locks:
                            locks_t[qualname] |= extra_locks
                            changed = True
                        callee_tags = set(tags_t.get(callee, set()))
                        if self._is_device_fn(callee):
                            callee_tags.add("device_compute")
                        extra_tags = callee_tags - tags_t[qualname]
                        if extra_tags:
                            tags_t[qualname] |= extra_tags
                            changed = True

        edges: Dict[Tuple[str, str], Edge] = {}
        findings: List[common.Finding] = []
        unresolved = 0

        for qualname, summary in self.summaries.items():
            fn_path = qualname.split("::", 1)[0]
            unresolved += summary.unresolved_under_lock
            for held, lock_id, line in summary.acquisitions:
                for src in held:
                    if src != lock_id:
                        edges.setdefault(
                            (src, lock_id), Edge(src, lock_id, qualname, line)
                        )
            for held, callees, _, _, line in summary.calls:
                if not held:
                    continue
                for callee in callees:
                    for dst in locks_t.get(callee, ()):
                        for src in held:
                            if src != dst:
                                edges.setdefault(
                                    (src, dst), Edge(src, dst, qualname, line)
                                )
                    callee_tags = set(tags_t.get(callee, set()))
                    if self._is_device_fn(callee):
                        callee_tags.add("device_compute")
                    for tag in sorted(callee_tags):
                        self._hazard_findings(
                            findings, held, tag, f"call to {callee}", qualname,
                            fn_path, line,
                        )
            for held, tag, detail, line in summary.held_hazards:
                self._hazard_findings(
                    findings, held, tag, detail, qualname, fn_path, line
                )

        findings.extend(self._cycle_findings(list(edges.values())))
        # De-duplicate by key, keep first occurrence (stable order).
        seen: Set[str] = set()
        unique: List[common.Finding] = []
        for f in sorted(findings, key=lambda f: (f.path, f.line, f.key)):
            if f.key not in seen:
                seen.add(f.key)
                unique.append(f)
        return LockOrderResult(
            sites=self.sites,
            edges=sorted(edges.values(), key=lambda e: (e.src, e.dst)),
            findings=unique,
            unresolved_calls=unresolved,
        )

    def _is_device_fn(self, qualname: str) -> bool:
        path = qualname.split("::", 1)[0].replace("\\", "/")
        return any(part in path for part in DEVICE_MODULE_PARTS)

    def _hazard_findings(
        self,
        findings: List[common.Finding],
        held: Tuple[str, ...],
        tag: str,
        detail: str,
        qualname: str,
        path: str,
        line: int,
    ) -> None:
        fn_name = qualname.split("::", 1)[1]
        for lock_id in held:
            if lock_id not in self.critical:
                continue
            findings.append(
                common.Finding(
                    pass_name=PASS_NAME,
                    rule="hazard-under-critical-lock",
                    key=f"{lock_id}->{tag}@{path}::{fn_name}",
                    message=(
                        f"{tag} ({detail}) while holding critical lock "
                        f"{lock_id} in {fn_name}"
                    ),
                    path=path,
                    line=line,
                )
            )

    def _cycle_findings(self, edges: List[Edge]) -> List[common.Finding]:
        graph: Dict[str, Set[str]] = {}
        for e in edges:
            graph.setdefault(e.src, set()).add(e.dst)
        # Tarjan SCC: any SCC with >1 node (or a self-loop) is a cycle.
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        counter = [0]
        sccs: List[List[str]] = []

        def strongconnect(v: str):
            # Iterative Tarjan to stay safe on deep graphs.
            work = [(v, iter(sorted(graph.get(v, ()))))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack.add(w)
                        work.append((w, iter(sorted(graph.get(w, ())))))
                        advanced = True
                        break
                    elif w in on_stack:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    scc = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        scc.append(w)
                        if w == node:
                            break
                    sccs.append(scc)

        for v in sorted(graph):
            if v not in index:
                strongconnect(v)

        findings = []
        edge_set = {(e.src, e.dst) for e in edges}
        by_pair = {(e.src, e.dst): e for e in edges}
        for scc in sccs:
            is_cycle = len(scc) > 1 or (scc[0], scc[0]) in edge_set
            if not is_cycle:
                continue
            nodes = sorted(scc)
            witness = next(
                (by_pair[(a, b)] for a in nodes for b in nodes
                 if (a, b) in by_pair),
                None,
            )
            findings.append(
                common.Finding(
                    pass_name=PASS_NAME,
                    rule="lock-cycle",
                    key="cycle:" + "->".join(nodes),
                    message=(
                        "lock acquisition cycle between "
                        + ", ".join(nodes)
                        + (f" (e.g. via {witness.via})" if witness else "")
                    ),
                    path=witness.via.split("::", 1)[0] if witness else "",
                    line=witness.line if witness else 0,
                )
            )
        return findings


def run(
    project: common.Project,
    critical_locks: Sequence[str] = DEFAULT_CRITICAL_LOCKS,
) -> LockOrderResult:
    return LockOrderAnalyzer(project, critical_locks=critical_locks).run()
