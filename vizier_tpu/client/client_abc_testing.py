"""Client-ABC conformance suite.

Parity with ``/root/reference/vizier/client/client_abc_testing.py:48``: a
behavioral test mixin any ``StudyInterface`` implementation (this OSS
service, a cloud client, an in-RAM fake) must pass. Subclasses implement
``create_study(problem, study_id)``.
"""

from __future__ import annotations

import abc
from typing import TypeVar

from vizier_tpu import pyvizier as vz
from vizier_tpu.client import client_abc

_S = TypeVar("_S", bound=client_abc.StudyInterface)


class StudyConformance(abc.ABC):
    """Mixin of behavioral tests over the StudyInterface contract."""

    @abc.abstractmethod
    def create_study(self, problem: vz.ProblemStatement, study_id: str) -> _S:
        ...

    def _problem(self) -> vz.ProblemStatement:
        problem = vz.ProblemStatement()
        problem.search_space.root.add_float_param("x", 0.0, 1.0)
        problem.search_space.root.add_categorical_param("c", ["a", "b"])
        problem.metric_information.append(
            vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
        )
        return problem

    # -- suggest / complete --------------------------------------------------

    def test_suggest_returns_count(self):
        study = self.create_study(self._problem(), "conf-suggest")
        trials = study.suggest(count=3)
        assert len(trials) == 3
        assert all(t.status == vz.TrialStatus.ACTIVE for t in trials)

    def test_complete_and_materialize(self):
        study = self.create_study(self._problem(), "conf-complete")
        (trial,) = study.suggest(count=1)
        final = trial.complete(vz.Measurement(metrics={"obj": 0.7}))
        assert final.metrics["obj"].value == 0.7
        materialized = trial.materialize()
        assert materialized.status == vz.TrialStatus.COMPLETED

    def test_parameters_external_types(self):
        study = self.create_study(self._problem(), "conf-params")
        (trial,) = study.suggest(count=1)
        params = trial.parameters
        assert isinstance(params["x"], float)
        assert params["c"] in ("a", "b")

    def test_infeasible_completion(self):
        study = self.create_study(self._problem(), "conf-infeasible")
        (trial,) = study.suggest(count=1)
        trial.complete(infeasible_reason="broke")
        assert trial.materialize().infeasible

    def test_intermediate_measurements(self):
        study = self.create_study(self._problem(), "conf-measure")
        (trial,) = study.suggest(count=1)
        trial.add_measurement(vz.Measurement(metrics={"obj": 0.1}, steps=1))
        trial.add_measurement(vz.Measurement(metrics={"obj": 0.2}, steps=2))
        assert len(trial.materialize().measurements) == 2

    # -- listing / filtering -------------------------------------------------

    def test_trials_listing_and_filter(self):
        study = self.create_study(self._problem(), "conf-list")
        a, b = study.suggest(count=2)
        a.complete(vz.Measurement(metrics={"obj": 1.0}))
        completed = list(study.trials(vz.TrialFilter(status=[vz.TrialStatus.COMPLETED])))
        assert [t.id for t in completed] == [a.id]
        assert len(list(study.trials())) == 2

    def test_get_trial_and_missing(self):
        study = self.create_study(self._problem(), "conf-get")
        (trial,) = study.suggest(count=1)
        assert study.get_trial(trial.id).id == trial.id
        try:
            study.get_trial(424242)
        except client_abc.ResourceNotFoundError:
            pass
        else:  # pragma: no cover
            raise AssertionError("Expected ResourceNotFoundError.")

    def test_optimal_trials(self):
        study = self.create_study(self._problem(), "conf-optimal")
        values = [0.2, 0.9, 0.5]
        for trial, v in zip(study.suggest(count=3), values):
            trial.complete(vz.Measurement(metrics={"obj": v}))
        (best,) = study.optimal_trials()
        assert best.materialize().final_measurement.metrics["obj"].value == 0.9

    # -- study-level ----------------------------------------------------------

    def test_materialize_study_config(self):
        study = self.create_study(self._problem(), "conf-config")
        config = study.materialize_study_config()
        assert set(config.search_space.parameter_names()) == {"x", "c"}

    def test_metadata_roundtrip(self):
        study = self.create_study(self._problem(), "conf-md")
        md = vz.Metadata()
        md.ns("user")["note"] = "hello"
        study.update_metadata(md)
        assert study.materialize_study_config().metadata.ns("user")["note"] == "hello"

    def test_delete_trial(self):
        study = self.create_study(self._problem(), "conf-del")
        a, b = study.suggest(count=2)
        a.delete()
        assert [t.id for t in study.trials()] == [b.id]

    # -- worker semantics (reference `test_suggest_*`) -----------------------

    def test_suggest_same_worker_reuses_active_trials(self):
        """A crashed worker re-requesting suggestions gets its trials back."""
        study = self.create_study(self._problem(), "conf-worker-same")
        first = study.suggest(count=2, client_id="w1")
        again = study.suggest(count=2, client_id="w1")
        assert sorted(t.id for t in first) == sorted(t.id for t in again)

    def test_suggest_different_workers_get_distinct_trials(self):
        study = self.create_study(self._problem(), "conf-worker-diff")
        a = study.suggest(count=2, client_id="w1")
        b = study.suggest(count=2, client_id="w2")
        assert not set(t.id for t in a) & set(t.id for t in b)

    def test_completed_worker_gets_fresh_trials(self):
        study = self.create_study(self._problem(), "conf-worker-fresh")
        (t1,) = study.suggest(count=1, client_id="w1")
        t1.complete(vz.Measurement(metrics={"obj": 0.5}))
        (t2,) = study.suggest(count=1, client_id="w1")
        assert t2.id != t1.id

    # -- completion semantics ------------------------------------------------

    def test_complete_no_measurements_is_infeasible(self):
        study = self.create_study(self._problem(), "conf-complete-empty")
        (trial,) = study.suggest(count=1)
        trial.complete()
        assert trial.materialize().infeasible

    def test_complete_auto_selects_last_measurement(self):
        study = self.create_study(self._problem(), "conf-complete-auto")
        (trial,) = study.suggest(count=1)
        trial.add_measurement(vz.Measurement(metrics={"obj": 0.1}, steps=1))
        trial.add_measurement(vz.Measurement(metrics={"obj": 0.8}, steps=2))
        trial.complete()
        final = trial.materialize().final_measurement
        assert final.metrics["obj"].value == 0.8

    def test_measurement_after_completion_fails(self):
        study = self.create_study(self._problem(), "conf-complete-immutable")
        (trial,) = study.suggest(count=1)
        trial.complete(vz.Measurement(metrics={"obj": 0.4}))
        try:
            trial.add_measurement(vz.Measurement(metrics={"obj": 0.5}))
        except Exception:
            pass
        else:  # pragma: no cover
            raise AssertionError("Completed trials must be immutable.")

    def test_double_complete_fails(self):
        study = self.create_study(self._problem(), "conf-complete-twice")
        (trial,) = study.suggest(count=1)
        trial.complete(vz.Measurement(metrics={"obj": 0.4}))
        try:
            trial.complete(vz.Measurement(metrics={"obj": 0.9}))
        except Exception:
            pass
        else:  # pragma: no cover
            raise AssertionError("Second complete() must fail.")

    # -- early stopping ------------------------------------------------------

    def test_stop_trial(self):
        study = self.create_study(self._problem(), "conf-stop")
        (trial,) = study.suggest(count=1)
        trial.stop()
        assert trial.materialize().status == vz.TrialStatus.STOPPING

    def test_check_early_stopping_returns_bool(self):
        study = self.create_study(self._problem(), "conf-earlystop")
        (trial,) = study.suggest(count=1)
        assert isinstance(trial.check_early_stopping(), bool)

    # -- study lifecycle -----------------------------------------------------

    def test_optimal_trials_on_empty_study(self):
        study = self.create_study(self._problem(), "conf-optimal-empty")
        assert len(list(study.optimal_trials())) == 0

    def test_trials_iter_and_get_are_equal(self):
        study = self.create_study(self._problem(), "conf-iter-get")
        study.suggest(count=3)
        for listed in study.trials():
            direct = study.get_trial(listed.id)
            assert direct.id == listed.id
            assert direct.parameters == listed.parameters

    def test_set_state_aborts_study(self):
        study = self.create_study(self._problem(), "conf-state")
        study.set_state(vz.StudyState.ABORTED)
        config_or_state = study.materialize_state()
        assert config_or_state == vz.StudyState.ABORTED

    def test_delete_study(self):
        study = self.create_study(self._problem(), "conf-delete-study")
        study.suggest(count=1)
        study.delete()
        try:
            study.get_trial(1)
        except Exception:
            pass
        else:  # pragma: no cover
            raise AssertionError("Deleted study must not serve trials.")

    def test_trial_update_metadata(self):
        study = self.create_study(self._problem(), "conf-trial-md")
        (trial,) = study.suggest(count=1)
        md = vz.Metadata()
        md.ns("worker")["note"] = "t1"
        trial.update_metadata(md)
        assert trial.materialize().metadata.ns("worker")["note"] == "t1"
