"""Client-ABC conformance suite.

Parity with ``/root/reference/vizier/client/client_abc_testing.py:48``: a
behavioral test mixin any ``StudyInterface`` implementation (this OSS
service, a cloud client, an in-RAM fake) must pass. Subclasses implement
``create_study(problem, study_id)``.
"""

from __future__ import annotations

import abc
from typing import TypeVar

from vizier_tpu import pyvizier as vz
from vizier_tpu.client import client_abc

_S = TypeVar("_S", bound=client_abc.StudyInterface)


class StudyConformance(abc.ABC):
    """Mixin of behavioral tests over the StudyInterface contract."""

    @abc.abstractmethod
    def create_study(self, problem: vz.ProblemStatement, study_id: str) -> _S:
        ...

    def _problem(self) -> vz.ProblemStatement:
        problem = vz.ProblemStatement()
        problem.search_space.root.add_float_param("x", 0.0, 1.0)
        problem.search_space.root.add_categorical_param("c", ["a", "b"])
        problem.metric_information.append(
            vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
        )
        return problem

    # -- suggest / complete --------------------------------------------------

    def test_suggest_returns_count(self):
        study = self.create_study(self._problem(), "conf-suggest")
        trials = study.suggest(count=3)
        assert len(trials) == 3
        assert all(t.status == vz.TrialStatus.ACTIVE for t in trials)

    def test_complete_and_materialize(self):
        study = self.create_study(self._problem(), "conf-complete")
        (trial,) = study.suggest(count=1)
        final = trial.complete(vz.Measurement(metrics={"obj": 0.7}))
        assert final.metrics["obj"].value == 0.7
        materialized = trial.materialize()
        assert materialized.status == vz.TrialStatus.COMPLETED

    def test_parameters_external_types(self):
        study = self.create_study(self._problem(), "conf-params")
        (trial,) = study.suggest(count=1)
        params = trial.parameters
        assert isinstance(params["x"], float)
        assert params["c"] in ("a", "b")

    def test_infeasible_completion(self):
        study = self.create_study(self._problem(), "conf-infeasible")
        (trial,) = study.suggest(count=1)
        trial.complete(infeasible_reason="broke")
        assert trial.materialize().infeasible

    def test_intermediate_measurements(self):
        study = self.create_study(self._problem(), "conf-measure")
        (trial,) = study.suggest(count=1)
        trial.add_measurement(vz.Measurement(metrics={"obj": 0.1}, steps=1))
        trial.add_measurement(vz.Measurement(metrics={"obj": 0.2}, steps=2))
        assert len(trial.materialize().measurements) == 2

    # -- listing / filtering -------------------------------------------------

    def test_trials_listing_and_filter(self):
        study = self.create_study(self._problem(), "conf-list")
        a, b = study.suggest(count=2)
        a.complete(vz.Measurement(metrics={"obj": 1.0}))
        completed = list(study.trials(vz.TrialFilter(status=[vz.TrialStatus.COMPLETED])))
        assert [t.id for t in completed] == [a.id]
        assert len(list(study.trials())) == 2

    def test_get_trial_and_missing(self):
        study = self.create_study(self._problem(), "conf-get")
        (trial,) = study.suggest(count=1)
        assert study.get_trial(trial.id).id == trial.id
        try:
            study.get_trial(424242)
        except client_abc.ResourceNotFoundError:
            pass
        else:  # pragma: no cover
            raise AssertionError("Expected ResourceNotFoundError.")

    def test_optimal_trials(self):
        study = self.create_study(self._problem(), "conf-optimal")
        values = [0.2, 0.9, 0.5]
        for trial, v in zip(study.suggest(count=3), values):
            trial.complete(vz.Measurement(metrics={"obj": v}))
        (best,) = study.optimal_trials()
        assert best.materialize().final_measurement.metrics["obj"].value == 0.9

    # -- study-level ----------------------------------------------------------

    def test_materialize_study_config(self):
        study = self.create_study(self._problem(), "conf-config")
        config = study.materialize_study_config()
        assert set(config.search_space.parameter_names()) == {"x", "c"}

    def test_metadata_roundtrip(self):
        study = self.create_study(self._problem(), "conf-md")
        md = vz.Metadata()
        md.ns("user")["note"] = "hello"
        study.update_metadata(md)
        assert study.materialize_study_config().metadata.ns("user")["note"] == "hello"

    def test_delete_trial(self):
        study = self.create_study(self._problem(), "conf-del")
        a, b = study.suggest(count=2)
        a.delete()
        assert [t.id for t in study.trials()] == [b.id]
