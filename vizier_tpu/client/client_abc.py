"""Platform-independent client interface contracts.

Parity with ``/root/reference/vizier/client/client_abc.py:47,169,191``: any
Vizier backend (this OSS service, a cloud service, an in-RAM fake) exposes
the same ``StudyInterface``/``TrialInterface`` so user code is portable.
"""

from __future__ import annotations

import abc
from typing import Any, Collection, Iterator, List, Optional, Union

from vizier_tpu import pyvizier as vz


class ResourceNotFoundError(KeyError):
    """The referenced study/trial does not exist."""


class TrialInterface(abc.ABC):
    """A handle to one trial on the service."""

    @property
    @abc.abstractmethod
    def id(self) -> int:
        ...

    @property
    @abc.abstractmethod
    def parameters(self) -> dict:
        """User-facing parameter values (external types applied)."""

    @abc.abstractmethod
    def add_measurement(self, measurement: vz.Measurement) -> None:
        ...

    @abc.abstractmethod
    def complete(
        self,
        measurement: Optional[vz.Measurement] = None,
        *,
        infeasible_reason: Optional[str] = None,
    ) -> Optional[vz.Measurement]:
        """Completes the trial; returns the final measurement."""

    @abc.abstractmethod
    def check_early_stopping(self) -> bool:
        """True if the service wants this trial to stop."""

    @abc.abstractmethod
    def stop(self) -> None:
        ...

    @abc.abstractmethod
    def delete(self) -> None:
        ...

    @abc.abstractmethod
    def materialize(self) -> vz.Trial:
        """Fetches the full current trial state."""

    @abc.abstractmethod
    def update_metadata(self, delta: vz.Metadata) -> None:
        ...

    @property
    @abc.abstractmethod
    def status(self) -> vz.TrialStatus:
        ...


class StudyInterface(abc.ABC):
    """A handle to one study on the service."""

    @property
    @abc.abstractmethod
    def resource_name(self) -> str:
        ...

    @abc.abstractmethod
    def suggest(
        self, *, count: Optional[int] = None, client_id: str = "default_client_id"
    ) -> List[TrialInterface]:
        ...

    @abc.abstractmethod
    def delete(self) -> None:
        ...

    @abc.abstractmethod
    def trials(
        self, trial_filter: Optional[vz.TrialFilter] = None
    ) -> Collection[TrialInterface]:
        ...

    @abc.abstractmethod
    def get_trial(self, uid: int) -> TrialInterface:
        ...

    @abc.abstractmethod
    def optimal_trials(self, count: Optional[int] = None) -> Collection[TrialInterface]:
        ...

    @abc.abstractmethod
    def materialize_study_config(self) -> vz.StudyConfig:
        ...

    @abc.abstractmethod
    def set_state(self, state: vz.StudyState) -> None:
        ...

    @abc.abstractmethod
    def update_metadata(self, delta: vz.Metadata) -> None:
        ...
