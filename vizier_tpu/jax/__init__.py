"""Public JAX-layer facade.

Parity with the reference facade ``vizier/jax`` (re-exporting the numerical
core: models, optimizers, padded types). Absolute imports keep the name
``vizier_tpu.jax`` from shadowing the real ``jax`` package.
"""

from vizier_tpu.models.gp import (
    EnsemblePredictive,
    GPData,
    GPState,
    VizierGaussianProcess,
)
from vizier_tpu.models.kernels import MixedFeatures, matern52_ard
from vizier_tpu.models.multitask_gp import MultiTaskGaussianProcess, MultiTaskType
from vizier_tpu.models.output_warpers import create_default_warper
from vizier_tpu.models.params import ParameterCollection, ParameterSpec, SoftClip
from vizier_tpu.models.stacked_residual import (
    StackedResidualGP,
    train_stacked_residual_gp,
)
from vizier_tpu.optimizers.lbfgs import (
    DEFAULT_RANDOM_RESTARTS,
    AdamOptimizer,
    LbfgsOptimizer,
    Optimizer,
    default_optimizer,
)
from vizier_tpu.types import ContinuousAndCategorical, ModelData, ModelInput, PaddedArray
