"""VizierGPBandit: the flagship TPU-native GP Bayesian-optimization designer.

Parity with ``/root/reference/vizier/_src/algorithms/designers/gp_bandit.py:88``
("The Vizier GP Bandit Algorithm", arXiv:2408.11527), rebuilt TPU-first:

- quasi-random (+default-point) seeding for the first trials;
- output warping (half-rank → z-score → infeasible imputation);
- ARD via multi-restart pure-JAX L-BFGS — one jitted program, restarts
  vmapped (shardable over the mesh);
- hyperparameter *ensembles* (top-k restarts) combined as a uniform mixture;
- UCB/EI acquisition with an L∞ trust region;
- acquisition maximized by the vectorized Eagle strategy inside a jitted
  ``fori_loop`` (75k evaluations per suggest, no host round-trips).

Padding keeps jit caches stable as the study grows (``converters.padding``);
every model-side op is mask-safe.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from vizier_tpu import types
from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.compute import ir as compute_ir
from vizier_tpu.compute import registry as compute_registry
from vizier_tpu.converters import core as converters
from vizier_tpu.converters import padding as padding_lib
from vizier_tpu.designers import quasi_random
from vizier_tpu.designers.gp import acquisitions
from vizier_tpu.models import gp as gp_lib
from vizier_tpu.models import kernels
from vizier_tpu.models import output_warpers
from vizier_tpu.models import params as params_lib
from vizier_tpu.optimizers import eagle as eagle_lib
from vizier_tpu.optimizers import lbfgs as lbfgs_lib
from vizier_tpu.observability import jax_timing
from vizier_tpu.optimizers import vectorized as vectorized_lib
from vizier_tpu.surrogates import config as surrogate_config_lib
from vizier_tpu.surrogates import sparse_bandit
from vizier_tpu.surrogates import sparse_gp
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import trial as trial_
from vizier_tpu.utils import profiler

Array = jax.Array


def _as_prng_key(rng) -> Array:
    """Coerces the Predictor contract's rng (numpy Generator | PRNGKey |
    None) into a jax PRNGKey."""
    if rng is None:
        return jax.random.PRNGKey(0)
    if isinstance(rng, np.random.Generator):
        return jax.random.PRNGKey(int(rng.integers(0, 2**31 - 1)))
    return rng


@functools.partial(
    jax.jit, static_argnames=("model", "optimizer", "num_restarts", "ensemble_size")
)
def _train_gp(
    model: gp_lib.VizierGaussianProcess,
    optimizer: lbfgs_lib.LbfgsOptimizer,
    data: gp_lib.GPData,
    rng: Array,
    num_restarts: int,
    ensemble_size: int,
    warm_start: Optional[gp_lib.Params] = None,
) -> gp_lib.GPState:
    """ARD: restarts → L-BFGS (vmapped) → top-k precomputed posteriors.

    ``warm_start`` (previous suggest's best unconstrained params) is
    prepended as an EXTRA restart row — steady-state hyperparameters move
    little between suggests, so that row usually lands at the optimum
    immediately, while the random restarts keep their full exploration
    budget. (It used to *replace* restart 0; losing one random init
    measurably regressed small-budget mixed-space convergence — see
    PARITY.md "Warm-start ARD seeding".)
    """
    coll = model.param_collection()
    inits = coll.batch_random_init_unconstrained(rng, num_restarts)
    if warm_start is not None:
        inits = jax.tree_util.tree_map(
            lambda batch, warm: jnp.concatenate([warm[None], batch], axis=0),
            inits,
            warm_start,
        )
    loss_fn = lambda p: model.neg_log_likelihood(p, data)
    result = optimizer(loss_fn, inits, best_n=ensemble_size)
    return jax.vmap(lambda p: model.precompute(p, data))(result.params)


@functools.partial(jax.jit, static_argnames=("vec_opt", "count"))
def _maximize_acquisition(
    vec_opt: vectorized_lib.VectorizedOptimizer,
    scoring: acquisitions.ScoringFunction,
    rng: Array,
    count: int,
    prior_features: kernels.MixedFeatures,
) -> vectorized_lib.VectorizedOptimizerResult:
    return vec_opt(scoring.score, rng, count=count, prior_features=prior_features)


def _prior_features_from_data(data: gp_lib.GPData) -> kernels.MixedFeatures:
    """Top observed points (by warped label) to seed the eagle pool.

    Traceable (used both eagerly by the sequential path and under vmap by
    the multi-study batched path): k is a function of the *padded* row
    count so shapes stay stable within a padding bucket.
    """
    labels = jnp.where(data.row_mask, data.labels, -jnp.inf)
    k = min(10, data.num_rows)
    _, idx = jax.lax.top_k(labels, k)
    num_valid = jnp.sum(data.row_mask)
    idx = jnp.where(jnp.arange(k) < num_valid, idx, idx[0])
    return kernels.MixedFeatures(data.continuous[idx], data.categorical[idx])


# -- cross-study batched programs (vizier_tpu.parallel.batch_executor) ------
#
# The padding schedule makes concurrent studies shape-identical by
# construction, so the per-study jitted programs above vmap cleanly over a
# leading study axis: N same-bucket studies per device dispatch instead of
# N dispatches. Inputs are stacked pytrees (``batch_executor.stack_pytrees``)
# with per-study PRNG keys; the inner computation is the SAME program the
# sequential path runs, so slot i of a batch matches study i run alone.


@functools.partial(
    jax.jit, static_argnames=("model", "optimizer", "num_restarts", "ensemble_size")
)
def train_batched(
    model: gp_lib.VizierGaussianProcess,
    optimizer: lbfgs_lib.LbfgsOptimizer,
    data: gp_lib.GPData,  # leading study axis [B, ...]
    rng: Array,  # [B] per-study keys
    num_restarts: int,
    ensemble_size: int,
    warm_start: Optional[gp_lib.Params] = None,  # leading axis [B]
) -> gp_lib.GPState:
    """Multi-study ARD: one device program vmapping :func:`_train_gp`."""
    if warm_start is None:
        return jax.vmap(
            lambda d, k: _train_gp(
                model, optimizer, d, k, num_restarts, ensemble_size
            )
        )(data, rng)
    return jax.vmap(
        lambda d, k, w: _train_gp(
            model, optimizer, d, k, num_restarts, ensemble_size, w
        )
    )(data, rng, warm_start)


def _sweep_one(vec_opt, acquisition, s, d, k, count, use_trust_region):
    """Per-study scoring + eagle sweep (trace-shared by the batched entry
    points below; identical math to the sequential suggest)."""
    best_label = jnp.max(jnp.where(d.row_mask, d.labels, -jnp.inf))
    trust = acquisitions.TrustRegion.from_data(d) if use_trust_region else None
    scoring = acquisitions.ScoringFunction(
        predictive=gp_lib.EnsemblePredictive(s),
        acquisition=acquisition,
        best_label=best_label,
        trust_region=trust,
    )
    return _maximize_acquisition(
        vec_opt, scoring, k, count, _prior_features_from_data(d)
    )


@functools.partial(
    jax.jit, static_argnames=("vec_opt", "acquisition", "count", "use_trust_region")
)
def suggest_batched(
    vec_opt: vectorized_lib.VectorizedOptimizer,
    acquisition,  # hashable Acquisition instance (UCB/EI/...), jit-static
    states: gp_lib.GPState,  # leading study axis [B, E, ...]
    data: gp_lib.GPData,  # leading study axis [B, ...]
    rng: Array,  # [B] per-study keys
    count: int,
    use_trust_region: bool = True,
) -> vectorized_lib.VectorizedOptimizerResult:
    """Multi-study acquisition sweep: one device program, one eagle pool
    per study slot, vmapping the sequential scoring + sweep."""
    return jax.vmap(
        lambda s, d, k: _sweep_one(
            vec_opt, acquisition, s, d, k, count, use_trust_region
        )
    )(states, data, rng)


@jax.jit
def _to_gp_data_batched(md: types.ModelData) -> gp_lib.GPData:
    """Stacked host ModelData → batched device GPData, inside ONE program.

    The eager per-study ``GPData.from_model_data`` costs ~6 dispatches per
    study; done here the whole batch pays one transfer + one fused program.
    """
    return jax.vmap(lambda m: gp_lib.GPData.from_model_data(m))(md)


def _warm_next_batched(model: gp_lib.VizierGaussianProcess, states) -> gp_lib.Params:
    """Per-slot warm seed for the NEXT train: best member's params mapped
    back through the bijectors — the sequential writeback, traced + vmapped."""
    coll = model.param_collection()
    return jax.vmap(
        lambda p: coll.unconstrain(jax.tree_util.tree_map(lambda a: a[0], p))
    )(states.params)


@functools.partial(
    jax.jit,
    static_argnames=(
        "model", "optimizer", "vec_opt", "acquisition",
        "num_restarts", "ensemble_size", "count", "use_trust_region",
    ),
)
def _gp_bandit_flush_program(
    model: gp_lib.VizierGaussianProcess,
    optimizer: lbfgs_lib.LbfgsOptimizer,
    vec_opt: vectorized_lib.VectorizedOptimizer,
    acquisition,
    md: types.ModelData,  # stacked host ModelData, leading study axis
    rng_train: Array,  # [B]
    rng_acq: Array,  # [B]
    warm: gp_lib.Params,  # [B]
    num_restarts: int,
    ensemble_size: int,
    count: int,
    use_trust_region: bool,
):
    """ONE device program per bucket flush: encode→train→sweep→warm seed.

    Fusing the stages keeps the whole flush a single XLA dispatch — the
    per-program launch + host-sync overhead that dominates N-small-program
    serving happens once per BATCH instead of ~3·N times.
    """
    data = jax.vmap(lambda m: gp_lib.GPData.from_model_data(m))(md)
    states = jax.vmap(
        lambda d, k, w: _train_gp(
            model, optimizer, d, k, num_restarts, ensemble_size, w
        )
    )(data, rng_train, warm)
    result = jax.vmap(
        lambda s, d, k: _sweep_one(
            vec_opt, acquisition, s, d, k, count, use_trust_region
        )
    )(states, data, rng_acq)
    return states, _warm_next_batched(model, states), result


@functools.partial(
    jax.jit, static_argnames=("model", "optimizer", "num_restarts")
)
def _train_gp_per_metric(
    model: gp_lib.VizierGaussianProcess,
    optimizer: lbfgs_lib.Optimizer,
    batched_data: gp_lib.GPData,  # leading axis M on labels/masks/features
    rng: Array,
    num_restarts: int,
) -> gp_lib.GPState:
    """One independently-trained GP per objective metric (vmapped)."""
    coll = model.param_collection()

    def train_one(data: gp_lib.GPData, key: Array) -> gp_lib.GPState:
        inits = coll.batch_random_init_unconstrained(key, num_restarts)
        loss_fn = lambda p: model.neg_log_likelihood(p, data)
        result = optimizer(loss_fn, inits)
        return model.precompute(result.params, data)

    m = batched_data.labels.shape[0]
    keys = jax.random.split(rng, m)
    return jax.vmap(train_one)(batched_data, keys)


@dataclasses.dataclass
class VizierGPBandit(core_lib.Designer, core_lib.Predictor):
    """GP-UCB/EI designer over flat (non-conditional) search spaces."""

    problem: base_study_config.ProblemStatement
    acquisition: str = "ucb"  # 'ucb' | 'ei' | 'pi' | 'pe'
    ucb_coefficient: float = 1.8
    num_seed_trials: int = 2
    ard_restarts: int = lbfgs_lib.DEFAULT_RANDOM_RESTARTS
    ensemble_size: int = 1
    max_acquisition_evaluations: int = 75_000
    use_trust_region: bool = True
    # HEBO-style learnable Kumaraswamy input warping (non-stationary
    # objectives); see models.gp.VizierGaussianProcess.use_input_warping.
    use_input_warping: bool = False
    padding: Optional[padding_lib.PaddingSchedule] = None
    metric_index: int = 0
    rng_seed: int = 0
    # Injectable ARD optimizer (tests swap in a cheaper one; must be hashable).
    ard_optimizer: Optional[lbfgs_lib.Optimizer] = None
    # Carry the previous suggest's trained params into the next train as
    # an extra restart seed. False restores the reference's per-request
    # cold train (trained params are discarded between suggests).
    use_warm_start_ard: bool = True
    # Completed trials required before warm seeding ENGAGES. Early in a
    # study the NLL landscape is nearly flat, and a previously trained seed
    # keeps winning the restart selection — a self-reinforcing mode lock-in
    # that measurably regressed 40-trial mixed-space convergence (see
    # PARITY.md "Warm-start ARD seeding"). Below the floor every train is
    # cold (full random restarts); steady-state serving, where the warm
    # latency win lives, sits far above it.
    warm_start_min_trials: int = 20
    # Restart budget for a WARM train (one with trained seed params). None
    # keeps the full ``ard_restarts`` budget; the serving runtime sets 1 so
    # steady-state suggests pay one early-exiting L-BFGS run instead of
    # ``ard_restarts`` full cold starts (A/B: WARM_START_AB.json).
    warm_ard_restarts: Optional[int] = None
    # Multi-chip data plane: None = auto (build a mesh over all devices when
    # more than one exists and route ARD restarts + acquisition pools through
    # vizier_tpu.parallel); True/False force it on/off.
    use_mesh: Optional[bool] = None
    # Scalable-surrogate auto-switch (vizier_tpu.surrogates): above the
    # config's trial threshold the single-objective suggest path trains an
    # SGPR sparse posterior (O(n·m²)) instead of the exact GP (O(n³)), with
    # hysteresis at the boundary. None (and SurrogateConfig(sparse=False))
    # keep the exact path everywhere — bit-identical to the seed. The
    # serving runtime threads its process-wide config in here.
    surrogate: Optional[surrogate_config_lib.SurrogateConfig] = None

    def __post_init__(self):
        if self.problem.search_space.is_conditional:
            raise ValueError("VizierGPBandit requires a flat search space.")
        if self.problem.search_space.is_empty():
            raise ValueError("Empty search space.")
        self._converter = converters.TrialToModelInputConverter.from_problem(
            self.problem, padding=self.padding
        )
        enc = self._converter.encoder
        self._model = gp_lib.VizierGaussianProcess(
            num_continuous=enc.num_continuous,
            num_categorical=enc.num_categorical,
            use_input_warping=self.use_input_warping,
        )
        self._ard = self.ard_optimizer or lbfgs_lib.LbfgsOptimizer()
        # The acquisition optimizer works in the (possibly feature-padded)
        # model space so its candidates match the GP kernel's shapes; padded
        # dims are masked out of the kernel and sliced off at decode time.
        pad = self._converter.padding
        self._cont_width = pad.pad_features(enc.num_continuous)
        self._cat_width = pad.pad_features(enc.num_categorical)
        cat_sizes = tuple(enc.category_sizes) + (1,) * (
            self._cat_width - enc.num_categorical
        )
        strategy = eagle_lib.VectorizedEagleStrategy(
            num_continuous=self._cont_width,
            category_sizes=cat_sizes,
        )
        self._vec_opt = vectorized_lib.VectorizedOptimizer(
            strategy, max_evaluations=self.max_acquisition_evaluations
        )
        self._warper = output_warpers.create_default_warper()
        self._seeder = quasi_random.QuasiRandomDesigner(
            self.problem.search_space, seed=self.rng_seed
        )
        self._trials: List[trial_.Trial] = []
        self._warper_fitted = False
        self._rng = jax.random.PRNGKey(self.rng_seed)
        self._last_predictive: Optional[gp_lib.EnsemblePredictive] = None
        # Production multi-chip path (SURVEY §2.10): when more than one
        # device is visible, suggest() shards ARD restarts and acquisition
        # pools over a mesh automatically — a user calling suggest() on a
        # v5e-8 gets all 8 chips of work without any configuration.
        self._mesh = None
        if self.use_mesh is not None:
            want_mesh = self.use_mesh
        else:
            # VIZIER_DISABLE_MESH opts out of the auto-mesh (the CPU test
            # suite sets it: 8 *virtual* host devices share the same cores,
            # so pool-sharding only multiplies work there). Read through
            # the central switch registry; env_set also fixes the old raw
            # read treating "0" as set-and-therefore-disabled.
            from vizier_tpu.analysis import registry as _registry

            want_mesh = len(jax.devices()) > 1 and not _registry.env_set(
                "VIZIER_DISABLE_MESH"
            )
        if want_mesh:
            from vizier_tpu import parallel

            self._mesh = parallel.create_mesh()
        # Seed the warm start with a random init so _train_gp's pytree
        # structure never changes across suggests (None -> dict would force
        # a full recompile of the ARD program on the second call).
        self._warm_params = self._model.param_collection().random_init_unconstrained(
            jax.random.PRNGKey(self.rng_seed + 1)
        )
        # True once _warm_params holds genuinely TRAINED params (vs the
        # random placeholder above) — gates the reduced warm restart budget
        # and the warm/cold accounting below.
        self._warm_is_trained = False
        self._ard_train_counts = {"warm": 0, "cold": 0}
        # Sparse-surrogate auto-switch state (vizier_tpu.surrogates): the
        # mode is sticky (hysteresis) and a crossover drops all warm/
        # posterior state so neither surrogate ever trains from the
        # other's optimum (see _refresh_surrogate_mode).
        self._surrogate_mode = surrogate_config_lib.MODE_EXACT
        self._sparse_model_cache: Optional[sparse_gp.SparseGaussianProcess] = None
        self._last_sparse_state: Optional[sparse_gp.SparseGPState] = None
        self._surrogate_counts = {"sparse_suggests": 0, "crossovers": 0}

    # -- Designer ----------------------------------------------------------

    def update(
        self,
        completed: core_lib.CompletedTrials,
        all_active: core_lib.ActiveTrials = core_lib.ActiveTrials(),
    ) -> None:
        del all_active
        self._trials.extend(completed.trials)

    # -- mesh-aware compute (the ONE production train/sweep implementation) --

    def _mesh_size(self) -> int:
        return len(self._mesh.devices.flat) if self._mesh is not None else 1

    def _train(
        self,
        data: gp_lib.GPData,
        rng: Array,
        ensemble_size: int,
        warm_start: Optional[gp_lib.Params] = None,
        num_restarts: Optional[int] = None,
    ) -> gp_lib.GPState:
        """ARD train; restarts shard over the mesh when one is present.

        ``num_restarts`` overrides ``self.ard_restarts`` (the warm-started
        steady-state path trains with ``warm_ard_restarts``); it is floored
        at ``ensemble_size`` so the top-k ensemble selection stays valid.
        """
        restarts = max(num_restarts or self.ard_restarts, ensemble_size)
        if self._mesh is None:
            return _train_gp(
                self._model, self._ard, data, rng,
                restarts, ensemble_size, warm_start,
            )
        from vizier_tpu import parallel

        ndev = self._mesh_size()
        restarts = -(-restarts // ndev) * ndev  # ceil to mesh multiple
        return parallel.train_gp_sharded(
            self._model, self._ard, data, rng,
            restarts, ensemble_size, self._mesh, warm_start,
        )

    def _warm_update_allowed(self) -> bool:
        """Whether this train's optimum may seed the next one (floor met)."""
        return (
            self.use_warm_start_ard
            and len(self._trials) >= self.warm_start_min_trials
        )

    def _warm_restart_budget(self) -> Optional[int]:
        """Restart override for the NEXT train: set only when a trained warm
        seed exists and a reduced warm budget is configured."""
        if (
            self.use_warm_start_ard
            and self._warm_is_trained
            and self.warm_ard_restarts is not None
        ):
            return self.warm_ard_restarts
        return None

    def _record_train(self) -> None:
        self._ard_train_counts[
            "warm" if (self.use_warm_start_ard and self._warm_is_trained) else "cold"
        ] += 1

    # -- serving warm-start surface (vizier_tpu.serving) --------------------

    def warm_start_state(self) -> Optional[gp_lib.Params]:
        """Last trained unconstrained ARD params (None before first train)."""
        return self._warm_params if self._warm_is_trained else None

    def set_warm_start_state(self, params: gp_lib.Params) -> None:
        """Injects trained unconstrained params as the next restart seed 0."""
        self._warm_params = params
        self._warm_is_trained = True

    @property
    def ard_train_counts(self) -> dict:
        """Copies of the warm/cold ARD train counters (serving stats)."""
        return dict(self._ard_train_counts)

    # -- scalable-surrogate auto-switch (vizier_tpu.surrogates) -------------

    @property
    def surrogate_mode(self) -> str:
        """The active surrogate mode ("exact" | "sparse")."""
        return self._surrogate_mode

    @property
    def surrogate_counts(self) -> dict:
        """Copies of the sparse-suggest / crossover counters (serving stats)."""
        return dict(self._surrogate_counts)

    def sparse_inducing_state(self) -> Optional[sparse_gp.SparseGPState]:
        """The last trained sparse posterior (inducing set + factorization);
        None on the exact path or before the first sparse train."""
        return self._last_sparse_state

    def _sparse_model(self) -> sparse_gp.SparseGaussianProcess:
        if self._sparse_model_cache is None:
            # m rides the SAME bucket grid as trial counts so every
            # (n-bucket, m-bucket) pair is one compiled program family.
            m_pad = self._converter.padding.pad_trials(
                self.surrogate.num_inducing
            )
            self._sparse_model_cache = sparse_gp.SparseGaussianProcess(
                base=self._model, num_inducing=m_pad
            )
        return self._sparse_model_cache

    def _refresh_surrogate_mode(self) -> str:
        """Applies the auto-switch for the current trial count.

        A crossover (either direction) drops every piece of cross-surrogate
        state: the warm ARD seed is re-randomized (a fresh placeholder keeps
        the train program's pytree structure stable) and the cached
        posterior cleared, so stale exact-GP params can never seed — or be
        served from — the sparse posterior, and vice versa. The next train
        after a crossover is therefore a full-budget cold train.
        """
        cfg = self.surrogate
        if cfg is None:
            return self._surrogate_mode
        mode = cfg.mode_for(len(self._trials), current=self._surrogate_mode)
        if mode != self._surrogate_mode:
            old_mode = self._surrogate_mode
            self._surrogate_mode = mode
            self._surrogate_counts["crossovers"] += 1
            # Serving-tier observers (speculative pre-compute) invalidate
            # their derived state the moment the flip happens.
            surrogate_config_lib.fire_crossover_hook(self, old_mode, mode)
            self._warm_params = (
                self._model.param_collection().random_init_unconstrained(
                    jax.random.PRNGKey(
                        self.rng_seed + 1 + self._surrogate_counts["crossovers"]
                    )
                )
            )
            self._warm_is_trained = False
            self._last_predictive = None
            self._last_sparse_state = None
        return mode

    def _suggest_sparse(self, count: int) -> List[trial_.TrialSuggestion]:
        """The sparse twin of the single-objective suggest: SGPR collapsed-
        bound train (k-center inducing selection inside the program) + the
        same UCB/EI + trust-region eagle sweep over the sparse posterior.
        Consumes the RNG stream in the exact order of the exact path (train
        key, then acquisition key)."""
        with profiler.timeit("convert_trials"):
            data = gp_lib.GPData.from_model_data(self._warped_model_data())
        model = self._sparse_model()
        restarts = max(
            self._warm_restart_budget() or self.ard_restarts, self.ensemble_size
        )
        with profiler.timeit("train_gp"):
            with jax_timing.device_phase("sparse_gp.train") as phase:
                states = sparse_bandit._train_sparse_gp(
                    model,
                    self._ard,
                    data,
                    self._next_rng(),
                    restarts,
                    self.ensemble_size,
                    self._warm_params,
                )
                phase.block(states)
        self._record_train()
        if self._warm_update_allowed():
            coll = self._model.param_collection()
            self._warm_params = coll.unconstrain(
                jax.tree_util.tree_map(lambda a: a[0], states.params)
            )
            self._warm_is_trained = True
        predictive = sparse_gp.SparseEnsemblePredictive(states)
        self._last_predictive = predictive
        self._last_sparse_state = states
        best_label = jnp.max(jnp.where(data.row_mask, data.labels, -jnp.inf))
        trust = (
            acquisitions.TrustRegion.from_data(data)
            if self.use_trust_region
            else None
        )
        scoring = acquisitions.ScoringFunction(
            predictive=predictive,
            acquisition=self._make_acquisition(),
            best_label=best_label,
            trust_region=trust,
        )
        prior = self._prior_features(data)
        with profiler.timeit("acquisition_optimizer"):
            with jax_timing.device_phase("sparse_gp.acquisition") as phase:
                result = sparse_bandit._maximize_sparse_acquisition(
                    self._vec_opt, scoring, self._next_rng(), count, prior
                )
                jax.block_until_ready(result.scores)
                phase.block(result)
        self._surrogate_counts["sparse_suggests"] += 1
        with profiler.timeit("best_candidates_to_trials"):
            return self._decode_result(
                result, count, kind=f"{self.acquisition}+sparse"
            )

    # -- cross-study batch protocol (vizier_tpu.compute IR) -----------------
    #
    # The real implementations live in the registered DesignerProgram
    # classes at the bottom of this module (GPBanditProgram /
    # GPBanditSparseProgram); these thin methods keep the legacy duck-typed
    # surface working for callers that talk to the designer directly
    # (tests, chaos wrappers, subclass overrides).

    def _batch_restarts(self) -> int:
        """The jit-static restart budget the next train would use (mirrors
        ``_train``'s floor-at-ensemble rule)."""
        return max(
            self._warm_restart_budget() or self.ard_restarts, self.ensemble_size
        )

    def _active_batch_program(self):
        """The compute-IR program the current surrogate mode routes to."""
        from vizier_tpu.compute import registry as compute_registry

        kind = (
            "gp_bandit_sparse"
            if self._surrogate_mode == surrogate_config_lib.MODE_SPARSE
            else "gp_bandit"
        )
        return compute_registry.get(kind)

    def batch_bucket_key(self, count: Optional[int] = None):
        """Shape-bucket identity for cross-study batching, or None.

        None marks the paths the batched programs do not cover (seeding,
        multi-objective, transfer priors, joint qEI, mesh-sharded): those
        run the ordinary sequential suggest.
        """
        from vizier_tpu.compute import registry as compute_registry

        resolved = compute_registry.resolve(self, count)
        return resolved[1] if resolved is not None else None

    def batch_prepare(self, count: Optional[int] = None) -> dict:
        """Host-side half of a batched suggest (see the program classes)."""
        return self._active_batch_program().prepare(self, count or 1)

    @classmethod
    def batch_execute(
        cls,
        items: Sequence[dict],
        pad_to: Optional[int] = None,
        placement: Optional[Any] = None,
    ):
        """Device half: dispatched to the bucket's registered program
        (slot 0's item says which — the bucket key guarantees agreement)."""
        from vizier_tpu.compute import registry as compute_registry

        kind = "gp_bandit_sparse" if items[0].get("sparse") else "gp_bandit"
        return compute_registry.get(kind).device_program(
            items, pad_to=pad_to, placement=placement
        )

    def batch_finalize(self, item: dict, output: dict) -> List[trial_.TrialSuggestion]:
        """Host-side demux (see the program classes)."""
        from vizier_tpu.compute import registry as compute_registry

        kind = "gp_bandit_sparse" if output.get("sparse") else "gp_bandit"
        return compute_registry.get(kind).finalize(self, item, output)

    def _maximize(
        self,
        scoring,
        rng: Array,
        count: int,
        prior_features: kernels.MixedFeatures,
    ) -> vectorized_lib.VectorizedOptimizerResult:
        """Acquisition sweep; one independent eagle pool per device."""
        if self._mesh is None:
            return _maximize_acquisition(
                self._vec_opt, scoring, rng, count, prior_features
            )
        from vizier_tpu import parallel

        return parallel.maximize_acquisition_sharded(
            self._vec_opt, scoring, rng, count,
            self._mesh_size(), self._mesh, prior_features,
        )

    def _next_rng(self) -> Array:
        self._rng, out = jax.random.split(self._rng)
        return out

    def _padded_features(
        self, trials: Sequence[trial_.Trial], extra_rows: int = 0
    ) -> tuple:
        """(ModelInput, n_pad): the ONE encode+pad implementation.

        ``extra_rows`` reserves additional padded capacity (e.g. for batch
        fantasy conditioning in GP-UCB-PE).
        """
        conv = self._converter
        n_pad = conv.padding.pad_trials(len(trials) + extra_rows)
        cont, cat = conv.encoder.encode(trials)
        features = types.ContinuousAndCategorical(
            continuous=types.PaddedArray.from_array(
                cont.astype(np.float32),
                (n_pad, conv.padding.pad_features(conv.encoder.num_continuous)),
            ),
            categorical=types.PaddedArray.from_array(
                cat.astype(np.int32),
                (n_pad, conv.padding.pad_features(conv.encoder.num_categorical)),
                fill_value=0,
            ),
        )
        return features, n_pad

    @staticmethod
    def _padded_labels(warped: np.ndarray, n_pad: int) -> types.PaddedArray:
        """The ONE warped-label padding implementation."""
        return types.PaddedArray.from_array(
            warped[:, None].astype(np.float32), (n_pad, 1), fill_value=np.nan
        )

    def _warped_model_data(self, extra_rows: int = 0) -> types.ModelData:
        """Encode + warp labels + pad. Labels leave here all-MAXIMIZE ~N(0,1)."""
        conv = self._converter
        raw_labels = conv.metrics.encode(self._trials)  # [N, M], NaN infeasible
        warped = self._warper(raw_labels[:, self.metric_index])
        self._warper_fitted = raw_labels.shape[0] > 0
        features, n_pad = self._padded_features(self._trials, extra_rows)
        return types.ModelData(
            features=features, labels=self._padded_labels(warped, n_pad)
        )

    def set_priors(self, prior_trials: Sequence[Sequence[trial_.Trial]]) -> None:
        """Registers prior-study trials for stacked-residual transfer learning.

        Parity with ``gp_bandit.py:289`` (``set_priors``): each sequence is
        one prior study (oldest first); priors must share the search space.
        """
        self._priors = [list(p) for p in prior_trials]

    def _num_objectives(self) -> int:
        return sum(
            1 for m in self.problem.metric_information if not m.is_safety_metric
        )

    def suggest(self, count: Optional[int] = None) -> List[trial_.TrialSuggestion]:
        count = count or 1
        n = len(self._trials)
        if n < self.num_seed_trials:
            return self._seed_suggestions(count)
        if self._num_objectives() > 1:
            return self._suggest_multiobjective(count)
        if getattr(self, "_priors", None):
            return self._suggest_with_priors(count)
        if (
            self._refresh_surrogate_mode() == surrogate_config_lib.MODE_SPARSE
            # Joint qEI optimizes the whole batch through predict_joint,
            # which the collapsed sparse posterior does not expose — q-batch
            # qEI studies stay exact rather than silently degrading to
            # independent EI picks.
            and not (self.acquisition == "qei" and count > 1)
        ):
            return self._suggest_sparse(count)

        with profiler.timeit("convert_trials"):
            data = gp_lib.GPData.from_model_data(self._warped_model_data())
        with profiler.timeit("train_gp"):
            # Device-phase timing: block the trained states INSIDE the span
            # so async dispatch cannot shift ARD device time onto whatever
            # later op first synchronizes; the first call per process is
            # recorded as compile, the rest as steady-state execute.
            with jax_timing.device_phase("gp_bandit.train_gp") as phase:
                states = self._train(
                    data,
                    self._next_rng(),
                    self.ensemble_size,
                    self._warm_params,
                    num_restarts=self._warm_restart_budget(),
                )
                phase.block(states)
        self._record_train()
        if self._warm_update_allowed():
            # Warm-start the next suggest from this one's best member
            # (states.params are constrained; map back through the bijectors).
            coll = self._model.param_collection()
            self._warm_params = coll.unconstrain(
                jax.tree_util.tree_map(lambda a: a[0], states.params)
            )
            self._warm_is_trained = True
        predictive = gp_lib.EnsemblePredictive(states)
        self._last_predictive = predictive

        best_label = jnp.max(jnp.where(data.row_mask, data.labels, -jnp.inf))
        trust = (
            acquisitions.TrustRegion.from_data(data) if self.use_trust_region else None
        )
        if self.acquisition == "qei" and count > 1:
            if self._converter.encoder.num_categorical:
                raise ValueError(
                    "acquisition='qei' joint batches support continuous spaces "
                    "only; use VizierGPUCBPEBandit for batch suggestions on "
                    "mixed continuous/categorical spaces."
                )
            # Joint q-batch: optimize the whole batch as one point in
            # (q*Dc)-space under Monte-Carlo qEI.
            strategy = eagle_lib.VectorizedEagleStrategy(
                num_continuous=self._cont_width * count, category_sizes=()
            )
            vec = vectorized_lib.VectorizedOptimizer(
                strategy, max_evaluations=self.max_acquisition_evaluations
            )
            result = _maximize_q_batch(
                vec,
                states,
                best_label,
                trust,
                self._next_rng(),
                count,
                16,
                self._prior_features(data),
                mesh=self._mesh,
            )
            rows = jnp.asarray(result.features.continuous[0]).reshape(
                count, self._cont_width
            )
            unrolled = vectorized_lib.VectorizedOptimizerResult(
                kernels.MixedFeatures(rows, jnp.zeros((count, 0), jnp.int32)),
                jnp.full((count,), result.scores[0]),
            )
            return self._decode_result(unrolled, count, kind="qei_joint")
        acq = self._make_acquisition()
        scoring = acquisitions.ScoringFunction(
            predictive=predictive,
            acquisition=acq,
            best_label=best_label,
            trust_region=trust,
        )
        prior = self._prior_features(data)
        with profiler.timeit("acquisition_optimizer"):
            with jax_timing.device_phase("gp_bandit.acquisition") as phase:
                result = self._maximize(scoring, self._next_rng(), count, prior)
                jax.block_until_ready(result.scores)
                phase.block(result)
        with profiler.timeit("best_candidates_to_trials"):
            return self._decode_result(result, count, kind=self.acquisition)

    def _decode_result(
        self, result: vectorized_lib.VectorizedOptimizerResult, count: int, *, kind: str
    ) -> List[trial_.TrialSuggestion]:
        # One batched device->host fetch (separate np.asarray calls are one
        # blocking round trip each — costly on tunneled TPU links).
        cont, cat, scores = jax.device_get(
            (result.features.continuous, result.features.categorical, result.scores)
        )
        cont, cat, scores = cont[:count], cat[:count], scores[:count]
        suggestions = []
        for row_cont, row_cat, score in zip(cont, cat, scores):
            params = self._converter.to_parameters(
                row_cont[None, : self._converter.encoder.num_continuous],
                row_cat[None, : self._converter.encoder.num_categorical],
            )[0]
            s = trial_.TrialSuggestion(parameters=params)
            s.metadata.ns("gp_bandit")["acquisition"] = float(score)
            s.metadata.ns("gp_bandit")["acquisition_kind"] = kind
            suggestions.append(s)
        return suggestions

    # -- transfer learning -------------------------------------------------

    def _data_for_trials(self, trials: Sequence[trial_.Trial]) -> gp_lib.GPData:
        """Encodes an arbitrary trial set with this designer's converter."""
        conv = self._converter
        raw = conv.metrics.encode(trials)
        warped = self._warper(raw[:, self.metric_index])
        self._warper_fitted = raw.shape[0] > 0
        features, n_pad = self._padded_features(trials)
        return gp_lib.GPData.from_model_data(
            types.ModelData(features, self._padded_labels(warped, n_pad))
        )

    def _suggest_with_priors(self, count: int) -> List[trial_.TrialSuggestion]:
        from vizier_tpu.models import stacked_residual

        with profiler.timeit("convert_trials"):
            datasets = [self._data_for_trials(p) for p in self._priors]
            data = gp_lib.GPData.from_model_data(self._warped_model_data())
            datasets.append(data)
        with profiler.timeit("train_gp"):
            stack = stacked_residual.train_stacked_residual_gp(
                self._model,
                self._ard,
                datasets,
                self._next_rng(),
                num_restarts=self.ard_restarts,
            )
        # Stacked-residual training has no warm-start path (priors retrain
        # the whole stack); it always counts as a cold train.
        self._ard_train_counts["cold"] += 1
        self._last_predictive = stack  # duck-typed .predict
        best_label = jnp.max(jnp.where(data.row_mask, data.labels, -jnp.inf))
        scoring = acquisitions.ScoringFunction(
            predictive=stack,
            acquisition=self._make_acquisition(),
            best_label=best_label,
            trust_region=(
                acquisitions.TrustRegion.from_data(data)
                if self.use_trust_region
                else None
            ),
        )
        with profiler.timeit("acquisition_optimizer"):
            result = self._maximize(
                scoring, self._next_rng(), count, self._prior_features(data)
            )
            jax.block_until_ready(result.scores)
        with profiler.timeit("best_candidates_to_trials"):
            return self._decode_result(
                result, count, kind=f"{self.acquisition}+priors"
            )

    # -- multi-objective ---------------------------------------------------

    def _suggest_multiobjective(self, count: int) -> List[trial_.TrialSuggestion]:
        """Random-hypervolume scalarized UCB over per-metric GPs."""
        conv = self._converter
        trials = self._trials
        raw = conv.metrics.encode(trials)  # [N, M] all-MAXIMIZE
        objective_idx = [
            j
            for j, m in enumerate(self.problem.metric_information)
            if not m.is_safety_metric
        ]
        features, n_pad = self._padded_features(trials)
        datas = []
        refs = []
        for j in objective_idx:
            warped = self._warper(raw[:, j])
            datas.append(
                gp_lib.GPData.from_model_data(
                    types.ModelData(features, self._padded_labels(warped, n_pad))
                )
            )
            refs.append(
                float(
                    acquisitions.get_reference_point(
                        jnp.asarray(warped, jnp.float32),
                        jnp.ones(len(warped), bool),
                    )
                )
            )
        batched = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *datas)
        with profiler.timeit("train_gp"):
            states = _train_gp_per_metric(
                self._model, self._ard, batched, self._next_rng(), self.ard_restarts
            )
        # Per-metric vmapped training is not warm-started (GP-UCB-PE owns
        # the warm multimetric path); cold by definition.
        self._ard_train_counts["cold"] += 1
        m = len(objective_idx)
        directions = jnp.abs(
            jax.random.normal(self._next_rng(), (64, m), dtype=jnp.float32)
        )
        directions = directions / jnp.linalg.norm(directions, axis=-1, keepdims=True)
        scoring = acquisitions.HVScalarizedScoring(
            metric_states=states,
            directions=directions,
            reference_point=jnp.asarray(refs, jnp.float32),
            ucb_coefficient=self.ucb_coefficient,
            trust_region=(
                acquisitions.TrustRegion.from_data(datas[0])
                if self.use_trust_region
                else None
            ),
        )
        with profiler.timeit("acquisition_optimizer"):
            result = self._maximize(
                scoring, self._next_rng(), count, self._prior_features(datas[0])
            )
            jax.block_until_ready(result.scores)
        with profiler.timeit("best_candidates_to_trials"):
            return self._decode_result(result, count, kind="hv_scalarized_ucb")

    # -- pieces ------------------------------------------------------------

    def _make_acquisition(self):
        if self.acquisition == "ucb":
            return acquisitions.UCB(self.ucb_coefficient)
        if self.acquisition in ("ei", "qei"):  # qei degenerates to EI at q=1
            return acquisitions.EI()
        if self.acquisition == "pi":
            return acquisitions.PI()
        if self.acquisition == "pe":
            return acquisitions.PE()
        raise ValueError(f"Unknown acquisition {self.acquisition!r}.")

    def _seed_suggestions(self, count: int) -> List[trial_.TrialSuggestion]:
        out: List[trial_.TrialSuggestion] = []
        if not self._trials:
            from vizier_tpu.algorithms import designer_policy

            out.append(designer_policy.default_suggestion(self.problem))
        while len(out) < count:
            out.extend(self._seeder.suggest(count - len(out)))
        return out[:count]

    def _prior_features(self, data: gp_lib.GPData) -> kernels.MixedFeatures:
        """Top observed points (by warped label) to seed the eagle pool.

        Slots past the valid rows would be all-zero padding rows, so
        :func:`_prior_features_from_data` redirects them to the best row.
        """
        return _prior_features_from_data(data)

    # -- Predictor ---------------------------------------------------------

    def sample(
        self,
        suggestions: Sequence[trial_.TrialSuggestion],
        rng=None,
        num_samples: int = 1000,
    ) -> np.ndarray:
        """UNWARPED posterior samples [S, T] (original metric scale).

        Reference ``VizierGPBandit.sample``: draw in the warped space the GP
        was trained in, then invert the output-warper pipeline. ``rng`` may
        be a jax PRNGKey OR a numpy Generator (the Predictor base contract).
        """
        rng = _as_prng_key(rng)
        if not suggestions:
            return np.zeros((num_samples, 0))
        predictive = self._require_predictive()
        feats = self._encode_suggestions(suggestions)
        mean, stddev = predictive.predict(feats)
        eps = jax.random.normal(rng, (num_samples,) + mean.shape, mean.dtype)
        warped = np.asarray(mean[None] + stddev[None] * eps)  # [S, T]
        if not self._warper_fitted:
            # Predict before any training labels: the warped space IS the
            # native space (prior samples on a fresh study).
            return warped
        out = self._warper.unwarp(warped.reshape(-1, 1)).reshape(warped.shape)
        # The model trains on sign-flipped (all-MAXIMIZE) labels; the
        # converter owns the flip rule, so route back through it for
        # genuine user-scale samples on MINIMIZE objectives.
        return self._converter.metrics.decode_column(out, self.metric_index)

    def predict(
        self,
        suggestions: Sequence[trial_.TrialSuggestion],
        rng: Optional[np.random.Generator] = None,
        num_samples: Optional[int] = None,
    ) -> core_lib.Prediction:
        """Empirical mean/stddev of UNWARPED posterior samples.

        Parity with the reference predict contract (``gp_bandit.py`` predict
        → sample → unwarp): values come back in the original metric scale.
        """
        samples = self.sample(suggestions, rng=rng, num_samples=num_samples or 1000)
        return core_lib.Prediction(
            mean=np.mean(samples, axis=0), stddev=np.std(samples, axis=0)
        )

    def _require_predictive(self) -> gp_lib.EnsemblePredictive:
        if self._last_predictive is None:
            if len(self._trials) < max(self.num_seed_trials, 1):
                raise ValueError("Not enough completed trials to predict.")
            data = gp_lib.GPData.from_model_data(self._warped_model_data())
            states = self._train(data, self._next_rng(), self.ensemble_size)
            self._last_predictive = gp_lib.EnsemblePredictive(states)
        return self._last_predictive

    def _encode_suggestions(
        self, suggestions: Sequence[trial_.TrialSuggestion]
    ) -> kernels.MixedFeatures:
        trials = [s.to_trial(i + 1) for i, s in enumerate(suggestions)]
        cont, cat = self._converter.encoder.encode(trials)
        n = len(trials)
        cont_p = np.zeros((n, self._cont_width), dtype=np.float32)
        cont_p[:, : cont.shape[1]] = cont
        cat_p = np.zeros((n, self._cat_width), dtype=np.int32)
        cat_p[:, : cat.shape[1]] = cat
        return kernels.MixedFeatures(jnp.asarray(cont_p), jnp.asarray(cat_p))


def default_factory(
    problem: base_study_config.ProblemStatement, seed: Optional[int] = None, **kwargs
) -> VizierGPBandit:
    return VizierGPBandit(problem, rng_seed=seed or 0, **kwargs)


@functools.partial(
    jax.jit, static_argnames=("vec_opt", "q", "num_samples", "mesh")
)
def _maximize_q_batch(
    vec_opt: vectorized_lib.VectorizedOptimizer,
    states: gp_lib.GPState,  # leading ensemble axis
    best_label: Array,
    trust: Optional[acquisitions.TrustRegion],
    rng: Array,
    q: int,
    num_samples: int,
    prior_features: Optional[kernels.MixedFeatures] = None,
    mesh=None,
) -> vectorized_lib.VectorizedOptimizerResult:
    """Joint q-batch qEI: each candidate is a whole batch in q*Dc space.

    Parity with the reference's ``n_parallel`` q-group mode
    (``vectorized_base.py:364-372``): the strategy explores flattened
    [q * Dc] points; the score of a candidate is the Monte-Carlo qEI of its
    q constituent points under the *joint* ensemble posterior (full q×q
    covariance per candidate — duplicated members are perfectly correlated,
    so collapsing the batch onto one point earns no extra credit).

    With a ``mesh``, the (q·Dc)-space search runs one independent eagle
    pool per device with a single top-k merge
    (``parallel.maximize_score_fn_sharded``) — the same pool-sharding the
    single-point acquisitions use.
    """
    dc = states.data.continuous.shape[-1]
    ds = states.data.categorical.shape[-1]
    mc_rng = jax.random.fold_in(rng, 7)

    def score_fn(flat: kernels.MixedFeatures) -> Array:
        b = flat.continuous.shape[0]
        pts = flat.continuous.reshape(b, q, dc)

        def per_candidate(batch_pts: Array) -> Array:
            query = kernels.MixedFeatures(
                batch_pts, jnp.zeros((q, ds), jnp.int32)
            )
            means, covs = jax.vmap(lambda s: s.predict_joint(query))(states)
            chols = jnp.linalg.cholesky(covs)  # [E, q, q]
            eps = jax.random.normal(
                mc_rng, (num_samples,) + means.shape, dtype=means.dtype
            )  # [S, E, q]
            draws = means[None] + jnp.einsum("eqr,ser->seq", chols, eps)
            batch_max = jnp.max(draws, axis=-1)  # [S, E]
            qei = jnp.mean(jnp.maximum(batch_max - best_label, 0.0))
            if trust is not None:
                # Sum (not mean): each member pays the single-point penalty.
                qei = qei - jnp.sum(trust.penalty(query))
            return qei

        return jax.vmap(per_candidate)(pts)

    prior = None
    if prior_features is not None:
        # Tile the top observed points across the q slots so the joint
        # search starts anchored at the incumbent region.
        k = prior_features.continuous.shape[0]
        tiled = jnp.tile(prior_features.continuous, (1, q)).reshape(k, q * dc)
        prior = kernels.MixedFeatures(tiled, jnp.zeros((k, 0), jnp.int32))
    if mesh is not None:
        from vizier_tpu import parallel

        return parallel.maximize_score_fn_sharded(
            vec_opt,
            score_fn,
            rng,
            count=1,
            num_pools=len(mesh.devices.flat),
            mesh=mesh,
            prior_features=prior,
        )
    return vec_opt(score_fn, rng, count=1, prior_features=prior)


# -- compute-IR programs (vizier_tpu.compute) --------------------------------
#
# The batched designer-compute contract for the GP-bandit family: one
# program per compiled-flush family (exact | sparse), registered so the
# batch executor, prewarm walker, chaos wrappers, device-phase tracing and
# the speculative lane consume them generically. The hook bodies ARE the
# pre-IR ``batch_*`` designer methods, moved verbatim — slot i of a batch
# stays bit-identical to study i run alone, and the thin designer methods
# above delegate here for legacy callers.


def _gp_bandit_unbatchable(designer: "VizierGPBandit", count: int) -> bool:
    """Paths the batched flush programs do not cover (seeding, multi-
    objective, transfer priors, joint qEI, mesh-sharded): those run the
    ordinary sequential suggest."""
    return bool(
        designer._mesh is not None
        or len(designer._trials) < designer.num_seed_trials
        or designer._num_objectives() > 1
        or getattr(designer, "_priors", None)
        or (designer.acquisition == "qei" and count > 1)
    )


def _gp_bandit_prepare(designer: "VizierGPBandit", count: int, sparse: bool) -> dict:
    """Host-side half of a batched suggest: encode + warp + RNG draws.

    Consumes the designer's RNG stream in exactly the order the sequential
    ``suggest`` would (train key, then acquisition key), so batched and
    sequential runs of the same study are key-for-key identical. Host-only:
    the ModelData leaves stay numpy; the GPData conversion happens inside
    the batched program, so prepare issues zero device dispatches.
    """
    return dict(
        designer=designer,
        count=count,
        md=designer._warped_model_data(),
        rng_train=designer._next_rng(),
        rng_acq=designer._next_rng(),
        warm=designer._warm_params,
        restarts=designer._batch_restarts(),
        # The bucket key (computed just before prepare) already refreshed
        # the auto-switch; equal keys guarantee a whole bucket agrees.
        sparse=sparse,
    )


def _gp_bandit_demux(items, pad_to, states, warm_next, result, sparse: bool):
    """ONE device->host fetch for the whole batch; per-slot demux is then
    free numpy views (per-slot device slices would be ~20 dispatches per
    slot and dominated the executor's wall time)."""
    from vizier_tpu.parallel import batch_executor

    states, warm_next, result = jax.device_get((states, warm_next, result))
    return [
        dict(
            states=batch_executor.slice_pytree(states, i),
            warm_next=batch_executor.slice_pytree(warm_next, i),
            result=batch_executor.slice_pytree(result, i),
            sparse=sparse,
        )
        for i in range(len(items))
    ]


class GPBanditProgram(compute_ir.DesignerProgram):
    """Exact-GP single-objective flush: encode→multi-restart ARD→UCB/EI
    sweep, one fused vmapped dispatch per bucket."""

    kind = "gp_bandit"
    device_phase = "gp_bandit.suggest_batched"
    surrogate_family = "exact"
    shardable_batch_axis = "study"
    algorithms = ("GAUSSIAN_PROCESS_BANDIT",)

    def bucket_key(self, designer, count):
        if _gp_bandit_unbatchable(designer, count):
            return None
        if (
            designer._refresh_surrogate_mode()
            == surrogate_config_lib.MODE_SPARSE
        ):
            return None  # the sparse program owns this study
        return compute_ir.BucketKey(
            kind=self.kind,
            pad_trials=designer._converter.padding.pad_trials(
                len(designer._trials)
            ),
            cont_width=designer._cont_width,
            cat_width=designer._cat_width,
            metric_count=1,
            count=count,
            statics=(
                designer._model,
                designer._ard,
                designer._vec_opt,
                designer._batch_restarts(),
                designer.ensemble_size,
                designer._make_acquisition(),
                designer.use_trust_region,
            ),
        )

    def prepare(self, designer, count):
        return _gp_bandit_prepare(designer, count, sparse=False)

    def device_program(self, items, pad_to=None, placement=None):
        """ONE vmapped train + ONE vmapped sweep for the whole bucket
        (slot 0's jit statics stand in for everyone's — the bucket key
        guarantees they are equal). With a mesh ``placement`` the stacked
        study axis is committed onto its submesh, so the fused dispatch
        spans the placement's devices."""
        from vizier_tpu.parallel import batch_executor

        d0: "VizierGPBandit" = items[0]["designer"]
        stack = lambda name: batch_executor.place_batch(  # noqa: E731
            batch_executor.stack_pytrees([it[name] for it in items], pad_to),
            placement,
        )
        with jax_timing.device_phase(self.device_phase) as phase:
            states, warm_next, result = _gp_bandit_flush_program(
                d0._model, d0._ard, d0._vec_opt, d0._make_acquisition(),
                stack("md"), stack("rng_train"), stack("rng_acq"),
                stack("warm"),
                items[0]["restarts"], d0.ensemble_size,
                items[0]["count"], d0.use_trust_region,
            )
            phase.block(result)
        return _gp_bandit_demux(
            items, pad_to, states, warm_next, result, sparse=False
        )

    def finalize(self, designer, item, output):
        """Host-side demux: per-study warm-param writeback + decode — the
        same state transitions the sequential suggest performs."""
        states = output["states"]
        designer._record_train()
        if designer._warm_update_allowed():
            # The unconstrain already ran (vmapped) inside the flush program.
            designer._warm_params = output["warm_next"]
            designer._warm_is_trained = True
        designer._last_predictive = gp_lib.EnsemblePredictive(states)
        return designer._decode_result(
            output["result"], item["count"], kind=designer.acquisition
        )

    def prewarm_factory(self, problem, **kwargs):
        return VizierGPBandit(problem, **kwargs)


class GPBanditSparseProgram(compute_ir.DesignerProgram):
    """Sparse (SGPR) flush twin: same stages over the collapsed-bound
    posterior, one compiled program per (n-bucket, m-bucket) pair, its own
    device-phase bucket so ``vizier_jax_phase_seconds`` separates sparse
    from exact time."""

    kind = "gp_bandit_sparse"
    device_phase = "sparse_gp.suggest_batched"
    surrogate_family = "sparse"
    shardable_batch_axis = "study"
    algorithms = ("GAUSSIAN_PROCESS_BANDIT",)

    def bucket_key(self, designer, count):
        if _gp_bandit_unbatchable(designer, count):
            return None
        if (
            designer._refresh_surrogate_mode()
            != surrogate_config_lib.MODE_SPARSE
        ):
            return None
        # Sparse studies batch among themselves: the sparse model (with
        # its padded inducing-slot count — the m-bucket) rides in the
        # statics, so equal keys ⇒ one compiled _sparse_flush_program per
        # (n-bucket, m-bucket) pair.
        return compute_ir.BucketKey(
            kind=self.kind,
            pad_trials=designer._converter.padding.pad_trials(
                len(designer._trials)
            ),
            cont_width=designer._cont_width,
            cat_width=designer._cat_width,
            metric_count=1,
            count=count,
            statics=(
                designer._sparse_model(),
                designer._ard,
                designer._vec_opt,
                designer._batch_restarts(),
                designer.ensemble_size,
                designer._make_acquisition(),
                designer.use_trust_region,
            ),
        )

    def prepare(self, designer, count):
        return _gp_bandit_prepare(designer, count, sparse=True)

    def device_program(self, items, pad_to=None, placement=None):
        from vizier_tpu.parallel import batch_executor

        d0: "VizierGPBandit" = items[0]["designer"]
        stack = lambda name: batch_executor.place_batch(  # noqa: E731
            batch_executor.stack_pytrees([it[name] for it in items], pad_to),
            placement,
        )
        with jax_timing.device_phase(self.device_phase) as phase:
            states, warm_next, result = sparse_bandit._sparse_flush_program(
                d0._sparse_model(), d0._ard, d0._vec_opt,
                d0._make_acquisition(),
                stack("md"), stack("rng_train"), stack("rng_acq"),
                stack("warm"),
                items[0]["restarts"], d0.ensemble_size,
                items[0]["count"], d0.use_trust_region,
            )
            phase.block(result)
        return _gp_bandit_demux(
            items, pad_to, states, warm_next, result, sparse=True
        )

    def finalize(self, designer, item, output):
        states = output["states"]
        designer._record_train()
        if designer._warm_update_allowed():
            designer._warm_params = output["warm_next"]
            designer._warm_is_trained = True
        designer._last_predictive = sparse_gp.SparseEnsemblePredictive(states)
        designer._last_sparse_state = states
        designer._surrogate_counts["sparse_suggests"] += 1
        return designer._decode_result(
            output["result"],
            item["count"],
            kind=f"{designer.acquisition}+sparse",
        )

    def prewarm_factory(self, problem, **kwargs):
        # The walker's synthetic studies engage this program exactly when
        # the factory's surrogate config flips them sparse (threshold vs
        # the walked trial bucket) — the same auto-switch live studies use.
        return VizierGPBandit(problem, **kwargs)


compute_registry.register(VizierGPBandit, GPBanditProgram())
compute_registry.register(VizierGPBandit, GPBanditSparseProgram())
