"""Scrambled-Halton quasi-random designer.

Parity with ``/root/reference/vizier/_src/algorithms/designers/quasi_random.py:32``,
with our own Halton implementation (no scipy dependency in the hot path —
the generator is pure numpy and supports ``fast_forward`` for partial
serializability; the same radical-inverse core is reused by the GP designer's
seeding stage).
"""

from __future__ import annotations

import json
from typing import List, Optional, Sequence

import numpy as np

from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import common
from vizier_tpu.pyvizier import parameter_config as pc
from vizier_tpu.pyvizier import trial as trial_
from vizier_tpu.utils import serializable

_PRIMES = [
    2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37, 41, 43, 47, 53, 59, 61, 67,
    71, 73, 79, 83, 89, 97, 101, 103, 107, 109, 113, 127, 131, 137, 139, 149,
    151, 157, 163, 167, 173, 179, 181, 191, 193, 197, 199, 211, 223, 227, 229,
    233, 239, 241, 251, 257, 263, 269, 271, 277, 281, 283, 293, 307, 311, 313,
    317, 331, 337, 347, 349, 353, 359, 367, 373, 379, 383, 389, 397, 401, 409,
    419, 421, 431, 433, 439, 443, 449, 457, 461, 463, 467, 479, 487, 491, 499,
]


def _radical_inverse(index: int, base: int, perm: np.ndarray) -> float:
    """Scrambled radical inverse of ``index`` in ``base``."""
    result = 0.0
    inv_base = 1.0 / base
    factor = inv_base
    while index > 0:
        digit = perm[index % base]
        result += digit * factor
        index //= base
        factor *= inv_base
    return result


class HaltonSequence:
    """Scrambled Halton sequence over [0, 1]^d with skip + fast-forward."""

    def __init__(self, num_dimensions: int, *, seed: Optional[int] = None, skip: int = 100):
        if num_dimensions > len(_PRIMES):
            raise ValueError(
                f"Halton supports up to {len(_PRIMES)} dims, got {num_dimensions}."
            )
        self._dim = num_dimensions
        self._index = skip
        rng = np.random.default_rng(seed)
        # One digit permutation per dimension (fixing 0 -> 0 keeps the
        # sequence's low-discrepancy structure).
        self._perms = []
        for d in range(num_dimensions):
            base = _PRIMES[d]
            perm = np.concatenate([[0], rng.permutation(np.arange(1, base))])
            self._perms.append(perm)

    @property
    def index(self) -> int:
        return self._index

    def fast_forward(self, count: int) -> None:
        self._index += count

    def sample(self, count: int) -> np.ndarray:
        out = np.empty((count, self._dim))
        for i in range(count):
            for d in range(self._dim):
                out[i, d] = _radical_inverse(self._index + 1, _PRIMES[d], self._perms[d])
            self._index += 1
        return out


class QuasiRandomDesigner(core_lib.PartiallySerializableDesigner):
    """Halton sampling over a flat search space (scaled per parameter)."""

    def __init__(
        self,
        search_space: pc.SearchSpace,
        *,
        seed: Optional[int] = None,
        skip_points: int = 100,
    ):
        if search_space.is_conditional:
            raise ValueError("QuasiRandomDesigner requires a flat search space.")
        self._search_space = search_space
        self._configs = search_space.parameters
        self._seed = seed if seed is not None else 0
        self._halton = HaltonSequence(
            len(self._configs), seed=self._seed, skip=skip_points
        )

    @classmethod
    def from_problem(
        cls, problem: base_study_config.ProblemStatement, seed: Optional[int] = None
    ) -> "QuasiRandomDesigner":
        return cls(problem.search_space, seed=seed)

    def update(self, completed, all_active=core_lib.ActiveTrials()) -> None:
        del completed, all_active

    def _to_value(self, config: pc.ParameterConfig, u: float) -> pc.ParameterValueTypes:
        if config.type == pc.ParameterType.DOUBLE:
            from vizier_tpu.designers import random as random_designer

            return random_designer.unit_to_double(config, u)
        if config.type == pc.ParameterType.INTEGER:
            lo, hi = config.bounds
            return int(np.clip(int(lo) + int(u * (int(hi) - int(lo) + 1)), int(lo), int(hi)))
        values = config.feasible_values
        idx = min(int(u * len(values)), len(values) - 1)
        return values[idx]

    def suggest(self, count: Optional[int] = None) -> List[trial_.TrialSuggestion]:
        count = count or 1
        samples = self._halton.sample(count)
        out = []
        for row in samples:
            params = trial_.ParameterDict()
            for config, u in zip(self._configs, row):
                params[config.name] = config.cast_value(self._to_value(config, float(u)))
            out.append(trial_.TrialSuggestion(parameters=params))
        return out

    # -- PartiallySerializable --------------------------------------------

    def dump(self) -> common.Metadata:
        md = common.Metadata()
        md["halton"] = json.dumps({"index": self._halton.index, "seed": self._seed})
        return md

    def load(self, metadata: common.Metadata) -> None:
        raw = metadata.get("halton")
        if raw is None:
            raise serializable.DecodeError("Missing 'halton' key.")
        try:
            state = json.loads(raw)
            index = int(state["index"])
            seed = int(state["seed"])
        except (ValueError, KeyError, TypeError) as e:
            raise serializable.DecodeError(f"Bad halton state: {e}")
        self._seed = seed  # keep dump() consistent with the restored stream
        self._halton = HaltonSequence(len(self._configs), seed=seed, skip=0)
        self._halton.fast_forward(index)
