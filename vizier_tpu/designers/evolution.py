"""Evolution scaffolding + NSGA-II.

Parity with ``/root/reference/vizier/_src/algorithms/evolution/``
(``templates.py`` ask/tell scaffolding + ``numpy_populations.py`` population
containers + ``nsga2.py:244``): a canonical evolution designer drives
(population → selection → offspring) generations from completed trials; the
NSGA-II ranking (nondomination layers + crowding distance) runs on the XLA
ops in ``vizier_tpu.ops.pareto``.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import numpy as np

from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.converters import core as converters
from vizier_tpu.ops import pareto as pareto_ops
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import trial as trial_


@dataclasses.dataclass
class Population:
    """Genomes in model space ([N, Dc] floats in [0,1] + [N, Ds] ints)."""

    continuous: np.ndarray
    categorical: np.ndarray
    objectives: np.ndarray  # [N, M] all-MAXIMIZE; NaN = unevaluated

    def __len__(self) -> int:
        return self.continuous.shape[0]

    @classmethod
    def concat(cls, pops: Sequence["Population"]) -> "Population":
        return cls(
            continuous=np.concatenate([p.continuous for p in pops]),
            categorical=np.concatenate([p.categorical for p in pops]),
            objectives=np.concatenate([p.objectives for p in pops]),
        )

    def take(self, idx: np.ndarray) -> "Population":
        return Population(
            continuous=self.continuous[idx],
            categorical=self.categorical[idx],
            objectives=self.objectives[idx],
        )


def nsga2_survival(population: Population, target_size: int) -> Population:
    """NSGA-II elitist survival: layer rank, then crowding distance."""
    points = np.asarray(population.objectives, dtype=np.float32)
    finite = np.all(np.isfinite(points), axis=1)
    points = np.where(finite[:, None], points, -1e30)
    layers = np.asarray(pareto_ops.nondomination_layers(points))
    crowding = np.asarray(
        pareto_ops.crowding_distance(points, layers)
    )
    # Sort: lower layer first; within layer, higher crowding first.
    order = np.lexsort((-crowding, layers))
    return population.take(order[:target_size])


@dataclasses.dataclass
class UniformMutation:
    """Gaussian perturbation of continuous genes + categorical resampling."""

    scale: float = 0.1
    categorical_mutate_prob: float = 0.1

    def __call__(
        self,
        parents: Population,
        category_sizes: Sequence[int],
        rng: np.random.Generator,
    ) -> Tuple[np.ndarray, np.ndarray]:
        n, dc = parents.continuous.shape
        cont = parents.continuous + rng.normal(0.0, self.scale, size=(n, dc))
        cont = np.clip(cont, 0.0, 1.0)
        cat = parents.categorical.copy()
        for j, size in enumerate(category_sizes):
            mutate = rng.uniform(size=n) < self.categorical_mutate_prob
            cat[mutate, j] = rng.integers(0, size, size=int(mutate.sum()))
        return cont, cat


def sbx_crossover(
    a: np.ndarray, b: np.ndarray, rng: np.random.Generator, eta: float = 15.0
) -> np.ndarray:
    """Simulated binary crossover for continuous genes (one child per pair)."""
    u = rng.uniform(size=a.shape)
    beta = np.where(
        u <= 0.5,
        (2.0 * u) ** (1.0 / (eta + 1.0)),
        (1.0 / (2.0 * (1.0 - u))) ** (1.0 / (eta + 1.0)),
    )
    child = 0.5 * ((1 + beta) * a + (1 - beta) * b)
    return np.clip(child, 0.0, 1.0)


@dataclasses.dataclass
class NSGA2Designer(core_lib.PartiallySerializableDesigner):
    """NSGA-II over flat search spaces; single- or multi-objective."""

    problem: base_study_config.ProblemStatement
    population_size: int = 50
    mutation: UniformMutation = dataclasses.field(default_factory=UniformMutation)
    eta: float = 15.0
    seed: Optional[int] = None

    def __post_init__(self):
        self._converter = converters.TrialToModelInputConverter.from_problem(
            self.problem
        )
        self._enc = self._converter.encoder
        self._rng = np.random.default_rng(self.seed)
        self._num_suggested = 0
        m = self._converter.metrics.num_metrics
        self._population = Population(
            continuous=np.zeros((0, self._enc.num_continuous)),
            categorical=np.zeros((0, self._enc.num_categorical), dtype=np.int32),
            objectives=np.zeros((0, m)),
        )

    def update(
        self,
        completed: core_lib.CompletedTrials,
        all_active: core_lib.ActiveTrials = core_lib.ActiveTrials(),
    ) -> None:
        del all_active
        trials = list(completed.trials)
        if not trials:
            return
        cont, cat = self._enc.encode(trials)
        objectives = self._converter.metrics.encode(trials)  # all-MAXIMIZE
        newcomers = Population(cont, cat.astype(np.int32), objectives)
        merged = Population.concat([self._population, newcomers])
        self._population = nsga2_survival(merged, self.population_size)

    def suggest(self, count: Optional[int] = None) -> List[trial_.TrialSuggestion]:
        count = count or 1
        out: List[trial_.TrialSuggestion] = []
        pop = self._population
        # NSGA-II is generation-based: the whole first generation is random.
        # Starting crossover after only a few evaluated points collapses the
        # population prematurely (visible as sub-random ZDT hypervolume).
        in_first_generation = self._num_suggested < self.population_size
        evaluated = (
            not in_first_generation
            and len(pop) > 0
            and np.isfinite(pop.objectives).any()
        )
        self._num_suggested += count
        for _ in range(count):
            if not evaluated or len(pop) < 2:
                cont = self._rng.uniform(size=(1, self._enc.num_continuous))
                cat = np.asarray(
                    [
                        [self._rng.integers(0, s) for s in self._enc.category_sizes]
                    ],
                    dtype=np.int32,
                ).reshape(1, self._enc.num_categorical)
            else:
                # Binary tournament on (layer, crowding) implicit in survival
                # order: earlier rows are better.
                i = min(self._rng.integers(0, len(pop)), self._rng.integers(0, len(pop)))
                j = min(self._rng.integers(0, len(pop)), self._rng.integers(0, len(pop)))
                child_cont = sbx_crossover(
                    pop.continuous[i : i + 1], pop.continuous[j : j + 1], self._rng, self.eta
                )
                pick = self._rng.uniform(size=(1, self._enc.num_categorical)) < 0.5
                child_cat = np.where(
                    pick, pop.categorical[i : i + 1], pop.categorical[j : j + 1]
                )
                parents = Population(
                    child_cont,
                    child_cat.astype(np.int32),
                    np.full((1, pop.objectives.shape[1]), np.nan),
                )
                cont, cat = self.mutation(parents, self._enc.category_sizes, self._rng)
            params = self._converter.to_parameters(cont, cat)[0]
            out.append(trial_.TrialSuggestion(parameters=params))
        return out

    # -- PartiallySerializable --------------------------------------------

    def dump(self):
        from vizier_tpu.pyvizier import common
        from vizier_tpu.utils import json_utils

        md = common.Metadata()
        md["population"] = json_utils.dumps(
            {
                "continuous": self._population.continuous,
                "categorical": self._population.categorical,
                "objectives": self._population.objectives,
                "num_suggested": self._num_suggested,
            }
        )
        return md

    def load(self, metadata) -> None:
        from vizier_tpu.utils import json_utils, serializable

        raw = metadata.get("population")
        if raw is None:
            raise serializable.DecodeError("Missing 'population'.")
        try:
            state = json_utils.loads(raw)
            self._population = Population(
                continuous=np.asarray(state["continuous"], dtype=np.float64),
                categorical=np.asarray(state["categorical"], dtype=np.int32),
                objectives=np.asarray(state["objectives"], dtype=np.float64),
            )
            # Older checkpoints lack num_suggested: a restored evaluated
            # population implies its generation was already spent — do not
            # re-run the random first generation after resume.
            self._num_suggested = int(
                state.get("num_suggested", len(self._population))
            )
        except (KeyError, ValueError, TypeError) as e:
            raise serializable.DecodeError(f"Bad population state: {e}")
