"""Uniform random designer.

Parity with ``/root/reference/vizier/_src/algorithms/designers/random.py:27``.
Handles conditional search spaces by sampling the tree top-down.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import parameter_config as pc
from vizier_tpu.pyvizier import trial as trial_


def unit_to_double(config: pc.ParameterConfig, u: float) -> float:
    """Maps u ∈ [0, 1] to the parameter's range honoring its scale type.

    Shared by the random/quasi-random/grid samplers so LOG and REVERSE_LOG
    parameters get the density their scale type promises.
    """
    lo, hi = config.bounds
    if hi <= lo:
        return float(lo)
    scale = config.scale_type
    if scale == pc.ScaleType.LOG and lo > 0:
        return float(np.exp(np.log(lo) + u * (np.log(hi) - np.log(lo))))
    if scale == pc.ScaleType.REVERSE_LOG and lo > 0:
        return float(hi + lo - np.exp(np.log(lo) + (1.0 - u) * (np.log(hi) - np.log(lo))))
    return float(lo + u * (hi - lo))


def sample_parameter(
    config: pc.ParameterConfig, rng: np.random.Generator
) -> pc.ParameterValueTypes:
    """Uniformly samples one feasible value (scale-aware for DOUBLEs)."""
    if config.type == pc.ParameterType.DOUBLE:
        return unit_to_double(config, float(rng.uniform()))
    if config.type == pc.ParameterType.INTEGER:
        lo, hi = config.bounds
        return int(rng.integers(int(lo), int(hi) + 1))
    values = config.feasible_values
    return values[int(rng.integers(0, len(values)))]


def sample_point(
    search_space: pc.SearchSpace, rng: np.random.Generator
) -> trial_.ParameterDict:
    """Samples a full (conditionally-consistent) point."""
    params = trial_.ParameterDict()

    def walk(config: pc.ParameterConfig) -> None:
        value = sample_parameter(config, rng)
        params[config.name] = config.cast_value(value)
        for child in config.children:
            if any(pc.parent_value_matches(value, pv) for pv in child.matching_parent_values):
                walk(child)

    for config in search_space.parameters:
        walk(config)
    return params


class RandomDesigner(core_lib.Designer):
    """Stateless uniform sampling."""

    def __init__(
        self,
        search_space: pc.SearchSpace,
        *,
        seed: Optional[int] = None,
    ):
        self._search_space = search_space
        self._rng = np.random.default_rng(seed)

    @classmethod
    def from_problem(
        cls, problem: base_study_config.ProblemStatement, seed: Optional[int] = None
    ) -> "RandomDesigner":
        return cls(problem.search_space, seed=seed)

    def update(self, completed, all_active=core_lib.ActiveTrials()) -> None:
        del completed, all_active

    def suggest(self, count: Optional[int] = None) -> List[trial_.TrialSuggestion]:
        count = count or 1
        return [
            trial_.TrialSuggestion(parameters=sample_point(self._search_space, self._rng))
            for _ in range(count)
        ]
