"""CMA-ES designer (continuous search spaces).

Parity with ``/root/reference/vizier/_src/algorithms/designers/cmaes.py:32``:
the standard (mu/mu_w, lambda) CMA-ES — weighted recombination, cumulative
step-size adaptation, rank-one + rank-mu covariance updates — over the
[0, 1]^D model space.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np

from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.converters import core as converters
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import trial as trial_


class _CMAState:
    def __init__(self, dim: int, sigma: float, rng: np.random.Generator):
        self.dim = dim
        self.mean = rng.uniform(0.3, 0.7, size=dim)
        self.sigma = sigma
        self.cov = np.eye(dim)
        self.p_sigma = np.zeros(dim)
        self.p_c = np.zeros(dim)
        self.generation = 0


@dataclasses.dataclass
class CMAESDesigner(core_lib.Designer):
    problem: base_study_config.ProblemStatement
    population_size: Optional[int] = None  # default 4 + 3 ln D
    sigma0: float = 0.3
    seed: Optional[int] = None

    def __post_init__(self):
        space = self.problem.search_space
        if space.is_conditional:
            raise ValueError("CMAESDesigner requires a flat search space.")
        self._converter = converters.TrialToModelInputConverter.from_problem(
            self.problem
        )
        enc = self._converter.encoder
        if enc.num_categorical:
            raise ValueError("CMAESDesigner supports continuous parameters only.")
        self._dim = enc.num_continuous
        self._rng = np.random.default_rng(self.seed)
        self._lambda = self.population_size or (4 + int(3 * np.log(self._dim)))
        self._state = _CMAState(self._dim, self.sigma0, self._rng)
        self._setup_weights()
        self._told: List[tuple] = []  # (genome, objective) awaiting a generation

    def _setup_weights(self):
        lam, dim = self._lambda, self._dim
        mu = lam // 2
        raw = np.log(mu + 0.5) - np.log(np.arange(1, mu + 1))
        self._weights = raw / raw.sum()
        self._mu = mu
        self._mu_eff = 1.0 / np.sum(self._weights**2)
        self._c_sigma = (self._mu_eff + 2) / (dim + self._mu_eff + 5)
        self._d_sigma = (
            1
            + 2 * max(0.0, np.sqrt((self._mu_eff - 1) / (dim + 1)) - 1)
            + self._c_sigma
        )
        self._c_c = (4 + self._mu_eff / dim) / (dim + 4 + 2 * self._mu_eff / dim)
        self._c_1 = 2.0 / ((dim + 1.3) ** 2 + self._mu_eff)
        self._c_mu = min(
            1 - self._c_1,
            2 * (self._mu_eff - 2 + 1 / self._mu_eff) / ((dim + 2) ** 2 + self._mu_eff),
        )
        self._chi_n = np.sqrt(dim) * (1 - 1 / (4 * dim) + 1 / (21 * dim**2))

    # -- Designer ----------------------------------------------------------

    def update(
        self,
        completed: core_lib.CompletedTrials,
        all_active: core_lib.ActiveTrials = core_lib.ActiveTrials(),
    ) -> None:
        del all_active
        trials = list(completed.trials)
        if not trials:
            return
        cont, _ = self._converter.encoder.encode(trials)
        objectives = self._converter.metrics.encode(trials)[:, 0]  # MAXIMIZE
        for x, y in zip(cont, objectives):
            if np.isfinite(y):
                self._told.append((x, y))
        # One CMA generation per lambda evaluations.
        while len(self._told) >= self._lambda:
            batch = self._told[: self._lambda]
            self._told = self._told[self._lambda :]
            self._tell_generation(batch)

    def _tell_generation(self, batch) -> None:
        s = self._state
        xs = np.stack([x for x, _ in batch])
        ys = np.asarray([y for _, y in batch])
        order = np.argsort(-ys)  # best (max) first
        elite = xs[order[: self._mu]]

        old_mean = s.mean.copy()
        sigma_old = s.sigma  # sampling-time sigma: scales y_w AND artmp below
        s.mean = self._weights @ elite
        y_w = (s.mean - old_mean) / sigma_old

        # Step-size path (CSA).
        cov_inv_sqrt = self._cov_inv_sqrt(s.cov)
        s.p_sigma = (1 - self._c_sigma) * s.p_sigma + np.sqrt(
            self._c_sigma * (2 - self._c_sigma) * self._mu_eff
        ) * (cov_inv_sqrt @ y_w)
        s.sigma = s.sigma * np.exp(
            (self._c_sigma / self._d_sigma)
            * (np.linalg.norm(s.p_sigma) / self._chi_n - 1)
        )
        s.sigma = float(np.clip(s.sigma, 1e-8, 1.0))

        # Covariance paths and update.
        h_sigma = float(
            np.linalg.norm(s.p_sigma)
            / np.sqrt(1 - (1 - self._c_sigma) ** (2 * (s.generation + 1)))
            < (1.4 + 2 / (self._dim + 1)) * self._chi_n
        )
        s.p_c = (1 - self._c_c) * s.p_c + h_sigma * np.sqrt(
            self._c_c * (2 - self._c_c) * self._mu_eff
        ) * y_w
        artmp = (elite - old_mean) / sigma_old
        rank_mu = sum(
            w * np.outer(a, a) for w, a in zip(self._weights, artmp)
        )
        s.cov = (
            (1 - self._c_1 - self._c_mu) * s.cov
            + self._c_1
            * (np.outer(s.p_c, s.p_c) + (1 - h_sigma) * self._c_c * (2 - self._c_c) * s.cov)
            + self._c_mu * rank_mu
        )
        s.cov = (s.cov + s.cov.T) / 2.0  # keep symmetric
        s.generation += 1

    @staticmethod
    def _cov_inv_sqrt(cov: np.ndarray) -> np.ndarray:
        vals, vecs = np.linalg.eigh(cov)
        vals = np.maximum(vals, 1e-12)
        return vecs @ np.diag(vals**-0.5) @ vecs.T

    def suggest(self, count: Optional[int] = None) -> List[trial_.TrialSuggestion]:
        count = count or 1
        s = self._state
        vals, vecs = np.linalg.eigh(s.cov)
        sqrt_cov = vecs @ np.diag(np.sqrt(np.maximum(vals, 1e-12))) @ vecs.T
        out = []
        for _ in range(count):
            z = self._rng.standard_normal(self._dim)
            x = np.clip(s.mean + s.sigma * (sqrt_cov @ z), 0.0, 1.0)
            params = self._converter.to_parameters(
                x[None, :], np.zeros((1, 0), dtype=np.int32)
            )[0]
            out.append(trial_.TrialSuggestion(parameters=params))
        return out
