"""Multi-objective scalarizers.

Parity with ``/root/reference/vizier/_src/algorithms/designers/scalarization.py:135``:
linear, Chebyshev (augmented), and hypervolume scalarizations mapping
[..., M] objective vectors to scalars (all-MAXIMIZE convention), as jax
functions usable inside acquisition graphs.
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

Array = jax.Array


class Scalarization(abc.ABC):
    """Maps [..., M] objectives to [...] scalars (bigger = better)."""

    @abc.abstractmethod
    def __call__(self, objectives: Array) -> Array:
        ...


@dataclasses.dataclass(frozen=True)
class LinearScalarization(Scalarization):
    weights: tuple

    def __call__(self, objectives: Array) -> Array:
        w = jnp.asarray(self.weights, dtype=objectives.dtype)
        return jnp.sum(objectives * w, axis=-1)


@dataclasses.dataclass(frozen=True)
class ChebyshevScalarization(Scalarization):
    """Augmented Chebyshev: min_j w_j (f_j - ref_j) + rho * sum_j w_j f_j."""

    weights: tuple
    reference_point: Optional[tuple] = None
    rho: float = 0.05

    def __call__(self, objectives: Array) -> Array:
        w = jnp.asarray(self.weights, dtype=objectives.dtype)
        ref = (
            jnp.asarray(self.reference_point, dtype=objectives.dtype)
            if self.reference_point is not None
            else jnp.zeros_like(w)
        )
        shifted = objectives - ref
        return jnp.min(w * shifted, axis=-1) + self.rho * jnp.sum(w * shifted, axis=-1)


@dataclasses.dataclass(frozen=True)
class HyperVolumeScalarization(Scalarization):
    """Random-direction HV scalarization: min_j ((f_j - ref_j)_+ / w_j)^M.

    Averaging this over random positive directions w estimates hypervolume
    (the scalarization used by the reference's multi-objective GP bandit,
    ``acquisitions.py:571``).
    """

    weights: tuple
    reference_point: Optional[tuple] = None

    def __call__(self, objectives: Array) -> Array:
        w = jnp.asarray(self.weights, dtype=objectives.dtype)
        ref = (
            jnp.asarray(self.reference_point, dtype=objectives.dtype)
            if self.reference_point is not None
            else jnp.zeros_like(w)
        )
        m = objectives.shape[-1]
        ratios = jnp.maximum(objectives - ref, 0.0) / jnp.maximum(w, 1e-12)
        return jnp.min(ratios, axis=-1) ** m


def random_hv_directions(rng: Array, num: int, num_objectives: int) -> Array:
    """[num, M] positive unit directions for HV scalarization ensembles."""
    v = jnp.abs(jax.random.normal(rng, (num, num_objectives)))
    return v / jnp.linalg.norm(v, axis=-1, keepdims=True)
