"""The designer (algorithm) zoo."""

from vizier_tpu.designers.grid import GridSearchDesigner
from vizier_tpu.designers.quasi_random import HaltonSequence, QuasiRandomDesigner
from vizier_tpu.designers.random import RandomDesigner

__all__ = [
    "GridSearchDesigner",
    "HaltonSequence",
    "QuasiRandomDesigner",
    "RandomDesigner",
]


def __getattr__(name):
    # Heavy (jax-importing) designers load lazily.
    lazy = {
        "VizierGPBandit": ("vizier_tpu.designers.gp_bandit", "VizierGPBandit"),
        "VizierGPUCBPEBandit": ("vizier_tpu.designers.gp_ucb_pe", "VizierGPUCBPEBandit"),
        "UCBPEConfig": ("vizier_tpu.designers.gp_ucb_pe", "UCBPEConfig"),
        "NSGA2Designer": ("vizier_tpu.designers.evolution", "NSGA2Designer"),
        "CMAESDesigner": ("vizier_tpu.designers.cmaes", "CMAESDesigner"),
        "PyCMAESDesigner": ("vizier_tpu.designers.pycmaes", "PyCMAESDesigner"),
        "EagleStrategyDesigner": ("vizier_tpu.designers.eagle_strategy", "EagleStrategyDesigner"),
        "BOCSDesigner": ("vizier_tpu.designers.bocs", "BOCSDesigner"),
        "HarmonicaDesigner": ("vizier_tpu.designers.harmonica", "HarmonicaDesigner"),
        "ScalarizingDesigner": ("vizier_tpu.designers.scalarizing_designer", "ScalarizingDesigner"),
        "EnsembleDesigner": ("vizier_tpu.designers.ensemble", "EnsembleDesigner"),
        "ScheduledDesigner": ("vizier_tpu.designers.scheduled_designer", "ScheduledDesigner"),
        "MetaLearningDesigner": ("vizier_tpu.designers.meta_learning", "MetaLearningDesigner"),
        "eagle_meta_learning_designer": (
            "vizier_tpu.designers.eagle_meta_learning",
            "eagle_meta_learning_designer",
        ),
        "meta_eagle_search_space": (
            "vizier_tpu.designers.eagle_meta_learning",
            "meta_eagle_search_space",
        ),
        "UnsafeAsInfeasibleDesigner": (
            "vizier_tpu.designers.unsafe_as_infeasible_designer",
            "UnsafeAsInfeasibleDesigner",
        ),
    }
    if name in lazy:
        import importlib

        module, attr = lazy[name]
        return getattr(importlib.import_module(module), attr)
    raise AttributeError(name)
