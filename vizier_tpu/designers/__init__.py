"""The designer (algorithm) zoo."""

from vizier_tpu.designers.grid import GridSearchDesigner
from vizier_tpu.designers.quasi_random import HaltonSequence, QuasiRandomDesigner
from vizier_tpu.designers.random import RandomDesigner
