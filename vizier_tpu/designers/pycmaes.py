"""CMA-ES designer wrapping the external ``pycma`` package.

Parity target: ``/root/reference/vizier/_src/algorithms/designers/pycmaes.py:32``
(PyCMAESDesigner). The self-contained XLA-friendly implementation lives in
``designers/cmaes.py``; this wrapper exists for users who specifically want
pycma's reference implementation (restart heuristics, option surface). The
``cma`` package is absent from this image, so only :meth:`suggest` touches
it — construction, validation, and state handling are plain code and run
(and are tested) without the library via an injected module.

Protocol notes mirrored from the reference: features are scaled to the
unit cube; labels are converted maximization-signed and sign-flipped
before feeding (pycma minimizes); the resume feed truncates history to a
multiple of the population size, as ``feed_for_resume`` requires.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np

from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.converters import core as converters
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import trial as trial_


@dataclasses.dataclass
class PyCMAESDesigner(core_lib.Designer):
    """CMA-ES via pycma over a flat all-continuous search space."""

    problem: base_study_config.ProblemStatement
    sigma0: float = 0.1
    popsize: Optional[int] = None

    def __post_init__(self):
        if self.popsize is not None and self.popsize < 2:
            raise ValueError(f"popsize must be at least 2, got {self.popsize}.")
        space = self.problem.search_space
        if space.is_conditional:
            raise ValueError("PyCMAESDesigner requires a flat search space.")
        if len(self.problem.metric_information) != 1:
            raise ValueError("PyCMAESDesigner works with exactly one metric.")
        self._converter = converters.TrialToModelInputConverter.from_problem(
            self.problem
        )
        enc = self._converter.encoder
        if enc.num_categorical:
            raise ValueError(
                "PyCMAESDesigner supports continuous parameters only."
            )
        # Start point: per-parameter default value when set, else the bounds
        # midpoint — NATIVE frame, then through the converter's own codecs
        # so scale types (LOG/REVERSE_LOG) land in the same unit-cube frame
        # as the resume-fed features.
        init_params = {}
        for pc_ in space.parameters:
            lo, hi = pc_.bounds
            init_params[pc_.name] = (
                pc_.default_value
                if pc_.default_value is not None
                else (lo + hi) / 2.0
            )
        cont, _ = self._converter.encoder.encode(
            [trial_.Trial(id=0, parameters=init_params)]
        )
        self._x0 = np.asarray(cont[0], dtype=np.float64)
        self._completed: List[trial_.Trial] = []

    def update(
        self,
        completed: core_lib.CompletedTrials,
        all_active: core_lib.ActiveTrials = core_lib.ActiveTrials(),
    ) -> None:
        del all_active
        self._completed.extend(completed.trials)

    def _labels_for(self, trials: Sequence[trial_.Trial]) -> np.ndarray:
        """Maximization-signed labels, sign-flipped for pycma (minimizer)."""
        out = self._converter.metrics.encode(trials)[:, 0]
        return -np.asarray(out, dtype=np.float64)

    def suggest(
        self, count: Optional[int] = None
    ) -> List[trial_.TrialSuggestion]:
        try:
            import cma
        except ImportError as e:
            raise ImportError(
                "PyCMAESDesigner needs the external pycma package (absent "
                "from this image); use designers.cmaes.CMAESDesigner for the "
                "self-contained implementation."
            ) from e
        return self._suggest_with(cma, count)

    def _suggest_with(
        self, cma_module, count: Optional[int]
    ) -> List[trial_.TrialSuggestion]:
        """The full protocol against any module with pycma's surface."""
        count = count or 1
        options = {"bounds": [0.0, 1.0]}
        if self.popsize is not None:
            options["popsize"] = self.popsize
        evolution = cma_module.CMAEvolutionStrategy(
            self._x0, self.sigma0, options
        )
        # Infeasible / metric-missing trials encode to NaN labels, which
        # would poison pycma's covariance update — drop them before the
        # whole-generation truncation feed_for_resume requires.
        usable = (
            [
                t
                for t, label in zip(self._completed, self._labels_for(self._completed))
                if np.isfinite(label)
            ]
            if self._completed
            else []
        )
        feed_size = (len(usable) // evolution.popsize) * evolution.popsize
        if feed_size > 0:
            recent = usable[-feed_size:]
            features, _ = self._converter.encoder.encode(recent)
            evolution.feed_for_resume(
                np.asarray(features, dtype=np.float64),
                self._labels_for(recent),
            )
        asked = np.asarray(evolution.ask(count), dtype=np.float64)
        asked = np.clip(asked, 0.0, 1.0)
        empty_cat = np.zeros((len(asked), 0), dtype=np.int32)
        return [
            trial_.TrialSuggestion(parameters=params)
            for params in self._converter.to_parameters(asked, empty_cat)
        ]
