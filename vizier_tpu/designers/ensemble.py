"""Ensemble designers: multi-armed-bandit expert selection.

Parity with ``/root/reference/vizier/_src/algorithms/ensemble/``
(``ensemble_design.py:28+`` Random/EXP3/EXP3-IX/UCB designs +
``ensemble_designer.py`` wrapper): each suggestion round picks an expert
(inner designer) by a bandit rule over observed rewards; rewards default to
rank-normalized objective improvements.
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.converters import core as converters
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import trial as trial_

_NS = "ensemble"


class EnsembleDesign(abc.ABC):
    """Bandit over K experts: observe(arm, reward) / select(rng)."""

    def __init__(self, num_experts: int):
        self.num_experts = num_experts

    @abc.abstractmethod
    def observe(self, arm: int, reward: float) -> None:
        ...

    @abc.abstractmethod
    def select(self, rng: np.random.Generator) -> int:
        ...

    @property
    @abc.abstractmethod
    def probabilities(self) -> np.ndarray:
        ...


class RandomEnsembleDesign(EnsembleDesign):
    def observe(self, arm: int, reward: float) -> None:
        pass

    def select(self, rng: np.random.Generator) -> int:
        return int(rng.integers(0, self.num_experts))

    @property
    def probabilities(self) -> np.ndarray:
        return np.full(self.num_experts, 1.0 / self.num_experts)


class EXP3UniformEnsembleDesign(EnsembleDesign):
    """EXP3 with uniform exploration mixing."""

    def __init__(self, num_experts: int, *, learning_rate: float = 0.5, mix: float = 0.1):
        super().__init__(num_experts)
        self._lr = learning_rate
        self._mix = mix
        self._log_weights = np.zeros(num_experts)

    @property
    def probabilities(self) -> np.ndarray:
        w = np.exp(self._log_weights - self._log_weights.max())
        p = w / w.sum()
        return (1 - self._mix) * p + self._mix / self.num_experts

    def observe(self, arm: int, reward: float) -> None:
        p = self.probabilities[arm]
        self._log_weights[arm] += self._lr * reward / max(p, 1e-6)
        self._log_weights -= self._log_weights.max()  # stability

    def select(self, rng: np.random.Generator) -> int:
        return int(rng.choice(self.num_experts, p=self.probabilities))


class EXP3IXEnsembleDesign(EXP3UniformEnsembleDesign):
    """EXP3-IX: implicit exploration via a biased importance weight."""

    def __init__(self, num_experts: int, *, learning_rate: float = 0.5, gamma: float = 0.1):
        super().__init__(num_experts, learning_rate=learning_rate, mix=0.0)
        self._gamma = gamma

    def observe(self, arm: int, reward: float) -> None:
        p = self.probabilities[arm]
        self._log_weights[arm] += self._lr * reward / (p + self._gamma)
        self._log_weights -= self._log_weights.max()


class UCBEnsembleDesign(EnsembleDesign):
    def __init__(self, num_experts: int, *, exploration: float = 1.0):
        super().__init__(num_experts)
        self._counts = np.zeros(num_experts)
        self._sums = np.zeros(num_experts)
        self._exploration = exploration

    def observe(self, arm: int, reward: float) -> None:
        self._counts[arm] += 1
        self._sums[arm] += reward

    def select(self, rng: np.random.Generator) -> int:
        unseen = np.nonzero(self._counts == 0)[0]
        if len(unseen):
            return int(unseen[0])
        t = self._counts.sum()
        means = self._sums / self._counts
        ucb = means + self._exploration * np.sqrt(2 * np.log(t) / self._counts)
        return int(np.argmax(ucb))

    @property
    def probabilities(self) -> np.ndarray:
        p = np.zeros(self.num_experts)
        p[self.select(np.random.default_rng(0))] = 1.0
        return p


@dataclasses.dataclass
class EnsembleDesigner(core_lib.Designer):
    """Routes each suggestion round to a bandit-selected inner designer."""

    problem: base_study_config.ProblemStatement
    designers: Dict[str, core_lib.Designer] = dataclasses.field(default_factory=dict)
    design: Optional[EnsembleDesign] = None
    seed: Optional[int] = None

    def __post_init__(self):
        if not self.designers:
            raise ValueError("EnsembleDesigner needs at least one inner designer.")
        self._names = list(self.designers)
        if self.design is None:
            self.design = EXP3IXEnsembleDesign(len(self._names))
        self._rng = np.random.default_rng(self.seed)
        self._metrics = converters.MetricsEncoder(self.problem.metric_information)
        self._best = -np.inf

    def update(
        self,
        completed: core_lib.CompletedTrials,
        all_active: core_lib.ActiveTrials = core_lib.ActiveTrials(),
    ) -> None:
        for t in completed.trials:
            label = self._metrics.encode([t])[0, 0]
            expert_raw = t.metadata.ns(_NS).get("expert")
            if expert_raw in self.designers and np.isfinite(label):
                arm = self._names.index(expert_raw)
                # Reward: improvement over the incumbent, squashed to [0, 1].
                reward = 1.0 if label > self._best else 0.0
                self.design.observe(arm, reward)
            if np.isfinite(label):
                self._best = max(self._best, label)
        for designer in self.designers.values():
            designer.update(completed, all_active)

    def suggest(self, count: Optional[int] = None) -> List[trial_.TrialSuggestion]:
        count = count or 1
        arm = self.design.select(self._rng)
        name = self._names[arm]
        suggestions = list(self.designers[name].suggest(count))
        for s in suggestions:
            s.metadata.ns(_NS)["expert"] = name
        return suggestions
