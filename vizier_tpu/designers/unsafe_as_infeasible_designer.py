"""Safety wrapper: unsafe trials are shown to the inner designer as infeasible.

Parity with
``/root/reference/vizier/_src/algorithms/designers/unsafe_as_infeasible_designer.py:92``.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import multimetric
from vizier_tpu.pyvizier import trial as trial_


@dataclasses.dataclass
class UnsafeAsInfeasibleDesigner(core_lib.Designer):
    problem: base_study_config.ProblemStatement
    designer_factory: core_lib.DesignerFactory = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.designer_factory is None:
            raise ValueError("designer_factory is required.")
        self._checker = multimetric.SafetyChecker(self.problem.metric_information)
        self._inner = self.designer_factory(self.problem)

    def update(
        self,
        completed: core_lib.CompletedTrials,
        all_active: core_lib.ActiveTrials = core_lib.ActiveTrials(),
    ) -> None:
        rewritten = []
        for t in completed.trials:
            if self._checker.is_safe(t):
                rewritten.append(t)
            else:
                clone = trial_.Trial(
                    id=t.id, parameters=t.parameters, metadata=t.metadata
                )
                clone.complete(infeasibility_reason="Safety violation.")
                rewritten.append(clone)
        self._inner.update(core_lib.CompletedTrials(rewritten), all_active)

    def suggest(self, count: Optional[int] = None) -> List[trial_.TrialSuggestion]:
        return list(self._inner.suggest(count))
