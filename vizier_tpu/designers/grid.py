"""Grid-search designer (+ shuffled variant).

Parity with ``/root/reference/vizier/_src/algorithms/designers/grid.py:36``:
cross-product grid over the (flat) search space with a serialized position;
DOUBLE parameters are discretized to ``double_grid_resolution`` points.
"""

from __future__ import annotations

import json
from typing import List, Optional, Sequence

import numpy as np

from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import common
from vizier_tpu.pyvizier import parameter_config as pc
from vizier_tpu.pyvizier import trial as trial_
from vizier_tpu.utils import serializable


def _axis_values(
    config: pc.ParameterConfig, resolution: int
) -> List[pc.ParameterValueTypes]:
    if config.type == pc.ParameterType.DOUBLE:
        lo, hi = config.bounds
        if lo == hi:
            return [lo]
        from vizier_tpu.designers import random as random_designer

        return [
            random_designer.unit_to_double(config, u)
            for u in np.linspace(0.0, 1.0, resolution)
        ]
    return list(config.feasible_values)


class GridSearchDesigner(core_lib.PartiallySerializableDesigner):
    """Enumerates the grid in mixed-radix order from a stored position."""

    def __init__(
        self,
        search_space: pc.SearchSpace,
        *,
        shuffle_seed: Optional[int] = None,
        double_grid_resolution: int = 10,
    ):
        if search_space.is_conditional:
            raise ValueError("GridSearchDesigner requires a flat search space.")
        self._search_space = search_space
        self._configs = search_space.parameters
        self._axes = [
            _axis_values(c, double_grid_resolution) for c in self._configs
        ]
        self._size = int(np.prod([len(a) for a in self._axes])) if self._axes else 0
        self._position = 0
        self._shuffle_seed = shuffle_seed
        if shuffle_seed is not None and self._size > 0:
            rng = np.random.default_rng(shuffle_seed)
            self._order = rng.permutation(self._size)
        else:
            self._order = None

    @classmethod
    def from_problem(
        cls, problem: base_study_config.ProblemStatement, seed: Optional[int] = None
    ) -> "GridSearchDesigner":
        return cls(problem.search_space, shuffle_seed=seed)

    @property
    def grid_size(self) -> int:
        return self._size

    def update(self, completed, all_active=core_lib.ActiveTrials()) -> None:
        del completed, all_active

    def _point(self, flat_index: int) -> trial_.ParameterDict:
        if self._order is not None:
            flat_index = int(self._order[flat_index])
        params = trial_.ParameterDict()
        for config, axis in zip(self._configs, self._axes):
            flat_index, idx = divmod(flat_index, len(axis))
            params[config.name] = config.cast_value(axis[idx])
        return params

    def suggest(self, count: Optional[int] = None) -> List[trial_.TrialSuggestion]:
        count = count or 1
        out = []
        while len(out) < count and self._position < self._size:
            out.append(trial_.TrialSuggestion(parameters=self._point(self._position)))
            self._position += 1
        return out  # may be fewer than requested once the grid is exhausted

    # -- PartiallySerializable --------------------------------------------

    def dump(self) -> common.Metadata:
        md = common.Metadata()
        md["grid"] = json.dumps(
            {"position": self._position, "shuffle_seed": self._shuffle_seed}
        )
        return md

    def load(self, metadata: common.Metadata) -> None:
        raw = metadata.get("grid")
        if raw is None:
            raise serializable.DecodeError("Missing 'grid' key.")
        try:
            state = json.loads(raw)
            position = int(state["position"])
            shuffle_seed = state.get("shuffle_seed")
        except (ValueError, KeyError, TypeError) as e:
            raise serializable.DecodeError(f"Bad grid state: {e}")
        self._position = position
        # The stored order, not the constructor's, must govern the walk.
        if shuffle_seed != self._shuffle_seed:
            self._shuffle_seed = shuffle_seed
            if shuffle_seed is not None and self._size > 0:
                rng = np.random.default_rng(shuffle_seed)
                self._order = rng.permutation(self._size)
            else:
                self._order = None
