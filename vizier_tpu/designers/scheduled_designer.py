"""ScheduledDesigner: time-varying designer hyperparameters.

Parity with
``/root/reference/vizier/_src/algorithms/designers/scheduled_designer.py:253``
(+ ``scheduled_gp_bandit``): designer knobs follow exponential/linear
schedules over the expected trial budget, and the designer is rebuilt when
the scheduled values change.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Dict, List, Optional

from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import trial as trial_


@dataclasses.dataclass(frozen=True)
class ExponentialSchedule:
    init_value: float
    final_value: float
    rate: float = 1.0

    def __call__(self, progress: float) -> float:
        progress = min(max(progress, 0.0), 1.0)
        if self.init_value <= 0 or self.final_value <= 0:
            return self.init_value + (self.final_value - self.init_value) * progress
        log_v = math.log(self.init_value) + (
            math.log(self.final_value) - math.log(self.init_value)
        ) * (progress**self.rate)
        return math.exp(log_v)


@dataclasses.dataclass(frozen=True)
class LinearSchedule:
    init_value: float
    final_value: float

    def __call__(self, progress: float) -> float:
        progress = min(max(progress, 0.0), 1.0)
        return self.init_value + (self.final_value - self.init_value) * progress


@dataclasses.dataclass
class ScheduledDesigner(core_lib.Designer):
    """Rebuilds an inner designer with scheduled params as trials accrue.

    ``designer_factory(problem, **scheduled_params)`` is invoked whenever the
    scheduled values change; all completed trials are replayed into the new
    instance.
    """

    problem: base_study_config.ProblemStatement
    designer_factory: Callable[..., core_lib.Designer] = None  # type: ignore[assignment]
    scheduled_params: Dict[str, Callable[[float], float]] = dataclasses.field(
        default_factory=dict
    )
    expected_total_num_trials: int = 100
    # Rebuild (and replay all trials) only when a scheduled value moves by
    # more than this relative amount — continuous schedules would otherwise
    # rebuild on every suggest.
    rebuild_tolerance: float = 0.05

    def __post_init__(self):
        if self.designer_factory is None:
            raise ValueError("designer_factory is required.")
        self._all_completed: List[trial_.Trial] = []
        self._designer: Optional[core_lib.Designer] = None
        self._current_values: Optional[Dict[str, float]] = None

    def _progress(self) -> float:
        return len(self._all_completed) / max(self.expected_total_num_trials, 1)

    def _maybe_rebuild(self) -> core_lib.Designer:
        values = {
            name: schedule(self._progress())
            for name, schedule in self.scheduled_params.items()
        }
        changed = self._designer is None or any(
            abs(values[k] - self._current_values[k])
            > self.rebuild_tolerance * max(abs(self._current_values[k]), 1e-9)
            for k in values
        )
        if changed:
            self._designer = self.designer_factory(self.problem, **values)
            self._current_values = values
            if self._all_completed:
                self._designer.update(
                    core_lib.CompletedTrials(self._all_completed),
                    core_lib.ActiveTrials(),
                )
        return self._designer

    def update(
        self,
        completed: core_lib.CompletedTrials,
        all_active: core_lib.ActiveTrials = core_lib.ActiveTrials(),
    ) -> None:
        self._all_completed.extend(completed.trials)
        if self._designer is not None:
            self._designer.update(completed, all_active)

    def suggest(self, count: Optional[int] = None) -> List[trial_.TrialSuggestion]:
        return list(self._maybe_rebuild().suggest(count))


def scheduled_gp_ucb_pe(
    problem: base_study_config.ProblemStatement,
    *,
    expected_total_num_trials: int = 100,
    init_ucb: float = 2.5,
    final_ucb: float = 0.8,
    init_explore_ucb: float = 1.0,
    final_explore_ucb: float = 0.3,
    seed: Optional[int] = None,
) -> ScheduledDesigner:
    """DEFAULT algorithm with decaying UCB + explore-region coefficients.

    Parity with the reference ``scheduled_gp_ucb_pe`` preset: early trials
    explore (large confidence bounds, wide promising region), late trials
    exploit — a documented quality win over fixed coefficients on budgeted
    studies.
    """
    from vizier_tpu.designers import gp_ucb_pe

    def factory(p, ucb_coefficient, explore_region_ucb_coefficient):
        return gp_ucb_pe.VizierGPUCBPEBandit(
            p,
            rng_seed=seed or 0,
            config=gp_ucb_pe.UCBPEConfig(
                ucb_coefficient=round(ucb_coefficient, 2),
                explore_region_ucb_coefficient=round(
                    explore_region_ucb_coefficient, 2
                ),
            ),
        )

    return ScheduledDesigner(
        problem=problem,
        designer_factory=factory,
        scheduled_params={
            "ucb_coefficient": ExponentialSchedule(init_ucb, final_ucb),
            "explore_region_ucb_coefficient": ExponentialSchedule(
                init_explore_ucb, final_explore_ucb
            ),
        },
        expected_total_num_trials=expected_total_num_trials,
    )


def scheduled_gp_bandit(
    problem: base_study_config.ProblemStatement,
    *,
    expected_total_num_trials: int = 100,
    init_ucb: float = 2.5,
    final_ucb: float = 0.8,
    seed: Optional[int] = None,
) -> ScheduledDesigner:
    """GP bandit with a decaying UCB coefficient (explore → exploit)."""
    from vizier_tpu.designers import gp_bandit

    return ScheduledDesigner(
        problem=problem,
        designer_factory=lambda p, ucb_coefficient: gp_bandit.VizierGPBandit(
            p, ucb_coefficient=round(ucb_coefficient, 2), rng_seed=seed or 0
        ),
        scheduled_params={
            "ucb_coefficient": ExponentialSchedule(init_ucb, final_ucb)
        },
        expected_total_num_trials=expected_total_num_trials,
    )
