"""Eagle meta-learning preset: tune the firefly hyperparameters online.

Parity with
``/root/reference/vizier/_src/algorithms/designers/meta_learning/eagle_meta_learning.py:23``:
a log-scaled search space over the eagle strategy's own coefficients, plus a
factory that wires it into :class:`MetaLearningDesigner` so the firefly
coefficients are tuned on the user's objective instead of fixed at defaults.

The tuned set is the reference's: perturbation (+ lower bound), gravity,
negative gravity, continuous/categorical visibility, categorical
perturbation factor, pool-size factor. The reference's ``discrete_*`` and
``pure_categorical_perturbation`` knobs are absent because this rebuild
routes DISCRETE parameters through the categorical force model and has no
separate pure-categorical perturbation coefficient. FireflyConfig fields
outside the reference's tuned set (``max_perturbation``, ``explore_rate``,
``penalize_factor``, ``max_pool_size``) stay at their defaults, as they do
in the reference.
"""

from __future__ import annotations

from typing import Optional

from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.designers import eagle_strategy
from vizier_tpu.designers import meta_learning
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import parameter_config as pc


def meta_eagle_search_space() -> pc.SearchSpace:
    """Search space over the firefly coefficients (log-uniform, ref defaults)."""
    space = pc.SearchSpace()
    root = space.root
    root.add_float_param(
        "perturbation", 1e-4, 1e2, default_value=1e-1, scale_type=pc.ScaleType.LOG
    )
    root.add_float_param(
        "perturbation_lower_bound",
        1e-5,
        1e-1,
        default_value=1e-3,
        scale_type=pc.ScaleType.LOG,
    )
    root.add_float_param(
        "gravity", 1e-2, 1e2, default_value=1.0, scale_type=pc.ScaleType.LOG
    )
    root.add_float_param(
        "negative_gravity",
        2e-4,
        2.0,
        default_value=2e-2,
        scale_type=pc.ScaleType.LOG,
    )
    root.add_float_param(
        "visibility", 3e-2, 3e2, default_value=3.0, scale_type=pc.ScaleType.LOG
    )
    root.add_float_param(
        "categorical_visibility",
        2e-3,
        2e1,
        default_value=2e-1,
        scale_type=pc.ScaleType.LOG,
    )
    root.add_float_param(
        "categorical_perturbation_factor",
        2.5e-1,
        2.5e3,
        default_value=2.5e1,
        scale_type=pc.ScaleType.LOG,
    )
    root.add_float_param(
        "pool_size_factor", 1.0, 2.0, default_value=1.2, scale_type=pc.ScaleType.LOG
    )
    return space


def eagle_designer_factory(
    problem: base_study_config.ProblemStatement,
    *,
    seed: Optional[int] = None,
    **hyperparams: float,
) -> eagle_strategy.EagleStrategyDesigner:
    """Builds an eagle designer from meta-suggested coefficient values."""
    config = eagle_strategy.FireflyConfig(
        **{k: float(v) for k, v in hyperparams.items()}
    )
    return eagle_strategy.EagleStrategyDesigner(
        problem=problem, config=config, seed=seed
    )


def eagle_meta_learning_designer(
    problem: base_study_config.ProblemStatement,
    *,
    config: Optional[meta_learning.MetaLearningConfig] = None,
    meta_factory: Optional[core_lib.DesignerFactory] = None,
    seed: Optional[int] = None,
) -> meta_learning.MetaLearningDesigner:
    """The reference's eagle meta-learning setup as one call."""
    return meta_learning.MetaLearningDesigner(
        problem=problem,
        tuning_space=meta_eagle_search_space(),
        inner_factory=lambda p, **hp: eagle_designer_factory(p, seed=seed, **hp),
        meta_factory=meta_factory,
        config=config or meta_learning.MetaLearningConfig(),
        seed=seed,
    )
