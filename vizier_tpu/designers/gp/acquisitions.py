"""Acquisition functions and the trust region.

Parity with
``/root/reference/vizier/_src/algorithms/designers/gp/acquisitions.py``
(UCB/LCB/EI/PI/Sample at ``:177-300``, q-variants ``:496-569``, TrustRegion
``:691``), rebuilt as stateless jax functions over posterior (mean, stddev)
so they fuse into the vectorized optimizer's scoring graph on device.
All-MAXIMIZE convention (labels are pre-flipped by the converters).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Protocol, Tuple

import flax.struct
import jax
import jax.numpy as jnp

from vizier_tpu.models import gp as gp_lib
from vizier_tpu.models import kernels

Array = jax.Array

_NORM_CONST = 0.3989422804014327  # 1/sqrt(2*pi)


def _norm_pdf(z: Array) -> Array:
    return _NORM_CONST * jnp.exp(-0.5 * z * z)


def _norm_cdf(z: Array) -> Array:
    return 0.5 * (1.0 + jax.scipy.special.erf(z / jnp.sqrt(2.0)))


def get_best_labels(labels: Array, mask: Array) -> Array:
    """Per-metric maxima over valid rows; labels ``[..., N]``, mask ``[N]``."""
    return jnp.max(jnp.where(mask, labels, -jnp.inf), axis=-1)


def get_worst_labels(labels: Array, mask: Array) -> Array:
    """Per-metric minima over valid rows; labels ``[..., N]``, mask ``[N]``."""
    return jnp.min(jnp.where(mask, labels, jnp.inf), axis=-1)


def get_reference_point(labels: Array, mask: Array, scale: float = 0.1) -> Array:
    """Hypervolume reference point: nadir − scale·range.

    [Ishibuchi2011] find 0.1 a robust scaling of the nadir offset (reference
    ``acquisitions.py:132``). With no valid rows the point falls back to 0
    so downstream scalarizations stay finite.
    """
    best = get_best_labels(labels, mask)
    worst = get_worst_labels(labels, mask)
    # Floor the span at 1.0 (warped labels are ~N(0,1) scale): with all-equal
    # labels a ref point AT the nadir would clamp every hypervolume
    # scalarization to a flat 0, leaving the acquisition optimizer nothing
    # to discriminate on.
    span = jnp.maximum(best - worst, 1.0)
    ref = worst - scale * span
    return jnp.where(jnp.isfinite(ref), ref, 0.0)


class Acquisition(Protocol):
    def __call__(self, mean: Array, stddev: Array, best_label: Array) -> Array:
        ...


@flax.struct.dataclass
class UCB:
    """Upper confidence bound: mean + c·stddev."""

    coefficient: float = flax.struct.field(pytree_node=False, default=1.8)

    def __call__(self, mean: Array, stddev: Array, best_label: Array) -> Array:
        del best_label
        return mean + self.coefficient * stddev


@flax.struct.dataclass
class LCB:
    coefficient: float = flax.struct.field(pytree_node=False, default=1.8)

    def __call__(self, mean: Array, stddev: Array, best_label: Array) -> Array:
        del best_label
        return mean - self.coefficient * stddev


@flax.struct.dataclass
class EI:
    """Expected improvement over the best observed label."""

    def __call__(self, mean: Array, stddev: Array, best_label: Array) -> Array:
        z = (mean - best_label) / stddev
        return stddev * (z * _norm_cdf(z) + _norm_pdf(z))


@flax.struct.dataclass
class LogEI:
    """Numerically-robust log(EI); same argmax as EI, better gradients."""

    def __call__(self, mean: Array, stddev: Array, best_label: Array) -> Array:
        z = (mean - best_label) / stddev
        # log(s * h(z)), h(z) = z Φ(z) + φ(z), in three regimes. Direct
        # evaluation cancels catastrophically in f32 once z ≲ -2 (both terms
        # shrink to ~φ(z) while h ~ φ(z)/z²), so the mid range uses
        # h = φ(z)·(1 + z Φ(z)/φ(z)) via log1p — the cancellation then
        # happens on an O(1) ratio instead of two tiny near-equal terms —
        # and the deep tail (where Φ, φ underflow f32) uses the asymptotic
        # h ≈ φ(z)(z²-3)/z⁴. Each branch is computed on a clipped copy of z
        # so the unused branches stay finite under jnp.where gradients.
        c = 0.5 * jnp.log(2.0 * jnp.pi)
        log_s = jnp.log(stddev)

        zd = jnp.maximum(z, -1.5)  # direct: z > -1
        direct = jnp.log(zd * _norm_cdf(zd) + _norm_pdf(zd))

        # mills: -10 < z <= -1. The ratio z·Φ(z)/φ(z) ∈ (-1, 0) is formed in
        # log space (log_ndtr stays accurate where f32 Φ saturates to 0).
        zm = jnp.clip(z, -12.0, -0.5)
        log_phi_m = -0.5 * zm * zm - c
        t = jnp.log(-zm) + jax.scipy.special.log_ndtr(zm) - log_phi_m
        ratio = -jnp.exp(jnp.minimum(t, 0.0))
        mills = log_phi_m + jnp.log1p(jnp.maximum(ratio, -0.9999999))

        zt = jnp.minimum(z, -4.0)  # tail: z <= -10
        tail = -0.5 * zt * zt - c + jnp.log(zt * zt - 3.0) - 2.0 * jnp.log(zt * zt)

        return jnp.where(z > -1.0, direct, jnp.where(z > -10.0, mills, tail)) + log_s


@flax.struct.dataclass
class PI:
    """Probability of improvement."""

    def __call__(self, mean: Array, stddev: Array, best_label: Array) -> Array:
        return _norm_cdf((mean - best_label) / stddev)


@flax.struct.dataclass
class PE:
    """Pure exploration: maximize posterior stddev (GP-UCB-PE batches)."""

    def __call__(self, mean: Array, stddev: Array, best_label: Array) -> Array:
        del mean, best_label
        return stddev


@flax.struct.dataclass
class Sample:
    """Thompson sampling via one marginal posterior sample."""

    seed: Array

    def __call__(self, mean: Array, stddev: Array, best_label: Array) -> Array:
        del best_label
        eps = jax.random.normal(self.seed, mean.shape, dtype=mean.dtype)
        return mean + stddev * eps


def q_acquisition(
    per_member_means: Array,  # [E, M]
    per_member_stddevs: Array,  # [E, M]
    rng: Array,
    *,
    best_label: Array,
    num_samples: int = 32,
    kind: str = "qei",
) -> Array:
    """Monte-Carlo q-style score per point: E[max(improvement, 0)] etc.

    Used for parallel-batch (q) acquisitions: samples fantasize over member
    × posterior draws (parity with QEI/QUCB, ``acquisitions.py:496-569``).
    """
    e, m = per_member_means.shape
    eps = jax.random.normal(rng, (num_samples, e, m), dtype=per_member_means.dtype)
    draws = per_member_means[None] + per_member_stddevs[None] * eps  # [S, E, M]
    draws = draws.reshape(-1, m)
    if kind == "qei":
        return jnp.mean(jnp.maximum(draws - best_label, 0.0), axis=0)
    if kind == "qpi":
        return jnp.mean((draws > best_label).astype(draws.dtype), axis=0)
    if kind == "qucb":
        mean = jnp.mean(draws, axis=0)
        return mean + 1.8 * jnp.std(draws, axis=0)
    raise ValueError(f"Unknown q-acquisition {kind!r}.")


@flax.struct.dataclass
class TrustRegion:
    """L∞ trust region around observed points.

    Parity with the reference ``TrustRegion`` (``acquisitions.py:691``):
    candidates farther than the trust radius from every observed point are
    penalized linearly, pushing the acquisition argmax back toward explored
    space until enough trials justify global moves. The radius grows with
    the number of observed trials.
    """

    observed_continuous: Array  # [N, Dc] scaled features
    observed_cat: Array  # [N, Ds]
    row_mask: Array  # [N]
    min_radius: float = flax.struct.field(pytree_node=False, default=0.2)
    penalty_weight: float = flax.struct.field(pytree_node=False, default=30.0)

    @classmethod
    def from_data(cls, data: gp_lib.GPData, **kwargs) -> "TrustRegion":
        return cls(
            observed_continuous=data.continuous,
            observed_cat=data.categorical,
            row_mask=data.row_mask,
            **kwargs,
        )

    def trust_radius(self) -> Array:
        n = jnp.sum(self.row_mask.astype(jnp.float32))
        dim = self.observed_continuous.shape[-1] + self.observed_cat.shape[-1]
        # 0.2 → 1.0 as observations accumulate relative to dimension.
        grow = 0.1 * n / jnp.maximum(jnp.sqrt(jnp.asarray(dim, jnp.float32)), 1.0)
        return jnp.minimum(self.min_radius + grow * 0.05, 1.0)

    def linf_distance(self, query: kernels.MixedFeatures) -> Array:
        """[M] distance to the nearest valid observed point (L∞).

        CONTINUOUS dims only: the reference's ``min_linf_distance``
        (``acquisitions.py:758``) deliberately excludes categorical
        features from the trust-region distance — a mismatch would put
        every unobserved category at L∞ = 1 > radius, and the penalty
        would forbid exploring new categorical combinations outright (on a
        pure-categorical space the argmax then collapses onto observed
        cells).
        """
        qc = query.continuous
        if qc.shape[-1] == 0:
            return jnp.zeros(qc.shape[0], jnp.float32)
        dc = jnp.abs(qc[:, None, :] - self.observed_continuous[None, :, :])  # [M,N,Dc]
        linf = jnp.max(dc, axis=-1)  # [M, N]
        linf = jnp.where(self.row_mask[None, :], linf, jnp.inf)
        dist = jnp.min(linf, axis=-1)
        # No observations at all -> everything is trusted.
        return jnp.where(jnp.isfinite(dist), dist, 0.0)

    def penalty(self, query: kernels.MixedFeatures) -> Array:
        excess = jnp.maximum(self.linf_distance(query) - self.trust_radius(), 0.0)
        return self.penalty_weight * excess


@flax.struct.dataclass
class ScoringFunction:
    """Predictive + acquisition + optional trust region, as one callable.

    This is the function the vectorized optimizer maximizes on device; it is
    a pytree, so it can be donated/captured by jitted loops.
    """

    predictive: gp_lib.EnsemblePredictive
    acquisition: UCB  # any Acquisition pytree
    best_label: Array
    trust_region: Optional[TrustRegion] = None

    def score(self, query: kernels.MixedFeatures) -> Array:
        mean, stddev = self.predictive.predict(query)
        values = self.acquisition(mean, stddev, self.best_label)
        if self.trust_region is not None:
            values = values - self.trust_region.penalty(query)
        return values


@flax.struct.dataclass
class HVScalarizedScoring:
    """Multi-objective scoring: random-direction HV scalarization of UCB.

    Parity with the reference's multi-objective GP bandit path
    (``gp_bandit.py:213-242`` + ``create_hv_scalarization``,
    ``acquisitions.py:571``): per-metric UCB vectors are scalarized along K
    random positive directions and averaged — maximizing the expected
    hypervolume improvement direction-by-direction.
    """

    metric_states: gp_lib.GPState  # leading axis M (one GP per objective)
    directions: Array  # [K, M] positive unit vectors
    reference_point: Array  # [M]
    ucb_coefficient: float = flax.struct.field(pytree_node=False, default=1.8)
    trust_region: Optional[TrustRegion] = None

    def score(self, query: kernels.MixedFeatures) -> Array:
        means, stddevs = jax.vmap(lambda s: s.predict(query))(self.metric_states)
        ucb = means + self.ucb_coefficient * stddevs  # [M, Q]
        m = ucb.shape[0]
        shifted = jnp.maximum(ucb - self.reference_point[:, None], 0.0)  # [M, Q]
        # ratios[k, m, q] then min over m, ^M, mean over k.
        ratios = shifted[None, :, :] / jnp.maximum(self.directions[:, :, None], 1e-12)
        values = jnp.mean(jnp.min(ratios, axis=1) ** m, axis=0)  # [Q]
        if self.trust_region is not None:
            values = values - self.trust_region.penalty(query)
        return values


@flax.struct.dataclass
class MaxValueEntropySearch:
    """Max-value entropy search (MES) via Gumbel-sampled optimum values.

    Parity with the reference ``MaxValueEntropySearch``: approximates the
    mutual information between a candidate's observation and the (unknown)
    optimum value y*, with y* samples drawn from a Gumbel approximation to
    the max-posterior distribution.
    """

    y_star_samples: Array  # [S] sampled optimum values

    @classmethod
    def from_predictive(
        cls,
        predictive,
        observed: kernels.MixedFeatures,
        rng: Array,
        *,
        num_samples: int = 16,
    ) -> "MaxValueEntropySearch":
        mean, stddev = predictive.predict(observed)
        # Gumbel approximation: fit location/scale from the max of the
        # posterior marginals at observed points.
        upper = jnp.max(mean + 3.0 * stddev)
        lower = jnp.max(mean)
        scale = jnp.maximum((upper - lower) / 3.0, 1e-3)
        u = jax.random.uniform(
            rng, (num_samples,), minval=jnp.finfo(jnp.float32).tiny, maxval=1.0
        )
        gumbel = -jnp.log(-jnp.log(u))
        return cls(y_star_samples=lower + scale * gumbel)

    def __call__(self, mean: Array, stddev: Array, best_label: Array) -> Array:
        del best_label
        z = (self.y_star_samples[:, None] - mean[None, :]) / stddev[None, :]  # [S, Q]
        pdf = _norm_pdf(z)
        cdf = jnp.clip(_norm_cdf(z), 1e-9, 1.0 - 1e-9)
        # MI ≈ E_y*[ z φ(z) / (2 Φ(z)) − log Φ(z) ].
        return jnp.mean(z * pdf / (2.0 * cdf) - jnp.log(cdf), axis=0)
