"""Meta-learning designer: tunes a designer's own hyperparameters online.

Parity with
``/root/reference/vizier/_src/algorithms/designers/meta_learning/meta_learning.py:259``:
an outer (meta) designer proposes hyperparameter configs for the inner
designer factory; each config is scored by the objective progress achieved
during its tenure, and the meta designer is updated with those scores.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional

import numpy as np

from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.converters import core as converters
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import trial as trial_

META_METRIC = "meta_reward"


@dataclasses.dataclass
class MetaLearningConfig:
    """Reference ``MetaLearningConfig`` (``meta_learning.py:58``) semantics.

    The meta-learner runs through three phases by completed-trial count:
    INITIALIZE (below ``tuning_min_num_trials``: default hyperparams, gather
    signal), TUNE (between the thresholds: each meta round tries one
    hyperparameter config for ``tuning_interval`` trials and scores it), and
    USE_BEST_PARAMS (past ``tuning_max_num_trials``: lock in the best-scoring
    config — further exploration wastes suggestion budget).
    """

    tuning_interval: int = 100  # trials per meta round (num_trials_per_tuning)
    num_seed_rounds: int = 1
    tuning_min_num_trials: int = 3_000  # TUNE starts at this many completed
    tuning_max_num_trials: int = 10_000  # TUNE stops here → USE_BEST_PARAMS


class MetaLearningState:
    """Phase labels (reference ``MetaLearningState``)."""

    INITIALIZE = "INITIALIZE"
    TUNE = "TUNE"
    USE_BEST_PARAMS = "USE_BEST_PARAMS"


@dataclasses.dataclass
class MetaLearningDesigner(core_lib.Designer):
    """Outer loop tuning inner-designer hyperparameters.

    Args:
      problem: the user problem.
      tuning_space: search space over the inner designer's hyperparameters.
      inner_factory: (problem, **hyperparams) -> Designer.
      meta_factory: factory for the meta problem (defaults to random search).
    """

    problem: base_study_config.ProblemStatement
    tuning_space: base_study_config.pc.SearchSpace = None  # type: ignore[assignment]
    inner_factory: Callable[..., core_lib.Designer] = None  # type: ignore[assignment]
    meta_factory: Optional[core_lib.DesignerFactory] = None
    config: MetaLearningConfig = dataclasses.field(default_factory=MetaLearningConfig)
    seed: Optional[int] = None

    def __post_init__(self):
        if self.tuning_space is None or self.inner_factory is None:
            raise ValueError("tuning_space and inner_factory are required.")
        meta_problem = base_study_config.ProblemStatement(
            search_space=self.tuning_space,
            metric_information=base_study_config.MetricsConfig(
                [
                    base_study_config.MetricInformation(
                        name=META_METRIC,
                        goal=base_study_config.ObjectiveMetricGoal.MAXIMIZE,
                    )
                ]
            ),
        )
        if self.meta_factory is None:
            from vizier_tpu.designers import random as random_designer

            self.meta_factory = lambda p, **kw: random_designer.RandomDesigner(
                p.search_space, seed=self.seed
            )
        self._meta = self.meta_factory(meta_problem)
        self._metrics = converters.MetricsEncoder(self.problem.metric_information)
        self._current_hparams: Optional[trial_.TrialSuggestion] = None
        self._inner: Optional[core_lib.Designer] = None
        self._round_trials = 0
        self._round_best = -np.inf
        self._prev_best = -np.inf
        self._meta_trial_id = 0
        self._all_completed: List[trial_.Trial] = []
        self._meta_trials: List[trial_.Trial] = []  # scored hyperparam configs
        self._locked_best = False

    @property
    def state(self) -> str:
        n = len(self._all_completed)
        if self._locked_best or n >= self.config.tuning_max_num_trials:
            return MetaLearningState.USE_BEST_PARAMS
        if n < self.config.tuning_min_num_trials:
            return MetaLearningState.INITIALIZE
        return MetaLearningState.TUNE

    def _default_hparams(self) -> Dict:
        """Center/default point of the tuning space (INITIALIZE phase)."""
        return {
            cfg.name: cfg.first_feasible_value()
            for cfg in self.tuning_space.parameters
        }

    def _best_hparams(self) -> Dict:
        """Hyperparams of the best-scoring completed meta trial."""
        if not self._meta_trials:
            return self._default_hparams()
        best = max(
            self._meta_trials,
            key=lambda t: t.final_measurement.metrics[META_METRIC].value,
        )
        return {k: v.value for k, v in best.parameters.items()}

    def _start_fixed(self, hparams: Dict) -> None:
        """Builds the inner designer on fixed hyperparams (no meta round)."""
        self._current_hparams = None
        self._inner = self.inner_factory(self.problem, **hparams)
        if self._all_completed:
            self._inner.update(
                core_lib.CompletedTrials(self._all_completed),
                core_lib.ActiveTrials(),
            )
        self._round_trials = 0

    def _start_round(self) -> None:
        (suggestion,) = self._meta.suggest(1)
        self._current_hparams = suggestion
        hparams = {k: v.value for k, v in suggestion.parameters.items()}
        self._inner = self.inner_factory(self.problem, **hparams)
        if self._all_completed:
            self._inner.update(
                core_lib.CompletedTrials(self._all_completed), core_lib.ActiveTrials()
            )
        self._prev_best = max(self._prev_best, self._round_best)
        self._round_trials = 0
        self._round_best = -np.inf

    def _finish_round(self) -> None:
        """Scores the finished config by its improvement over the incumbent."""
        if self._current_hparams is None:
            return  # fixed-hyperparam tenure (INITIALIZE/USE_BEST), unscored
        if np.isfinite(self._prev_best) and np.isfinite(self._round_best):
            reward = float(self._round_best - self._prev_best)
        elif np.isfinite(self._round_best):
            # First round: no incumbent to improve over — neutral reward.
            reward = 0.0
        else:
            reward = 0.0
        self._meta_trial_id += 1
        t = self._current_hparams.to_trial(self._meta_trial_id)
        t.complete(trial_.Measurement(metrics={META_METRIC: reward}))
        self._meta_trials.append(t)
        self._meta.update(core_lib.CompletedTrials([t]), core_lib.ActiveTrials())

    def update(
        self,
        completed: core_lib.CompletedTrials,
        all_active: core_lib.ActiveTrials = core_lib.ActiveTrials(),
    ) -> None:
        self._all_completed.extend(completed.trials)
        for t in completed.trials:
            label = self._metrics.encode([t])[0, 0]
            if np.isfinite(label):
                self._round_best = max(self._round_best, float(label))
        self._round_trials += len(completed.trials)
        if self._inner is not None:
            self._inner.update(completed, all_active)

    def suggest(self, count: Optional[int] = None) -> List[trial_.TrialSuggestion]:
        state = self.state
        if state == MetaLearningState.USE_BEST_PARAMS:
            if not self._locked_best:
                # Transition: score the in-flight config, lock in the winner.
                self._finish_round()
                self._locked_best = True
                self._start_fixed(self._best_hparams())
        elif state == MetaLearningState.INITIALIZE:
            if self._inner is None:
                self._start_fixed(self._default_hparams())
        elif self._inner is None or self._current_hparams is None:
            # Entering TUNE (fresh, or leaving INITIALIZE).
            self._start_round()
        elif self._round_trials >= self.config.tuning_interval:
            self._finish_round()
            self._start_round()
        return list(self._inner.suggest(count))
