"""BOCS: Bayesian Optimization of Combinatorial Structures.

Parity with ``/root/reference/vizier/_src/algorithms/designers/bocs.py:531``
(Baptista & Poloczek 2018): a second-order Bayesian linear surrogate over
binary features with a Thompson-sampled coefficient draw, maximized over bit
vectors.

Two surrogates (``surrogate=``):
- ``"horseshoe"`` (default, reference parity): sparse Bayesian regression
  with the horseshoe prior, Gibbs-sampled via the Makalic–Schmidt (2015)
  auxiliary-variable hierarchy — second-order interaction coefficients are
  mostly near-zero in real combinatorial objectives, and the sparse prior
  recovers that structure from few samples.
- ``"ridge"``: the round-1 Bayesian ridge (kept for cheap smoke paths).

Two acquisition optimizers (``acquisition_optimizer=``):
- ``"sa"``: simulated annealing over bit flips (reference default).
- ``"sdp"``: spectral relaxation + randomized hyperplane rounding — a
  solver-free counterpart of the reference's cvxpy semidefinite rounding
  (same Goemans–Williamson rounding idea, using the relaxation's top
  eigenvectors instead of the exact SDP factor).
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import List, Optional

import numpy as np

from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.converters import core as converters
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import parameter_config as pc
from vizier_tpu.pyvizier import trial as trial_


def _binary_dim(space: pc.SearchSpace) -> int:
    total = 0
    for p in space.parameters:
        if p.type == pc.ParameterType.CATEGORICAL and p.num_feasible_values == 2:
            total += 1
        else:
            raise ValueError(
                "BOCSDesigner requires all parameters to be binary "
                f"(2-value categorical/bool); got {p.name} ({p.type})."
            )
    return total


def _horseshoe_gibbs(
    phi: np.ndarray,
    y: np.ndarray,
    rng: np.random.Generator,
    num_samples: int = 50,
) -> np.ndarray:
    """One horseshoe-posterior coefficient draw (last sample of a Gibbs run).

    Makalic & Schmidt (2015) auxiliary hierarchy: β|A ~ N(A⁻¹Φ'y, σ²A⁻¹)
    with A = Φ'Φ + diag(1/(τ²λ²)); λ²,ν,τ²,ξ inverse-gamma steps. The y mean
    is absorbed host-side so the intercept needs no shrinkage exception.
    """
    n, p = phi.shape
    mu_y = float(np.mean(y))
    y = y - mu_y

    def inv_gamma(shape, scale):
        return scale / rng.gamma(shape, 1.0, size=np.shape(scale))

    sigma2 = 1.0
    lambda2 = rng.uniform(size=p) + 1e-3
    tau2, xi = 1.0, 1.0
    nu = np.ones(p)
    ptp = phi.T @ phi
    beta = np.zeros(p)
    for _ in range(num_samples):
        # β | rest
        a = ptp + np.diag(1.0 / np.maximum(tau2 * lambda2, 1e-12))
        chol = np.linalg.cholesky(a + 1e-10 * np.eye(p))
        mean = np.linalg.solve(chol.T, np.linalg.solve(chol, phi.T @ y))
        z = rng.standard_normal(p)
        beta = mean + np.sqrt(sigma2) * np.linalg.solve(chol.T, z)
        # σ² | rest
        resid = y - phi @ beta
        shrink = np.sum(beta**2 / np.maximum(tau2 * lambda2, 1e-12))
        sigma2 = float(
            inv_gamma((n + p) / 2.0, (resid @ resid + shrink) / 2.0 + 1e-12)
        )
        # λ², ν | rest
        lambda2 = inv_gamma(
            1.0, 1.0 / nu + beta**2 / np.maximum(2.0 * tau2 * sigma2, 1e-12)
        )
        nu = inv_gamma(1.0, 1.0 + 1.0 / np.maximum(lambda2, 1e-12))
        # τ², ξ | rest
        tau2 = float(
            inv_gamma(
                (p + 1) / 2.0,
                1.0 / xi
                + np.sum(beta**2 / np.maximum(lambda2, 1e-12))
                / max(2.0 * sigma2, 1e-12),
            )
        )
        xi = float(inv_gamma(1.0, 1.0 + 1.0 / max(tau2, 1e-12)))
    out = beta.copy()
    # Re-inject the absorbed mean into the intercept coefficient (column 0
    # of phi is the all-ones feature).
    out[0] += mu_y
    return out


@dataclasses.dataclass
class BOCSDesigner(core_lib.Designer):
    problem: base_study_config.ProblemStatement
    num_restarts: int = 4
    anneal_steps: int = 200
    regularization: float = 1.0
    surrogate: str = "horseshoe"  # 'horseshoe' | 'ridge'
    acquisition_optimizer: str = "sa"  # 'sa' | 'sdp'
    gibbs_samples: int = 50
    seed: Optional[int] = None

    def __post_init__(self):
        self._dim = _binary_dim(self.problem.search_space)
        self._converter = converters.TrialToModelInputConverter.from_problem(
            self.problem
        )
        self._rng = np.random.default_rng(self.seed)
        self._pairs = list(itertools.combinations(range(self._dim), 2))
        self._x: List[np.ndarray] = []
        self._y: List[float] = []

    # -- features: [1, x, x_i x_j] -----------------------------------------

    def _phi(self, bits: np.ndarray) -> np.ndarray:
        bits = np.atleast_2d(bits)
        inter = np.stack(
            [bits[:, i] * bits[:, j] for i, j in self._pairs], axis=1
        ) if self._pairs else np.zeros((bits.shape[0], 0))
        return np.concatenate(
            [np.ones((bits.shape[0], 1)), bits, inter], axis=1
        )

    def update(
        self,
        completed: core_lib.CompletedTrials,
        all_active: core_lib.ActiveTrials = core_lib.ActiveTrials(),
    ) -> None:
        del all_active
        trials = list(completed.trials)
        if not trials:
            return
        _, cat = self._converter.encoder.encode(trials)
        labels = self._converter.metrics.encode(trials)[:, 0]
        for row, y in zip(cat, labels):
            if np.isfinite(y):
                self._x.append(row.astype(np.float64))
                self._y.append(float(y))

    def _sample_coefficients(self) -> np.ndarray:
        """Thompson draw: horseshoe Gibbs sample or Bayesian-ridge draw."""
        phi = self._phi(np.stack(self._x))
        y = np.asarray(self._y)
        if self.surrogate == "horseshoe":
            return _horseshoe_gibbs(phi, y, self._rng, self.gibbs_samples)
        if self.surrogate != "ridge":
            raise ValueError(f"Unknown surrogate {self.surrogate!r}.")
        d = phi.shape[1]
        precision = self.regularization * np.eye(d) + phi.T @ phi
        cov = np.linalg.inv(precision)
        mean = cov @ phi.T @ y
        noise = np.var(y - phi @ mean) + 1e-6
        chol = np.linalg.cholesky(noise * cov + 1e-10 * np.eye(d))
        return mean + chol @ self._rng.standard_normal(d)

    def _coef_to_quadratic(self, coef: np.ndarray):
        """Splits φ-space coefficients into (linear b [d], pair matrix Q)."""
        b = coef[1 : 1 + self._dim]
        q = np.zeros((self._dim, self._dim))
        for k, (i, j) in enumerate(self._pairs):
            q[i, j] = q[j, i] = coef[1 + self._dim + k] / 2.0
        return b, q

    def _sdp_round(self, coef: np.ndarray, num_rounds: int = 64) -> np.ndarray:
        """Spectral relaxation + randomized hyperplane rounding.

        Maximize b'x + x'Qx over x∈{0,1}^d via the ±1 substitution
        s = 2x − 1, relaxing the augmented quadratic form [[M, c/2],[c'/2, 0]]
        to its top eigenvectors and rounding random Gaussian combinations by
        sign — the Goemans–Williamson rounding step without an SDP solver.
        """
        b, q = self._coef_to_quadratic(coef)
        # f(x) over s: x = (1+s)/2 ⇒ quadratic M = Q/4, linear c = b/2 + Q·1/2.
        m = q / 4.0
        c = b / 2.0 + q.sum(axis=1) / 4.0
        aug = np.zeros((self._dim + 1, self._dim + 1))
        aug[: self._dim, : self._dim] = m
        aug[: self._dim, -1] = c / 2.0
        aug[-1, : self._dim] = c / 2.0
        w, v = np.linalg.eigh(aug)
        k = min(8, len(w))
        top = v[:, np.argsort(w)[-k:]] * np.sqrt(np.maximum(w[np.argsort(w)[-k:]], 0.0))
        best_bits, best_val = None, -np.inf
        for _ in range(num_rounds):
            r = self._rng.standard_normal(k)
            s = np.sign(top @ r)
            s[s == 0] = 1.0
            s = s[: self._dim] * s[-1]  # gauge-fix the homogenizing variable
            bits = (s + 1.0) / 2.0
            val = float((self._phi(bits) @ coef)[0])
            if val > best_val:
                best_bits, best_val = bits, val
        return best_bits

    def _anneal(self, coef: np.ndarray) -> np.ndarray:
        best_bits, best_val = None, -np.inf
        for _ in range(self.num_restarts):
            bits = self._rng.integers(0, 2, size=self._dim).astype(np.float64)
            val = float((self._phi(bits) @ coef)[0])
            temp = 1.0
            for step in range(self.anneal_steps):
                flip = self._rng.integers(0, self._dim)
                cand = bits.copy()
                cand[flip] = 1.0 - cand[flip]
                cand_val = float((self._phi(cand) @ coef)[0])
                if cand_val > val or self._rng.uniform() < np.exp(
                    (cand_val - val) / max(temp, 1e-8)
                ):
                    bits, val = cand, cand_val
                temp *= 0.97
            if val > best_val:
                best_bits, best_val = bits, val
        return best_bits

    def suggest(self, count: Optional[int] = None) -> List[trial_.TrialSuggestion]:
        count = count or 1
        out = []
        for _ in range(count):
            if len(self._x) < 2:
                bits = self._rng.integers(0, 2, size=self._dim)
            else:
                coef = self._sample_coefficients()
                if self.acquisition_optimizer == "sdp":
                    bits = self._sdp_round(coef)
                elif self.acquisition_optimizer == "sa":
                    bits = self._anneal(coef)
                else:
                    raise ValueError(
                        f"Unknown acquisition_optimizer "
                        f"{self.acquisition_optimizer!r}."
                    )
            params = self._converter.to_parameters(
                np.zeros((1, 0)), np.asarray(bits, dtype=np.int32)[None, :]
            )[0]
            out.append(trial_.TrialSuggestion(parameters=params))
        return out
