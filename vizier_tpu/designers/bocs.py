"""BOCS: Bayesian Optimization of Combinatorial Structures.

Parity with ``/root/reference/vizier/_src/algorithms/designers/bocs.py:531``
(Baptista & Poloczek 2018): a second-order Bayesian linear surrogate over
binary features with a Thompson-sampled coefficient draw, maximized by
simulated annealing over bit flips.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import List, Optional

import numpy as np

from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.converters import core as converters
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import parameter_config as pc
from vizier_tpu.pyvizier import trial as trial_


def _binary_dim(space: pc.SearchSpace) -> int:
    total = 0
    for p in space.parameters:
        if p.type == pc.ParameterType.CATEGORICAL and p.num_feasible_values == 2:
            total += 1
        else:
            raise ValueError(
                "BOCSDesigner requires all parameters to be binary "
                f"(2-value categorical/bool); got {p.name} ({p.type})."
            )
    return total


@dataclasses.dataclass
class BOCSDesigner(core_lib.Designer):
    problem: base_study_config.ProblemStatement
    num_restarts: int = 4
    anneal_steps: int = 200
    regularization: float = 1.0
    seed: Optional[int] = None

    def __post_init__(self):
        self._dim = _binary_dim(self.problem.search_space)
        self._converter = converters.TrialToModelInputConverter.from_problem(
            self.problem
        )
        self._rng = np.random.default_rng(self.seed)
        self._pairs = list(itertools.combinations(range(self._dim), 2))
        self._x: List[np.ndarray] = []
        self._y: List[float] = []

    # -- features: [1, x, x_i x_j] -----------------------------------------

    def _phi(self, bits: np.ndarray) -> np.ndarray:
        bits = np.atleast_2d(bits)
        inter = np.stack(
            [bits[:, i] * bits[:, j] for i, j in self._pairs], axis=1
        ) if self._pairs else np.zeros((bits.shape[0], 0))
        return np.concatenate(
            [np.ones((bits.shape[0], 1)), bits, inter], axis=1
        )

    def update(
        self,
        completed: core_lib.CompletedTrials,
        all_active: core_lib.ActiveTrials = core_lib.ActiveTrials(),
    ) -> None:
        del all_active
        trials = list(completed.trials)
        if not trials:
            return
        _, cat = self._converter.encoder.encode(trials)
        labels = self._converter.metrics.encode(trials)[:, 0]
        for row, y in zip(cat, labels):
            if np.isfinite(y):
                self._x.append(row.astype(np.float64))
                self._y.append(float(y))

    def _sample_coefficients(self) -> np.ndarray:
        """Thompson draw from the Bayesian ridge posterior."""
        phi = self._phi(np.stack(self._x))
        y = np.asarray(self._y)
        d = phi.shape[1]
        precision = self.regularization * np.eye(d) + phi.T @ phi
        cov = np.linalg.inv(precision)
        mean = cov @ phi.T @ y
        noise = np.var(y - phi @ mean) + 1e-6
        chol = np.linalg.cholesky(noise * cov + 1e-10 * np.eye(d))
        return mean + chol @ self._rng.standard_normal(d)

    def _anneal(self, coef: np.ndarray) -> np.ndarray:
        best_bits, best_val = None, -np.inf
        for _ in range(self.num_restarts):
            bits = self._rng.integers(0, 2, size=self._dim).astype(np.float64)
            val = float((self._phi(bits) @ coef)[0])
            temp = 1.0
            for step in range(self.anneal_steps):
                flip = self._rng.integers(0, self._dim)
                cand = bits.copy()
                cand[flip] = 1.0 - cand[flip]
                cand_val = float((self._phi(cand) @ coef)[0])
                if cand_val > val or self._rng.uniform() < np.exp(
                    (cand_val - val) / max(temp, 1e-8)
                ):
                    bits, val = cand, cand_val
                temp *= 0.97
            if val > best_val:
                best_bits, best_val = bits, val
        return best_bits

    def suggest(self, count: Optional[int] = None) -> List[trial_.TrialSuggestion]:
        count = count or 1
        out = []
        for _ in range(count):
            if len(self._x) < 2:
                bits = self._rng.integers(0, 2, size=self._dim)
            else:
                bits = self._anneal(self._sample_coefficients())
            params = self._converter.to_parameters(
                np.zeros((1, 0)), np.asarray(bits, dtype=np.int32)[None, :]
            )[0]
            out.append(trial_.TrialSuggestion(parameters=params))
        return out
