"""Harmonica: boolean Fourier-basis regression designer.

Parity with ``/root/reference/vizier/_src/algorithms/designers/harmonica.py:237``
(Hazan et al. 2017): fit a sparse low-degree Fourier expansion over {-1,+1}
features, fix the most influential variables to their best polarity, sample
the rest uniformly.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, List, Optional, Tuple

import numpy as np

from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.converters import core as converters
from vizier_tpu.designers.bocs import _binary_dim
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import trial as trial_


@dataclasses.dataclass
class HarmonicaDesigner(core_lib.Designer):
    problem: base_study_config.ProblemStatement
    degree: int = 2
    num_top_monomials: int = 5
    ridge: float = 1e-2
    seed: Optional[int] = None

    def __post_init__(self):
        self._dim = _binary_dim(self.problem.search_space)
        self._converter = converters.TrialToModelInputConverter.from_problem(
            self.problem
        )
        self._rng = np.random.default_rng(self.seed)
        self._monomials: List[Tuple[int, ...]] = []
        for deg in range(1, self.degree + 1):
            self._monomials.extend(itertools.combinations(range(self._dim), deg))
        self._x: List[np.ndarray] = []
        self._y: List[float] = []

    def _signs(self, bits: np.ndarray) -> np.ndarray:
        return 2.0 * np.atleast_2d(bits) - 1.0  # {0,1} -> {-1,+1}

    def _phi(self, bits: np.ndarray) -> np.ndarray:
        s = self._signs(bits)
        cols = [np.prod(s[:, list(mono)], axis=1) for mono in self._monomials]
        return np.stack(cols, axis=1) if cols else np.zeros((s.shape[0], 0))

    def update(
        self,
        completed: core_lib.CompletedTrials,
        all_active: core_lib.ActiveTrials = core_lib.ActiveTrials(),
    ) -> None:
        del all_active
        trials = list(completed.trials)
        if not trials:
            return
        _, cat = self._converter.encoder.encode(trials)
        labels = self._converter.metrics.encode(trials)[:, 0]
        for row, y in zip(cat, labels):
            if np.isfinite(y):
                self._x.append(row.astype(np.float64))
                self._y.append(float(y))

    def _fit_and_fix(self) -> Dict[int, int]:
        """Fits the Fourier model; returns {variable: fixed bit} decisions."""
        phi = self._phi(np.stack(self._x))
        y = np.asarray(self._y)
        y = y - y.mean()
        d = phi.shape[1]
        coef = np.linalg.solve(phi.T @ phi + self.ridge * np.eye(d), phi.T @ y)
        top = np.argsort(-np.abs(coef))[: self.num_top_monomials]
        # Influence of each variable: sum |coef| of monomials containing it.
        influence = np.zeros(self._dim)
        for idx in top:
            for var in self._monomials[idx]:
                influence[var] += abs(coef[idx])
        fixed_vars = [int(v) for v in np.argsort(-influence) if influence[v] > 0][:3]
        if not fixed_vars:
            return {}
        # Choose polarities greedily: evaluate the restricted surrogate on
        # all assignments of the fixed vars with the rest at random.
        best_assign, best_val = None, -np.inf
        probes = self._rng.integers(0, 2, size=(64, self._dim)).astype(np.float64)
        for assign in itertools.product([0.0, 1.0], repeat=len(fixed_vars)):
            probes_a = probes.copy()
            for var, bit in zip(fixed_vars, assign):
                probes_a[:, var] = bit
            val = float(np.mean(self._phi(probes_a) @ coef))
            if val > best_val:
                best_assign, best_val = assign, val
        return {var: int(bit) for var, bit in zip(fixed_vars, best_assign)}

    def suggest(self, count: Optional[int] = None) -> List[trial_.TrialSuggestion]:
        count = count or 1
        fixed: Dict[int, int] = {}
        if len(self._x) >= max(8, self._dim):
            fixed = self._fit_and_fix()
        out = []
        for _ in range(count):
            bits = self._rng.integers(0, 2, size=self._dim)
            for var, bit in fixed.items():
                bits[var] = bit
            params = self._converter.to_parameters(
                np.zeros((1, 0)), np.asarray(bits, dtype=np.int32)[None, :]
            )[0]
            out.append(trial_.TrialSuggestion(parameters=params))
        return out
