"""Harmonica: staged boolean Fourier-basis regression designer.

Parity with ``/root/reference/vizier/_src/algorithms/designers/harmonica.py:237``
(Hazan et al., "Hyperparameter Optimization: A Spectral Approach", 2017):
each *stage* fits a sparse (lasso) low-degree Fourier expansion over {-1,+1}
features of the samples drawn in that stage, identifies the most influential
variables, fixes them to their best polarity, and restarts sampling in the
restricted subcube — fixed sets accumulate across stages, shrinking the
search space geometrically (the reference's staged-restart structure that a
single global fit lacks).
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, List, Optional, Tuple

import numpy as np

from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.converters import core as converters
from vizier_tpu.designers.bocs import _binary_dim
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import trial as trial_


@dataclasses.dataclass
class HarmonicaDesigner(core_lib.Designer):
    problem: base_study_config.ProblemStatement
    degree: int = 2
    num_top_monomials: int = 5
    # Staged restarts: after `samples_per_stage` observations, fix
    # `num_fixed_per_stage` more variables and restart in the subcube.
    num_stages: int = 3
    samples_per_stage: Optional[int] = None  # default: max(8, dim)
    num_fixed_per_stage: int = 3
    lasso_alpha: float = 0.01
    seed: Optional[int] = None

    def __post_init__(self):
        self._dim = _binary_dim(self.problem.search_space)
        self._converter = converters.TrialToModelInputConverter.from_problem(
            self.problem
        )
        self._rng = np.random.default_rng(self.seed)
        self._monomials: List[Tuple[int, ...]] = []
        for deg in range(1, self.degree + 1):
            self._monomials.extend(itertools.combinations(range(self._dim), deg))
        if self.samples_per_stage is None:
            self.samples_per_stage = max(8, self._dim)
        self._fixed: Dict[int, int] = {}  # accumulated across stages
        self._stage = 0
        self._stage_x: List[np.ndarray] = []
        self._stage_y: List[float] = []

    def _signs(self, bits: np.ndarray) -> np.ndarray:
        return 2.0 * np.atleast_2d(bits) - 1.0  # {0,1} -> {-1,+1}

    def _phi(self, bits: np.ndarray) -> np.ndarray:
        s = self._signs(bits)
        cols = [np.prod(s[:, list(mono)], axis=1) for mono in self._monomials]
        return np.stack(cols, axis=1) if cols else np.zeros((s.shape[0], 0))

    def update(
        self,
        completed: core_lib.CompletedTrials,
        all_active: core_lib.ActiveTrials = core_lib.ActiveTrials(),
    ) -> None:
        del all_active
        trials = list(completed.trials)
        if not trials:
            return
        _, cat = self._converter.encoder.encode(trials)
        labels = self._converter.metrics.encode(trials)[:, 0]
        for row, y in zip(cat, labels):
            if np.isfinite(y):
                self._stage_x.append(row.astype(np.float64))
                self._stage_y.append(float(y))

    def _fit_coefficients(self, phi: np.ndarray, y: np.ndarray) -> np.ndarray:
        """Sparse Fourier coefficients (lasso; ridge only without sklearn)."""
        try:
            from sklearn import linear_model
        except ImportError:
            d = phi.shape[1]
            return np.linalg.solve(phi.T @ phi + 1e-2 * np.eye(d), phi.T @ y)
        model = linear_model.Lasso(
            alpha=self.lasso_alpha, fit_intercept=False, max_iter=2000
        )
        model.fit(phi, y)  # genuine fit errors must surface, not degrade
        return np.asarray(model.coef_, dtype=np.float64)

    def _advance_stage(self) -> None:
        """Fits this stage's samples; fixes the top free variables."""
        phi = self._phi(np.stack(self._stage_x))
        y = np.asarray(self._stage_y)
        y = y - y.mean()
        coef = self._fit_coefficients(phi, y)
        top = np.argsort(-np.abs(coef))[: self.num_top_monomials]
        # Influence of each FREE variable: sum |coef| over monomials using it.
        influence = np.zeros(self._dim)
        for idx in top:
            for var in self._monomials[idx]:
                if var not in self._fixed:
                    influence[var] += abs(coef[idx])
        candidates = [
            int(v) for v in np.argsort(-influence) if influence[v] > 0
        ][: self.num_fixed_per_stage]
        if candidates:
            # Best polarity: evaluate the surrogate with the candidates set to
            # each assignment and the remaining free vars sampled uniformly.
            probes = self._rng.integers(0, 2, size=(64, self._dim)).astype(
                np.float64
            )
            for var, bit in self._fixed.items():
                probes[:, var] = bit
            best_assign, best_val = None, -np.inf
            for assign in itertools.product([0.0, 1.0], repeat=len(candidates)):
                probes_a = probes.copy()
                for var, bit in zip(candidates, assign):
                    probes_a[:, var] = bit
                val = float(np.mean(self._phi(probes_a) @ coef))
                if val > best_val:
                    best_assign, best_val = assign, val
            for var, bit in zip(candidates, best_assign):
                self._fixed[var] = int(bit)
        # Restart: next stage samples fresh in the restricted subcube.
        self._stage += 1
        self._stage_x, self._stage_y = [], []

    def suggest(self, count: Optional[int] = None) -> List[trial_.TrialSuggestion]:
        count = count or 1
        if (
            self._stage < self.num_stages
            and len(self._stage_x) >= self.samples_per_stage
            and len(self._fixed) < self._dim
        ):
            self._advance_stage()
        out = []
        for _ in range(count):
            bits = self._rng.integers(0, 2, size=self._dim)
            for var, bit in self._fixed.items():
                bits[var] = bit
            params = self._converter.to_parameters(
                np.zeros((1, 0)), np.asarray(bits, dtype=np.int32)[None, :]
            )[0]
            out.append(trial_.TrialSuggestion(parameters=params))
        return out
