"""ScalarizingDesigner: multi-objective → single-objective reduction.

Parity with
``/root/reference/vizier/_src/algorithms/designers/scalarizing_designer.py:138``:
wraps any single-objective designer factory; completed trials get a
synthetic scalarized metric and the inner designer optimizes that.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.converters import core as converters
from vizier_tpu.designers import scalarization as scalarization_lib
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import trial as trial_

SCALARIZED_METRIC = "scalarized"


@dataclasses.dataclass
class ScalarizingDesigner(core_lib.Designer):
    problem: base_study_config.ProblemStatement
    scalarization: scalarization_lib.Scalarization = None  # type: ignore[assignment]
    designer_factory: Optional[core_lib.DesignerFactory] = None
    seed: Optional[int] = None

    def __post_init__(self):
        metrics = [
            m for m in self.problem.metric_information if not m.is_safety_metric
        ]
        self._num_objectives = len(metrics)
        if self.scalarization is None:
            self.scalarization = scalarization_lib.ChebyshevScalarization(
                weights=tuple([1.0 / self._num_objectives] * self._num_objectives)
            )
        self._metrics_encoder = converters.MetricsEncoder(
            base_study_config.MetricsConfig(metrics)
        )
        inner_problem = base_study_config.ProblemStatement(
            search_space=self.problem.search_space,
            metric_information=base_study_config.MetricsConfig(
                [
                    base_study_config.MetricInformation(
                        name=SCALARIZED_METRIC,
                        goal=base_study_config.ObjectiveMetricGoal.MAXIMIZE,
                    )
                ]
            ),
        )
        if self.designer_factory is None:
            from vizier_tpu.designers import gp_bandit

            self.designer_factory = lambda p, **kw: gp_bandit.VizierGPBandit(
                p, rng_seed=self.seed or 0
            )
        self._inner = self.designer_factory(inner_problem)

    def update(
        self,
        completed: core_lib.CompletedTrials,
        all_active: core_lib.ActiveTrials = core_lib.ActiveTrials(),
    ) -> None:
        rewritten = []
        for t in completed.trials:
            objectives = self._metrics_encoder.encode([t])[0]  # all-MAXIMIZE
            clone = trial_.Trial(id=t.id, parameters=t.parameters, metadata=t.metadata)
            if np.all(np.isfinite(objectives)):
                value = float(self.scalarization(jnp.asarray(objectives)))
                clone.complete(
                    trial_.Measurement(metrics={SCALARIZED_METRIC: value})
                )
            else:
                clone.complete(infeasibility_reason=t.infeasibility_reason or "NaN")
            rewritten.append(clone)
        self._inner.update(core_lib.CompletedTrials(rewritten), all_active)

    def suggest(self, count: Optional[int] = None) -> List[trial_.TrialSuggestion]:
        return list(self._inner.suggest(count))
