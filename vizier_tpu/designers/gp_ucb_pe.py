"""VizierGPUCBPEBandit: the DEFAULT algorithm (GP-UCB with Pure Exploration).

Parity with ``/root/reference/vizier/_src/algorithms/designers/gp_ucb_pe.py``
(config ``:80``, score functions ``:282,384,510``, designer ``:609`` — the
service default, ``policy_factory.py:40-47``; algorithm from Contal et al.,
"Parallel Gaussian Process Optimization with UCB and Pure Exploration"):

- Two conditioned posteriors: ``completed`` (observed labels) and ``all``
  (completed + pending/active + already-picked batch points, labels ignored
  — only the stddev matters, and GP posterior stddev is label-free).
- **UCB score** = mean(completed) + c·stddev(all): pending points deflate
  the stddev so concurrent workers do not duplicate suggestions.
- **PE score** = stddev(all) + penalty·min(explore_ucb − threshold, 0) where
  the threshold is the completed-posterior *mean at the argmax-UCB point*
  over observed+pending features, and explore_ucb uses its own (smaller)
  coefficient — pure exploration restricted to the promising region.
- **UCB/PE choice** per pick: fresh completed trials → UCB except w.p.
  ``pe_overwrite_probability`` (raised in the high-noise regime detected by
  the signal-to-noise threshold); otherwise PE except w.p.
  ``ucb_overwrite_probability``. Within a batch, picks after the first see
  the earlier picks as pending, so they explore.
- **Multimetric**: per-metric independent GPs; UCB hypervolume-scalarized
  along random directions (clamped at the observed labels' scalarization);
  PE penalty scalarized by union/intersection/average across metrics.
- **Set acquisition** (optional): the PE batch is optimized *jointly* —
  log-det of the batch posterior covariance — instead of greedily.

TPU-first: the WHOLE batch loop — per-pick Cholesky re-conditioning on the
growing pending set, penalty, and the eagle acquisition sweep — is one
jitted ``fori_loop``; picks are written into spare padded rows (no reshapes
or retraces within a padding bucket), and ensemble members × metrics are
``vmap``-batched Cholesky factorizations that XLA maps onto the MXU.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from vizier_tpu import types
from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.compute import ir as compute_ir
from vizier_tpu.compute import registry as compute_registry
from vizier_tpu.designers import gp_bandit
from vizier_tpu.surrogates import config as surrogate_config_lib
from vizier_tpu.surrogates import sparse_bandit
from vizier_tpu.surrogates import sparse_gp
from vizier_tpu.designers.gp import acquisitions
from vizier_tpu.models import gp as gp_lib
from vizier_tpu.models import kernels
from vizier_tpu.models import multitask_gp as mtgp
from vizier_tpu.models import output_warpers
from vizier_tpu.observability import jax_timing
from vizier_tpu.optimizers import eagle as eagle_lib
from vizier_tpu.optimizers import vectorized as vectorized_lib
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import trial as trial_
from vizier_tpu.utils import profiler

Array = jax.Array

# Re-export: `UCBPEConfig(multitask_type=MultiTaskType.SEPARABLE)` matches
# the reference's `UCBPEConfig.multitask_type` (gp_ucb_pe.py:130-134).
MultiTaskType = mtgp.MultiTaskType

_PE_NOISE_STDDEV = 1e-5  # noise floor for the all-predictive in high noise


@dataclasses.dataclass(frozen=True)
class UCBPEConfig:
    """UCB-PE config (reference ``UCBPEConfig``, ``gp_ucb_pe.py:80-132``).

    Frozen/hashable so it rides into jitted programs as a static argument.
    """

    ucb_coefficient: float = 1.8
    # A separate (smaller) coefficient defining the region worth exploring.
    explore_region_ucb_coefficient: float = 0.5
    # Slope of the linear penalty for violating UCB(x) >= threshold.
    cb_violation_penalty_coefficient: float = 10.0
    # P(UCB) when there are NO new completed trials.
    ucb_overwrite_probability: float = 0.25
    # P(PE) when there ARE new completed trials.
    pe_overwrite_probability: float = 0.1
    # Same, in the detected-high-noise regime.
    pe_overwrite_probability_in_high_noise: float = 0.7
    # signal/noise variance ratio below which noise is considered high
    # (0 disables the high-noise behaviors).
    signal_to_noise_threshold: float = 0.7
    # Optimize the exploration batch jointly (log-det set acquisition).
    optimize_set_acquisition_for_exploration: bool = False
    # Multimetric promising-region penalty: union | intersection | average.
    multimetric_promising_region_penalty_type: str = "average"
    # Random HV-scalarization directions for multimetric UCB.
    num_scalarizations: int = 1000
    # Multimetric GP structure (reference ``UCBPEConfig.multitask_type``,
    # ``gp_ucb_pe.py:130-134``): INDEPENDENT trains one GP per metric; the
    # SEPARABLE* variants train a single GP with a learned task-covariance B
    # over a B ⊗ Kx Kronecker Gram, sharing statistical strength across
    # metrics. SEPARABLE (= SEPARABLE_NORMAL) is a free signed Cholesky;
    # SEPARABLE_LKJ uses an LKJ-prior correlation factor; SEPARABLE_DIAG a
    # diagonal B (see ``models.multitask_gp``).
    multitask_type: mtgp.MultiTaskType = mtgp.MultiTaskType.INDEPENDENT

    def __post_init__(self):
        if self.multimetric_promising_region_penalty_type not in (
            "union",
            "intersection",
            "average",
        ):
            raise ValueError(
                "multimetric_promising_region_penalty_type must be one of "
                "'union' | 'intersection' | 'average', got "
                f"{self.multimetric_promising_region_penalty_type!r}."
            )
        if not isinstance(self.multitask_type, mtgp.MultiTaskType):
            raise ValueError(
                f"multitask_type must be a MultiTaskType, got "
                f"{self.multitask_type!r}."
            )


def _mixture_predict(
    states, query: kernels.MixedFeatures
) -> Tuple[Array, Array]:
    """Moment-matched mixture over the ensemble axis, per metric.

    ``states``: GPState pytree with leading axes [M, E]. Returns
    ([M, Q] mean, [M, Q] stddev).
    """
    means, stddevs = jax.vmap(jax.vmap(lambda s: s.predict(query)))(states)
    mean = jnp.mean(means, axis=1)
    second = jnp.mean(stddevs**2 + means**2, axis=1)
    var = jnp.maximum(second - mean**2, 1e-12)
    return mean, jnp.sqrt(var)


def _mt_mixture_predict(
    states: "mtgp.MultiTaskGPState", query: kernels.MixedFeatures
) -> Tuple[Array, Array]:
    """Moment-matched mixture over the ensemble axis for a multitask state.

    ``states``: MultiTaskGPState pytree with leading axis [E]; its
    ``predict`` is per-task already. Returns ([M, Q] mean, [M, Q] stddev) —
    the same contract as :func:`_mixture_predict`.
    """
    means, stddevs = jax.vmap(lambda s: s.predict(query))(states)  # [E, M, Q]
    mean = jnp.mean(means, axis=0)
    second = jnp.mean(stddevs**2 + means**2, axis=0)
    var = jnp.maximum(second - mean**2, 1e-12)
    return mean, jnp.sqrt(var)


def _pe_conditioning(
    states_completed,  # GPState [M, E] or MultiTaskGPState [E]
    all_data,  # GPData or MultiTaskData
    config: UCBPEConfig,
    *,
    mixture=None,
    base_data=None,
    snr=None,
) -> Tuple[dict, Array, Array]:
    """(pe_params, noise_is_high, threshold[M]): shared UCB-PE conditioning.

    - High-noise detection: all ensemble members' signal/noise variance
      ratios below the config threshold → the all-points predictive gets a
      near-zero noise floor so pending points fully deflate local stddev.
      ``snr`` overrides the default scalar amplitude²/noise² ratio (the
      multitask path scales signal by the learned task covariance diag).
    - Promising-region threshold: completed-posterior mean at the
      argmax-UCB point among observed + pending features, per metric.
    """
    mixture = mixture or _mixture_predict
    base = base_data(all_data) if base_data is not None else all_data
    params = states_completed.params  # constrained, [M, E] (or [E]) leaves
    if snr is None:
        snr = (params["amplitude"] / params["noise_stddev"]) ** 2
    noise_is_high = jnp.all(snr < config.signal_to_noise_threshold) & (
        config.signal_to_noise_threshold > 0.0
    )
    pe_params = dict(params)
    pe_params["noise_stddev"] = jnp.where(
        noise_is_high, _PE_NOISE_STDDEV, params["noise_stddev"]
    )
    all_pts = base.features()
    mean_at, std_at = mixture(states_completed, all_pts)  # [M, N2]
    ucb_at = jnp.where(
        base.row_mask[None, :],
        mean_at + config.ucb_coefficient * std_at,
        -jnp.inf,
    )
    threshold = jnp.take_along_axis(
        mean_at, jnp.argmax(ucb_at, axis=-1, keepdims=True), axis=-1
    )[:, 0]  # [M]
    return pe_params, noise_is_high, threshold


def _append_row(
    data: gp_lib.GPData, x: kernels.MixedFeatures
) -> gp_lib.GPData:
    """Writes x into the first free padded row (labels stay 0: stddev-only)."""
    idx = jnp.sum(data.row_mask.astype(jnp.int32))  # first free slot
    return gp_lib.GPData(
        continuous=data.continuous.at[idx].set(x.continuous[0]),
        categorical=data.categorical.at[idx].set(x.categorical[0]),
        labels=data.labels,
        row_mask=data.row_mask.at[idx].set(True),
        cont_dim_mask=data.cont_dim_mask,
        cat_dim_mask=data.cat_dim_mask,
    )


def _append_row_mt(
    data: "mtgp.MultiTaskData", x: kernels.MixedFeatures
) -> "mtgp.MultiTaskData":
    """Multitask pending-point append: every task observes the new row."""
    fd = data.features_data
    idx = jnp.sum(fd.row_mask.astype(jnp.int32))
    return mtgp.MultiTaskData(
        features_data=_append_row(fd, x),
        task_labels=data.task_labels,
        task_mask=data.task_mask.at[:, idx].set(True),
    )


# A pick whose Nyström residual k** − ‖L⁻¹k(Z,x)‖² exceeds this fraction of
# the prior variance is "not near an inducing row": the base inducing set
# carries (almost) no information at x, so conditioning through it would
# barely deflate the local stddev and the PE score would re-pick the same
# point for the rest of the batch. Such picks join the inducing set.
_NYSTROM_RESIDUAL_FRACTION = 0.1


def _append_row_sparse(
    sdata: "sparse_gp.SparseGPData",
    x: kernels.MixedFeatures,
    ref_state: "sparse_gp.SparseGPState",
) -> "sparse_gp.SparseGPData":
    """Sparse pending-pick conditioning: append + conditional Nyström augment.

    The pick always joins the all-points data rows (so ``A`` gains a
    column and the inducing posterior's stddev deflates near it, exactly
    like the exact path's pending rows). When the pick is NOT near an
    inducing row — measured by its Nyström residual under ``ref_state``,
    the trained completed-posterior's member-0 factorization — it is also
    written into the next spare (masked-off) inducing slot reserved by
    :func:`sparse_gp.with_pending_capacity`, restoring the variance
    deflation the inducing bottleneck would otherwise swallow. Traceable:
    fixed shapes, pure ``at[].set`` writes.
    """
    data = _append_row(sdata.data, x)
    # Residual vs the BASE inducing set (amp² − ‖L⁻¹k(Z,x)‖² at member 0).
    kz = ref_state.model.base._kernel(
        ref_state.params, x, ref_state.sdata.z_features(), ref_state.sdata.data
    )  # [1, m]
    kz = jnp.where(ref_state.sdata.inducing_mask[None, :], kz, 0.0)
    t1 = ref_state.linv @ kz[0]
    amp2 = ref_state.params["amplitude"] * ref_state.params["amplitude"]
    residual = amp2 - jnp.sum(t1 * t1)
    augment = residual > _NYSTROM_RESIDUAL_FRACTION * amp2
    # Masks stay a true-prefix (k-center fills a prefix; augments extend
    # it), so the next free slot is the current true count.
    idx = jnp.sum(sdata.inducing_mask.astype(jnp.int32))
    idx = jnp.minimum(idx, sdata.inducing_mask.shape[0] - 1)
    write = augment & ~sdata.inducing_mask[idx]
    z_cont = sdata.z_continuous.at[idx].set(
        jnp.where(write, x.continuous[0], sdata.z_continuous[idx])
    )
    z_cat = sdata.z_categorical.at[idx].set(
        jnp.where(write, x.categorical[0], sdata.z_categorical[idx])
    )
    mask = sdata.inducing_mask.at[idx].set(sdata.inducing_mask[idx] | write)
    return sparse_gp.SparseGPData(
        data=data,
        z_continuous=z_cont,
        z_categorical=z_cat,
        inducing_mask=mask,
        inducing_indices=sdata.inducing_indices,
    )


def _hv_scalarized(
    values: Array,  # [M, Q] per-metric acquisition values
    weights: Array,  # [K, M] positive scalarization directions
    ref_point: Array,  # [M]
    labels: Array,  # [M, N] warped labels (completed)
    labels_mask: Array,  # [N]
) -> Array:
    """Random-direction hypervolume scalarization, clamped at the labels.

    Reference ``UCBScoreFunction.score_with_aux`` + ``create_hv_scalarization``
    (``acquisitions.py:571``, https://arxiv.org/abs/2006.04655): scalarize
    per direction as min_m((v_m - ref_m)/w_m)^M, floor each direction at the
    best scalarized observed label, then average over directions.
    """
    m = values.shape[0]
    inv_w = 1.0 / jnp.maximum(weights, 1e-6)  # [K, M]
    shifted = jnp.maximum(values - ref_point[:, None], 0.0)  # [M, Q]
    per_dir = jnp.min(inv_w[:, :, None] * shifted[None, :, :], axis=1) ** m  # [K, Q]
    lab_shifted = jnp.maximum(labels - ref_point[:, None], 0.0)  # [M, N]
    lab_per_dir = jnp.min(inv_w[:, :, None] * lab_shifted[None, :, :], axis=1) ** m
    lab_best = jnp.max(
        jnp.where(labels_mask[None, :], lab_per_dir, -jnp.inf), axis=-1
    )  # [K]
    return jnp.mean(jnp.maximum(per_dir, lab_best[:, None]), axis=0)  # [Q]


def _scalarize_penalty(penalty: Array, mode: str) -> Array:
    """[M, Q] per-metric promising-region penalties → [Q] (reference modes)."""
    if mode == "union":
        return jnp.max(penalty, axis=0)
    if mode == "intersection":
        return jnp.min(penalty, axis=0)
    return jnp.mean(penalty, axis=0)


@functools.partial(
    jax.jit,
    static_argnames=(
        "model", "vec_opt", "count", "config", "use_trust_region", "mesh",
        "prior_acquisition",
    ),
)
def _suggest_batch(
    model,  # VizierGaussianProcess or MultiTaskGaussianProcess (static)
    vec_opt: vectorized_lib.VectorizedOptimizer,
    states_completed,  # GPState [M, E], or MultiTaskGPState [E]
    all_data,  # GPData/MultiTaskData: completed+active rows valid; labels 0
    labels_mn: Array,  # [M, N1] warped labels of the completed data
    labels_mask: Array,  # [N1]
    ref_point: Array,  # [M]
    prior_features: kernels.MixedFeatures,
    rng: Array,
    first_has_new: Array,  # scalar bool: new completed since last active
    has_completed: Array,  # scalar bool
    count: int,
    config: UCBPEConfig,
    use_trust_region: bool = True,
    mesh=None,  # jax.sharding.Mesh: shard the per-pick sweep's eagle pools
    prior_acquisition=None,  # Callable[[MixedFeatures], [Q]-array] user prior
) -> Tuple[vectorized_lib.VectorizedOptimizerResult, dict]:
    """The greedy batch: per pick, UCB-or-PE with pending-point conditioning."""
    # Static dispatch: the multitask (SEPARABLE) and sparse (SGPR) paths
    # swap the posterior ops; every acquisition formula below is shared.
    is_mt = isinstance(model, mtgp.MultiTaskGaussianProcess)
    is_sparse = isinstance(model, sparse_gp.SparseGaussianProcess)
    if is_sparse:
        # Pending-pick conditioning through the inducing-point posterior:
        # ``all_data`` is a SparseGPData (completed+active rows + the
        # trained Z with spare augment slots); re-conditioning rebuilds the
        # O(n·m²) SGPR factorization on the grown pending set instead of
        # the exact path's O(n³) per-pick Cholesky. ``model`` is the
        # augmented-capacity SparseGaussianProcess (m + count slots).
        mixture = _mixture_predict  # SparseGPState duck-types .predict
        base_data = lambda d: d.data  # noqa: E731
        member0 = jax.tree_util.tree_map(lambda a: a[0, 0], states_completed)
        append = lambda d, x: _append_row_sparse(d, x, member0)  # noqa: E731
        recondition = lambda p, d: jax.vmap(  # noqa: E731
            jax.vmap(lambda q: model.precompute_constrained(q, d))
        )(p)
        mt_snr = None
    elif is_mt:
        mixture = _mt_mixture_predict
        base_data = lambda d: d.features_data  # noqa: E731
        append = _append_row_mt
        recondition = lambda p, d: jax.vmap(  # noqa: E731
            lambda q: model.precompute_constrained(q, d)
        )(p)
        # Per-task signal variance is amplitude² · B[m,m] (what the MT
        # posterior uses as prior variance), not amplitude² alone.
        mt_p = states_completed.params  # [E] leaves
        b_diag = jax.vmap(lambda q: jnp.diagonal(model._task_cov(q)))(mt_p)
        mt_snr = (
            (mt_p["amplitude"][:, None] ** 2)
            * b_diag
            / (mt_p["noise_stddev"][:, None] ** 2)
        )  # [E, M]
    else:
        mixture = _mixture_predict
        base_data = lambda d: d  # noqa: E731
        append = _append_row
        recondition = lambda p, d: jax.vmap(  # noqa: E731
            jax.vmap(lambda q: model.precompute_constrained(q, d))
        )(p)
        mt_snr = None

    dc = base_data(all_data).continuous.shape[-1]
    ds = base_data(all_data).categorical.shape[-1]
    num_metrics = labels_mn.shape[0]

    trust = (
        acquisitions.TrustRegion.from_data(base_data(all_data))
        if use_trust_region
        else None
    )

    def pick(b, carry):
        all_data, out_cont, out_cat, out_scores, aux, rng = carry
        rng, ucb_rng, w_rng, opt_rng = jax.random.split(rng, 4)

        # Shared conditioning, recomputed on the grown pending set.
        pe_params, noise_is_high, threshold = _pe_conditioning(
            states_completed, all_data, config,
            mixture=mixture, base_data=base_data, snr=mt_snr,
        )
        # Re-condition the all-points posterior on the grown pending set.
        states_all = recondition(pe_params, all_data)

        # Pick-level UCB/PE decision (reference `_suggest_one` logic).
        pe_p = jnp.where(
            noise_is_high,
            config.pe_overwrite_probability_in_high_noise,
            config.pe_overwrite_probability,
        )
        use_ucb = jnp.where(
            (b == 0) & first_has_new,
            ~jax.random.bernoulli(ucb_rng, pe_p),
            has_completed
            & jax.random.bernoulli(ucb_rng, config.ucb_overwrite_probability),
        )

        weights = jnp.abs(
            jax.random.normal(
                w_rng, (config.num_scalarizations, num_metrics), jnp.float32
            )
        )
        weights = weights / jnp.linalg.norm(weights, axis=-1, keepdims=True)

        def score_fn(query: kernels.MixedFeatures) -> Array:
            mean_c, std_c = mixture(states_completed, query)  # [M, Q]
            _, std_all = mixture(states_all, query)  # [M, Q]
            ucb_vals = mean_c + config.ucb_coefficient * std_all
            if num_metrics == 1:
                ucb_score = ucb_vals[0]
            else:
                ucb_score = _hv_scalarized(
                    ucb_vals, weights, ref_point, labels_mn, labels_mask
                )
            explore_ucb = mean_c + config.explore_region_ucb_coefficient * std_c
            penalty = config.cb_violation_penalty_coefficient * jnp.minimum(
                explore_ucb - threshold[:, None], 0.0
            )
            if num_metrics == 1:
                pe_score = std_all[0] + penalty[0]
            else:
                pe_score = jnp.mean(std_all, axis=0) + _scalarize_penalty(
                    penalty, config.multimetric_promising_region_penalty_type
                )
            value = jnp.where(use_ucb, ucb_score, pe_score)
            if prior_acquisition is not None:
                # Additive user prior over the space (reference adds it to
                # both the UCB and PE scores, `gp_ucb_pe.py:377,419`).
                value = value + prior_acquisition(query)
            if trust is not None:
                value = value - trust.penalty(query)
            return value

        if mesh is None:
            result = vec_opt(
                score_fn, opt_rng, count=1, prior_features=prior_features
            )
        else:
            from vizier_tpu import parallel

            result = parallel.maximize_score_fn_sharded(
                vec_opt, score_fn, opt_rng, 1,
                len(mesh.devices.flat), mesh, prior_features,
            )
        x = kernels.MixedFeatures(
            result.features.continuous[:1], result.features.categorical[:1]
        )
        mean_x, std_x = mixture(states_completed, x)  # [M, 1]
        _, std_all_x = mixture(states_all, x)
        all_data = append(all_data, x)
        out_cont = out_cont.at[b].set(x.continuous[0])
        out_cat = out_cat.at[b].set(x.categorical[0])
        out_scores = out_scores.at[b].set(result.scores[0])
        aux = dict(
            mean=aux["mean"].at[b].set(mean_x[:, 0]),
            stddev=aux["stddev"].at[b].set(std_x[:, 0]),
            stddev_from_all=aux["stddev_from_all"].at[b].set(std_all_x[:, 0]),
            use_ucb=aux["use_ucb"].at[b].set(use_ucb),
        )
        return all_data, out_cont, out_cat, out_scores, aux, rng

    init_aux = dict(
        mean=jnp.zeros((count, num_metrics), jnp.float32),
        stddev=jnp.zeros((count, num_metrics), jnp.float32),
        stddev_from_all=jnp.zeros((count, num_metrics), jnp.float32),
        use_ucb=jnp.zeros((count,), bool),
    )
    init = (
        all_data,
        jnp.zeros((count, dc), base_data(all_data).continuous.dtype),
        jnp.zeros((count, ds), base_data(all_data).categorical.dtype),
        jnp.zeros((count,), jnp.float32),
        init_aux,
        rng,
    )
    _, out_cont, out_cat, out_scores, aux, _ = jax.lax.fori_loop(
        0, count, pick, init
    )
    aux["trust_radius"] = (
        trust.trust_radius() if trust is not None else jnp.asarray(jnp.inf)
    )
    return (
        vectorized_lib.VectorizedOptimizerResult(
            kernels.MixedFeatures(out_cont, out_cat), out_scores
        ),
        aux,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "model", "vec_opt", "q", "config", "use_trust_region",
        "prior_acquisition",
    ),
)
def _suggest_set_pe(
    model: gp_lib.VizierGaussianProcess,
    vec_opt: vectorized_lib.VectorizedOptimizer,
    states_completed: gp_lib.GPState,  # [M=1, E]
    all_data: gp_lib.GPData,
    rng: Array,
    q: int,
    config: UCBPEConfig,
    use_trust_region: bool = True,
    prior_acquisition=None,  # Callable[[MixedFeatures], [Q]-array] user prior
) -> Tuple[vectorized_lib.VectorizedOptimizerResult, dict]:
    """Joint exploration batch: maximize log-det of the set's posterior cov.

    Reference ``SetPEScoreFunction`` (``gp_ucb_pe.py:510``, eq. (8) of
    Contal et al.): candidates are whole q-point sets, searched in the
    flattened (q·D)-space by the same eagle strategy; single-metric only.
    """
    dc = all_data.continuous.shape[-1]
    ds = all_data.categorical.shape[-1]

    pe_params, _, thresholds = _pe_conditioning(
        states_completed, all_data, config
    )
    threshold = thresholds[0]  # single metric
    states_all = jax.vmap(
        jax.vmap(lambda p: model.precompute_constrained(p, all_data))
    )(pe_params)
    # Flatten [M=1, E] -> [E] for the joint-covariance math.
    states_all_e = jax.tree_util.tree_map(lambda a: a[0], states_all)
    trust = (
        acquisitions.TrustRegion.from_data(all_data) if use_trust_region else None
    )

    def score_fn(flat: kernels.MixedFeatures) -> Array:
        bsz = flat.continuous.shape[0]
        pts_c = flat.continuous.reshape(bsz, q, dc)
        pts_s = flat.categorical.reshape(bsz, q, ds)

        def per_candidate(cont: Array, cat: Array) -> Array:
            query = kernels.MixedFeatures(cont, cat)
            means, covs = jax.vmap(lambda s: s.predict_joint(query))(
                states_all_e
            )  # [E, q], [E, q, q]
            mu = jnp.mean(means, axis=0)
            # Moment-matched mixture covariance over ensemble members.
            cov = (
                jnp.mean(covs + means[:, :, None] * means[:, None, :], axis=0)
                - mu[:, None] * mu[None, :]
            )
            chol = jnp.linalg.cholesky(
                cov + 1e-6 * jnp.eye(q, dtype=cov.dtype)
            )
            logdet = 2.0 * jnp.sum(jnp.log(jnp.diagonal(chol)))
            logdet = jnp.where(jnp.isnan(logdet), -jnp.inf, logdet)
            mean_c, std_c = _mixture_predict(states_completed, query)  # [1, q]
            explore_ucb = (
                mean_c[0] + config.explore_region_ucb_coefficient * std_c[0]
            )
            value = logdet + config.cb_violation_penalty_coefficient * jnp.sum(
                jnp.minimum(explore_ucb - threshold, 0.0)
            )
            if prior_acquisition is not None:
                value = value + jnp.sum(prior_acquisition(query))
            if trust is not None:
                value = value - jnp.sum(trust.penalty(query))
            return value

        return jax.vmap(per_candidate)(pts_c, pts_s)

    result = vec_opt(score_fn, rng, count=1)
    # Unflatten the winning set into q suggestions.
    cont_rows = result.features.continuous[0].reshape(q, dc)
    cat_rows = result.features.categorical[0].reshape(q, ds)
    set_query = kernels.MixedFeatures(cont_rows, cat_rows)
    mean_x, std_x = _mixture_predict(states_completed, set_query)  # [1, q]
    _, std_all_x = _mixture_predict(states_all, set_query)
    aux = dict(
        mean=mean_x.T,  # [q, 1]
        stddev=std_x.T,
        stddev_from_all=std_all_x.T,
        use_ucb=jnp.zeros((q,), bool),
        trust_radius=(
            trust.trust_radius() if trust is not None else jnp.asarray(jnp.inf)
        ),
    )
    return (
        vectorized_lib.VectorizedOptimizerResult(
            set_query, jnp.full((q,), result.scores[0])
        ),
        aux,
    )


@functools.partial(
    jax.jit,
    static_argnames=("model", "vec_opt", "count", "config", "use_trust_region"),
)
def suggest_batched(
    model: gp_lib.VizierGaussianProcess,
    vec_opt: vectorized_lib.VectorizedOptimizer,
    states_me,  # leading study axis [B, M, E]
    all_data,  # GPData with leading study axis [B, ...]
    data,  # completed-trials GPData with leading study axis [B, ...]
    rng: Array,  # [B] per-study keys
    first_has_new: Array,  # [B] bool
    has_completed: Array,  # [B] bool
    count: int,
    config: UCBPEConfig,
    use_trust_region: bool = True,
) -> Tuple[vectorized_lib.VectorizedOptimizerResult, dict]:
    """Multi-study UCB-PE batch: ONE device program vmapping the sequential
    :func:`_suggest_batch` (greedy per-pick UCB/PE with pending-point
    conditioning) over a leading study axis.

    Used by the cross-study batch executor
    (``vizier_tpu.parallel.batch_executor``): every slot runs the exact
    per-study program, so slot i matches study i executed alone. The labels
    / reference-point / prior-feature plumbing the sequential path computes
    eagerly is folded into the traced program (same formulas, zero host
    dispatches per study). The mesh-sharded and prior-acquisition variants
    are not batchable (their bucket key is None).
    """

    return _sweep_batched(
        model, vec_opt, states_me, all_data, data, rng,
        first_has_new, has_completed, count, config, use_trust_region,
    )


def _sweep_batched(
    model, vec_opt, states_me, all_data, data, rng,
    first_has_new, has_completed, count, config, use_trust_region,
):
    """Trace-shared body of :func:`suggest_batched` (also used by the fused
    flush program): vmap of the per-study greedy batch loop, with the label
    stack / reference point / prior features folded into the trace."""

    def one(s, ad, d, r, f, h):
        labels_mn = d.labels[None]  # [M=1, N1]
        labels_mask = d.row_mask
        ref_point = acquisitions.get_reference_point(labels_mn, labels_mask)
        prior = gp_bandit._prior_features_from_data(d)
        return _suggest_batch(
            model, vec_opt, s, ad, labels_mn, labels_mask, ref_point, prior,
            r, f, h, count, config, use_trust_region, None, None,
        )

    return jax.vmap(one)(
        states_me, all_data, data, rng, first_has_new, has_completed
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "model", "optimizer", "vec_opt", "vec_opt_rest", "num_restarts",
        "ensemble_size", "count", "config", "use_trust_region", "two_phase",
    ),
)
def _ucb_pe_flush_program(
    model,
    optimizer,
    vec_opt,  # full-budget sweep (the two-phase first pick)
    vec_opt_rest,  # the budget policy's sweep for the (remaining) picks
    md,  # stacked host ModelData (completed trials), leading study axis
    all_md,  # stacked host ModelData (completed+active, spare pick rows)
    rng_train: Array,  # [B]
    rng_acq: Array,  # [B]
    rng_rest: Array,  # [B] (ignored unless two_phase)
    warm,  # per-study warm ARD seeds, leading axis [B]
    first_has_new: Array,  # [B] bool
    has_completed: Array,  # [B] bool
    num_restarts: int,
    ensemble_size: int,
    count: int,
    config: UCBPEConfig,
    use_trust_region: bool,
    two_phase: bool,
):
    """ONE device program per bucket flush: encode→ARD→UCB-PE batch→warm.

    The whole multi-study suggest — including the two-phase
    ``first_pick_full`` flow with its mid-flight pending-row append — is a
    single XLA dispatch, so a flush pays program-launch/host-sync overhead
    once instead of ~4·B times.
    """
    data = jax.vmap(lambda m: gp_lib.GPData.from_model_data(m))(md)
    all_data = jax.vmap(lambda m: gp_lib.GPData.from_model_data(m))(all_md)
    states = jax.vmap(
        lambda d, k, w: gp_bandit._train_gp(
            model, optimizer, d, k, num_restarts, ensemble_size, w
        )
    )(data, rng_train, warm)
    warm_next = gp_bandit._warm_next_batched(model, states)
    # [B, E] -> [B, M=1, E]: the UCB-PE programs are per-metric batched.
    states_me = jax.tree_util.tree_map(lambda a: a[:, None], states)
    if two_phase:
        first, aux1 = _sweep_batched(
            model, vec_opt, states_me, all_data, data, rng_acq,
            first_has_new, has_completed, 1, config, use_trust_region,
        )
        x = kernels.MixedFeatures(
            first.features.continuous[:, :1], first.features.categorical[:, :1]
        )
        all_data = jax.vmap(_append_row)(all_data, x)
        rest, aux2 = _sweep_batched(
            model, vec_opt_rest, states_me, all_data, data, rng_rest,
            jnp.zeros_like(first_has_new), has_completed, count - 1,
            config, use_trust_region,
        )
        segments = ((first, aux1), (rest, aux2))
    else:
        batch, aux = _sweep_batched(
            model, vec_opt_rest, states_me, all_data, data, rng_acq,
            first_has_new, has_completed, count, config, use_trust_region,
        )
        segments = ((batch, aux),)
    return states, warm_next, data, segments


@functools.partial(
    jax.jit,
    static_argnames=(
        "model", "aug_model", "optimizer", "vec_opt", "vec_opt_rest",
        "num_restarts", "ensemble_size", "count", "config",
        "use_trust_region", "two_phase",
    ),
)
def _sparse_ucb_pe_flush_program(
    model,  # SparseGaussianProcess over the trained m-bucket
    aug_model,  # SparseGaussianProcess with m + count augment slots
    optimizer,
    vec_opt,
    vec_opt_rest,
    md,  # stacked host ModelData (completed trials), leading study axis
    all_md,  # stacked host ModelData (completed+active, spare pick rows)
    rng_train: Array,  # [B]
    rng_acq: Array,  # [B]
    rng_rest: Array,  # [B] (ignored unless two_phase)
    warm,  # per-study warm ARD seeds, leading axis [B]
    first_has_new: Array,  # [B] bool
    has_completed: Array,  # [B] bool
    num_restarts: int,
    ensemble_size: int,
    count: int,
    config: UCBPEConfig,
    use_trust_region: bool,
    two_phase: bool,
):
    """The sparse twin of :func:`_ucb_pe_flush_program`: ONE device program
    per bucket flush — encode → k-center inducing selection → collapsed-
    bound ARD → the greedy UCB-PE batch with pending-pick conditioning
    through the inducing posterior (Nyström-augmented) → warm seed. A
    slot matches its study run alone through the sequential sparse path.
    """
    data = jax.vmap(lambda m: gp_lib.GPData.from_model_data(m))(md)
    all_gp = jax.vmap(lambda m: gp_lib.GPData.from_model_data(m))(all_md)
    states = jax.vmap(
        lambda d, k, w: sparse_bandit._train_sparse_gp(
            model, optimizer, d, k, num_restarts, ensemble_size, w
        )
    )(data, rng_train, warm)
    warm_next = sparse_bandit._warm_next_batched(model, states)
    # [B, E] -> [B, M=1, E]: the UCB-PE programs are per-metric batched.
    states_me = jax.tree_util.tree_map(lambda a: a[:, None], states)
    # Per-slot all-points data over the slot's trained inducing set (every
    # ensemble member shares it), with count spare Nyström slots.
    all_sdata = jax.vmap(
        lambda s, ag: sparse_gp.with_pending_capacity(
            jax.tree_util.tree_map(lambda a: a[0], s.sdata), ag, count
        )
    )(states, all_gp)
    if two_phase:
        first, aux1 = _sweep_batched(
            aug_model, vec_opt, states_me, all_sdata, data, rng_acq,
            first_has_new, has_completed, 1, config, use_trust_region,
        )
        x = kernels.MixedFeatures(
            first.features.continuous[:, :1], first.features.categorical[:, :1]
        )
        member0 = jax.tree_util.tree_map(lambda a: a[:, 0, 0], states_me)
        all_sdata = jax.vmap(_append_row_sparse)(all_sdata, x, member0)
        rest, aux2 = _sweep_batched(
            aug_model, vec_opt_rest, states_me, all_sdata, data, rng_rest,
            jnp.zeros_like(first_has_new), has_completed, count - 1,
            config, use_trust_region,
        )
        segments = ((first, aux1), (rest, aux2))
    else:
        batch, aux = _sweep_batched(
            aug_model, vec_opt_rest, states_me, all_sdata, data, rng_acq,
            first_has_new, has_completed, count, config, use_trust_region,
        )
        segments = ((batch, aux),)
    return states, warm_next, data, segments


def _train_mt_gp(
    model: mtgp.MultiTaskGaussianProcess,
    optimizer,
    data: mtgp.MultiTaskData,
    rng: Array,
    num_restarts: int,
    ensemble_size: int,
) -> mtgp.MultiTaskGPState:
    """Joint multitask ARD: restarts → L-BFGS → top-k posteriors ([E])."""
    coll = model.param_collection()
    inits = coll.batch_random_init_unconstrained(rng, num_restarts)
    loss_fn = lambda p: model.neg_log_likelihood(p, data)  # noqa: E731
    result = optimizer(loss_fn, inits, best_n=ensemble_size)
    return jax.vmap(lambda p: model.precompute(p, data))(result.params)


class _MetricZeroMTPredictive:
    """Duck-typed ``.predict`` over the FIRST metric of a multitask state.

    Mirrors what the independent path exposes via ``EnsemblePredictive``
    (metric 0 only) so ``predict``/``sample`` keep one contract.
    """

    def __init__(self, states: mtgp.MultiTaskGPState):
        self._states = states

    def predict(self, query: kernels.MixedFeatures) -> Tuple[Array, Array]:
        mean, std = _mt_mixture_predict(self._states, query)
        return mean[0], std[0]


_MIN_PICK_EVALUATIONS = 500  # ≥10 eagle generations at the default pool of 50


@dataclasses.dataclass
class VizierGPUCBPEBandit(gp_bandit.VizierGPBandit):
    """GP-UCB-PE batch designer (service DEFAULT)."""

    config: UCBPEConfig = UCBPEConfig()
    num_seed_trials: int = 1  # reference default: center point first
    # Acquisition evaluation budget semantics for batch suggests (measured
    # A/B in docs/guides/tpu_architecture.md):
    # - "first_pick_full" (default): the batch's FIRST pick — the
    #   exploitation (UCB) pick whose local optimization precision drives
    #   simple regret — runs the full ``max_acquisition_evaluations``;
    #   the remaining picks, which maximize the flatter pure-exploration
    #   stddev surface, split one further full budget between them. Total
    #   ≈ 2 sweeps per suggest() regardless of batch size.
    # - "per_batch": one full budget split across ALL picks (floored at
    #   _MIN_PICK_EVALUATIONS) — cheapest, measurably worse exploitation
    #   precision on 20-D (the per-pick sweep dominates e2e latency, ~88%
    #   at 1000x20-D).
    # - "per_pick": every pick runs the full budget — the reference's
    #   effective behavior (its ``_suggest_one`` spends max_evaluations=75k
    #   per pick, ``gp_ucb_pe.py:693-697,1440-1446``, with a TODO
    #   acknowledging the budget should scale with count).
    acquisition_budget_policy: str = "first_pick_full"
    # Optional additive acquisition prior (reference `prior_acquisition`,
    # gp_ucb_pe.py:299): called with the candidate MixedFeatures batch,
    # returns a [Q] score added to both the UCB and PE acquisitions. Must be
    # a jax-traceable callable; it is baked into the jitted suggest program,
    # so use one stable callable per designer (a fresh lambda per call would
    # retrace).
    prior_acquisition: Optional[Callable[[kernels.MixedFeatures], Array]] = None

    def __post_init__(self):
        super().__post_init__()
        if self.acquisition_budget_policy not in (
            "first_pick_full",
            "per_batch",
            "per_pick",
        ):
            raise ValueError(
                "acquisition_budget_policy must be 'first_pick_full' | "
                "'per_batch' | 'per_pick', got "
                f"{self.acquisition_budget_policy!r}."
            )
        self._active_trials: List[trial_.Trial] = []
        self._metric_warpers: List[output_warpers.WarperPipeline] = []
        self._warpers_fitted = False
        # Trained per-metric states, reused until new data arrives (predict/
        # sample after a suggest must not pay a second ARD optimization).
        self._cached_states = None
        # Joint set-PE optimizers are built lazily per batch size.
        self._set_opt_cache: dict = {}
        # Per-pick sweep optimizers under the per_batch budget policy, keyed
        # by their per-pick evaluation budget.
        self._pick_opt_cache: dict = {}
        # Per-objective warm-start seeds for the independent-GP path,
        # random-initialized so the ARD program's pytree structure is
        # stable from the first suggest (same trick as the base class's
        # scalar `_warm_params`). The multitask (SEPARABLE) trainer has no
        # warm-start path and always counts as a cold train.
        coll = self._model.param_collection()
        n_obj = len(self._objective_indices())
        keys = jax.random.split(jax.random.PRNGKey(self.rng_seed + 2), max(n_obj, 1))
        self._warm_params_me = [
            coll.random_init_unconstrained(k) for k in keys[:n_obj]
        ]

    def _split_vec_opt(self, num_picks: int) -> vectorized_lib.VectorizedOptimizer:
        """One full budget split evenly across ``num_picks`` picks."""
        if num_picks <= 1:
            return self._vec_opt
        per_pick = max(
            self.max_acquisition_evaluations // num_picks,
            _MIN_PICK_EVALUATIONS,
        )
        opt = self._pick_opt_cache.get(per_pick)
        if opt is None:
            opt = vectorized_lib.VectorizedOptimizer(
                self._vec_opt.strategy, max_evaluations=per_pick
            )
            self._pick_opt_cache[per_pick] = opt
        return opt

    def _pick_vec_opt(self, count: int) -> vectorized_lib.VectorizedOptimizer:
        """The acquisition optimizer the batch loop's picks run with.

        "per_batch" splits ``max_acquisition_evaluations`` across all
        ``count`` picks; "first_pick_full" handles its full-budget first
        pick separately in ``suggest`` and splits across the remainder.
        """
        if self.acquisition_budget_policy == "per_pick" or count <= 1:
            return self._vec_opt
        if self.acquisition_budget_policy == "first_pick_full":
            return self._split_vec_opt(count - 1)
        return self._split_vec_opt(count)

    # -- Designer ----------------------------------------------------------

    def update(
        self,
        completed: core_lib.CompletedTrials,
        all_active: core_lib.ActiveTrials = core_lib.ActiveTrials(),
    ) -> None:
        if completed.trials:
            self._cached_states = None  # new labels invalidate the GP fit
        self._trials.extend(completed.trials)
        self._active_trials = list(all_active.trials)

    def _has_new_completed_trials(self) -> bool:
        """True iff a completed trial postdates every active trial's creation
        (reference ``_has_new_completed_trials``, ``gp_ucb_pe.py:142``)."""
        if not self._trials:
            return False
        if not self._active_trials:
            return True
        completion = [t.completion_time for t in self._trials if t.completion_time]
        creation = [t.creation_time for t in self._active_trials if t.creation_time]
        if not completion or not creation:
            return True
        return max(completion) > max(creation)

    def _objective_indices(self) -> List[int]:
        return [
            j
            for j, m in enumerate(self.problem.metric_information)
            if not m.is_safety_metric
        ]

    # -- scalable surrogate for the DEFAULT (vizier_tpu.surrogates) ---------

    def _sparse_ucb_pe_eligible(self) -> bool:
        """Whether the sparse surrogate may serve this designer's suggests.

        The single-objective independent-GP greedy path only: multitask,
        multi-objective, set-acquisition, transfer priors, custom
        acquisition priors, and mesh-sharded designers stay exact — the
        same carve-outs the base class documents for its sparse path.
        """
        cfg = self.surrogate
        return bool(
            cfg is not None
            and cfg.sparse
            and getattr(cfg, "sparse_ucb_pe", True)
            and self._mesh is None
            and len(self._objective_indices()) == 1
            and not self.config.optimize_set_acquisition_for_exploration
            and self.prior_acquisition is None
            and not getattr(self, "_priors", None)
        )

    def _refresh_ucb_pe_surrogate_mode(self) -> str:
        """The auto-switch, applied only where the sparse UCB-PE programs
        cover; ineligible designers never leave exact (bit-identical)."""
        if not self._sparse_ucb_pe_eligible():
            return self._surrogate_mode
        return self._refresh_surrogate_mode()

    def _refresh_surrogate_mode(self) -> str:
        before = self._surrogate_counts["crossovers"]
        mode = super()._refresh_surrogate_mode()
        if self._surrogate_counts["crossovers"] != before:
            # The base crossover dropped ITS warm/posterior state; the
            # UCB-PE designer's cross-surrogate state — per-metric warm
            # seeds and the cached fit — is equally stale. Fresh random
            # placeholders keep the train program's pytree stable.
            coll = self._model.param_collection()
            n = max(len(self._warm_params_me), 1)
            keys = jax.random.split(
                jax.random.PRNGKey(
                    self.rng_seed + 2 + self._surrogate_counts["crossovers"]
                ),
                n,
            )
            self._warm_params_me = [
                coll.random_init_unconstrained(k)
                for k in keys[: len(self._warm_params_me)]
            ]
            self._cached_states = None
        return mode

    def _sparse_all_model(self, count: int) -> sparse_gp.SparseGaussianProcess:
        """The re-conditioning model over the augmented inducing capacity:
        the trained posterior's m slots plus one spare Nyström slot per
        batch pick (a frozen value object — stable jit static)."""
        base = self._sparse_model()
        return sparse_gp.SparseGaussianProcess(
            base=self._model, num_inducing=base.num_inducing + count
        )

    def _train_states_me(self) -> Tuple[gp_lib.GPState, List[gp_lib.GPData]]:
        """Per-metric GP training: GPState with leading [M, E] + the datas.

        Cached between calls until update() delivers new completed trials —
        predict()/sample() right after a suggest() reuse the same fit.
        """
        if self._cached_states is not None:
            return self._cached_states
        conv = self._converter
        raw = conv.metrics.encode(self._trials)  # [N, M_all], all-MAXIMIZE
        features, n_pad = self._padded_features(self._trials)
        ensemble = max(self.ensemble_size, 1)
        datas = []
        self._metric_warpers = []
        self._warpers_fitted = raw.shape[0] > 0
        for j in self._objective_indices():
            warper = output_warpers.create_default_warper()
            warped = warper(raw[:, j]) if raw.shape[0] else raw[:, j]
            self._metric_warpers.append(warper)
            data = gp_lib.GPData.from_model_data(
                types.ModelData(features, self._padded_labels(warped, n_pad))
            )
            datas.append(data)
        if (
            len(datas) == 1
            and self._refresh_ucb_pe_surrogate_mode()
            == surrogate_config_lib.MODE_SPARSE
        ):
            # Sparse DEFAULT: the SGPR collapsed bound replaces the exact
            # O(n³) ARD — same multi-restart L-BFGS program shape, same
            # warm-seed-as-extra-restart-row semantics, k-center inducing
            # selection inside the jitted program.
            model = self._sparse_model()
            restarts = max(
                self._warm_restart_budget() or self.ard_restarts, ensemble
            )
            states = sparse_bandit._train_sparse_gp(
                model,
                self._ard,
                datas[0],
                self._next_rng(),
                restarts,
                ensemble,
                self._warm_params_me[0],
            )
            self._record_train()
            if self._warm_update_allowed():
                coll = self._model.param_collection()
                self._warm_params_me = [
                    coll.unconstrain(
                        jax.tree_util.tree_map(lambda a: a[0], states.params)
                    )
                ]
                self._warm_is_trained = True
            states_me = jax.tree_util.tree_map(lambda a: a[None], states)
            self._cached_states = (states_me, datas)
            return self._cached_states
        if self._use_multitask(len(datas)):
            # One joint GP: learned task covariance over a B ⊗ Kx Gram.
            mt_model = self._mt_model(len(datas))
            mt_data = mtgp.MultiTaskData.from_gp_datas(tuple(datas))
            if self._mesh is None:
                states = _train_mt_gp(
                    mt_model, self._ard, mt_data, self._next_rng(),
                    self.ard_restarts, ensemble,
                )
            else:
                # Same restart sharding as the independent path — the
                # sharded trainer is model-agnostic (duck-typed
                # param_collection / neg_log_likelihood / precompute).
                from vizier_tpu import parallel

                ndev = self._mesh_size()
                restarts = -(-self.ard_restarts // ndev) * ndev
                states = parallel.train_gp_sharded(
                    mt_model, self._ard, mt_data, self._next_rng(),
                    restarts, ensemble, self._mesh,
                )
            self._ard_train_counts["cold"] += 1
            self._cached_states = (states, datas)
            return self._cached_states
        # Mesh-aware: restarts shard over devices when a mesh is present.
        # Each metric's train is seeded with ITS previous optimum (restart
        # 0); with a trained seed and a configured warm budget the restart
        # count drops to ``warm_ard_restarts`` — the steady-state serving
        # win (hyperparameters move little between suggests, so the seeded
        # restart early-exits the L-BFGS while random restarts burn the
        # full budget).
        warm_budget = self._warm_restart_budget()
        states_list = [
            self._train(
                data,
                self._next_rng(),
                ensemble,
                warm_start=self._warm_params_me[j],
                num_restarts=warm_budget,
            )
            for j, data in enumerate(datas)
        ]
        self._record_train()
        if self._warm_update_allowed():
            coll = self._model.param_collection()
            self._warm_params_me = [
                coll.unconstrain(
                    jax.tree_util.tree_map(lambda a: a[0], states.params)
                )
                for states in states_list
            ]
            self._warm_is_trained = True
        states_me = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *states_list
        )
        self._cached_states = (states_me, datas)
        return self._cached_states

    # -- serving warm-start surface (vizier_tpu.serving) --------------------

    def warm_start_state(self) -> Optional[List]:
        """Per-objective trained unconstrained params (independent path)."""
        return list(self._warm_params_me) if self._warm_is_trained else None

    def set_warm_start_state(self, params: List) -> None:
        if len(params) != len(self._warm_params_me):
            raise ValueError(
                f"Expected {len(self._warm_params_me)} per-metric param "
                f"pytrees, got {len(params)}."
            )
        self._warm_params_me = list(params)
        self._warm_is_trained = True

    # -- cross-study batch protocol (vizier_tpu.compute IR) -----------------
    #
    # The real implementations live in the registered DesignerProgram
    # classes at the bottom of this module (UCBPEProgram /
    # UCBPESparseProgram); the thin methods inherited from VizierGPBandit
    # keep the duck-typed surface working, routed here via
    # ``_active_batch_program``.

    def _batch_ensemble(self) -> int:
        return max(self.ensemble_size, 1)

    def _batch_restarts(self) -> int:
        """Mirrors ``_train_states_me``'s budget: warm override or full,
        floored at the ensemble size."""
        return max(
            self._warm_restart_budget() or self.ard_restarts,
            self._batch_ensemble(),
        )

    def _active_batch_program(self):
        from vizier_tpu.compute import registry as compute_registry

        kind = (
            "gp_ucb_pe_sparse"
            if self._surrogate_mode == surrogate_config_lib.MODE_SPARSE
            else "gp_ucb_pe"
        )
        return compute_registry.get(kind)

    @classmethod
    def batch_execute(
        cls,
        items: Sequence[dict],
        pad_to: Optional[int] = None,
        placement: Optional[Any] = None,
    ):
        """Device half: dispatched to the bucket's registered program."""
        from vizier_tpu.compute import registry as compute_registry

        kind = "gp_ucb_pe_sparse" if items[0].get("sparse") else "gp_ucb_pe"
        return compute_registry.get(kind).device_program(
            items, pad_to=pad_to, placement=placement
        )

    def batch_finalize(self, item: dict, output: dict) -> List[trial_.TrialSuggestion]:
        from vizier_tpu.compute import registry as compute_registry

        kind = "gp_ucb_pe_sparse" if output.get("sparse") else "gp_ucb_pe"
        return compute_registry.get(kind).finalize(self, item, output)

    def _use_multitask(self, num_metrics: int) -> bool:
        return (
            self.config.multitask_type is not mtgp.MultiTaskType.INDEPENDENT
            and num_metrics > 1
        )

    def _mt_model(self, num_metrics: int) -> mtgp.MultiTaskGaussianProcess:
        return mtgp.MultiTaskGaussianProcess(
            num_continuous=self._model.num_continuous,
            num_categorical=self._model.num_categorical,
            num_tasks=num_metrics,
            multitask_type=self.config.multitask_type,
        )

    def _all_points_model_data(self, count: int) -> types.ModelData:
        """Host (numpy) ModelData over completed+active rows with capacity
        for the picks."""
        all_trials = list(self._trials) + list(self._active_trials)
        features, n_pad = self._padded_features(all_trials, extra_rows=count)
        spare = n_pad - len(all_trials)
        if spare < count:  # capacity guard: _append_row must never no-op
            raise RuntimeError(
                f"Padded capacity {n_pad} leaves {spare} spare rows for a "
                f"batch of {count}; padding schedule must reserve the batch."
            )
        zero_labels = types.PaddedArray.from_array(
            np.zeros((len(all_trials), 1), np.float32), (n_pad, 1), fill_value=np.nan
        )
        return types.ModelData(features, zero_labels)

    def _all_points_data(self, count: int) -> gp_lib.GPData:
        """GPData over completed+active rows with capacity for the picks."""
        return gp_lib.GPData.from_model_data(self._all_points_model_data(count))

    def suggest(self, count: Optional[int] = None) -> List[trial_.TrialSuggestion]:
        count = count or 1
        if len(self._trials) + len(self._active_trials) < self.num_seed_trials:
            return self._seed_suggestions(count)
        if getattr(self, "_priors", None):
            return self._suggest_with_priors(count)

        # The surrogate auto-switch decides the device-phase family up
        # front (idempotent; ineligible designers always report exact).
        sparse_mode = (
            self._refresh_ucb_pe_surrogate_mode()
            == surrogate_config_lib.MODE_SPARSE
        )
        with profiler.timeit("train_gp"):
            # Device-attributed ARD timing (compile vs. steady-state): see
            # gp_bandit.suggest for the rationale; no-op + no device sync
            # when observability is off.
            with jax_timing.device_phase(
                "sparse_gp.ucb_pe_train_gp" if sparse_mode else "gp_ucb_pe.train_gp"
            ) as phase:
                states_me, datas = self._train_states_me()
                phase.block(states_me)
        is_mt = isinstance(states_me, mtgp.MultiTaskGPState)
        is_sparse = isinstance(states_me, sparse_gp.SparseGPState)
        if is_mt:
            self._last_predictive = _MetricZeroMTPredictive(states_me)
        elif is_sparse:
            member_states = jax.tree_util.tree_map(lambda a: a[0], states_me)
            self._last_predictive = sparse_gp.SparseEnsemblePredictive(
                member_states
            )
            self._last_sparse_state = member_states
        else:
            self._last_predictive = gp_lib.EnsemblePredictive(
                jax.tree_util.tree_map(lambda a: a[0], states_me)
            )
        all_data = self._all_points_data(count)
        num_metrics = len(datas)
        if num_metrics > 1 and self.config.optimize_set_acquisition_for_exploration:
            raise ValueError(
                "optimize_set_acquisition_for_exploration supports exactly "
                "one objective metric."
            )

        labels_mn = jnp.stack([d.labels for d in datas])  # [M, N1]
        labels_mask = datas[0].row_mask
        # Reference point: nadir − 0.1·range (Ishibuchi2011, shared helper).
        ref_point = acquisitions.get_reference_point(labels_mn, labels_mask)

        first_has_new = jnp.asarray(self._has_new_completed_trials())
        has_completed = jnp.asarray(bool(self._trials))

        if (
            self.config.optimize_set_acquisition_for_exploration
            and count > 1
        ):
            return self._suggest_with_set_acquisition(
                count, states_me, all_data, labels_mn, labels_mask, ref_point,
                first_has_new, has_completed, datas,
            )

        if is_mt:
            model = self._mt_model(num_metrics)
            all_data = mtgp.MultiTaskData(
                features_data=all_data,
                task_labels=jnp.zeros(
                    (num_metrics,) + all_data.labels.shape, jnp.float32
                ),
                task_mask=jnp.tile(all_data.row_mask[None, :], (num_metrics, 1)),
            )
        elif is_sparse:
            # All-points twin of the trained posterior's inducing set, with
            # one spare Nyström slot per pick; the augmented-capacity model
            # re-conditions per pick in O(n·m²) instead of O(n³).
            model = self._sparse_all_model(count)
            sdata0 = jax.tree_util.tree_map(
                lambda a: a[0, 0], states_me.sdata
            )
            all_data = sparse_gp.with_pending_capacity(sdata0, all_data, count)
        else:
            model = self._model
        prior_feats = self._prior_features(datas[0])
        results: List[Tuple] = []  # [(result, aux, rows)]
        # Device-attributed sweep timing; the block_until_ready calls on the
        # batch scores below already pin device time inside this phase.
        with profiler.timeit("acquisition_optimizer"), jax_timing.device_phase(
            "sparse_gp.ucb_pe_acquisition"
            if is_sparse
            else "gp_ucb_pe.acquisition"
        ):
            if self.acquisition_budget_policy == "first_pick_full" and count > 1:
                # Full budget on the exploitation-critical first pick; one
                # further full budget split across the remaining picks.
                first, aux1 = _suggest_batch(
                    model, self._vec_opt, states_me, all_data,
                    labels_mn, labels_mask, ref_point, prior_feats,
                    self._next_rng(), first_has_new, has_completed, 1,
                    self.config, self.use_trust_region, self._mesh,
                    self.prior_acquisition,
                )
                x = kernels.MixedFeatures(
                    first.features.continuous[:1],
                    first.features.categorical[:1],
                )
                if is_sparse:
                    all_data = _append_row_sparse(
                        all_data,
                        x,
                        jax.tree_util.tree_map(
                            lambda a: a[0, 0], states_me
                        ),
                    )
                else:
                    all_data = (_append_row_mt if is_mt else _append_row)(
                        all_data, x
                    )
                # _pick_vec_opt(count) is the ONE budget-dispatch point: under
                # first_pick_full it returns the (count-1)-way split sweep.
                rest, aux2 = _suggest_batch(
                    model, self._pick_vec_opt(count), states_me,
                    all_data, labels_mn, labels_mask, ref_point, prior_feats,
                    self._next_rng(), jnp.asarray(False), has_completed,
                    count - 1, self.config, self.use_trust_region,
                    self._mesh, self.prior_acquisition,
                )
                jax.block_until_ready(rest.scores)
                results = [(first, aux1, 1), (rest, aux2, count - 1)]
            else:
                batch, aux = _suggest_batch(
                    model,
                    self._pick_vec_opt(count),
                    states_me,
                    all_data,
                    labels_mn,
                    labels_mask,
                    ref_point,
                    prior_feats,
                    self._next_rng(),
                    first_has_new,
                    has_completed,
                    count,
                    self.config,
                    self.use_trust_region,
                    self._mesh,
                    self.prior_acquisition,
                )
                jax.block_until_ready(batch.scores)
                results = [(batch, aux, count)]
        if is_sparse:
            self._surrogate_counts["sparse_suggests"] += 1
        with profiler.timeit("best_candidates_to_trials"):
            out: List[trial_.TrialSuggestion] = []
            for result, aux, rows in results:
                out.extend(self._decode_ucb_pe(result, aux, rows))
            return out

    def _suggest_with_set_acquisition(
        self, count, states_me, all_data, labels_mn, labels_mask, ref_point,
        first_has_new, has_completed, datas,
    ) -> List[trial_.TrialSuggestion]:
        """Reference flow: one UCB pick if fresh data, then a joint PE set."""
        suggestions: List[trial_.TrialSuggestion] = []
        if bool(first_has_new):
            with profiler.timeit("acquisition_optimizer"):
                first, aux1 = _suggest_batch(
                    self._model, self._vec_opt, states_me, all_data,
                    labels_mn, labels_mask, ref_point,
                    self._prior_features(datas[0]), self._next_rng(),
                    first_has_new, has_completed, 1, self.config,
                    self.use_trust_region, self._mesh, self.prior_acquisition,
                )
                jax.block_until_ready(first.scores)
            suggestions.extend(self._decode_ucb_pe(first, aux1, 1))
            all_data = _append_row(
                all_data,
                kernels.MixedFeatures(
                    first.features.continuous[:1], first.features.categorical[:1]
                ),
            )
        q = count - len(suggestions)
        set_opt = self._set_opt_cache.get(q)
        if set_opt is None:
            enc = self._converter.encoder
            cat_sizes = tuple(enc.category_sizes) + (1,) * (
                self._cat_width - enc.num_categorical
            )
            strategy = eagle_lib.VectorizedEagleStrategy(
                num_continuous=self._cont_width * q,
                category_sizes=cat_sizes * q,
            )
            set_opt = vectorized_lib.VectorizedOptimizer(
                strategy, max_evaluations=self.max_acquisition_evaluations
            )
            self._set_opt_cache[q] = set_opt
        with profiler.timeit("set_acquisition_optimizer"):
            result, aux = _suggest_set_pe(
                self._model,
                set_opt,
                states_me,
                all_data,
                self._next_rng(),
                q,
                self.config,
                self.use_trust_region,
                self.prior_acquisition,
            )
            jax.block_until_ready(result.scores)
        with profiler.timeit("best_candidates_to_trials"):
            suggestions.extend(self._decode_ucb_pe(result, aux, q))
        return suggestions

    def _decode_ucb_pe(
        self, result: vectorized_lib.VectorizedOptimizerResult, aux: dict, count: int
    ) -> List[trial_.TrialSuggestion]:
        conv = self._converter
        # ONE device->host fetch for everything this decode needs: each
        # separate np.asarray on a device array is a blocking round trip
        # (~75 ms over a tunneled TPU; 8 of them dominated suggest latency).
        fetched = jax.device_get(
            (
                result.features.continuous,
                result.features.categorical,
                result.scores,
                aux["mean"],
                aux["stddev"],
                aux["stddev_from_all"],
                aux["use_ucb"],
                aux["trust_radius"],
            )
        )
        cont, cat, scores = fetched[0][:count], fetched[1][:count], fetched[2][:count]
        mean, stddev, stddev_all, use_ucb = fetched[3:7]
        trust_radius = float(fetched[7])
        suggestions = []
        for i in range(count):
            params = conv.to_parameters(
                cont[i : i + 1, : conv.encoder.num_continuous],
                cat[i : i + 1, : conv.encoder.num_categorical],
            )[0]
            s = trial_.TrialSuggestion(parameters=params)
            ns = s.metadata.ns("gp_ucb_pe")
            ns["acquisition"] = float(scores[i])
            ns["use_ucb"] = str(bool(use_ucb[i]))
            ns["trust_radius"] = trust_radius
            pred = ns.ns("prediction_in_warped_y_space")
            pred["mean"] = np.array2string(mean[i], separator=",")
            pred["stddev"] = np.array2string(stddev[i], separator=",")
            pred["stddev_from_all"] = np.array2string(
                stddev_all[i], separator=","
            )
            suggestions.append(s)
        return suggestions

    # -- Predictor (unwarped; reference `sample`/`predict`) -----------------

    def sample(
        self,
        suggestions: Sequence[trial_.TrialSuggestion],
        rng=None,
        num_samples: int = 1000,
    ) -> np.ndarray:
        """Unwarped posterior samples: [S, T] (single) or [S, T, M] (multi).

        ``rng`` may be a jax PRNGKey or a numpy Generator (Predictor base
        contract)."""
        rng = gp_bandit._as_prng_key(rng)
        if not suggestions:
            return np.zeros((num_samples, 0))
        states_me, _ = self._train_states_me()
        feats = self._encode_suggestions(suggestions)
        if isinstance(states_me, mtgp.MultiTaskGPState):
            mean, stddev = _mt_mixture_predict(states_me, feats)  # [M, T]
        else:
            mean, stddev = _mixture_predict(states_me, feats)  # [M, T]
        eps = jax.random.normal(rng, (num_samples,) + mean.shape, mean.dtype)
        warped = np.asarray(mean[None] + stddev[None] * eps)  # [S, M, T]
        if not self._warpers_fitted:
            # No completed labels to fit a warper on: the warped space IS the
            # native space (prior samples on a fresh study).
            out = warped
            out = np.moveaxis(out, 1, 2)
            return out[:, :, 0] if out.shape[-1] == 1 else out
        out = np.empty_like(warped)
        metrics_enc = self._converter.metrics
        for m, (warper, idx) in enumerate(
            zip(self._metric_warpers, self._objective_indices())
        ):
            flat = warped[:, m, :].reshape(-1, 1)
            unwarped = warper.unwarp(flat).reshape(warped.shape[0], -1)
            # The converter owns the all-MAXIMIZE flip rule; route back
            # through it so samples land in the user's metric scale.
            out[:, m, :] = metrics_enc.decode_column(unwarped, idx)
        out = np.moveaxis(out, 1, 2)  # [S, T, M]
        return out[:, :, 0] if out.shape[-1] == 1 else out

    def predict(
        self,
        suggestions: Sequence[trial_.TrialSuggestion],
        rng=None,
        num_samples: Optional[int] = 1000,
    ) -> core_lib.Prediction:
        """Empirical mean/stddev of unwarped posterior samples."""
        samples = self.sample(suggestions, rng, num_samples or 1000)
        return core_lib.Prediction(
            mean=np.mean(samples, axis=0), stddev=np.std(samples, axis=0)
        )


def default_factory(
    problem: base_study_config.ProblemStatement, seed: Optional[int] = None, **kwargs
) -> VizierGPUCBPEBandit:
    return VizierGPUCBPEBandit(problem, rng_seed=seed or 0, **kwargs)


# -- compute-IR programs (vizier_tpu.compute) --------------------------------
#
# The batched designer-compute contract for the service DEFAULT: one
# program per compiled-flush family (exact | sparse UCB-PE). Hook bodies
# are the pre-IR ``batch_*`` methods moved verbatim (exact) and the sparse
# twin that exists because the seam does — SGPR train + pending-pick
# conditioning through the inducing-point posterior.


def _ucb_pe_unbatchable(designer: "VizierGPUCBPEBandit", count: int) -> bool:
    """Paths the batched UCB-PE flush programs do not cover.

    Batchable: the single-objective independent-GP greedy path with no
    cached fit (a cached fit means the sequential suggest would skip
    training — re-training it in a batch would deviate). Multitask,
    set-acquisition, priors, custom acquisition priors, mesh sharding, and
    the seeding stage run sequentially.
    """
    return bool(
        designer._mesh is not None
        or len(designer._trials) + len(designer._active_trials)
        < designer.num_seed_trials
        or getattr(designer, "_priors", None)
        or len(designer._objective_indices()) != 1
        or designer.config.optimize_set_acquisition_for_exploration
        or designer.prior_acquisition is not None
        or designer._cached_states is not None
    )


def _ucb_pe_prepare(
    designer: "VizierGPUCBPEBandit", count: int, sparse: bool
) -> dict:
    """Host-side half of a batched UCB-PE suggest (single-objective path).

    Encodes + warps this study's data and draws RNG keys in exactly the
    sequential order: one train key, then one acquisition key per
    ``_suggest_batch`` call the budget policy would make. Host-only (numpy
    ModelData): GPData conversion, label stacking, reference point, and
    prior features all happen inside the batched device programs —
    prepare's only device work is the RNG splits.
    """
    conv = designer._converter
    raw = conv.metrics.encode(designer._trials)
    features, n_pad = designer._padded_features(designer._trials)
    j = designer._objective_indices()[0]
    warper = output_warpers.create_default_warper()
    warped = warper(raw[:, j]) if raw.shape[0] else raw[:, j]
    designer._metric_warpers = [warper]
    designer._warpers_fitted = raw.shape[0] > 0
    md = types.ModelData(features, designer._padded_labels(warped, n_pad))
    rng_train = designer._next_rng()
    two_phase = (
        designer.acquisition_budget_policy == "first_pick_full" and count > 1
    )
    return dict(
        designer=designer,
        count=count,
        md=md,
        all_md=designer._all_points_model_data(count),
        first_has_new=np.asarray(designer._has_new_completed_trials()),
        has_completed=np.asarray(bool(designer._trials)),
        warm=designer._warm_params_me[0],
        restarts=designer._batch_restarts(),
        rng_train=rng_train,
        rng_acq=designer._next_rng(),
        rng_acq_rest=designer._next_rng() if two_phase else None,
        sparse=sparse,
    )


def _ucb_pe_demux(items, states, warm_next, data, segments, rows, sparse: bool):
    """ONE device->host fetch for everything the demux needs; per-slot
    slices below are then free numpy views."""
    from vizier_tpu.parallel import batch_executor

    states, warm_next, data, segments = jax.device_get(
        (states, warm_next, data, segments)
    )
    return [
        dict(
            states=batch_executor.slice_pytree(states, i),
            warm_next=batch_executor.slice_pytree(warm_next, i),
            data=batch_executor.slice_pytree(data, i),
            segments=[
                (
                    batch_executor.slice_pytree(result, i),
                    batch_executor.slice_pytree(aux, i),
                    n,
                )
                for (result, aux), n in zip(segments, rows)
            ],
            sparse=sparse,
        )
        for i in range(len(items))
    ]


class UCBPEProgram(compute_ir.DesignerProgram):
    """Exact UCB-PE flush: vmapped ARD train + vmapped greedy batch loop(s)
    (two sweep programs under ``first_pick_full`` with count > 1, exactly
    like the sequential flow)."""

    kind = "gp_ucb_pe"
    device_phase = "gp_ucb_pe.suggest_batched"
    surrogate_family = "exact"
    shardable_batch_axis = "study"
    algorithms = ("DEFAULT", "GP_UCB_PE", "ALGORITHM_UNSPECIFIED")

    def bucket_key(self, designer, count):
        if _ucb_pe_unbatchable(designer, count):
            return None
        if (
            designer._refresh_ucb_pe_surrogate_mode()
            == surrogate_config_lib.MODE_SPARSE
        ):
            return None  # the sparse UCB-PE program owns this study
        pad = designer._converter.padding
        n_all = len(designer._trials) + len(designer._active_trials)
        return compute_ir.BucketKey(
            kind=self.kind,
            pad_trials=pad.pad_trials(len(designer._trials)),
            cont_width=designer._cont_width,
            cat_width=designer._cat_width,
            metric_count=1,
            count=count,
            statics=(
                # all-points rows get their own padded size (spare rows for
                # the batch picks), so it is part of the shape identity.
                pad.pad_trials(n_all + count),
                designer._model,
                designer._ard,
                designer._vec_opt,
                designer._pick_vec_opt(count),
                designer._batch_restarts(),
                designer._batch_ensemble(),
                designer.config,
                designer.use_trust_region,
                designer.acquisition_budget_policy,
            ),
        )

    def prepare(self, designer, count):
        return _ucb_pe_prepare(designer, count, sparse=False)

    def device_program(self, items, pad_to=None, placement=None):
        from vizier_tpu.parallel import batch_executor

        d0: "VizierGPUCBPEBandit" = items[0]["designer"]
        stack = lambda name: batch_executor.place_batch(  # noqa: E731
            batch_executor.stack_pytrees([it[name] for it in items], pad_to),
            placement,
        )
        count = items[0]["count"]
        two_phase = (
            d0.acquisition_budget_policy == "first_pick_full" and count > 1
        )
        rng_a = stack("rng_acq")
        with jax_timing.device_phase(self.device_phase) as phase:
            states, warm_next, data, segments = _ucb_pe_flush_program(
                d0._model, d0._ard, d0._vec_opt, d0._pick_vec_opt(count),
                stack("md"), stack("all_md"),
                stack("rng_train"), rng_a,
                stack("rng_acq_rest") if two_phase else rng_a,
                stack("warm"), stack("first_has_new"), stack("has_completed"),
                items[0]["restarts"], d0._batch_ensemble(), count,
                d0.config, d0.use_trust_region, two_phase,
            )
            phase.block(segments)
        rows = [1, count - 1] if two_phase else [count]
        return _ucb_pe_demux(
            items, states, warm_next, data, segments, rows, sparse=False
        )

    def finalize(self, designer, item, output):
        """Host-side demux: warm writeback, fit caching for predict/sample,
        and per-segment decode — the sequential suggest's state
        transitions."""
        states = output["states"]  # [E] leaves (this study's ensemble)
        designer._record_train()
        if designer._warm_update_allowed():
            # The unconstrain already ran (vmapped) inside the flush program.
            designer._warm_params_me = [output["warm_next"]]
            designer._warm_is_trained = True
        states_me = jax.tree_util.tree_map(lambda a: a[None], states)  # [1, E]
        designer._cached_states = (states_me, [output["data"]])
        designer._last_predictive = gp_lib.EnsemblePredictive(states)
        out: List[trial_.TrialSuggestion] = []
        for result, aux, rows in output["segments"]:
            out.extend(designer._decode_ucb_pe(result, aux, rows))
        return out

    def prewarm_factory(self, problem, **kwargs):
        return VizierGPUCBPEBandit(problem, **kwargs)


class UCBPESparseProgram(compute_ir.DesignerProgram):
    """Sparse UCB-PE flush: SGPR collapsed-bound train + the greedy batch
    with pending-pick conditioning through the inducing-point posterior.

    Exists because the IR seam does: the program reuses the exact UCB-PE
    prepare/demux shapes and the shared ``_sweep_batched`` body, swapping
    only the train and the per-pick re-conditioning — 1000+-trial studies
    on the service DEFAULT scale like the sparse GP-bandit path."""

    kind = "gp_ucb_pe_sparse"
    device_phase = "sparse_gp.ucb_pe_suggest_batched"
    surrogate_family = "sparse"
    shardable_batch_axis = "study"
    algorithms = ("DEFAULT", "GP_UCB_PE", "ALGORITHM_UNSPECIFIED")

    def bucket_key(self, designer, count):
        if _ucb_pe_unbatchable(designer, count):
            return None
        if (
            designer._refresh_ucb_pe_surrogate_mode()
            != surrogate_config_lib.MODE_SPARSE
        ):
            return None
        pad = designer._converter.padding
        n_all = len(designer._trials) + len(designer._active_trials)
        return compute_ir.BucketKey(
            kind=self.kind,
            pad_trials=pad.pad_trials(len(designer._trials)),
            cont_width=designer._cont_width,
            cat_width=designer._cat_width,
            metric_count=1,
            count=count,
            statics=(
                pad.pad_trials(n_all + count),
                # Both sparse models ride the statics: the m-bucket (train)
                # AND the augmented-capacity model (re-conditioning), so
                # equal keys ⇒ one compiled program per (n, m, count).
                designer._sparse_model(),
                designer._sparse_all_model(count),
                designer._ard,
                designer._vec_opt,
                designer._pick_vec_opt(count),
                designer._batch_restarts(),
                designer._batch_ensemble(),
                designer.config,
                designer.use_trust_region,
                designer.acquisition_budget_policy,
            ),
        )

    def prepare(self, designer, count):
        return _ucb_pe_prepare(designer, count, sparse=True)

    def device_program(self, items, pad_to=None, placement=None):
        from vizier_tpu.parallel import batch_executor

        d0: "VizierGPUCBPEBandit" = items[0]["designer"]
        stack = lambda name: batch_executor.place_batch(  # noqa: E731
            batch_executor.stack_pytrees([it[name] for it in items], pad_to),
            placement,
        )
        count = items[0]["count"]
        two_phase = (
            d0.acquisition_budget_policy == "first_pick_full" and count > 1
        )
        rng_a = stack("rng_acq")
        with jax_timing.device_phase(self.device_phase) as phase:
            states, warm_next, data, segments = _sparse_ucb_pe_flush_program(
                d0._sparse_model(), d0._sparse_all_model(count),
                d0._ard, d0._vec_opt, d0._pick_vec_opt(count),
                stack("md"), stack("all_md"),
                stack("rng_train"), rng_a,
                stack("rng_acq_rest") if two_phase else rng_a,
                stack("warm"), stack("first_has_new"), stack("has_completed"),
                items[0]["restarts"], d0._batch_ensemble(), count,
                d0.config, d0.use_trust_region, two_phase,
            )
            phase.block(segments)
        rows = [1, count - 1] if two_phase else [count]
        return _ucb_pe_demux(
            items, states, warm_next, data, segments, rows, sparse=True
        )

    def finalize(self, designer, item, output):
        states = output["states"]  # sparse [E] leaves
        designer._record_train()
        if designer._warm_update_allowed():
            designer._warm_params_me = [output["warm_next"]]
            designer._warm_is_trained = True
        states_me = jax.tree_util.tree_map(lambda a: a[None], states)
        designer._cached_states = (states_me, [output["data"]])
        designer._last_predictive = sparse_gp.SparseEnsemblePredictive(states)
        designer._last_sparse_state = states
        designer._surrogate_counts["sparse_suggests"] += 1
        out: List[trial_.TrialSuggestion] = []
        for result, aux, rows in output["segments"]:
            out.extend(designer._decode_ucb_pe(result, aux, rows))
        return out

    def prewarm_factory(self, problem, **kwargs):
        return VizierGPUCBPEBandit(problem, **kwargs)


compute_registry.register(VizierGPUCBPEBandit, UCBPEProgram())
compute_registry.register(VizierGPUCBPEBandit, UCBPESparseProgram())
