"""VizierGPUCBPEBandit: the DEFAULT algorithm (GP-UCB with Pure Exploration).

Parity with ``/root/reference/vizier/_src/algorithms/designers/gp_ucb_pe.py:609``
(the service default, ``policy_factory.py:40-47``; algorithm from Contal et
al., "Parallel Gaussian Process Optimization with UCB and Pure Exploration"):
the first suggestion of a batch maximizes UCB; the rest maximize posterior
stddev (pure exploration) restricted to the *relevant region*
``{x : UCB(x) >= max LCB}``, with the GP fantasy-conditioned on each picked
point (label = posterior mean) so PE picks don't collapse onto each other.

TPU-first: the WHOLE batch loop — per-pick Cholesky re-conditioning, region
penalty, and the eagle acquisition sweep — is one jitted ``fori_loop``;
fantasy points are written into spare padded rows of the same ``GPData`` (no
reshapes, no retraces across batch sizes within a padding bucket).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from vizier_tpu import types
from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.designers import gp_bandit
from vizier_tpu.designers.gp import acquisitions
from vizier_tpu.models import gp as gp_lib
from vizier_tpu.models import kernels
from vizier_tpu.optimizers import lbfgs as lbfgs_lib
from vizier_tpu.optimizers import vectorized as vectorized_lib
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import trial as trial_

Array = jax.Array


def _append_fantasy(
    data: gp_lib.GPData, x: kernels.MixedFeatures, label: Array
) -> gp_lib.GPData:
    """Writes (x, label) into the first padded row (no-op if at capacity)."""
    idx = jnp.sum(data.row_mask.astype(jnp.int32))  # first free slot
    return gp_lib.GPData(
        continuous=data.continuous.at[idx].set(x.continuous[0]),
        categorical=data.categorical.at[idx].set(x.categorical[0]),
        labels=data.labels.at[idx].set(label),
        row_mask=data.row_mask.at[idx].set(True),
        cont_dim_mask=data.cont_dim_mask,
        cat_dim_mask=data.cat_dim_mask,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "model",
        "vec_opt",
        "count",
        "ucb_coefficient",
        "explore_coefficient",
        "use_trust_region",
    ),
)
def _suggest_batch(
    model: gp_lib.VizierGaussianProcess,
    vec_opt: vectorized_lib.VectorizedOptimizer,
    ens_params: gp_lib.Params,  # unconstrained, leading ensemble axis
    data: gp_lib.GPData,
    rng: Array,
    count: int,
    ucb_coefficient: float,
    explore_coefficient: float,
    use_trust_region: bool = True,
) -> vectorized_lib.VectorizedOptimizerResult:
    """UCB pick then PE picks with fantasy conditioning; all on device."""
    dc = data.continuous.shape[-1]
    ds = data.categorical.shape[-1]

    def pick(b, carry):
        data, out_cont, out_cat, out_scores, rng = carry
        rng, opt_rng = jax.random.split(rng)
        states = jax.vmap(lambda p: model.precompute(p, data))(ens_params)
        predictive = gp_lib.EnsemblePredictive(states)
        trust = acquisitions.TrustRegion.from_data(data) if use_trust_region else None

        # Relevant-region threshold: max LCB over observed points.
        obs = kernels.MixedFeatures(data.continuous, data.categorical)
        obs_mean, obs_std = predictive.predict(obs)
        lcb_obs = obs_mean - ucb_coefficient * obs_std
        y_star = jnp.max(jnp.where(data.row_mask, lcb_obs, -jnp.inf))

        def score_fn(query: kernels.MixedFeatures) -> Array:
            mean, stddev = predictive.predict(query)
            ucb = mean + ucb_coefficient * stddev
            # b == 0: UCB. b > 0: PE (stddev) penalized outside the region
            # where UCB >= y_star.
            pe = explore_coefficient * stddev - 10.0 * jnp.maximum(y_star - ucb, 0.0)
            value = jnp.where(b == 0, ucb, pe)
            if trust is not None:
                value = value - trust.penalty(query)
            return value

        result = vec_opt(score_fn, opt_rng, count=1)
        x = kernels.MixedFeatures(
            result.features.continuous[:1], result.features.categorical[:1]
        )
        mean, _ = predictive.predict(x)
        data = _append_fantasy(data, x, mean[0])
        out_cont = out_cont.at[b].set(x.continuous[0])
        out_cat = out_cat.at[b].set(x.categorical[0])
        out_scores = out_scores.at[b].set(result.scores[0])
        return data, out_cont, out_cat, out_scores, rng

    init = (
        data,
        jnp.zeros((count, dc), data.continuous.dtype),
        jnp.zeros((count, ds), data.categorical.dtype),
        jnp.zeros((count,), jnp.float32),
        rng,
    )
    _, out_cont, out_cat, out_scores, _ = jax.lax.fori_loop(0, count, pick, init)
    return vectorized_lib.VectorizedOptimizerResult(
        kernels.MixedFeatures(out_cont, out_cat), out_scores
    )


@dataclasses.dataclass
class VizierGPUCBPEBandit(gp_bandit.VizierGPBandit):
    """GP-UCB-PE batch designer (service DEFAULT)."""

    explore_coefficient: float = 1.0

    def suggest(self, count: Optional[int] = None) -> List[trial_.TrialSuggestion]:
        count = count or 1
        n = len(self._trials)
        if n < self.num_seed_trials:
            return self._seed_suggestions(count)
        # Multi-objective and transfer-learning studies route through the
        # parent's dedicated paths (UCB-PE batching is single-objective).
        if self._num_objectives() > 1:
            return self._suggest_multiobjective(count)
        if getattr(self, "_priors", None):
            return self._suggest_with_priors(count)

        # Reserve padded capacity for the batch's fantasy rows.
        conv = self._converter
        data = gp_lib.GPData.from_model_data(
            self._warped_model_data(extra_rows=count)
        )

        coll = self._model.param_collection()
        inits = coll.batch_random_init_unconstrained(self._next_rng(), self.ard_restarts)
        loss_fn = lambda p: self._model.neg_log_likelihood(p, data)
        result = self._ard(loss_fn, inits, best_n=max(self.ensemble_size, 1))
        self._last_predictive = gp_lib.EnsemblePredictive(
            jax.vmap(lambda p: self._model.precompute(p, data))(result.params)
        )

        batch = _suggest_batch(
            self._model,
            self._vec_opt,
            result.params,
            data,
            self._next_rng(),
            count,
            self.ucb_coefficient,
            self.explore_coefficient,
            self.use_trust_region,
        )
        cont_rows = np.asarray(batch.features.continuous)
        cat_rows = np.asarray(batch.features.categorical)
        scores = np.asarray(batch.scores)
        suggestions = []
        for i in range(count):
            params = conv.to_parameters(
                cont_rows[i : i + 1, : conv.encoder.num_continuous],
                cat_rows[i : i + 1, : conv.encoder.num_categorical],
            )[0]
            s = trial_.TrialSuggestion(parameters=params)
            s.metadata.ns("gp_ucb_pe")["acquisition"] = float(scores[i])
            s.metadata.ns("gp_ucb_pe")["kind"] = "ucb" if i == 0 else "pe"
            suggestions.append(s)
        return suggestions


def default_factory(
    problem: base_study_config.ProblemStatement, seed: Optional[int] = None, **kwargs
) -> VizierGPUCBPEBandit:
    return VizierGPUCBPEBandit(problem, rng_seed=seed or 0, **kwargs)
