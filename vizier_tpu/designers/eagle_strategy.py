"""EagleStrategyDesigner: ask/tell firefly algorithm as a Designer.

Parity with
``/root/reference/vizier/_src/algorithms/designers/eagle_strategy/eagle_strategy.py:95``
(+ ``eagle_strategy_utils.py``): a pool of fireflies explores the scaled
feature space. Key behaviors measured to matter (r2 parity suite):

- the pool fills with RANDOM suggestions until a dimension-dependent
  capacity ``10 + round((d^1.2 + d)/2)`` — premature swarming on a few
  points is what made the naive version lose 20-D BBOB by 30x;
- moves are sequential *interpolations* toward (away from) each shuffled
  pool member with weight ``±exp(-visibility · 10·d²/dof)`` per parameter
  type — not an averaged additive force;
- perturbation is a max-normalized Laplace direction scaled by the fly's
  perturbation level (fraction of the scaled range); categorical values
  resample with probability ``min(level · factor, 1)``;
- a fly that fails to improve decays its perturbation by ``penalize_factor``
  and is evicted below the lower bound (unless it is the incumbent), making
  room for fresh random flies.

State is partially serializable (trial-level algorithm checkpointing via
study metadata). Distinct from ``vizier_tpu.optimizers.eagle`` — that one is
the jitted *acquisition* sweep; this one spends real (expensive) trials.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np

from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.converters import core as converters
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import common
from vizier_tpu.pyvizier import trial as trial_
from vizier_tpu.utils import json_utils, serializable

_NS = "eagle"


@dataclasses.dataclass(frozen=True)
class FireflyConfig:
    """Reference ``FireflyAlgorithmConfig`` defaults."""

    gravity: float = 1.0
    negative_gravity: float = 0.02
    visibility: float = 3.0
    categorical_visibility: float = 0.2
    perturbation: float = 0.1
    max_perturbation: float = 0.5
    perturbation_lower_bound: float = 1e-3
    categorical_perturbation_factor: float = 25.0
    pure_categorical_perturbation: float = 0.1
    explore_rate: float = 1.0
    penalize_factor: float = 0.9
    pool_size_factor: float = 1.2
    max_pool_size: int = 1000


@dataclasses.dataclass
class _Fly:
    x: np.ndarray  # [Dc] scaled continuous
    cat: np.ndarray  # [Ds] int
    reward: float
    perturbation: float


@dataclasses.dataclass
class EagleStrategyDesigner(core_lib.PartiallySerializableDesigner):
    problem: base_study_config.ProblemStatement
    config: FireflyConfig = FireflyConfig()
    seed: Optional[int] = None

    def __post_init__(self):
        self._converter = converters.TrialToModelInputConverter.from_problem(
            self.problem
        )
        self._enc = self._converter.encoder
        self._rng = np.random.default_rng(self.seed)
        df = max(self._enc.num_continuous + self._enc.num_categorical, 1)
        self._capacity = min(
            10 + round((df**self.config.pool_size_factor + df) * 0.5),
            self.config.max_pool_size,
        )
        self._pool: Dict[int, _Fly] = {}
        self._next_id = 0
        self._move_order: List[int] = []

    # -- ask ---------------------------------------------------------------

    def _random_point(self):
        x = self._rng.uniform(size=self._enc.num_continuous)
        cat = np.asarray(
            [self._rng.integers(0, s) for s in self._enc.category_sizes],
            dtype=np.int32,
        )
        return x, cat

    def _pull_weight(self, d2: float, dof: int, better: bool, visibility: float):
        direction = self.config.gravity if better else -self.config.negative_gravity
        if dof == 0:
            return 0.0
        w = float(np.exp(-visibility * (d2 / dof) * 10.0)) * direction
        # Exploration accentuation (reference `_mutate_fly`).
        er = self.config.explore_rate
        return er * w + (1.0 - er) if w > 0.5 else er * w

    def _mutate(self, fly: _Fly):
        """Sequential interpolation pulls from every (shuffled) pool member."""
        x = fly.x.copy()
        cat = fly.cat.copy()
        others = [f for fid, f in self._pool.items() if f is not fly]
        self._rng.shuffle(others)
        dc = self._enc.num_continuous
        ds = self._enc.num_categorical
        for other in others:
            better = other.reward > fly.reward
            if dc:
                d2 = float(np.sum((other.x - x) ** 2))
                w = self._pull_weight(d2, dc, better, self.config.visibility)
                x = other.x * w + x * (1.0 - w)
            if ds:
                # Reference counts categorical MATCHES into the "distance".
                d2 = float(np.sum(other.cat == cat))
                w = self._pull_weight(
                    d2, ds, better, self.config.categorical_visibility
                )
                if w >= 1.0:
                    cat = other.cat.copy()
                elif w > 0.0:
                    pick = self._rng.uniform(size=ds) < w
                    cat = np.where(pick, other.cat, cat)
        return np.clip(x, 0.0, 1.0), cat

    def _perturb(self, x: np.ndarray, cat: np.ndarray, level: float):
        """Max-normalized Laplace direction scaled by the perturbation level."""
        n = self._enc.num_continuous + self._enc.num_categorical
        if n == 0:
            return x, cat
        if self._enc.num_continuous == 0:
            # Pure-categorical space (reference ``create_perturbations``,
            # eagle_strategy_utils.py:299-302): a CONSTANT resample
            # probability per parameter — no Laplace direction and no
            # ×categorical_perturbation_factor. Measured to matter: the
            # scaled path resamples ~every category each move on
            # NASBench-201, wiping out local search (r4 verdict weak #3).
            cat = cat.copy()
            for j, size in enumerate(self._enc.category_sizes):
                if self._rng.uniform() < self.config.pure_categorical_perturbation:
                    cat[j] = self._rng.integers(0, size)
            return x, cat
        raw = self._rng.laplace(size=n)
        direction = raw / max(np.max(np.abs(raw)), 1e-12)
        pert = direction * level
        if self._enc.num_continuous:
            x = np.clip(x + pert[: self._enc.num_continuous], 0.0, 1.0)
        for j, size in enumerate(self._enc.category_sizes):
            p = min(
                abs(pert[self._enc.num_continuous + j])
                * self.config.categorical_perturbation_factor,
                1.0,
            )
            if self._rng.uniform() < p:
                cat = cat.copy()
                cat[j] = self._rng.integers(0, size)
        return x, cat

    def suggest(self, count: Optional[int] = None) -> List[trial_.TrialSuggestion]:
        count = count or 1
        out = []
        for _ in range(count):
            # Pool-occupancy check (reference `_suggest_one`): random fill
            # whenever the pool is below capacity — initially, AND whenever
            # an exhausted fly has been evicted.
            if len(self._pool) < self._capacity:
                x, cat = self._random_point()
                fly_id = self._next_id
                self._next_id += 1
            else:
                if not self._move_order:
                    self._move_order = list(self._pool.keys())
                fly_id = self._move_order.pop(0)
                fly = self._pool.get(fly_id)
                if fly is None:  # evicted since scheduling; fall back random
                    x, cat = self._random_point()
                else:
                    x, cat = self._mutate(fly)
                    x, cat = self._perturb(x, cat, fly.perturbation)
            params = self._converter.to_parameters(x[None, :], cat[None, :])[0]
            s = trial_.TrialSuggestion(parameters=params)
            s.metadata.ns(_NS)["fly"] = str(fly_id)
            out.append(s)
        return out

    # -- tell --------------------------------------------------------------

    def _best_id(self) -> Optional[int]:
        if not self._pool:
            return None
        return max(self._pool, key=lambda fid: self._pool[fid].reward)

    def update(
        self,
        completed: core_lib.CompletedTrials,
        all_active: core_lib.ActiveTrials = core_lib.ActiveTrials(),
    ) -> None:
        del all_active
        cfg = self.config
        for t in completed.trials:
            labels = self._converter.metrics.encode([t])[0]
            reward = float(labels[0]) if np.isfinite(labels[0]) else -np.inf
            cont, cat = self._enc.encode([t])
            fly_raw = t.metadata.ns(_NS).get("fly")
            if fly_raw is None:
                fly_id = self._next_id  # foreign trial: fresh fly id
                self._next_id += 1
            else:
                fly_id = int(fly_raw)
            fly = self._pool.get(fly_id)
            if fly is None:
                if len(self._pool) < self._capacity and np.isfinite(reward):
                    self._pool[fly_id] = _Fly(
                        x=cont[0].astype(np.float64),
                        cat=cat[0].astype(np.int32),
                        reward=reward,
                        perturbation=cfg.perturbation,
                    )
                elif np.isfinite(reward):
                    # Pool full: adopt into the closest fly ONLY if the trial
                    # improves on it — the closest parent is not responsible
                    # for a foreign failure (reference _assign_closest_parent),
                    # so non-improving orphans must not penalize it.
                    nearest = min(
                        self._pool,
                        key=lambda fid: np.sum(
                            (self._pool[fid].x - cont[0]) ** 2
                        )
                        + np.sum(self._pool[fid].cat != cat[0]),
                    )
                    if reward > self._pool[nearest].reward:
                        self._settle(nearest, cont[0], cat[0], reward)
                continue
            self._settle(fly_id, cont[0], cat[0], reward)

    def _settle(self, fly_id: int, x, cat, reward: float) -> None:
        """Improvement adopts the move; failure decays the perturbation."""
        cfg = self.config
        fly = self._pool[fly_id]
        if reward > fly.reward:
            # Perturbation stays put on improvement (the reference only
            # boosts it when a fly is stuck repeating the same point).
            fly.x = np.asarray(x, dtype=np.float64)
            fly.cat = np.asarray(cat, dtype=np.int32)
            fly.reward = reward
        else:
            fly.perturbation *= cfg.penalize_factor
            if (
                fly.perturbation < cfg.perturbation_lower_bound
                and fly_id != self._best_id()
                and len(self._pool) >= self._capacity
            ):
                # Exhausted AND the pool is full: evict to make room for a
                # fresh random fly. Below capacity the stalled fly is kept —
                # in studies with few feasible trials it still carries signal.
                del self._pool[fly_id]

    # -- PartiallySerializable --------------------------------------------

    def dump(self) -> common.Metadata:
        md = common.Metadata()
        md["eagle"] = json_utils.dumps(
            {
                "ids": list(self._pool.keys()),
                "xs": np.stack([f.x for f in self._pool.values()])
                if self._pool
                else np.zeros((0, self._enc.num_continuous)),
                "cats": np.stack([f.cat for f in self._pool.values()])
                if self._pool
                else np.zeros((0, self._enc.num_categorical), dtype=np.int32),
                "rewards": [f.reward for f in self._pool.values()],
                "perturbations": [f.perturbation for f in self._pool.values()],
                "next_id": self._next_id,
            }
        )
        return md

    def load(self, metadata: common.Metadata) -> None:
        raw = metadata.get("eagle")
        if raw is None:
            raise serializable.DecodeError("Missing 'eagle' state.")
        try:
            state = json_utils.loads(raw)
            xs = np.asarray(state["xs"], dtype=np.float64)
            cats = np.asarray(state["cats"], dtype=np.int32)
            self._pool = {
                int(fid): _Fly(
                    x=xs[i],
                    cat=cats[i],
                    reward=float(state["rewards"][i]),
                    perturbation=float(state["perturbations"][i]),
                )
                for i, fid in enumerate(state["ids"])
            }
            self._next_id = int(state["next_id"])
            self._move_order = []
        except (KeyError, ValueError, TypeError, IndexError) as e:
            raise serializable.DecodeError(f"Bad eagle state: {e}")
