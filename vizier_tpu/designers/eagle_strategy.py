"""EagleStrategyDesigner: ask/tell firefly algorithm as a Designer.

Parity with
``/root/reference/vizier/_src/algorithms/designers/eagle_strategy/eagle_strategy.py:95``:
a pool of fireflies explores the scaled feature space; each suggestion is a
perturbed move of one fly (tagged in metadata), and ``update`` feeds the
objective back to that fly — improving moves are adopted, failing flies lose
perturbation and are eventually re-seeded. State is partially serializable.

Shares the firefly force model with the vectorized acquisition optimizer
(``vizier_tpu.optimizers.eagle``) but lives at the trial level: evaluations
here are real (expensive) trials, not acquisition scores.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np

from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.converters import core as converters
from vizier_tpu.optimizers import eagle as eagle_lib
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import common
from vizier_tpu.pyvizier import trial as trial_
from vizier_tpu.utils import json_utils, serializable

_NS = "eagle"


@dataclasses.dataclass
class EagleStrategyDesigner(core_lib.PartiallySerializableDesigner):
    problem: base_study_config.ProblemStatement
    config: eagle_lib.EagleStrategyConfig = dataclasses.field(
        default_factory=lambda: eagle_lib.EagleStrategyConfig(pool_size=12)
    )
    seed: Optional[int] = None

    def __post_init__(self):
        self._converter = converters.TrialToModelInputConverter.from_problem(
            self.problem
        )
        self._enc = self._converter.encoder
        self._rng = np.random.default_rng(self.seed)
        p = self.config.pool_size
        self._features = self._rng.uniform(size=(p, self._enc.num_continuous))
        self._categorical = np.stack(
            [
                self._rng.integers(0, max(s, 1), size=p)
                for s in (self._enc.category_sizes or [1])
            ],
            axis=1,
        )[:, : self._enc.num_categorical].astype(np.int32)
        if self._enc.num_categorical == 0:
            self._categorical = np.zeros((p, 0), dtype=np.int32)
        self._rewards = np.full(p, -np.inf)
        self._perturbations = np.full(p, self.config.perturbation)
        self._next_fly = 0

    # -- ask ---------------------------------------------------------------

    def _propose_move(self, fly: int) -> tuple:
        cfg = self.config
        x = self._features[fly]
        pull = np.zeros_like(x)
        if np.isfinite(self._rewards[fly]):
            for other in range(cfg.pool_size):
                if other == fly or not np.isfinite(self._rewards[other]):
                    continue
                diff = self._features[other] - x
                scale = np.exp(-np.sum(diff**2) / (2 * cfg.visibility**2 + 1e-12))
                if self._rewards[other] > self._rewards[fly]:
                    pull += cfg.gravity * scale * diff
                else:
                    pull -= cfg.negative_gravity * scale * diff
            pull /= max(cfg.pool_size - 1, 1)
        new_x = np.clip(
            x + pull + self._perturbations[fly] * self._rng.standard_normal(x.shape),
            0.0,
            1.0,
        )
        cat = self._categorical[fly].copy()
        for j, size in enumerate(self._enc.category_sizes):
            if self._rng.uniform() < min(
                self._perturbations[fly] * cfg.categorical_perturbation_factor, 1.0
            ):
                cat[j] = self._rng.integers(0, size)
        return new_x, cat

    def suggest(self, count: Optional[int] = None) -> List[trial_.TrialSuggestion]:
        count = count or 1
        out = []
        for _ in range(count):
            fly = self._next_fly % self.config.pool_size
            self._next_fly += 1
            new_x, cat = self._propose_move(fly)
            params = self._converter.to_parameters(
                new_x[None, :], cat[None, :]
            )[0]
            s = trial_.TrialSuggestion(parameters=params)
            s.metadata.ns(_NS)["fly"] = str(fly)
            out.append(s)
        return out

    # -- tell --------------------------------------------------------------

    def update(
        self,
        completed: core_lib.CompletedTrials,
        all_active: core_lib.ActiveTrials = core_lib.ActiveTrials(),
    ) -> None:
        del all_active
        cfg = self.config
        for t in completed.trials:
            labels = self._converter.metrics.encode([t])[0]
            reward = labels[0] if np.isfinite(labels[0]) else -np.inf
            fly_raw = t.metadata.ns(_NS).get("fly")
            if fly_raw is None:
                # Foreign trial (e.g. prior data): adopt into the weakest fly.
                fly = int(np.argmin(self._rewards))
            else:
                fly = int(fly_raw) % cfg.pool_size
            cont, cat = self._enc.encode([t])
            if reward > self._rewards[fly]:
                self._features[fly] = cont[0]
                if self._enc.num_categorical:
                    self._categorical[fly] = cat[0]
                self._rewards[fly] = reward
                self._perturbations[fly] = cfg.perturbation
            else:
                self._perturbations[fly] *= cfg.penalize_factor
                if self._perturbations[fly] < cfg.perturbation_lower_bound:
                    best = int(np.argmax(self._rewards))
                    if fly != best:
                        self._features[fly] = self._rng.uniform(
                            size=self._enc.num_continuous
                        )
                        if self._enc.num_categorical:
                            self._categorical[fly] = [
                                self._rng.integers(0, s)
                                for s in self._enc.category_sizes
                            ]
                        self._rewards[fly] = -np.inf
                        self._perturbations[fly] = cfg.perturbation

    # -- PartiallySerializable --------------------------------------------

    def dump(self) -> common.Metadata:
        md = common.Metadata()
        md["eagle"] = json_utils.dumps(
            {
                "features": self._features,
                "categorical": self._categorical,
                "rewards": self._rewards,
                "perturbations": self._perturbations,
                "next_fly": self._next_fly,
            }
        )
        return md

    def load(self, metadata: common.Metadata) -> None:
        raw = metadata.get("eagle")
        if raw is None:
            raise serializable.DecodeError("Missing 'eagle' state.")
        try:
            state = json_utils.loads(raw)
            self._features = np.asarray(state["features"], dtype=np.float64)
            self._categorical = np.asarray(state["categorical"], dtype=np.int32)
            self._rewards = np.asarray(state["rewards"], dtype=np.float64)
            self._perturbations = np.asarray(state["perturbations"], dtype=np.float64)
            self._next_fly = int(state["next_fly"])
        except (KeyError, ValueError, TypeError) as e:
            raise serializable.DecodeError(f"Bad eagle state: {e}")
