"""Shape-quantization schedules.

Parity with ``/root/reference/vizier/pyvizier/converters/padding.py:28,55``:
pads the number of trials and feature dimensions up to quantized sizes so the
jit cache hits as studies grow — the single most load-bearing perf discipline
in this codebase (every retrace costs ~seconds of XLA compile on TPU).
"""

from __future__ import annotations

import dataclasses
import enum
import math
from typing import List


class PaddingType(enum.Enum):
    NONE = "NONE"
    MULTIPLES_OF_10 = "MULTIPLES_OF_10"
    POWERS_OF_2 = "POWERS_OF_2"

    def pad(self, n: int) -> int:
        if n < 0:
            raise ValueError(f"Cannot pad negative size {n}.")
        if self == PaddingType.NONE:
            return n
        if self == PaddingType.MULTIPLES_OF_10:
            return max(10, math.ceil(n / 10) * 10)
        # POWERS_OF_2: next power of two, minimum 8 to bound retrace count
        # and keep the last MXU tile reasonably full.
        return max(8, 1 << max(0, (n - 1)).bit_length())


@dataclasses.dataclass(frozen=True)
class PaddingSchedule:
    """Per-axis padding types for (trials, continuous dims, categorical dims)."""

    num_trials: PaddingType = PaddingType.NONE
    num_features: PaddingType = PaddingType.NONE
    num_metrics: PaddingType = PaddingType.NONE

    def pad_trials(self, n: int) -> int:
        return self.num_trials.pad(n)

    def pad_features(self, n: int) -> int:
        return self.num_features.pad(n)

    def pad_metrics(self, n: int) -> int:
        return self.num_metrics.pad(n)

    def trial_bucket_grid(self, max_trials: int, start: int = 1) -> List[int]:
        """The distinct ``pad_trials`` buckets covering ``start..max_trials``.

        This is the grid the serving batch-executor prewarm walks: every
        study whose trial count is in range lands in exactly one of these
        padded sizes, so compiling one program per grid entry covers the
        whole range (``vizier_tpu.parallel.batch_executor``).
        """
        if max_trials < start:
            return []
        out: List[int] = []
        n = start
        while n <= max_trials:
            bucket = self.pad_trials(n)
            out.append(bucket)
            # NONE padding makes every size its own bucket; still terminate.
            n = max(bucket, n) + 1
        return out


DEFAULT_PADDING = PaddingSchedule(
    num_trials=PaddingType.POWERS_OF_2,
    num_features=PaddingType.NONE,
    num_metrics=PaddingType.NONE,
)
