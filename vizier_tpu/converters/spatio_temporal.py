"""Spatio-temporal converters: per-step measurement curves → arrays.

Parity with ``/root/reference/vizier/pyvizier/converters/spatio_temporal.py``
(``:234,341``): early-stopping and curve-extrapolation models consume
``[num_trials, num_steps]`` label matrices aligned on a common step grid;
this module extracts and aligns intermediate measurements.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import trial as trial_


@dataclasses.dataclass
class TimedLabels:
    """One trial's curve: positions [T] and values [T, M]."""

    positions: np.ndarray
    values: np.ndarray


@dataclasses.dataclass
class TimedLabelsExtractor:
    """Extracts per-trial measurement curves for the configured metrics.

    ``value_mode='cummax'`` converts each metric's curve to its running
    best (goal-aware: running min for MINIMIZE metrics) — the monotone form
    curve-extrapolation early-stopping models expect (reference
    ``TimedLabelsExtractor._cummax_fn``, ``spatio_temporal.py:104``).
    """

    metrics: base_study_config.MetricsConfig
    use_steps: bool = True
    value_mode: str = "raw"  # 'raw' | 'cummax'

    def __post_init__(self):
        if self.value_mode not in ("raw", "cummax"):
            raise ValueError(f"Unknown value_mode {self.value_mode!r}.")

    def convert_trial(self, trial: trial_.Trial) -> TimedLabels:
        names = [m.name for m in self.metrics]
        positions: List[float] = []
        rows: List[List[float]] = []
        for m in trial.measurements:
            positions.append(m.steps if self.use_steps else m.elapsed_secs)
            rows.append(
                [
                    m.metrics[n].value if n in m.metrics else np.nan
                    for n in names
                ]
            )
        values = np.asarray(rows, dtype=np.float64).reshape(len(rows), len(names))
        if self.value_mode == "cummax" and len(rows):
            for j, info in enumerate(self.metrics):
                col = values[:, j]
                if info.goal.is_maximize:
                    values[:, j] = np.fmax.accumulate(col)
                else:
                    values[:, j] = np.fmin.accumulate(col)
        return TimedLabels(
            positions=np.asarray(positions, dtype=np.float64),
            values=values,
        )

    def convert(self, trials: Sequence[trial_.Trial]) -> List[TimedLabels]:
        return [self.convert_trial(t) for t in trials]

    def extract_all_timestamps(
        self, trials: Sequence[trial_.Trial]
    ) -> np.ndarray:
        """Sorted union of every trial's measurement positions."""
        curves = self.convert(trials)
        parts = [c.positions for c in curves if len(c.positions)]
        return np.unique(np.concatenate(parts)) if parts else np.zeros(0)

    def to_timestamps(
        self, positions: np.ndarray, *, max_position: Optional[float] = None
    ) -> np.ndarray:
        """Normalizes raw positions into [0, 1] (for temporal kernels)."""
        positions = np.asarray(positions, dtype=np.float64)
        if max_position is None:
            max_position = float(positions.max()) if positions.size else 1.0
        return positions / max(max_position, 1e-12)


@dataclasses.dataclass
class SparseSpatioTemporalConverter:
    """Aligns trial curves onto a common step grid → [N, T, M] with a mask.

    Values are carried forward from the last reported position (the usual
    convention for training-curve models); the mask marks grid points at or
    beyond each trial's first measurement.
    """

    extractor: TimedLabelsExtractor

    def to_arrays(
        self, trials: Sequence[trial_.Trial], *, grid: Optional[np.ndarray] = None
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        curves = self.extractor.convert(trials)
        if grid is None:
            all_positions = np.concatenate(
                [c.positions for c in curves if len(c.positions)] or [np.zeros(0)]
            )
            grid = np.unique(all_positions)
        n, t = len(trials), len(grid)
        m = len(self.extractor.metrics)
        values = np.full((n, t, m), np.nan)
        mask = np.zeros((n, t), dtype=bool)
        for i, c in enumerate(curves):
            if not len(c.positions):
                continue
            order = np.argsort(c.positions)
            pos, val = c.positions[order], c.values[order]
            idx = np.searchsorted(pos, grid, side="right") - 1
            valid = idx >= 0  # grid points at/after the trial's first report
            safe = np.clip(idx, 0, len(pos) - 1)
            values[i] = val[safe]
            values[i, ~valid] = np.nan
            mask[i] = valid
        return values, mask, grid


@dataclasses.dataclass
class DenseSpatioTemporalConverter:
    """Interpolated dense curves on a fixed-size grid → [N, T, M].

    Unlike the sparse carry-forward aligner, values are linearly interpolated
    inside each trial's reported range (and clamped at its ends) on an
    evenly-spaced grid — the input format for batched curve-regression
    models (``algorithms/regression.py``): fixed T regardless of each
    trial's measurement cadence.
    """

    extractor: TimedLabelsExtractor
    num_steps: int = 16

    def to_arrays(
        self, trials: Sequence[trial_.Trial], *, max_position: Optional[float] = None
    ) -> Tuple[np.ndarray, np.ndarray]:
        curves = self.extractor.convert(trials)
        if max_position is None:
            tops = [c.positions.max() for c in curves if len(c.positions)]
            max_position = float(max(tops)) if tops else 1.0
        grid = np.linspace(0.0, max_position, self.num_steps)
        n = len(trials)
        m = len(self.extractor.metrics)
        values = np.full((n, self.num_steps, m), np.nan)
        for i, c in enumerate(curves):
            if not len(c.positions):
                continue
            order = np.argsort(c.positions)
            pos, val = c.positions[order], c.values[order]
            for j in range(m):
                finite = np.isfinite(val[:, j])
                if finite.any():
                    values[i, :, j] = np.interp(grid, pos[finite], val[finite, j])
        return values, grid

    def to_xty(
        self,
        trials: Sequence[trial_.Trial],
        search_space,
        *,
        max_position: Optional[float] = None,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(X [N, D], t [T], Y [N, T, M]): the spatio-temporal model input.

        Reference ``DenseSpatioTemporalConverter.to_xty``
        (``spatio_temporal.py:481``): spatial features via the standard
        search-space encoding (continuous block + categorical indices
        appended as float columns), timestamps normalized to [0, 1].
        """
        from vizier_tpu.converters import core as converters_core

        enc = converters_core.SearchSpaceEncoder(search_space)
        cont, cat = enc.encode(trials)
        x = np.concatenate([cont, cat.astype(np.float64)], axis=1)
        y, grid = self.to_arrays(trials, max_position=max_position)
        t = self.extractor.to_timestamps(grid, max_position=max_position)
        return x, t, y
