"""Trial ⇄ array converters and padding schedules."""

from vizier_tpu.converters.core import (
    MetricsEncoder,
    ParameterSpec,
    SearchSpaceEncoder,
    SpecType,
    TrialToArrayConverter,
    TrialToModelInputConverter,
)
from vizier_tpu.converters.padding import DEFAULT_PADDING, PaddingSchedule, PaddingType

__all__ = [
    "DEFAULT_PADDING",
    "MetricsEncoder",
    "PaddingSchedule",
    "PaddingType",
    "ParameterSpec",
    "SearchSpaceEncoder",
    "SpecType",
    "TrialToArrayConverter",
    "TrialToModelInputConverter",
]
