"""Trial ⇄ array converters.

TPU-first rebuild of the reference converter stack
(``/root/reference/vizier/pyvizier/converters/core.py:36,539,1217`` and
``jnp_converters.py:147``). Responsibilities:

- scale continuous/integer/discrete parameters into ``[0, 1]`` model space
  (LINEAR / LOG / REVERSE_LOG / index-based for discrete);
- map categorical parameters to integer category indices (the GP's
  categorical kernel consumes indices; one-hot is available for flat-vector
  consumers like evolutionary strategies);
- map metrics to a ``[N, M]`` label matrix, sign-flipped so every objective
  is MAXIMIZE, with NaN for infeasible/missing values;
- invert all of the above (decode model-space points back to parameter
  dicts, snapping integers/discretes to feasible values);
- assemble padded ``ModelData`` (``types.PaddedArray``) under a
  ``PaddingSchedule`` so jit caches hit as the study grows.

Conversion itself is cheap host-side numpy; everything downstream of the
produced arrays is jit/XLA. Conditional search spaces are rejected here
(as in the reference GP path); tree-structured spaces are handled by the
non-model designers directly on pyvizier objects.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from vizier_tpu import types
from vizier_tpu.converters import padding as padding_lib
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import parameter_config as pc
from vizier_tpu.pyvizier import trial as trial_


class SpecType(enum.Enum):
    """How one parameter is represented in model space."""

    CONTINUOUS = "CONTINUOUS"  # one float column in [0, 1]
    CATEGORICAL = "CATEGORICAL"  # one integer column in [0, K)


@dataclasses.dataclass(frozen=True)
class ParameterSpec:
    """Model-space description of a single parameter."""

    name: str
    type: SpecType
    num_categories: int = 0  # CATEGORICAL only


class _ContinuousCodec:
    """Scales one numeric parameter to/from [0, 1]."""

    def __init__(self, config: pc.ParameterConfig):
        self._config = config
        self._scale = config.scale_type or pc.ScaleType.LINEAR
        if config.type == pc.ParameterType.DISCRETE:
            self._values = np.asarray([float(v) for v in config.feasible_values])
        else:
            self._values = None
        lo, hi = config.bounds
        self._lo, self._hi = float(lo), float(hi)
        if self._scale.is_nonlinear() and self._lo <= 0:
            raise ValueError(f"{config.name}: log scaling needs positive bounds.")

    def encode(self, raw: np.ndarray) -> np.ndarray:
        lo, hi = self._lo, self._hi
        if self._scale == pc.ScaleType.UNIFORM_DISCRETE and self._values is not None:
            idx = np.abs(raw[:, None] - self._values[None, :]).argmin(axis=1)
            denom = max(len(self._values) - 1, 1)
            return idx / denom
        if hi == lo:
            return np.full_like(raw, 0.5, dtype=np.float64)
        if self._scale == pc.ScaleType.LOG:
            return (np.log(raw) - np.log(lo)) / (np.log(hi) - np.log(lo))
        if self._scale == pc.ScaleType.REVERSE_LOG:
            return 1.0 - (np.log(hi + lo - raw) - np.log(lo)) / (np.log(hi) - np.log(lo))
        return (raw - lo) / (hi - lo)

    def decode(self, scaled: np.ndarray) -> np.ndarray:
        scaled = np.clip(scaled, 0.0, 1.0)
        lo, hi = self._lo, self._hi
        if self._scale == pc.ScaleType.UNIFORM_DISCRETE and self._values is not None:
            denom = max(len(self._values) - 1, 1)
            idx = np.clip(np.round(scaled * denom), 0, len(self._values) - 1).astype(int)
            return self._values[idx]
        if hi == lo:
            raw = np.full_like(scaled, lo, dtype=np.float64)
        elif self._scale == pc.ScaleType.LOG:
            raw = np.exp(np.log(lo) + scaled * (np.log(hi) - np.log(lo)))
        elif self._scale == pc.ScaleType.REVERSE_LOG:
            raw = hi + lo - np.exp(np.log(lo) + (1.0 - scaled) * (np.log(hi) - np.log(lo)))
        else:
            raw = lo + scaled * (hi - lo)
        raw = np.clip(raw, lo, hi)
        if self._config.type == pc.ParameterType.INTEGER:
            return np.round(raw)
        if self._values is not None:  # DISCRETE: snap to nearest feasible.
            idx = np.abs(raw[:, None] - self._values[None, :]).argmin(axis=1)
            return self._values[idx]
        return raw

    def to_value(self, raw: float) -> pc.ParameterValueTypes:
        return self._config.cast_value(raw)


class SearchSpaceEncoder:
    """Encodes a flat search space into continuous + categorical columns."""

    def __init__(
        self,
        search_space: pc.SearchSpace,
        *,
        max_discrete_indices: int = 0,
    ):
        """Args:

        search_space: a *flat* (non-conditional) search space.
        max_discrete_indices: if > 0, DISCRETE/INTEGER parameters with at
          most this many feasible values are encoded as CATEGORICAL indices
          instead of scaled floats (mirrors the reference's
          ``max_discrete_indices`` behavior, ``converters/core.py:367``).
        """
        if search_space.is_conditional:
            raise ValueError(
                "SearchSpaceEncoder requires a flat search space; conditional "
                "spaces are served by tree-aware designers."
            )
        self._space = search_space
        self._continuous: List[pc.ParameterConfig] = []
        self._categorical: List[pc.ParameterConfig] = []
        for config in search_space.parameters:
            if config.type == pc.ParameterType.CATEGORICAL:
                self._categorical.append(config)
            elif config.type == pc.ParameterType.CUSTOM:
                raise ValueError(f"Cannot encode CUSTOM parameter {config.name!r}.")
            elif (
                max_discrete_indices
                and config.type in (pc.ParameterType.DISCRETE, pc.ParameterType.INTEGER)
                and config.num_feasible_values <= max_discrete_indices
            ):
                self._categorical.append(config)
            else:
                self._continuous.append(config)
        self._codecs = {c.name: _ContinuousCodec(c) for c in self._continuous}
        self._categories: Dict[str, List[pc.ParameterValueTypes]] = {}
        for c in self._categorical:
            if c.type == pc.ParameterType.CATEGORICAL:
                self._categories[c.name] = list(c.feasible_values)
            else:
                self._categories[c.name] = [float(v) for v in c.feasible_values]

    # -- specs -------------------------------------------------------------

    @property
    def continuous_specs(self) -> List[ParameterSpec]:
        return [ParameterSpec(c.name, SpecType.CONTINUOUS) for c in self._continuous]

    @property
    def categorical_specs(self) -> List[ParameterSpec]:
        return [
            ParameterSpec(c.name, SpecType.CATEGORICAL, len(self._categories[c.name]))
            for c in self._categorical
        ]

    @property
    def num_continuous(self) -> int:
        return len(self._continuous)

    @property
    def num_categorical(self) -> int:
        return len(self._categorical)

    @property
    def category_sizes(self) -> List[int]:
        return [len(self._categories[c.name]) for c in self._categorical]

    @property
    def onehot_dim(self) -> int:
        return self.num_continuous + sum(self.category_sizes)

    # -- encoding ----------------------------------------------------------

    def encode(
        self, trials: Sequence[trial_.Trial]
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Returns (continuous [N, Dc] float64, categorical [N, Ds] int32)."""
        n = len(trials)
        cont = np.zeros((n, self.num_continuous), dtype=np.float64)
        for j, config in enumerate(self._continuous):
            raw = np.asarray(
                [
                    float(
                        t.parameters.get_value(config.name)
                        if config.name in t.parameters
                        else config.first_feasible_value()
                    )
                    for t in trials
                ]
            )
            cont[:, j] = self._codecs[config.name].encode(raw)
        cat = np.zeros((n, self.num_categorical), dtype=np.int32)
        for j, config in enumerate(self._categorical):
            cats = self._categories[config.name]
            lookup = {v: i for i, v in enumerate(cats)}
            for i, t in enumerate(trials):
                v = t.parameters.get_value(config.name, cats[0])
                if isinstance(cats[0], float):
                    idx = int(np.abs(np.asarray(cats) - float(v)).argmin())
                else:
                    if isinstance(v, bool):
                        v = "True" if v else "False"
                    if str(v) not in lookup:
                        raise ValueError(
                            f"Trial {t.id}: value {v!r} is not a known category of "
                            f"{config.name!r} (categories: {cats})."
                        )
                    idx = lookup[str(v)]
                cat[i, j] = idx
        return cont, cat

    def decode(
        self, continuous: np.ndarray, categorical: np.ndarray
    ) -> List[trial_.ParameterDict]:
        """Inverse of ``encode``: model-space rows → parameter dicts.

        Accepts [N, Dc]/[N, Ds] matrices (1-D inputs are treated as a single
        row only when their length matches the feature count).
        """
        continuous = np.asarray(continuous, dtype=np.float64)
        categorical = np.asarray(categorical)
        if continuous.ndim == 1:
            continuous = (
                continuous.reshape(-1, self.num_continuous)
                if self.num_continuous
                else np.zeros((0, 0))
            )
        if categorical.ndim == 1:
            categorical = (
                categorical.reshape(-1, self.num_categorical)
                if self.num_categorical
                else np.zeros((0, 0), dtype=np.int32)
            )
        if continuous.shape[1] != self.num_continuous:
            raise ValueError(
                f"continuous has {continuous.shape[1]} columns, expected {self.num_continuous}."
            )
        if categorical.shape[1] != self.num_categorical:
            raise ValueError(
                f"categorical has {categorical.shape[1]} columns, expected {self.num_categorical}."
            )
        if self.num_continuous and self.num_categorical:
            if continuous.shape[0] != categorical.shape[0]:
                raise ValueError(
                    f"Row mismatch: continuous {continuous.shape[0]} vs "
                    f"categorical {categorical.shape[0]}."
                )
        n = continuous.shape[0] if self.num_continuous else (
            categorical.shape[0] if self.num_categorical else 0
        )
        out: List[trial_.ParameterDict] = []
        decoded_cont: Dict[str, np.ndarray] = {}
        for j, config in enumerate(self._continuous):
            decoded_cont[config.name] = self._codecs[config.name].decode(continuous[:, j])
        for i in range(n):
            params = trial_.ParameterDict()
            for config in self._continuous:
                params[config.name] = config.cast_value(float(decoded_cont[config.name][i]))
            for j, config in enumerate(self._categorical):
                cats = self._categories[config.name]
                idx = int(np.clip(categorical[i, j], 0, len(cats) - 1))
                params[config.name] = config.cast_value(cats[idx])
            out.append(params)
        return out

    # -- one-hot view (flat continuous vector consumers) -------------------

    def onehot_encode(self, trials: Sequence[trial_.Trial]) -> np.ndarray:
        cont, cat = self.encode(trials)
        return self.onehot_from_split(cont, cat)

    def onehot_from_split(self, continuous: np.ndarray, categorical: np.ndarray) -> np.ndarray:
        n = continuous.shape[0] if self.num_continuous else np.atleast_2d(categorical).shape[0]
        blocks = [np.atleast_2d(continuous)] if self.num_continuous else []
        categorical = np.atleast_2d(categorical)
        for j, size in enumerate(self.category_sizes):
            onehot = np.zeros((n, size))
            onehot[np.arange(n), np.clip(categorical[:, j], 0, size - 1)] = 1.0
            blocks.append(onehot)
        if not blocks:
            return np.zeros((n, 0))
        return np.concatenate(blocks, axis=1)

    def onehot_to_split(self, flat: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Splits a flat [N, onehot_dim] matrix back to (continuous, indices)."""
        flat = np.atleast_2d(flat)
        cont = flat[:, : self.num_continuous]
        cat = np.zeros((flat.shape[0], self.num_categorical), dtype=np.int32)
        offset = self.num_continuous
        for j, size in enumerate(self.category_sizes):
            cat[:, j] = flat[:, offset : offset + size].argmax(axis=1)
            offset += size
        return cont, cat


class MetricsEncoder:
    """Maps trial measurements to a [N, M] label matrix (all-MAXIMIZE)."""

    def __init__(self, metrics: base_study_config.MetricsConfig, *, flip_signs_for_min: bool = True):
        self._metrics = list(metrics)
        self._flip = flip_signs_for_min

    @property
    def metric_names(self) -> List[str]:
        return [m.name for m in self._metrics]

    @property
    def num_metrics(self) -> int:
        return len(self._metrics)

    def encode(self, trials: Sequence[trial_.Trial]) -> np.ndarray:
        out = np.full((len(trials), len(self._metrics)), np.nan, dtype=np.float64)
        for i, t in enumerate(trials):
            # Infeasible trials contribute NaN even if they carry a
            # measurement (e.g. safety-warped trials keep their data).
            if t.final_measurement is None or t.infeasible:
                continue
            for j, info in enumerate(self._metrics):
                metric = t.final_measurement.metrics.get(info.name)
                if metric is None:
                    continue
                value = metric.value
                if self._flip and info.goal == base_study_config.ObjectiveMetricGoal.MINIMIZE:
                    value = -value
                out[i, j] = value
        return out

    def decode(self, labels: np.ndarray) -> np.ndarray:
        """Undoes the sign flip (model space → user space)."""
        labels = np.array(labels, copy=True)
        for j, info in enumerate(self._metrics):
            if self._flip and info.goal == base_study_config.ObjectiveMetricGoal.MINIMIZE:
                labels[:, j] = -labels[:, j]
        return labels

    def decode_column(self, values: np.ndarray, index: int) -> np.ndarray:
        """model space → user space for ONE metric column (any shape).

        The single owner of the flip rule — designers' ``sample``/``predict``
        route through this so a converter built with
        ``flip_signs_for_min=False`` never gets double-(un)flipped.
        """
        info = self._metrics[index]
        if self._flip and info.goal == base_study_config.ObjectiveMetricGoal.MINIMIZE:
            return -np.asarray(values)
        return np.asarray(values)


@dataclasses.dataclass(frozen=True)
class TrialToModelInputConverter:
    """Trials → padded ``ModelData`` (the GP input path).

    Parity with the reference ``TrialToModelInputConverter``
    (``jnp_converters.py:147``), built on ``SearchSpaceEncoder`` +
    ``MetricsEncoder`` + a ``PaddingSchedule``.
    """

    encoder: SearchSpaceEncoder
    metrics: MetricsEncoder
    padding: padding_lib.PaddingSchedule

    @classmethod
    def from_problem(
        cls,
        problem: base_study_config.ProblemStatement,
        *,
        padding: Optional[padding_lib.PaddingSchedule] = None,
        max_discrete_indices: int = 0,
    ) -> "TrialToModelInputConverter":
        return cls(
            encoder=SearchSpaceEncoder(
                problem.search_space, max_discrete_indices=max_discrete_indices
            ),
            metrics=MetricsEncoder(problem.metric_information),
            padding=padding if padding is not None else padding_lib.DEFAULT_PADDING,
        )

    def _pad_rows(self, n: int) -> int:
        return self.padding.pad_trials(n)

    def to_features(self, trials: Sequence[trial_.Trial]) -> types.ModelInput:
        cont, cat = self.encoder.encode(trials)
        n_pad = self._pad_rows(len(trials))
        dc_pad = self.padding.pad_features(self.encoder.num_continuous)
        ds_pad = self.padding.pad_features(self.encoder.num_categorical)
        cont_pa = types.PaddedArray.from_array(
            cont.astype(np.float32), (n_pad, dc_pad), fill_value=0.0
        )
        cat_pa = types.PaddedArray.from_array(
            cat.astype(np.int32), (n_pad, ds_pad), fill_value=0
        )
        return types.ContinuousAndCategorical(continuous=cont_pa, categorical=cat_pa)

    def to_labels(self, trials: Sequence[trial_.Trial]) -> types.PaddedArray:
        labels = self.metrics.encode(trials)
        n_pad = self._pad_rows(len(trials))
        m_pad = self.padding.pad_metrics(self.metrics.num_metrics)
        return types.PaddedArray.from_array(
            labels.astype(np.float32), (n_pad, m_pad), fill_value=np.nan
        )

    def to_xy(self, trials: Sequence[trial_.Trial]) -> types.ModelData:
        return types.ModelData(
            features=self.to_features(trials), labels=self.to_labels(trials)
        )

    def to_parameters(
        self, continuous: np.ndarray, categorical: np.ndarray
    ) -> List[trial_.ParameterDict]:
        return self.encoder.decode(continuous, categorical)


@dataclasses.dataclass(frozen=True)
class TrialToArrayConverter:
    """Trials → flat [N, D] one-hot matrix (evolution / benchmark path).

    Parity with the reference ``TrialToArrayConverter`` (``core.py:1217``).
    """

    encoder: SearchSpaceEncoder
    metrics: MetricsEncoder

    @classmethod
    def from_study_config(
        cls,
        problem: base_study_config.ProblemStatement,
        *,
        max_discrete_indices: int = 0,
    ) -> "TrialToArrayConverter":
        return cls(
            encoder=SearchSpaceEncoder(
                problem.search_space, max_discrete_indices=max_discrete_indices
            ),
            metrics=MetricsEncoder(problem.metric_information),
        )

    @property
    def output_dim(self) -> int:
        return self.encoder.onehot_dim

    def to_features(self, trials: Sequence[trial_.Trial]) -> np.ndarray:
        return self.encoder.onehot_encode(trials)

    def to_labels(self, trials: Sequence[trial_.Trial]) -> np.ndarray:
        return self.metrics.encode(trials)

    def to_xy(self, trials: Sequence[trial_.Trial]) -> Tuple[np.ndarray, np.ndarray]:
        return self.to_features(trials), self.to_labels(trials)

    def to_parameters(self, flat: np.ndarray) -> List[trial_.ParameterDict]:
        cont, cat = self.encoder.onehot_to_split(flat)
        return self.encoder.decode(cont, cat)
