"""Cross-problem trial mapping for transfer learning.

Parity with ``/root/reference/vizier/pyvizier/converters/embedder.py:44``
(``ProblemAndTrialsScaler``) and ``feature_mapper.py``: prior-study trials
rarely share the exact search space of the current study — this module maps
a prior problem's trials into the current problem's space (shared names keep
their values clipped/snapped to the current domain; missing parameters take
the current default; extra parameters are dropped).
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence

import numpy as np

from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import parameter_config as pc
from vizier_tpu.pyvizier import trial as trial_


@dataclasses.dataclass
class ProblemAndTrialsScaler:
    """Maps trials from arbitrary (prior) problems into ``problem``'s space."""

    problem: base_study_config.ProblemStatement

    def _snap(self, config: pc.ParameterConfig, value) -> pc.ParameterValueTypes:
        try:
            if config.type == pc.ParameterType.DOUBLE:
                lo, hi = config.bounds
                return float(np.clip(float(value), lo, hi))
            if config.type == pc.ParameterType.INTEGER:
                lo, hi = config.bounds
                return int(np.clip(int(round(float(value))), int(lo), int(hi)))
            if config.type == pc.ParameterType.DISCRETE:
                values = np.asarray([float(v) for v in config.feasible_values])
                return float(values[np.abs(values - float(value)).argmin()])
        except (TypeError, ValueError):
            # Prior study typed this name differently (e.g. categorical
            # value in a numeric domain) — fall back to the default.
            return config.first_feasible_value()
        # CATEGORICAL: unknown categories fall back to the default value.
        if config.contains(str(value)):
            return str(value)
        return config.first_feasible_value()

    def map_trials(self, trials: Sequence[trial_.Trial]) -> List[trial_.Trial]:
        out = []
        for t in trials:
            params = trial_.ParameterDict()
            for config in self.problem.search_space.parameters:
                if config.name in t.parameters:
                    raw = t.parameters.get_value(config.name)
                    params[config.name] = config.cast_value(self._snap(config, raw))
                else:
                    params[config.name] = config.cast_value(
                        config.first_feasible_value()
                    )
            clone = trial_.Trial(
                id=t.id,
                parameters=params,
                metadata=t.metadata,
                measurements=list(t.measurements),
                final_measurement=t.final_measurement,
                infeasibility_reason=t.infeasibility_reason,
            )
            out.append(clone)
        return out
