"""vizier_tpu.loadgen: the production-shaped traffic engine.

MLPerf-loadgen-shaped workload subsystem for the serving fleet: seeded
deterministic traffic models (``models``), a virtual-client driver that
runs them against real serving targets with the opt-in planes toggled per
scenario (``driver``), and the assertion engine that turns one run into
``SOAK_REPORT.json`` (``report``). Entry point: ``tools/soak.py``.
"""

from vizier_tpu.loadgen.driver import (
    LoadgenPolicyFactory,
    RequestRecord,
    SoakResult,
    StudyOutcome,
    loadgen_reliability,
    run,
    run_gated_off,
    run_reference,
    scenario_env,
)
from vizier_tpu.loadgen.models import (
    EventSpec,
    PlaneConfig,
    Scenario,
    ScenarioConfig,
    StudySpec,
    build_scenario,
    default_event_track,
    parse_event_track,
    smoke_config,
    soak_config,
)
from vizier_tpu.loadgen.report import build_report, ranksum_p, render_verdict

__all__ = [
    "EventSpec",
    "LoadgenPolicyFactory",
    "PlaneConfig",
    "RequestRecord",
    "Scenario",
    "ScenarioConfig",
    "SoakResult",
    "StudyOutcome",
    "StudySpec",
    "build_report",
    "build_scenario",
    "default_event_track",
    "loadgen_reliability",
    "parse_event_track",
    "ranksum_p",
    "render_verdict",
    "run",
    "run_gated_off",
    "run_reference",
    "scenario_env",
    "smoke_config",
    "soak_config",
]
