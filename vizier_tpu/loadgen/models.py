"""Seeded, deterministic traffic models for the loadgen engine.

Every evidence file before this subsystem (SPARSE_AB, SPECULATIVE_AB,
MESH_AB, SERVICE_THROUGHPUT) is a point A/B of one subsystem in isolation.
The loadgen engine instead drives the FULL stack with production-shaped
mixed traffic, and this module is its workload description language —
everything here is a pure function of the scenario seed, so the same
:class:`ScenarioConfig` always expands to the same :class:`Scenario`:

- **open-loop arrivals** — a (optionally bursty) Poisson process: study
  arrival times come from exponential inter-arrival draws whose rate is
  modulated by a square burst wave, the MLPerf-loadgen "server" shape
  (requests arrive whether or not the service is keeping up);
- **Zipf study sizes** — per-study trial budgets from a bounded power law
  (most studies tiny, a heavy tail of big ones — the fleet-paper regime,
  arXiv:2408.11527);
- **tenant mix** — weighted tenants stamped on every study, so per-tenant
  outcome tables fall out of the report;
- **program-kind mix** — drawn against ``compute/registry.py``: every
  registered :class:`DesignerProgram` kind (gp_bandit, gp_bandit_sparse,
  gp_ucb_pe, gp_ucb_pe_sparse) can be given traffic, next to the cheap
  ``random``/``quasi_random`` baseline kinds that dominate real fleets.
  Sparse kinds are realized by pre-seeding a study past the (scenario-
  scoped) sparse threshold; crossover studies straddle the threshold
  mid-run so the surrogate auto-switch boundary gets traffic too;
- **a scripted event track** — kill/revive replicas, chaos fault windows
  (via ``testing/chaos.py``), fired at deterministic completed-trial
  counts so a soak's fault schedule is part of its fingerprint.

The scenario :meth:`~Scenario.fingerprint` hashes the full expansion;
``tests/loadgen/test_models.py`` pins that the same seed reproduces it
bit-for-bit and that different seeds diverge.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
import random
from typing import Dict, List, Optional, Sequence, Tuple

# All VIZIER_* switches are declared in (and read through) the central
# registry; enforced by the env_registry analysis pass.
from vizier_tpu.analysis import registry as _registry

# Kind → service algorithm string. The four GP kinds are the registered
# compute-IR program kinds (validated against compute/registry.py at
# scenario build); sparse variants are the same algorithms driven past the
# scenario's sparse threshold. ``random``/``quasi_random`` are the cheap
# baseline kinds that make up the bulk of a production mix.
KIND_TO_ALGORITHM: Dict[str, str] = {
    "random": "RANDOM_SEARCH",
    "quasi_random": "QUASI_RANDOM_SEARCH",
    "gp_bandit": "GAUSSIAN_PROCESS_BANDIT",
    "gp_bandit_sparse": "GAUSSIAN_PROCESS_BANDIT",
    "gp_ucb_pe": "DEFAULT",
    "gp_ucb_pe_sparse": "DEFAULT",
}
GP_KINDS = ("gp_bandit", "gp_bandit_sparse", "gp_ucb_pe", "gp_ucb_pe_sparse")
SPARSE_KINDS = ("gp_bandit_sparse", "gp_ucb_pe_sparse")

# Study owner segment per scenario tenant: owners/loadgen-{tenant}/... —
# ALSO the tenant id the admission plane sees (serving.admission.tenant_of
# reads the owner segment), so the driver maps scenario tenant names
# through this prefix when arming per-tenant weights and normalizes them
# back in controller snapshots.
TENANT_OWNER_PREFIX = "loadgen-"


def tenant_owner(tenant: str) -> str:
    return f"{TENANT_OWNER_PREFIX}{tenant}"


def owner_tenant(owner: str) -> str:
    """The scenario tenant for a study owner id (unknown owners pass
    through unchanged)."""
    if owner.startswith(TENANT_OWNER_PREFIX):
        return owner[len(TENANT_OWNER_PREFIX):]
    return owner

_TARGETS = ("inprocess", "replicas", "subprocess", "shared_compute")
_EVENT_KINDS = (
    "kill_replica",
    "revive_replica",
    "chaos_on",
    "chaos_off",
    # Disaggregated compute tier (target "shared_compute"):
    # kill_compute — SIGKILL the shared Pythia compute server; frontends
    #   must ride their local-Pythia fallback with zero lost studies.
    # revive_compute — respawn it (idempotent: the manager's health loop
    #   may already have brought it back).
    "kill_compute",
    "revive_compute",
    # Severity track (replica tiers with >= 3 replicas):
    # multi_kill — kill N replicas SIMULTANEOUSLY (arg = N, default 2);
    #   the fleet must fail all of them over in one sweep with zero lost
    #   studies (the concurrent-multi-failure path).
    # rolling_restart — kill → fail over → revive every replica in id
    #   order, one at a time, under live traffic (the epoch-fenced
    #   handback path); dead replicas are revived in the same sweep.
    # wal_corrupt — flip bytes mid-file in a replica's live wal.log
    #   (arg = replica id or owner:<study index>); a later restart must
    #   quarantine the suffix and recover the tail from standby logs.
    "multi_kill",
    "rolling_restart",
    "wal_corrupt",
)


@dataclasses.dataclass(frozen=True)
class PlaneConfig:
    """Which opt-in serving planes a scenario arms (the env switches the
    driver patches around the run). ``gated_off()`` is the sequential-
    reference shape: every plane off, the bit-identical seed path."""

    batching: bool = True
    speculative: bool = True
    mesh: bool = False
    slo: bool = True
    recorder: bool = True
    # Multi-tenant overload protection (serving.admission): fair-share
    # admission + shedding + degradation. Off by default — it is the
    # plane the OVERLOAD_AB scenario A/Bs.
    admission: bool = False

    @classmethod
    def all_on(cls) -> "PlaneConfig":
        return cls(batching=True, speculative=True, mesh=True, slo=True)

    @classmethod
    def gated_off(cls) -> "PlaneConfig":
        return cls(
            batching=False,
            speculative=False,
            mesh=False,
            slo=False,
            recorder=False,
            admission=False,
        )

    def as_dict(self) -> Dict[str, bool]:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class EventSpec:
    """One scripted fleet event, fired when the global completed-trial
    count reaches ``at_completed`` (deterministic under any concurrency:
    the counter, not the wall clock, is the trigger)."""

    at_completed: int
    kind: str  # kill_replica | revive_replica | chaos_on | chaos_off
    # kill/revive: "owner:<study index>" (the replica owning that study,
    # resolved at fire time) or a literal replica id ("replica-1").
    arg: str = ""

    def __post_init__(self):
        if self.kind not in _EVENT_KINDS:
            raise ValueError(
                f"Unknown event kind {self.kind!r}; expected one of "
                f"{_EVENT_KINDS}."
            )

    def as_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class StudySpec:
    """One study's worth of traffic, fully determined by the scenario."""

    index: int
    name: str  # full study resource name
    tenant: str
    kind: str
    algorithm: str
    budget: int  # suggest→complete round-trips the driver runs
    preseed: int  # completed trials seeded before the first suggest
    arrival_s: float  # open-loop arrival offset from scenario start
    seed: int  # per-study seed: objective optimum + designer rng

    def as_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class ScenarioConfig:
    """The full workload description. Everything the engine does is a
    deterministic function of this config (see :func:`build_scenario`)."""

    name: str = "default"
    seed: int = 0
    num_studies: int = 64
    # Multiplies num_studies (the one-knob way to scale a named scenario
    # up to soak size or down to a CI smoke).
    scale: float = 1.0
    # inprocess: one VizierServicer + shared Pythia. replicas: an
    # N-replica ReplicaManager tier (WAL-backed) behind the routed stub.
    target: str = "replicas"
    replicas: int = 2
    dim: int = 2
    concurrency: int = 4  # virtual clients
    # -- open-loop arrivals ------------------------------------------------
    arrival_rate_per_s: float = 50.0
    burst_factor: float = 4.0  # burst-window rate multiplier
    burst_fraction: float = 0.25  # fraction of each period spent bursting
    burst_period_s: float = 20.0
    # 0 = arrival ORDER only (as fast as the fleet can drain); 1 = real-
    # time pacing; in between scales the schedule. With a nonzero scale
    # the driver runs OPEN-LOOP: a dedicated pacer releases each study at
    # its scheduled arrival instant on its own client thread, whether or
    # not the fleet is keeping up (the MLPerf-loadgen "server" shape) —
    # arrivals are never gated on a free worker.
    time_scale: float = 0.0
    # Safety cap on concurrently-running open-loop studies; a release that
    # would exceed it queues until one finishes (logged, not silent).
    open_loop_max_clients: int = 128
    # -- study sizes (bounded Zipf) ---------------------------------------
    zipf_alpha: float = 1.1
    min_trials: int = 1
    max_trials: int = 16
    # -- mixes -------------------------------------------------------------
    tenants: Tuple[Tuple[str, float], ...] = (
        ("prod", 8.0),
        ("batch", 3.0),
        ("dev", 1.0),
    )
    kind_mix: Tuple[Tuple[str, float], ...] = (
        ("random", 60.0),
        ("quasi_random", 12.0),
        ("gp_bandit", 1.0),
        ("gp_bandit_sparse", 1.0),
        ("gp_ucb_pe", 1.0),
        ("gp_ucb_pe_sparse", 1.0),
    )
    # Per-tenant kind-mix overrides ((tenant, kind_mix) pairs): studies of
    # an overridden tenant redraw their kind from that tenant's own mix
    # (seeded separately so the base expansion stream is undisturbed) —
    # how the hot-tenant preset makes one tenant compute-heavy while the
    # light tenants stay cheap.
    tenant_kinds: Tuple[Tuple[str, Tuple[Tuple[str, float], ...]], ...] = ()
    # -- surrogate boundary (scenario-scoped VIZIER_SPARSE_* overrides) ----
    sparse_threshold: int = 8
    sparse_inducing: int = 8
    # Force at least one non-sparse GP study to cross the threshold
    # mid-run, so the surrogate-crossover boundary gets traffic.
    ensure_crossover: bool = True
    # -- designer economics (CI/CPU realism knobs) -------------------------
    acquisition_evals: int = 200  # 0 = designer default (the 75k sweep)
    ard_restarts: int = 0  # 0 = designer default
    ard_maxiter: int = 0  # 0 = designer default optimizer
    # Per-trial evaluation think time for GP studies (the window a real
    # evaluation gives the speculative pre-compute to land).
    think_time_s: float = 0.0
    # -- planes + events ---------------------------------------------------
    planes: PlaneConfig = dataclasses.field(default_factory=PlaneConfig)
    # () = the default track from :func:`default_event_track`; parsed
    # tracks come from VIZIER_LOADGEN_EVENTS / --events.
    events: Tuple[EventSpec, ...] = ()
    chaos_fault_prob: float = 0.1  # transport-fault rate inside windows
    # -- admission plane (scenario-scoped VIZIER_ADMISSION* overrides) -----
    # Applied only when ``planes.admission``; 0/empty = the switch default.
    admission_weights: Tuple[Tuple[str, float], ...] = ()
    admission_max_inflight: int = 0
    admission_tenant_inflight: int = 0
    admission_degraded_floor: float = 0.0
    admission_window_s: float = 0.0
    admission_retry_after_ms: float = 0.0
    # -- assertions --------------------------------------------------------
    parity_cohort: int = 8  # studies re-run on the sequential reference
    min_speculative_hits: int = 1
    min_hit_rate: float = 0.0
    max_fallback_rate: float = 0.25
    # Fleet shed-rate budget, asserted only while ``planes.admission`` is
    # armed (the default soak runs WITH admission and must not shed under
    # nominal load; the hot_tenant overload preset raises this to 1.0 —
    # shedding the hot tenant is its mechanism).
    max_shed_rate: float = 0.05
    parity_alpha: float = 0.05
    p99_budget_ms: float = 120000.0  # VIZIER_SLO_SUGGEST_P99_MS objective

    def __post_init__(self):
        if self.target not in _TARGETS:
            raise ValueError(
                f"Unknown target {self.target!r}; expected one of {_TARGETS}."
            )
        if self.min_trials < 1 or self.max_trials < self.min_trials:
            raise ValueError(
                "Need 1 <= min_trials <= max_trials, got "
                f"[{self.min_trials}, {self.max_trials}]."
            )
        if not self.kind_mix:
            raise ValueError("kind_mix must not be empty.")
        unknown = [k for k, _ in self.kind_mix if k not in KIND_TO_ALGORITHM]
        for _tenant, mix in self.tenant_kinds:
            unknown.extend(k for k, _ in mix if k not in KIND_TO_ALGORITHM)
        if unknown:
            raise ValueError(
                f"Unknown traffic kinds {unknown}; known kinds: "
                f"{sorted(KIND_TO_ALGORITHM)}."
            )

    @property
    def total_studies(self) -> int:
        return max(1, int(round(self.num_studies * self.scale)))

    @classmethod
    def from_env(cls, **overrides) -> "ScenarioConfig":
        """The env-driven scenario (``VIZIER_LOADGEN*``): seed, scale,
        study count, target, and event track, on top of the defaults.
        Explicit ``overrides`` win over the environment."""
        values: Dict[str, object] = dict(
            seed=_registry.env_int("VIZIER_LOADGEN_SEED", 0),
            scale=_registry.env_float("VIZIER_LOADGEN_SCALE", 1.0),
            num_studies=_registry.env_int("VIZIER_LOADGEN_STUDIES", 64),
            target=_registry.env_str("VIZIER_LOADGEN_TARGET", "replicas"),
        )
        track = _registry.env_str("VIZIER_LOADGEN_EVENTS")
        values.update(overrides)
        config = cls(**values)
        if track and "events" not in overrides:
            config = dataclasses.replace(
                config, events=parse_event_track(track, config)
            )
        return config

    def as_dict(self) -> Dict[str, object]:
        out = dataclasses.asdict(self)
        out["planes"] = self.planes.as_dict()
        out["events"] = [e.as_dict() for e in self.events]
        out["total_studies"] = self.total_studies
        return out


# -- seeded samplers -------------------------------------------------------


def zipf_budgets(
    rng: random.Random, count: int, *, alpha: float, lo: int, hi: int
) -> List[int]:
    """Bounded Zipf draws: P(k) ∝ k^-alpha over [lo, hi], inverse-CDF
    sampled from ``rng`` (deterministic, no numpy dependency)."""
    support = list(range(lo, hi + 1))
    weights = [k ** -alpha for k in support]
    total = sum(weights)
    cumulative, acc = [], 0.0
    for w in weights:
        acc += w / total
        cumulative.append(acc)
    out = []
    for _ in range(count):
        u = rng.random()
        # First bucket whose CDF covers u (support is small: linear scan).
        for k, c in zip(support, cumulative):
            if u <= c:
                out.append(k)
                break
        else:  # float-roundoff tail
            out.append(hi)
    return out


def weighted_choice(
    rng: random.Random, pairs: Sequence[Tuple[str, float]]
) -> str:
    total = sum(w for _, w in pairs)
    u = rng.random() * total
    acc = 0.0
    for name, w in pairs:
        acc += w
        if u <= acc:
            return name
    return pairs[-1][0]


def arrival_times(rng: random.Random, config: ScenarioConfig, count: int) -> List[float]:
    """Open-loop (optionally bursty) Poisson arrival offsets, seconds.

    The rate is a square wave: ``burst_factor`` × the base rate for the
    first ``burst_fraction`` of every ``burst_period_s``, the base rate
    otherwise — a thinning-free construction (the instantaneous rate at
    the current time drives each exponential draw), deterministic in the
    draw sequence.
    """
    times, t = [], 0.0
    base = max(1e-6, config.arrival_rate_per_s)
    for _ in range(count):
        in_burst = (
            config.burst_period_s > 0
            and (t % config.burst_period_s)
            < config.burst_fraction * config.burst_period_s
        )
        rate = base * (config.burst_factor if in_burst else 1.0)
        t += rng.expovariate(rate)
        times.append(t)
    return times


# -- scenario expansion ----------------------------------------------------


def registered_gp_kinds() -> Tuple[str, ...]:
    """The compute-IR program kinds the registry currently serves; the
    scenario build validates GP traffic kinds against this set so a mix
    can never silently name a program that no longer exists."""
    from vizier_tpu.compute import registry as compute_registry

    return compute_registry.kinds()


class Scenario:
    """A fully expanded workload: study specs + events + objectives."""

    def __init__(
        self,
        config: ScenarioConfig,
        studies: List[StudySpec],
        events: Tuple[EventSpec, ...],
    ):
        self.config = config
        self.studies = studies
        self.events = events

    @property
    def total_trials(self) -> int:
        return sum(s.budget for s in self.studies)

    def kinds_present(self) -> List[str]:
        return sorted({s.kind for s in self.studies})

    def crossover_studies(self) -> List[StudySpec]:
        """Studies whose completed-trial count crosses the sparse
        threshold mid-run (surrogate auto-switch boundary traffic)."""
        threshold = self.config.sparse_threshold
        return [
            s
            for s in self.studies
            if s.kind in ("gp_bandit", "gp_ucb_pe")
            and s.preseed < threshold <= s.preseed + s.budget
        ]

    def parity_cohort(self) -> List[StudySpec]:
        """The studies re-run on the sequential reference arm: GP-heavy
        first (regret parity is about the designers, not random search),
        topped up with baseline studies, in index order."""
        gp = [s for s in self.studies if s.kind in GP_KINDS]
        rest = [s for s in self.studies if s.kind not in GP_KINDS]
        cohort = (gp + rest)[: max(1, self.config.parity_cohort)]
        return sorted(cohort, key=lambda s: s.index)

    # -- objectives --------------------------------------------------------

    def optimum(self, spec: StudySpec) -> List[float]:
        rng = random.Random((spec.seed << 8) ^ 0x5EED)
        return [rng.uniform(0.2, 0.8) for _ in range(self.config.dim)]

    def objective(self, spec: StudySpec, parameters: Dict[str, float]) -> float:
        """Seeded sphere (maximize): 0 at the study's hidden optimum.
        Deterministic, so the engine arm and the sequential reference see
        identical objective feedback for identical suggestions."""
        opt = self.optimum(spec)
        return -sum(
            (float(parameters.get(f"x{d}", 0.0)) - opt[d]) ** 2
            for d in range(self.config.dim)
        )

    def preseed_points(
        self, spec: StudySpec
    ) -> List[Tuple[Dict[str, float], float]]:
        """The completed trials seeded before the study's first suggest
        (what pushes sparse-kind studies past the threshold)."""
        rng = random.Random((spec.seed << 8) ^ 0xF00D)
        points = []
        for _ in range(spec.preseed):
            params = {
                f"x{d}": rng.uniform(0.0, 1.0) for d in range(self.config.dim)
            }
            points.append((params, self.objective(spec, params)))
        return points

    # -- provenance --------------------------------------------------------

    def as_dict(self) -> Dict[str, object]:
        return {
            "config": self.config.as_dict(),
            "studies": [s.as_dict() for s in self.studies],
            "events": [e.as_dict() for e in self.events],
        }

    def fingerprint(self) -> str:
        """sha256 over the full deterministic expansion (specs, arrival
        times, events): the identity a soak report stamps and the
        determinism tests pin."""
        payload = json.dumps(self.as_dict(), sort_keys=True).encode()
        return hashlib.sha256(payload).hexdigest()

    def summary(self) -> Dict[str, object]:
        by_kind: Dict[str, int] = {}
        by_tenant: Dict[str, int] = {}
        for s in self.studies:
            by_kind[s.kind] = by_kind.get(s.kind, 0) + 1
            by_tenant[s.tenant] = by_tenant.get(s.tenant, 0) + 1
        budgets = sorted(s.budget for s in self.studies)
        return {
            "studies": len(self.studies),
            "total_trials": self.total_trials,
            "studies_by_kind": dict(sorted(by_kind.items())),
            "studies_by_tenant": dict(sorted(by_tenant.items())),
            "trial_budget": {
                "min": budgets[0],
                "p50": budgets[len(budgets) // 2],
                "max": budgets[-1],
            },
            "crossover_studies": [s.index for s in self.crossover_studies()],
            "events": [e.as_dict() for e in self.events],
            "last_arrival_s": round(self.studies[-1].arrival_s, 4)
            if self.studies
            else 0.0,
        }


def default_event_track(
    config: ScenarioConfig, total_trials: int
) -> Tuple[EventSpec, ...]:
    """The canonical fleet track.

    2-replica tiers keep the original shape: kill the owner of study 0 at
    ~40% of the trial volume, revive it at ~70%, chaos window over the
    middle decile. Tiers with >= 3 replicas get the SEVERITY track
    instead: a 2-simultaneous ``multi_kill`` at ~35%, a mid-file
    ``wal_corrupt`` of study 0's (post-failover) owner at ~45%, and a
    ``rolling_restart`` of the whole fleet at ~75% — which also revives
    the multi-kill victims and forces the corrupted replica through
    quarantine + standby recovery. Kill/revive only make sense on the
    replica tier."""
    events: List[EventSpec] = []
    if config.chaos_fault_prob > 0:
        events.append(
            EventSpec(max(1, int(total_trials * 0.50)), "chaos_on")
        )
        events.append(
            EventSpec(max(2, int(total_trials * 0.60)), "chaos_off")
        )
    if config.target == "replicas" and config.replicas >= 3:
        events.append(
            EventSpec(max(1, int(total_trials * 0.35)), "multi_kill", "2")
        )
        events.append(
            EventSpec(
                max(2, int(total_trials * 0.45)), "wal_corrupt", "owner:0"
            )
        )
        events.append(
            EventSpec(max(3, int(total_trials * 0.75)), "rolling_restart")
        )
    elif config.target == "replicas" and config.replicas >= 2:
        events.append(
            EventSpec(max(1, int(total_trials * 0.40)), "kill_replica", "owner:0")
        )
        events.append(
            EventSpec(max(2, int(total_trials * 0.70)), "revive_replica", "owner:0")
        )
    if config.target == "shared_compute":
        # The tier's own severity arc: crash the shared compute server
        # mid-run (frontends degrade to local Pythia, zero lost studies),
        # then bring it back under live traffic.
        events.append(
            EventSpec(max(1, int(total_trials * 0.40)), "kill_compute")
        )
        events.append(
            EventSpec(max(2, int(total_trials * 0.70)), "revive_compute")
        )
    return tuple(sorted(events, key=lambda e: (e.at_completed, e.kind)))


def parse_event_track(track: str, config: ScenarioConfig) -> Tuple[EventSpec, ...]:
    """Parses ``VIZIER_LOADGEN_EVENTS`` / ``--events``.

    Comma-separated ``kind[:arg]@fraction`` entries, fractions of the
    total trial volume, e.g.::

        kill_replica:owner:0@0.4,revive_replica:owner:0@0.7,chaos_on@0.5,chaos_off@0.6
    """
    scenario = build_scenario(dataclasses.replace(config, events=()))
    total = max(1, scenario.total_trials)
    events = []
    for entry in track.split(","):
        entry = entry.strip()
        if not entry:
            continue
        head, _, frac = entry.rpartition("@")
        if not head:
            raise ValueError(f"Event entry {entry!r} needs kind@fraction.")
        kind, _, arg = head.partition(":")
        at = max(1, int(math.floor(float(frac) * total)))
        events.append(EventSpec(at, kind, arg))
    return tuple(sorted(events, key=lambda e: (e.at_completed, e.kind)))


def build_scenario(config: ScenarioConfig) -> Scenario:
    """Expands a config into the deterministic workload.

    One master ``random.Random(config.seed)`` drives every draw in a
    fixed order (budgets → kinds → tenants → arrivals → per-study seeds),
    so the expansion is reproducible independent of anything the driver
    later does with it.
    """
    gp_kinds_in_mix = [
        k for k, _ in config.kind_mix if k in GP_KINDS
    ]
    if gp_kinds_in_mix:
        registered = set(registered_gp_kinds())
        missing = [k for k in gp_kinds_in_mix if k not in registered]
        if missing:
            raise ValueError(
                f"kind_mix names unregistered program kinds {missing}; "
                f"registry serves {sorted(registered)}."
            )

    rng = random.Random(config.seed)
    count = config.total_studies
    budgets = zipf_budgets(
        rng,
        count,
        alpha=config.zipf_alpha,
        lo=config.min_trials,
        hi=config.max_trials,
    )
    kinds = [weighted_choice(rng, config.kind_mix) for _ in range(count)]
    # Guarantee every kind in the mix gets at least one study (a small
    # smoke must still cover all registered program kinds): overwrite the
    # tail with one study per missing kind, deterministically.
    mix_kinds = [k for k, w in config.kind_mix if w > 0]
    missing = [k for k in mix_kinds if k not in kinds]
    for offset, kind in enumerate(missing):
        kinds[count - 1 - offset] = kind
    tenants = [weighted_choice(rng, config.tenants) for _ in range(count)]
    if config.tenant_kinds:
        # Per-tenant kind overrides redraw from a DERIVED stream so the
        # base expansion (budgets/kinds/tenants/arrivals/seeds) is
        # byte-identical with the override absent.
        override = {tenant: mix for tenant, mix in config.tenant_kinds}
        kind_rng = random.Random((config.seed << 1) ^ 0x7E4A47)
        for i in range(count):
            mix = override.get(tenants[i])
            if mix is not None:
                kinds[i] = weighted_choice(kind_rng, mix)
    arrivals = arrival_times(rng, config, count)
    study_seeds = [rng.randrange(1 << 31) for _ in range(count)]

    studies: List[StudySpec] = []
    for i in range(count):
        kind = kinds[i]
        preseed = 0
        if kind in SPARSE_KINDS:
            # Born sparse: seeded past the threshold before first suggest.
            preseed = config.sparse_threshold
        elif kind in GP_KINDS:
            # Exact GP studies still need a seeded frontier (a designer
            # with zero completed trials just quasi-randoms); two points
            # keeps them cheap and in one padding bucket.
            preseed = min(2, max(0, config.sparse_threshold - 1))
        name = (
            f"owners/{tenant_owner(tenants[i])}/studies/"
            f"{config.name}-{i:05d}-{kind}"
        )
        studies.append(
            StudySpec(
                index=i,
                name=name,
                tenant=tenants[i],
                kind=kind,
                algorithm=KIND_TO_ALGORITHM[kind],
                budget=budgets[i],
                preseed=preseed,
                arrival_s=round(arrivals[i], 6),
                seed=study_seeds[i],
            )
        )

    if config.ensure_crossover:
        # At least one exact-GP study must straddle the sparse threshold
        # so the crossover boundary gets traffic: stretch the budget of
        # the first candidate that does not already cross.
        threshold = config.sparse_threshold
        candidates = [
            s for s in studies if s.kind in ("gp_bandit", "gp_ucb_pe")
        ]
        if candidates and not any(
            s.preseed < threshold <= s.preseed + s.budget for s in candidates
        ):
            s = candidates[0]
            studies[s.index] = dataclasses.replace(
                s, budget=threshold - s.preseed + 1
            )

    events = config.events or default_event_track(
        config, sum(s.budget for s in studies)
    )
    return Scenario(config, studies, events)


def smoke_config(**overrides) -> ScenarioConfig:
    """The seconds-scale CI scenario: every registered program kind gets
    exactly one tiny study next to a handful of random/quasi-random ones,
    on a 2-replica tier with one kill/revive — small enough for tier-1,
    full-stack enough to catch wiring regressions."""
    values: Dict[str, object] = dict(
        name="smoke",
        num_studies=8,
        max_trials=3,
        replicas=2,
        concurrency=2,
        sparse_threshold=4,
        sparse_inducing=4,
        acquisition_evals=50,
        ard_restarts=2,
        ard_maxiter=10,
        parity_cohort=4,
        chaos_fault_prob=0.0,
        kind_mix=(
            ("random", 3.0),
            ("quasi_random", 1.0),
            ("gp_bandit", 1.0),
            ("gp_bandit_sparse", 1.0),
            ("gp_ucb_pe", 1.0),
            ("gp_ucb_pe_sparse", 1.0),
        ),
        planes=PlaneConfig(
            batching=True, speculative=False, mesh=False, slo=True
        ),
    )
    values.update(overrides)
    return ScenarioConfig(**values)


def hot_tenant_config(**overrides) -> ScenarioConfig:
    """The overload scenario: one tenant with Zipf-head weight floods the
    fleet with GP compute at a saturating open-loop rate while three
    light tenants run occasional GP studies — the traffic shape where a
    serving tier without admission control collapses for everyone.

    Open-loop on purpose (``time_scale=1`` + real arrival pacing): the
    hot tenant's studies keep arriving whether or not the fleet drains,
    so suggest p99 measures queueing truthfully. The admission knobs
    (weights, caps, floor) describe the plane the ON arm arms; the OFF
    arm runs the identical workload with ``planes.admission=False``
    (``tools/overload_ab.py`` drives both).
    """
    values: Dict[str, object] = dict(
        name="hot_tenant",
        num_studies=28,
        min_trials=3,
        max_trials=3,
        target="inprocess",
        replicas=1,
        dim=2,
        concurrency=8,
        # Saturating open-loop arrivals: everything lands inside a few
        # seconds of real time, faster than the ~80 ms default-sweep GP
        # computes drain on one core (load ≈ 3).
        arrival_rate_per_s=12.0,
        burst_factor=1.0,
        time_scale=1.0,
        # One Zipf-head tenant, three light ones: ~4/5 of studies are hot.
        tenants=(
            ("hot", 12.0),
            ("light-a", 1.0),
            ("light-b", 1.0),
            ("light-c", 1.0),
        ),
        # The hot tenant is compute-heavy (all GP); light tenants mix one
        # GP study into cheap baseline traffic.
        kind_mix=(("random", 2.0), ("gp_bandit", 1.0)),
        tenant_kinds=(("hot", (("gp_bandit", 1.0),)),),
        sparse_threshold=64,  # stay exact: the A/B is about admission
        # Designer DEFAULTS (the production 75k-candidate sweep + full
        # ARD budget): the realistic heavy compute the hot tenant floods
        # the fleet with (~80 ms warm on 1-core CPU).
        acquisition_evals=0,
        ard_restarts=0,
        ard_maxiter=0,
        chaos_fault_prob=0.0,
        parity_cohort=4,
        max_fallback_rate=1.0,  # degraded-mode serves ARE the mechanism
        max_shed_rate=1.0,  # shedding the hot tenant IS the mechanism
        planes=PlaneConfig(
            batching=True,
            speculative=False,
            mesh=False,
            slo=True,
            recorder=True,
            admission=True,
        ),
        events=(),
        # The plane under test: light tenants outrank the hot one, whose
        # sub-floor weight routes it to quasi-random under degradation.
        admission_weights=(
            ("hot", 0.5),
            ("light-a", 4.0),
            ("light-b", 4.0),
            ("light-c", 4.0),
        ),
        # Headroom above the sum of plausible light-tenant concurrency so
        # the TOTAL cap never sheds a light tenant; the hot tenant's own
        # cap binds long before it.
        admission_max_inflight=12,
        admission_tenant_inflight=3,
        admission_degraded_floor=1.0,
        # Fast decisions under a seconds-scale flood: degrade within ~1 s
        # of sustained sheds, and pace shed retries widely enough
        # (6 attempts x >= 250 ms) that hot studies survive into the
        # degraded serve instead of exhausting their retry budget.
        admission_window_s=1.0,
        admission_retry_after_ms=250.0,
        # Between the two arms' measured light-tenant p99 (ON ~150 ms,
        # OFF ~1.4-1.7 s on the 1-core container): the plane keeps light
        # tenants inside it, the collapse arm breaches it.
        p99_budget_ms=1000.0,
    )
    values.update(overrides)
    return ScenarioConfig(**values)


def soak_config(**overrides) -> ScenarioConfig:
    """The acceptance-scale scenario: ≥1000 Zipf-sized studies across all
    registered program kinds on a 3-replica tier, speculation + batching
    + mesh + SLO + ADMISSION armed, with the SEVERITY event track
    (2-simultaneous multi_kill + mid-file wal_corrupt + rolling_restart)
    plus the chaos fault window.

    Admission runs armed by default (the PR 14 follow-on): the soak's
    nominal load must pass UNDER the overload-protection plane — the
    report gates assert the shed rate stays inside ``max_shed_rate`` and
    suggest p99 inside the SLO budget, so a regression that makes the
    plane shed healthy traffic (or a plane bypass that lets p99 collapse)
    fails the default soak, not just ``overload_ab``.
    """
    values: Dict[str, object] = dict(
        name="soak",
        num_studies=1000,
        max_trials=16,
        replicas=3,
        concurrency=8,
        sparse_threshold=8,
        sparse_inducing=8,
        acquisition_evals=100,
        ard_restarts=2,
        ard_maxiter=10,
        think_time_s=0.15,
        parity_cohort=10,
        planes=dataclasses.replace(PlaneConfig.all_on(), admission=True),
        # Nominal-load headroom: the closed-loop client pool (concurrency
        # 8) fits inside the fleet cap, and per-tenant caps sit above any
        # single tenant's plausible concurrency — a shed under this
        # scenario is a plane regression, not load.
        admission_max_inflight=16,
        admission_tenant_inflight=8,
        max_shed_rate=0.05,
    )
    values.update(overrides)
    return ScenarioConfig(**values)
