"""The loadgen driver: a virtual-client pool running a scenario against a
real serving target.

One :func:`run` call takes an expanded :class:`~vizier_tpu.loadgen.models.
Scenario` and drives it end to end through a REAL stack — the in-process
``VizierServicer`` + shared Pythia, or an N-replica ``ReplicaManager``
tier behind the routed stub — with the scenario's serving planes
(batching, speculation, mesh, SLO, flight recorder) armed via their own
env switches for exactly the duration of the run. Nothing here stubs the
serving path: suggestions come from the same policy factory, designer
cache, coalescer, batch executor, and surrogate auto-switch production
requests use, so a soak failure is a serving failure.

Per-request outcomes (latency, speculative-hit stamp, fallback stamp,
errors) are recorded keyed by trace_id into the PR 11 flight recorder and
returned as :class:`RequestRecord` rows; per-study trajectories and
best-so-far curves feed the report's regret-parity and bit-identity
checks. The scripted event track fires at deterministic completed-trial
counts: replica kill/revive, simultaneous ``multi_kill``, fleet-wide
``rolling_restart``, mid-file ``wal_corrupt``, and chaos transport-fault
windows via ``testing/chaos.py``. With WAL replication armed (the
default on the replica tier) revives run under LIVE traffic — the
epoch-fenced cutover + the tier's own failover barrier replace the
driver's external drain gate, which is kept only for replication-off
runs (the pre-replication handback contract).
"""

from __future__ import annotations

import dataclasses
import os
import tempfile
import threading
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

from vizier_tpu import pyvizier as vz
from vizier_tpu.loadgen import models
from vizier_tpu.observability import flight_recorder as recorder_lib
from vizier_tpu.observability import tracing as tracing_lib
from vizier_tpu.reliability import config as reliability_config_lib
from vizier_tpu.reliability import errors as errors_lib
from vizier_tpu.reliability import fallback as fallback_lib
from vizier_tpu.reliability import retry as retry_lib
from vizier_tpu.serving import admission as admission_lib
from vizier_tpu.serving import speculative as speculative_lib
from vizier_tpu.service import proto_converters as pc
from vizier_tpu.service import vizier_client
from vizier_tpu.service.protos import vizier_service_pb2
from vizier_tpu.testing import chaos as chaos_lib


@dataclasses.dataclass
class RequestRecord:
    """One driven request's outcome (what the report tables roll up)."""

    study_index: int
    kind: str
    tenant: str
    op: str  # "suggest" | "complete"
    latency_s: float
    trace_id: Optional[str] = None
    speculative_hit: bool = False
    fallback: bool = False
    # The admission plane served this request quasi-random (degraded-mode
    # stamp in trial metadata).
    degraded: bool = False
    error: Optional[str] = None

    @property
    def shed(self) -> bool:
        """Client-visible shed: the request failed with the admission
        plane's RESOURCE_EXHAUSTED marker after retries were exhausted
        (absorbed sheds surface in the controller snapshot instead)."""
        return self.error is not None and errors_lib.is_resource_exhausted(
            self.error
        )

    def as_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class StudyOutcome:
    """One study's end state after the soak."""

    spec: models.StudySpec
    completed: int = 0
    expected: int = 0
    listed_completed: int = -1  # post-run verification sweep (list_trials)
    trajectory: Tuple = ()
    best_curve: Tuple = ()
    error: Optional[str] = None

    @property
    def final_best(self) -> Optional[float]:
        return self.best_curve[-1] if self.best_curve else None

    @property
    def lost(self) -> bool:
        """True when the fleet dropped state for this study: driven
        completions that the post-run trial listing cannot account for."""
        return self.listed_completed < self.spec.preseed + self.completed


@dataclasses.dataclass
class SoakResult:
    """Everything one arm's run produced (input to ``report.py``)."""

    arm: str
    scenario_fingerprint: str
    records: List[RequestRecord]
    outcomes: Dict[int, StudyOutcome]
    events_fired: List[Dict[str, object]]
    serving_stats: Dict[str, object]
    slo: Dict[str, object]
    wall_s: float
    wal_root: Optional[str] = None
    recorder_event_kinds: Dict[str, int] = dataclasses.field(default_factory=dict)
    # The admission controller's snapshot (per-tenant sheds/admits,
    # overload state, transitions); {"enabled": False} with the plane off.
    admission: Dict[str, object] = dataclasses.field(default_factory=dict)
    # Open-loop releases delayed by the runaway client cap (0 = the run
    # was truly open-loop end to end).
    open_loop_capped: int = 0

    def lost_studies(self) -> List[int]:
        return sorted(i for i, o in self.outcomes.items() if o.lost)

    def errored_studies(self) -> List[int]:
        return sorted(
            i for i, o in self.outcomes.items() if o.error is not None
        )


def scenario_env(config: models.ScenarioConfig) -> Dict[str, str]:
    """The env-switch overlay a scenario runs under (patched around the
    run, restored after): the planes plus the scenario-scoped surrogate
    boundary, so a soak process needs no ambient environment setup."""
    planes = config.planes
    env = {
        "VIZIER_BATCHING": "1" if planes.batching else "0",
        "VIZIER_SPECULATIVE": "1" if planes.speculative else "0",
        "VIZIER_MESH": "1" if planes.mesh else "0",
        "VIZIER_SLO": "1" if planes.slo else "0",
        "VIZIER_FLIGHT_RECORDER": "1" if planes.recorder else "0",
        "VIZIER_SPARSE_THRESHOLD": str(config.sparse_threshold),
        "VIZIER_SPARSE_INDUCING": str(config.sparse_inducing),
        "VIZIER_SPARSE_HYSTERESIS": "2",
    }
    if planes.slo:
        # Manual evaluation cadence: the driver evaluates at deterministic
        # completion counts instead of a wall-clock sampler thread.
        env["VIZIER_SLO_EVAL_INTERVAL_S"] = "0"
        env["VIZIER_SLO_WINDOWS"] = "30,600"
        env["VIZIER_SLO_SUGGEST_P99_MS"] = str(config.p99_budget_ms)
    if planes.speculative:
        env["VIZIER_SPECULATIVE_WORKERS"] = "2"
    env["VIZIER_ADMISSION"] = "1" if planes.admission else "0"
    if planes.admission:
        if config.admission_weights:
            # The controller keys tenants by study OWNER id: map the
            # scenario tenant names through the loadgen owner prefix.
            env["VIZIER_ADMISSION_WEIGHTS"] = ",".join(
                f"{models.tenant_owner(tenant)}:{weight:g}"
                for tenant, weight in config.admission_weights
            )
        if config.admission_max_inflight:
            env["VIZIER_ADMISSION_MAX_INFLIGHT"] = str(
                config.admission_max_inflight
            )
        if config.admission_tenant_inflight:
            env["VIZIER_ADMISSION_TENANT_INFLIGHT"] = str(
                config.admission_tenant_inflight
            )
        if config.admission_degraded_floor:
            env["VIZIER_ADMISSION_DEGRADED_FLOOR"] = str(
                config.admission_degraded_floor
            )
        if config.admission_window_s:
            env["VIZIER_ADMISSION_WINDOW_S"] = str(config.admission_window_s)
        if config.admission_retry_after_ms:
            env["VIZIER_ADMISSION_RETRY_AFTER_MS"] = str(
                config.admission_retry_after_ms
            )
    return env


def loadgen_reliability() -> reliability_config_lib.ReliabilityConfig:
    """Soak-speed reliability: full machinery, compressed backoffs (the
    soak measures fleet behavior, not wall-clock sleeps — same shape as
    tools/chaos_ab.py). Attempts are provisioned for the fault rate the
    chaos windows inject: at the default 10% transport-fault probability,
    3 attempts lose ~1e-3 of RPCs to consecutive faults — a thousands-of-
    requests soak would flake on its own injected noise; 6 attempts put
    exhaustion at ~1e-6, so a lost study means a real fleet bug again."""
    return reliability_config_lib.ReliabilityConfig(
        retry_max_attempts=6,
        retry_base_delay_secs=0.01,
        retry_max_delay_secs=0.1,
        breaker_window_secs=0.5,
        breaker_cooldown_secs=0.2,
    )


class LoadgenPolicyFactory:
    """The service's own policy factory, made per-study deterministic.

    GP algorithms keep the full serving path (designer cache, warm ARD,
    surrogate auto-switch — ``DefaultPolicyFactory`` with the runtime)
    while the scenario injects a per-study ``rng_seed`` plus its designer
    economics (trimmed acquisition sweep / ARD budget) through the
    factory's kwargs hook; RANDOM_SEARCH gets a per-study seeded designer
    so baseline trajectories are reproducible too. Thread-safe: the
    per-call injection rides a thread-local around the delegate call.
    """

    def __init__(self, scenario: models.Scenario):
        self._scenario = scenario
        self._seed_by_study = {s.name: s.seed for s in scenario.studies}
        self._local = threading.local()
        self._base = None
        self._lock = threading.Lock()

    def bind_runtime(self, serving_runtime) -> None:
        """Connects the serving runtime (built by the target's Pythia)."""
        from vizier_tpu.service import policy_factory as policy_factory_lib

        with self._lock:
            base = policy_factory_lib.DefaultPolicyFactory(
                serving_runtime=serving_runtime
            )
            original = base._gp_designer_kwargs

            def kwargs_hook():
                kwargs = original()
                extra = getattr(self._local, "gp_kwargs", None)
                if extra:
                    kwargs.update(extra)
                return kwargs

            base._gp_designer_kwargs = kwargs_hook
            self._base = base

    def _require_base(self):
        with self._lock:
            if self._base is None:
                self.bind_runtime(None)
            return self._base

    def _gp_overrides(self, study_name: str) -> Dict[str, object]:
        config = self._scenario.config
        kwargs: Dict[str, object] = {}
        seed = self._seed_by_study.get(study_name)
        if seed is not None:
            kwargs["rng_seed"] = seed
        if config.acquisition_evals:
            kwargs["max_acquisition_evaluations"] = config.acquisition_evals
        if config.ard_restarts:
            kwargs["ard_restarts"] = config.ard_restarts
        if config.ard_maxiter:
            from vizier_tpu.optimizers import lbfgs as lbfgs_lib

            kwargs["ard_optimizer"] = lbfgs_lib.AdamOptimizer(
                maxiter=config.ard_maxiter
            )
            kwargs["warm_start_min_trials"] = 0
        return kwargs

    def __call__(self, problem, algorithm, supporter, study_name):
        base = self._require_base()
        algo = (algorithm or "DEFAULT").upper()
        seed = self._seed_by_study.get(study_name)
        if algo == "RANDOM_SEARCH" and seed is not None:
            from vizier_tpu.algorithms import designer_policy
            from vizier_tpu.designers import random as random_designer

            return designer_policy.DesignerPolicy(
                supporter,
                lambda p, **kw: random_designer.RandomDesigner(
                    p.search_space, seed=seed
                ),
            )
        self._local.gp_kwargs = self._gp_overrides(study_name)
        try:
            return base(problem, algorithm, supporter, study_name)
        finally:
            self._local.gp_kwargs = None


# -- targets ---------------------------------------------------------------


class _InProcessTarget:
    """One VizierServicer + shared Pythia (the PR 1–5 single-node stack)."""

    supports_replicas = False
    replication_active = False

    def __init__(self, scenario: models.Scenario, reliability, factory):
        from vizier_tpu.service import pythia_service, vizier_service

        self._servicer = vizier_service.VizierServicer(
            reliability_config=reliability
        )
        self._pythia = pythia_service.PythiaServicer(
            self._servicer, factory, reliability_config=reliability
        )
        factory.bind_runtime(self._pythia.serving_runtime)
        self._servicer.set_pythia(self._pythia)
        self.wal_root = None

    @property
    def stub(self):
        return self._servicer

    @property
    def runtime(self):
        return self._pythia.serving_runtime

    def serving_stats(self) -> dict:
        return self._pythia.serving_stats()

    def owner_of(self, study_name: str) -> Optional[str]:
        return None

    def replica_ids(self) -> List[str]:
        return []

    def kill_replica(self, replica_id: str) -> None:
        raise RuntimeError("kill_replica needs the replicas target.")

    revive_replica = kill_replica
    fail_over = kill_replica
    is_alive = kill_replica
    corrupt_wal = kill_replica

    def shutdown(self) -> None:
        self._pythia.shutdown()


class _ReplicaTarget:
    """An N-replica WAL-backed ``ReplicaManager`` tier (the PR 6 stack)."""

    supports_replicas = True

    def __init__(self, scenario: models.Scenario, reliability, factory):
        from vizier_tpu.distributed import ReplicaManager

        self.wal_root = tempfile.mkdtemp(prefix="vizier-loadgen-wal-")
        self._manager = ReplicaManager(
            scenario.config.replicas,
            wal_root=self.wal_root,
            policy_factory=factory,
            reliability_config=reliability,
        )
        factory.bind_runtime(self._manager.pythia.serving_runtime)

    @property
    def stub(self):
        return self._manager.stub

    @property
    def runtime(self):
        return self._manager.pythia.serving_runtime

    def serving_stats(self) -> dict:
        return self._manager.serving_stats()

    @property
    def replication_active(self) -> bool:
        """True when the tier streams WAL appends to standby logs — the
        regime where kill/revive are safe under live traffic (failover
        barrier + epoch fence) and the driver needs no external gate."""
        return self._manager.replication_active

    def owner_of(self, study_name: str) -> str:
        return self._manager.router.replica_for(study_name)

    def replica_ids(self) -> List[str]:
        return self._manager.replica_ids()

    def is_alive(self, replica_id: str) -> bool:
        return self._manager.replica(replica_id).alive

    def kill_replica(self, replica_id: str) -> None:
        self._manager.kill_replica(replica_id)

    def fail_over(self, replica_id: str) -> int:
        return self._manager.fail_over(replica_id)

    def revive_replica(self, replica_id: str) -> None:
        self._manager.revive_replica(replica_id)

    def corrupt_wal(self, replica_id: str) -> Dict[str, object]:
        """Deterministically flips 16 bytes at the midpoint of the
        replica's live wal.log (the mid-log corruption a ``wal_corrupt``
        event injects). A later restart of the replica must quarantine
        the now-unreadable suffix and recover it from standby logs."""
        replica = self._manager.replica(replica_id)
        if not replica.wal_dir:
            return {"skipped": "no wal dir"}
        path = os.path.join(replica.wal_dir, "wal.log")
        try:
            size = os.path.getsize(path)
        except OSError:
            return {"skipped": "no wal.log"}
        if size < 64:
            return {"skipped": f"log too small ({size} bytes)"}
        offset = size // 2
        with open(path, "r+b") as f:
            f.seek(offset)
            f.write(b"\xff" * 16)
        return {"log_bytes": size, "corrupted_at": offset}

    def shutdown(self) -> None:
        self._manager.shutdown()


class _DetachedRuntime:
    """Runtime shim for targets whose serving runtimes live in OTHER
    processes: the driver cannot reach a subprocess replica's SLO engine
    or admission controller, so those report sections come back empty
    (each replica dumps its own via ``--obs-dump-dir`` instead)."""

    slo_engine = None

    def slo_report(self) -> Dict[str, object]:
        return {}

    def admission_snapshot(self) -> Dict[str, object]:
        return {"enabled": False}


class _SubprocessTarget:
    """An N-replica fleet of REAL ``replica_main`` processes behind the
    lease-based ``SubprocessReplicaManager`` (cross-process standby
    replication over gRPC; kill = SIGKILL, revive = fenced restart +
    copy-back over the wire). The scenario's env overlay is inherited by
    the child processes, so the serving planes arm inside each replica;
    per-study designer seeding does NOT cross the process boundary —
    parity/bit-identity assertions are waived for this target (the
    in-process arms carry that evidence)."""

    supports_replicas = True
    replication_active = True
    supports_compute_tier = False

    def __init__(
        self,
        scenario: models.Scenario,
        reliability,
        factory,
        compute_tier: bool = False,
    ):
        from vizier_tpu.distributed import subprocess_fleet

        del reliability  # replicas configure their own from the env
        del factory  # subprocess replicas build their own policy factory
        self.wal_root = tempfile.mkdtemp(prefix="vizier-loadgen-subproc-")
        self._manager = subprocess_fleet.SubprocessReplicaManager(
            scenario.config.replicas,
            wal_root=self.wal_root,
            compute_tier=compute_tier,
        )
        self.runtime = _DetachedRuntime()

    @property
    def stub(self):
        return self._manager.stub

    def serving_stats(self) -> dict:
        return self._manager.serving_stats()

    def owner_of(self, study_name: str) -> str:
        return self._manager.owner_of(study_name)

    def replica_ids(self) -> List[str]:
        return self._manager.replica_ids()

    def is_alive(self, replica_id: str) -> bool:
        return self._manager.is_alive(replica_id)

    def kill_replica(self, replica_id: str) -> None:
        self._manager.kill_replica(replica_id)

    def fail_over(self, replica_id: str) -> int:
        return self._manager.fail_over(replica_id)

    def revive_replica(self, replica_id: str) -> None:
        self._manager.revive_replica(replica_id)

    def corrupt_wal(self, replica_id: str) -> Dict[str, object]:
        return self._manager.corrupt_wal(replica_id)

    def shutdown(self) -> None:
        self._manager.shutdown()


class _SharedComputeTarget(_SubprocessTarget):
    """The subprocess fleet PLUS one shared Pythia compute server: every
    frontend replica is spawned with ``--compute-endpoint`` pointed at the
    tier, so their Suggest/EarlyStop traffic crosses the remote hop and
    fuses in ONE batch executor. Killing the compute server must lose
    zero studies — frontends degrade to their local minimal Pythia until
    the manager's health loop (or a scripted revive event) restarts it."""

    supports_compute_tier = True

    def __init__(self, scenario: models.Scenario, reliability, factory):
        super().__init__(scenario, reliability, factory, compute_tier=True)

    def compute_is_alive(self) -> bool:
        return self._manager.compute_is_alive()

    def kill_compute_server(self) -> None:
        self._manager.kill_compute_server()

    def revive_compute_server(self) -> None:
        self._manager.revive_compute_server()


def _build_target(scenario, reliability, factory):
    if scenario.config.target == "replicas":
        return _ReplicaTarget(scenario, reliability, factory)
    if scenario.config.target == "subprocess":
        return _SubprocessTarget(scenario, reliability, factory)
    if scenario.config.target == "shared_compute":
        return _SharedComputeTarget(scenario, reliability, factory)
    return _InProcessTarget(scenario, reliability, factory)


# -- traffic gate + event engine -------------------------------------------


class _TrafficGate:
    """Drain gate for handback windows: ``quiesce`` blocks new requests
    and waits for in-flight ones; ``resume`` reopens. ``revive_replica``
    is not a transactional migration (see ReplicaManager docs), so the
    driver models what a production rollout would do: drain, hand back,
    resume."""

    def __init__(self):
        self._cond = threading.Condition()
        self._active = 0
        self._paused = False

    def __enter__(self):
        with self._cond:
            while self._paused:
                self._cond.wait()
            self._active += 1
        return self

    def __exit__(self, *exc):
        with self._cond:
            self._active -= 1
            self._cond.notify_all()
        return False

    def quiesce(self) -> None:
        with self._cond:
            self._paused = True
            while self._active > 0:
                self._cond.wait()

    def resume(self) -> None:
        with self._cond:
            self._paused = False
            self._cond.notify_all()


class _EventEngine:
    """Fires the scripted track at deterministic completed-trial counts.

    Exactly-once: whichever worker's completion crosses an event's
    threshold fires it (under a lock, outside the request gate). Kill is
    fire-and-forget — detection/failover runs through the normal channels;
    revive drains traffic first via the gate.
    """

    def __init__(
        self,
        scenario: models.Scenario,
        target,
        monkey: chaos_lib.ChaosMonkey,
        gate: _TrafficGate,
    ):
        self._scenario = scenario
        self._target = target
        self._monkey = monkey
        self._gate = gate
        self._lock = threading.Lock()
        self._pending = sorted(
            scenario.events, key=lambda e: (e.at_completed, e.kind)
        )
        self._resolved: Dict[str, str] = {}
        self.fired: List[Dict[str, object]] = []

    def _resolve_replica(self, arg: str, kind: str) -> Optional[str]:
        if arg.startswith("owner:"):
            # A kill's resolution is remembered so the paired revive
            # targets the replica that actually died — after failover the
            # router resolves the owner to the SUCCESSOR, not the corpse.
            if kind != "kill_replica" and arg in self._resolved:
                return self._resolved[arg]
            index = int(arg.split(":", 1)[1])
            spec = next(
                (s for s in self._scenario.studies if s.index == index),
                self._scenario.studies[0],
            )
            replica = self._target.owner_of(spec.name)
            if kind == "kill_replica" and replica is not None:
                self._resolved[arg] = replica
            return replica
        return arg or None

    def on_completed(self, total_completed: int) -> None:
        with self._lock:
            due = [
                e for e in self._pending if e.at_completed <= total_completed
            ]
            if not due:
                return
            self._pending = [
                e for e in self._pending if e.at_completed > total_completed
            ]
        for event in due:
            self._fire(event, total_completed)

    def _revive(self, replica: str) -> None:
        """Hands a replica back. With replication armed the cutover is
        epoch-fenced and fresh RPCs drain through the tier's own failover
        barrier — live traffic keeps flowing; without it the driver
        models a production rollout: drain via the external gate, hand
        back, resume."""
        if getattr(self._target, "replication_active", False):
            self._target.revive_replica(replica)
            return
        self._gate.quiesce()
        try:
            self._target.revive_replica(replica)
        finally:
            self._gate.resume()

    def _distinct_owners(self, count: int) -> List[str]:
        """The first ``count`` distinct LIVE owners in study-index order
        (deterministic under any concurrency)."""
        owners: List[str] = []
        for spec in self._scenario.studies:
            replica = self._target.owner_of(spec.name)
            if (
                replica is not None
                and replica not in owners
                and self._target.is_alive(replica)
            ):
                owners.append(replica)
            if len(owners) >= count:
                break
        return owners

    def _fire(self, event: models.EventSpec, at: int) -> None:
        record: Dict[str, object] = {
            "kind": event.kind,
            "scheduled_at": event.at_completed,
            "fired_at": at,
            "arg": event.arg,
        }
        try:
            if event.kind == "chaos_on":
                self._monkey.failure_prob = self._scenario.config.chaos_fault_prob
            elif event.kind == "chaos_off":
                self._monkey.failure_prob = 0.0
            elif event.kind == "kill_replica":
                replica = self._resolve_replica(event.arg, event.kind)
                record["replica"] = replica
                if replica is None or not self._target.supports_replicas:
                    record["skipped"] = "no replica tier"
                else:
                    self._target.kill_replica(replica)
            elif event.kind == "revive_replica":
                replica = self._resolve_replica(event.arg, event.kind)
                record["replica"] = replica
                if replica is None or not self._target.supports_replicas:
                    record["skipped"] = "no replica tier"
                else:
                    self._revive(replica)
            elif event.kind == "multi_kill":
                if not self._target.supports_replicas:
                    record["skipped"] = "no replica tier"
                else:
                    count = int(event.arg or "2")
                    victims = self._distinct_owners(count)
                    record["replicas"] = victims
                    if len(victims) < count:
                        record["skipped"] = (
                            f"only {len(victims)} live owners"
                        )
                    else:
                        # SIMULTANEOUS: all victims are dead before any
                        # failover runs, so the sweep must re-route
                        # around every corpse (the concurrent
                        # multi-failure path). One fail_over call sweeps
                        # them all, deterministically.
                        for replica in victims:
                            self._target.kill_replica(replica)
                        record["restored"] = self._target.fail_over(
                            victims[0]
                        )
            elif event.kind == "rolling_restart":
                if not self._target.supports_replicas:
                    record["skipped"] = "no replica tier"
                else:
                    # Revive already-dead replicas FIRST (multi_kill
                    # victims): restarting the last live replica while
                    # others are still down would leave zero live
                    # replicas mid-roll.
                    replicas = self._target.replica_ids()
                    dead = [
                        r for r in replicas if not self._target.is_alive(r)
                    ]
                    for replica in dead:
                        self._target.fail_over(replica)  # ensure swept
                        self._revive(replica)
                    restarted = []
                    for replica in replicas:
                        if replica in dead:
                            continue  # already cycled above
                        self._target.kill_replica(replica)
                        self._target.fail_over(replica)
                        self._revive(replica)
                        restarted.append(replica)
                    record["revived_first"] = dead
                    record["restarted"] = restarted
            elif event.kind == "kill_compute":
                if not getattr(self._target, "supports_compute_tier", False):
                    record["skipped"] = "no compute tier"
                else:
                    self._target.kill_compute_server()
                    record["compute_alive"] = self._target.compute_is_alive()
            elif event.kind == "revive_compute":
                if not getattr(self._target, "supports_compute_tier", False):
                    record["skipped"] = "no compute tier"
                else:
                    self._target.revive_compute_server()
                    record["compute_alive"] = self._target.compute_is_alive()
            elif event.kind == "wal_corrupt":
                replica = self._resolve_replica(event.arg, event.kind)
                record["replica"] = replica
                if replica is None or not self._target.supports_replicas:
                    record["skipped"] = "no replica tier"
                else:
                    record["corruption"] = self._target.corrupt_wal(replica)
        except Exception as e:  # a failed event is a finding, not a crash
            record["error"] = f"{type(e).__name__}: {e}"
        self.fired.append(record)


# -- the driver ------------------------------------------------------------


def _study_config(spec: models.StudySpec, dim: int) -> vz.StudyConfig:
    config = vz.StudyConfig(algorithm=spec.algorithm)
    for d in range(dim):
        config.search_space.root.add_float_param(f"x{d}", 0.0, 1.0)
    config.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    return config


def _is_speculative_hit(metadata) -> bool:
    return (
        metadata.ns(speculative_lib.SPECULATIVE_NAMESPACE).get(
            speculative_lib.SPECULATIVE_KEY
        )
        == speculative_lib.SPECULATIVE_HIT_VALUE
    )


class _Run:
    """Mutable state shared by the worker pool for one arm."""

    def __init__(self, scenario: models.Scenario, target, monkey, recorder):
        self.scenario = scenario
        self.target = target
        self.monkey = monkey
        self.recorder = recorder
        self.gate = _TrafficGate()
        self.events = _EventEngine(scenario, target, monkey, self.gate)
        self.records: List[RequestRecord] = []
        self.outcomes: Dict[int, StudyOutcome] = {}
        self.completed_total = 0
        self.lock = threading.Lock()
        self.start = time.perf_counter()
        self.next_index = 0
        # Open-loop releases that hit the runaway client cap (the report
        # surfaces this: a capped run is no longer purely open-loop).
        self.open_loop_capped = 0

    def record(self, row: RequestRecord) -> None:
        with self.lock:
            self.records.append(row)

    def completion(self) -> int:
        with self.lock:
            self.completed_total += 1
            total = self.completed_total
        if (
            self.scenario.config.planes.slo
            and total % 25 == 0
            and self.target.runtime.slo_engine is not None
        ):
            self.target.runtime.slo_engine.evaluate()
        self.events.on_completed(total)
        return total

    def pop_spec(self) -> Optional[models.StudySpec]:
        """Closed-loop dispatch (``time_scale=0``): workers pull the next
        study in arrival ORDER as soon as they free up. Real arrival
        pacing (``time_scale>0``) runs through the open-loop pacer in
        :func:`run` instead — a busy worker pool must not delay an
        arrival."""
        with self.lock:
            if self.next_index >= len(self.scenario.studies):
                return None
            spec = self.scenario.studies[self.next_index]
            self.next_index += 1
        return spec


def _run_study(run: _Run, spec: models.StudySpec, reliability) -> StudyOutcome:
    scenario = run.scenario
    outcome = StudyOutcome(spec=spec, expected=spec.budget)
    tracer = tracing_lib.get_tracer()
    parent = spec.name.rsplit("/studies/", 1)[0]
    stub = chaos_lib.ChaosServiceStub(run.target.stub, run.monkey)
    try:
        # CreateStudy goes straight to the stub (VizierClient has no
        # create-by-resource-name), so it needs its own transient-retry
        # wrap — a chaos fault or a mid-failover routing error here must
        # behave like it does on every other RPC. Every mutating RPC runs
        # inside the traffic gate: the revive event's handback window
        # quiesces ALL writes, not just the suggest loop (a study created
        # on a successor mid-copy-back would strand there).
        with run.gate:
            retry_lib.RetryPolicy.from_config(
                reliability, seed=spec.seed
            ).call(
                lambda: stub.CreateStudy(
                    vizier_service_pb2.CreateStudyRequest(
                        parent=parent,
                        study=pc.study_to_proto(
                            _study_config(spec, scenario.config.dim),
                            spec.name,
                        ),
                    )
                )
            )
        client = vizier_client.VizierClient(
            stub, spec.name, f"loadgen-{spec.tenant}", reliability=reliability
        )
        for params, value in scenario.preseed_points(spec):
            with run.gate:
                created = client.create_trial(vz.Trial(parameters=params))
                client.complete_trial(
                    created.id, vz.Measurement(metrics={"obj": value})
                )
        trajectory: List[Tuple] = []
        best_curve: List[float] = []
        best = float("-inf")
        for step in range(spec.budget):
            with run.gate, tracer.span(
                "loadgen.request",
                study=spec.name,
                kind=spec.kind,
                tenant=spec.tenant,
            ) as span:
                ctx = tracer.current_context()
                trace_id = ctx.trace_id if ctx is not None else None
                t0 = time.perf_counter()
                try:
                    (trial,) = client.get_suggestions(1)
                except Exception as e:
                    latency = time.perf_counter() - t0
                    span.add_event("loadgen.suggest_failed")
                    run.record(
                        RequestRecord(
                            spec.index,
                            spec.kind,
                            spec.tenant,
                            "suggest",
                            latency,
                            trace_id=trace_id,
                            error=f"{type(e).__name__}: {e}",
                        )
                    )
                    raise
                latency = time.perf_counter() - t0
                hit = _is_speculative_hit(trial.metadata)
                fellback = fallback_lib.is_fallback_suggestion(trial.metadata)
                degraded = (
                    trial.metadata.ns(admission_lib.ADMISSION_NAMESPACE).get(
                        admission_lib.ADMISSION_KEY
                    )
                    == admission_lib.ADMISSION_VALUE
                )
                run.record(
                    RequestRecord(
                        spec.index,
                        spec.kind,
                        spec.tenant,
                        "suggest",
                        latency,
                        trace_id=trace_id,
                        speculative_hit=hit,
                        fallback=fellback,
                        degraded=degraded,
                    )
                )
                run.recorder.record(
                    spec.name,
                    "loadgen_outcome",
                    op="suggest",
                    traffic_kind=spec.kind,
                    tenant=spec.tenant,
                    step=step,
                    latency_ms=round(latency * 1e3, 3),
                    speculative_hit=hit,
                    fallback=fellback,
                )
                parameters = {
                    name: float(value)
                    for name, value in trial.parameters.as_dict().items()
                }
                trajectory.append(
                    tuple(
                        sorted(
                            (name, round(value, 12))
                            for name, value in parameters.items()
                        )
                    )
                )
                objective = scenario.objective(spec, parameters)
                best = max(best, objective)
                best_curve.append(best)
                t1 = time.perf_counter()
                client.complete_trial(
                    trial.id, vz.Measurement(metrics={"obj": objective})
                )
                run.record(
                    RequestRecord(
                        spec.index,
                        spec.kind,
                        spec.tenant,
                        "complete",
                        time.perf_counter() - t1,
                        trace_id=trace_id,
                    )
                )
            outcome.completed += 1
            run.completion()
            if (
                scenario.config.think_time_s > 0
                and spec.kind in models.GP_KINDS
            ):
                # The evaluation window: real trials take time to
                # evaluate, which is exactly what gives the speculative
                # pre-compute room to land before the next suggest.
                time.sleep(scenario.config.think_time_s)
        outcome.trajectory = tuple(trajectory)
        outcome.best_curve = tuple(best_curve)
    except Exception as e:
        outcome.error = f"{type(e).__name__}: {e}"
    return outcome


def _normalize_admission(snapshot: Dict[str, object]) -> Dict[str, object]:
    """Maps the controller's owner-keyed per-tenant dicts back to scenario
    tenant names (``loadgen-hot`` → ``hot``) so report tables join."""
    out = dict(snapshot)
    for field in (
        "inflight",
        "admits_by_tenant",
        "sheds_by_tenant",
        "degraded_by_tenant",
    ):
        table = out.get(field)
        if isinstance(table, dict):
            out[field] = {
                models.owner_tenant(owner): value
                for owner, value in table.items()
            }
    return out


def _verification_sweep(run: _Run, reliability) -> None:
    """Post-run completeness check: every study's trials must all be
    accounted for through the (possibly failed-over) serving tier."""
    for spec in run.scenario.studies:
        outcome = run.outcomes.get(spec.index)
        if outcome is None:
            continue
        try:
            client = vizier_client.VizierClient(
                run.target.stub, spec.name, "loadgen-verify",
                reliability=reliability,
            )
            trials = client.list_trials()
            outcome.listed_completed = sum(
                1 for t in trials if t.status == vz.TrialStatus.COMPLETED
            )
        except Exception as e:
            outcome.listed_completed = -1
            if outcome.error is None:
                outcome.error = f"verify: {type(e).__name__}: {e}"


def _paced_release(run_state: "_Run", scenario, run_one, start) -> List[threading.Thread]:
    """The open-loop pacer: sleeps to each study's scheduled arrival and
    starts it on a fresh client thread. Returns the started threads.

    The only backpressure is ``open_loop_max_clients`` — a pure runaway
    cap (default 128): when it binds, the release blocks until a study
    finishes, which is recorded in the run's event log so a saturated
    report can't silently pass as open-loop.
    """
    config = scenario.config
    cap = max(1, config.open_loop_max_clients)
    slots = threading.Semaphore(cap)
    threads: List[threading.Thread] = []
    capped = 0

    def paced(spec):
        try:
            run_one(spec)
        finally:
            slots.release()

    for spec in scenario.studies:
        release = start + spec.arrival_s * config.time_scale
        delay = release - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        if not slots.acquire(blocking=False):
            capped += 1
            slots.acquire()
        thread = threading.Thread(
            target=paced, args=(spec,), name=f"loadgen-open-{spec.index}"
        )
        threads.append(thread)
        thread.start()
    run_state.open_loop_capped = capped
    return threads


def run(
    scenario: models.Scenario,
    *,
    arm: str = "engine",
    only_indices: Optional[Set[int]] = None,
) -> SoakResult:
    """Drives one arm of the scenario and returns its :class:`SoakResult`.

    The scenario's env overlay (planes + surrogate boundary) is patched
    around the run and restored after; the global tracer and flight
    recorder are swapped for fresh ones so the run's observability is
    self-contained.
    """
    import unittest.mock

    config = scenario.config
    if only_indices is not None:
        scenario = models.Scenario(
            config,
            [s for s in scenario.studies if s.index in only_indices],
            scenario.events,
        )
    env_patch = unittest.mock.patch.dict(
        "os.environ", scenario_env(config)
    )
    env_patch.start()
    prev_tracer = tracing_lib.set_tracer(tracing_lib.Tracer(max_spans=65536))
    prev_recorder = recorder_lib.set_recorder(None)
    target = None
    reliability = loadgen_reliability()
    try:
        recorder = recorder_lib.get_recorder()
        monkey = chaos_lib.ChaosMonkey(
            seed=config.seed, failure_prob=0.0
        )
        factory = LoadgenPolicyFactory(scenario)
        target = _build_target(scenario, reliability, factory)
        run_state = _Run(scenario, target, monkey, recorder)

        def run_one(spec: models.StudySpec) -> None:
            outcome = _run_study(run_state, spec, reliability)
            with run_state.lock:
                run_state.outcomes[spec.index] = outcome

        def worker():
            while True:
                spec = run_state.pop_spec()
                if spec is None:
                    return
                run_one(spec)

        start = time.perf_counter()
        if config.time_scale > 0:
            # OPEN LOOP: release each study at its scheduled arrival
            # instant on its own client thread, whether or not the fleet
            # is keeping up — a busy pool never delays an arrival, so
            # suggest latency under saturation measures real queueing
            # (the MLPerf-loadgen "server" shape). ``concurrency`` does
            # not gate dispatch here; ``open_loop_max_clients`` is only a
            # runaway safety cap.
            threads = _paced_release(run_state, scenario, run_one, start)
        else:
            threads = [
                threading.Thread(target=worker, name=f"loadgen-client-{i}")
                for i in range(max(1, config.concurrency))
            ]
            for t in threads:
                t.start()
        for t in threads:
            t.join()
        # Any events still pending at drain (trial volume fell short of a
        # threshold — e.g. an errored study) fire now so the track always
        # completes and the revive/copy-back is always exercised.
        run_state.events.on_completed(1 << 62)
        if config.planes.slo and target.runtime.slo_engine is not None:
            target.runtime.slo_engine.evaluate()
        _verification_sweep(run_state, reliability)
        wall = time.perf_counter() - start
        recorder_kinds: Dict[str, int] = {}
        for event in recorder.events():
            recorder_kinds[event["kind"]] = (
                recorder_kinds.get(event["kind"], 0) + 1
            )
        return SoakResult(
            arm=arm,
            scenario_fingerprint=scenario.fingerprint(),
            records=run_state.records,
            outcomes=run_state.outcomes,
            events_fired=run_state.events.fired,
            serving_stats=target.serving_stats(),
            slo=target.runtime.slo_report(),
            wall_s=round(wall, 3),
            wal_root=target.wal_root,
            recorder_event_kinds=dict(sorted(recorder_kinds.items())),
            admission=_normalize_admission(
                target.runtime.admission_snapshot()
            ),
            open_loop_capped=run_state.open_loop_capped,
        )
    finally:
        if target is not None:
            target.shutdown()
        tracing_lib.set_tracer(prev_tracer)
        recorder_lib.set_recorder(prev_recorder)
        env_patch.stop()


def run_reference(
    scenario: models.Scenario, indices: Optional[Sequence[int]] = None
) -> SoakResult:
    """The sequential reference arm: the parity cohort's studies, one
    client, in-process target, every plane gated off, no chaos, no events
    — the seed-path ground truth the engine is compared against."""
    cohort = (
        set(indices)
        if indices is not None
        else {s.index for s in scenario.parity_cohort()}
    )
    ref_config = dataclasses.replace(
        scenario.config,
        target="inprocess",
        concurrency=1,
        planes=models.PlaneConfig.gated_off(),
        chaos_fault_prob=0.0,
        think_time_s=0.0,
        time_scale=0.0,
    )
    reference = models.Scenario(
        ref_config,
        [s for s in scenario.studies if s.index in cohort],
        (),
    )
    return run(reference, arm="reference")


def run_gated_off(
    scenario: models.Scenario, indices: Optional[Sequence[int]] = None
) -> SoakResult:
    """The engine with every plane gated off, same cohort as the
    reference: bit-identity between this arm and the reference is the
    proof that the loadgen engine itself perturbs nothing."""
    cohort = (
        set(indices)
        if indices is not None
        else {s.index for s in scenario.parity_cohort()}
    )
    gated_config = dataclasses.replace(
        scenario.config,
        target="inprocess",
        planes=models.PlaneConfig.gated_off(),
        chaos_fault_prob=0.0,
        think_time_s=0.0,
        time_scale=0.0,
    )
    gated = models.Scenario(
        gated_config,
        [s for s in scenario.studies if s.index in cohort],
        (),
    )
    return run(gated, arm="gated_off")
