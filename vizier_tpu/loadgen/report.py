"""The soak assertion engine: one report, one verdict.

Turns the driver's raw arms into ``SOAK_REPORT.json`` — the single
artifact that replaces five separate point A/Bs with one repeatable
full-stack verdict. Sections:

- **scenario** — the config + deterministic fingerprint + the registered
  program-kind universe the mix was validated against;
- **traffic** — what was actually driven: studies/trials per kind and
  tenant, achieved arrival shape, wall time;
- **outcomes** — the per-kind table: suggest latency percentiles,
  speculative hits, fallbacks, errors;
- **slo** — the SLO engine's own ``slo_report()`` (p99s per hop, burn
  rates, breached set) from the armed run;
- **failover** — the scripted events as fired, replica failover counters,
  and the zero-lost-studies accounting from the verification sweep;
- **parity** — rank-sum regret parity of the engine arm against the
  sequential reference on the parity cohort;
- **bit_identity** — trajectory equality of the gated-off engine arm vs
  the sequential reference (the engine perturbs nothing when its planes
  are off);
- **assertions** — every check with its verdict; ``ok`` is their AND.

Stdlib-only (scipy used opportunistically for the rank-sum, with the
same normal-approximation fallback the A/B tools carry).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

from vizier_tpu.loadgen import driver as driver_lib
from vizier_tpu.loadgen import models

REPORT_VERSION = 2  # v2: admission section + per-tenant latency/sheds


def ranksum_p(a, b) -> float:
    """Two-sided rank-sum p-value (scipy when present, else normal
    approximation — same shape as tools/speculative_ab.py)."""
    if not a or not b:
        return 1.0
    try:
        from scipy import stats as sps

        return float(sps.ranksums(a, b).pvalue)
    except Exception:
        n, m = len(a), len(b)
        ranked = sorted((v, 0) for v in a) + sorted((v, 1) for v in b)
        ranked.sort()
        ra = sum(i + 1 for i, (v, g) in enumerate(ranked) if g == 0)
        mu = n * (n + m + 1) / 2.0
        sigma = math.sqrt(n * m * (n + m + 1) / 12.0) or 1.0
        z = (ra - mu) / sigma
        return 2.0 * (1.0 - 0.5 * (1.0 + math.erf(abs(z) / math.sqrt(2)))) or 1.0


def _percentile(sorted_values: List[float], q: float) -> float:
    if not sorted_values:
        return 0.0
    if len(sorted_values) == 1:
        return sorted_values[0]
    rank = (q / 100.0) * (len(sorted_values) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(sorted_values) - 1)
    frac = rank - lo
    return sorted_values[lo] * (1 - frac) + sorted_values[hi] * frac


def _latency_ms(values: List[float]) -> Dict[str, float]:
    values = sorted(values)
    return {
        "p50_ms": round(_percentile(values, 50) * 1e3, 3),
        "p95_ms": round(_percentile(values, 95) * 1e3, 3),
        "p99_ms": round(_percentile(values, 99) * 1e3, 3),
        "max_ms": round((values[-1] if values else 0.0) * 1e3, 3),
        "samples": len(values),
    }


def _outcome_tables(result: driver_lib.SoakResult) -> Dict[str, dict]:
    """The per-kind (and per-tenant) rollup of the request records."""
    by_kind: Dict[str, dict] = {}
    by_tenant: Dict[str, dict] = {}
    latencies: Dict[str, List[float]] = {}
    tenant_latencies: Dict[str, List[float]] = {}
    for record in result.records:
        if record.op != "suggest":
            continue
        for table, key in ((by_kind, record.kind), (by_tenant, record.tenant)):
            row = table.setdefault(
                key,
                {
                    "suggests": 0,
                    "errors": 0,
                    "fallbacks": 0,
                    "speculative_hits": 0,
                    "degraded": 0,
                    "shed_errors": 0,
                },
            )
            row["suggests"] += 1
            if record.error is not None:
                row["errors"] += 1
            if record.fallback:
                row["fallbacks"] += 1
            if record.speculative_hit:
                row["speculative_hits"] += 1
            if record.degraded:
                row["degraded"] += 1
            if record.shed:
                row["shed_errors"] += 1
        if record.error is None:
            latencies.setdefault(record.kind, []).append(record.latency_s)
            tenant_latencies.setdefault(record.tenant, []).append(
                record.latency_s
            )
    for kind, row in by_kind.items():
        row["studies"] = sum(
            1 for o in result.outcomes.values() if o.spec.kind == kind
        )
        served = max(1, row["suggests"] - row["errors"])
        row["fallback_rate"] = round(row["fallbacks"] / served, 4)
        row["hit_rate"] = round(row["speculative_hits"] / served, 4)
        row["latency"] = _latency_ms(latencies.get(kind, []))
    # Per-tenant sheds seen by the controller (retried-and-absorbed sheds
    # included, unlike the client-visible shed_errors) + latency — the
    # fairness view: one hot tenant's collapse must be visible as ITS
    # numbers, not smeared across the fleet aggregate.
    controller_sheds = (result.admission or {}).get("sheds_by_tenant", {})
    for tenant, row in by_tenant.items():
        row["studies"] = sum(
            1 for o in result.outcomes.values() if o.spec.tenant == tenant
        )
        row["sheds"] = sum(controller_sheds.get(tenant, {}).values())
        row["latency"] = _latency_ms(tenant_latencies.get(tenant, []))
    return {
        "by_kind": dict(sorted(by_kind.items())),
        "by_tenant": dict(sorted(by_tenant.items())),
    }


def _parity_section(
    scenario: models.Scenario,
    engine: driver_lib.SoakResult,
    reference: driver_lib.SoakResult,
) -> dict:
    """Rank-sum regret parity on the cohort's final best objectives."""
    cohort = sorted(reference.outcomes)
    engine_best, reference_best, skipped = [], [], []
    for index in cohort:
        e = engine.outcomes.get(index)
        r = reference.outcomes[index]
        if e is None or e.final_best is None or r.final_best is None:
            skipped.append(index)
            continue
        engine_best.append(round(e.final_best, 9))
        reference_best.append(round(r.final_best, 9))
    p = ranksum_p(engine_best, reference_best)
    return {
        "cohort": cohort,
        "skipped": skipped,
        "engine_final_best": engine_best,
        "reference_final_best": reference_best,
        "ranksum_p": round(p, 4),
        "alpha": scenario.config.parity_alpha,
    }


def _bit_identity_section(
    gated: driver_lib.SoakResult, reference: driver_lib.SoakResult
) -> dict:
    """Per-study trajectory equality, gated-off engine vs reference."""
    mismatched, compared = [], 0
    for index, ref in sorted(reference.outcomes.items()):
        g = gated.outcomes.get(index)
        if g is None:
            mismatched.append({"study": index, "reason": "missing in gated arm"})
            continue
        if not ref.trajectory:
            mismatched.append(
                {"study": index, "reason": "empty reference trajectory"}
            )
            continue
        compared += 1
        if g.trajectory != ref.trajectory:
            mismatched.append({"study": index, "reason": "trajectory differs"})
    return {
        "studies_compared": compared,
        "identical": not mismatched and compared > 0,
        "mismatched": mismatched,
    }


def _traffic_section(
    scenario: models.Scenario, engine: driver_lib.SoakResult
) -> dict:
    driven = sum(o.completed for o in engine.outcomes.values())
    return {
        **scenario.summary(),
        "driven_trials": driven,
        "preseeded_trials": sum(
            o.spec.preseed for o in engine.outcomes.values()
        ),
        "wall_s": engine.wall_s,
        "achieved_trials_per_s": round(driven / max(engine.wall_s, 1e-9), 2),
        "open_loop": scenario.config.time_scale > 0,
        "open_loop_capped": engine.open_loop_capped,
    }


def _admission_section(
    config: models.ScenarioConfig, engine: driver_lib.SoakResult
) -> dict:
    """The overload-protection rollup: the controller's own snapshot plus
    the fleet shed rate (controller sheds over controller decisions) the
    --diff regression gate compares."""
    snapshot = dict(engine.admission or {"enabled": False})
    sheds = sum(
        count
        for reasons in snapshot.get("sheds_by_tenant", {}).values()
        for count in reasons.values()
    )
    admits = sum(snapshot.get("admits_by_tenant", {}).values())
    degraded = sum(snapshot.get("degraded_by_tenant", {}).values())
    decisions = sheds + admits + degraded
    return {
        "armed": bool(config.planes.admission),
        "sheds": sheds,
        "degraded_serves": degraded,
        "shed_rate": round(sheds / decisions, 4) if decisions else 0.0,
        "snapshot": snapshot,
    }


def _assert_row(name: str, ok: bool, detail: str) -> dict:
    return {"name": name, "ok": bool(ok), "detail": detail}


def build_report(
    scenario: models.Scenario,
    engine: driver_lib.SoakResult,
    reference: Optional[driver_lib.SoakResult] = None,
    gated: Optional[driver_lib.SoakResult] = None,
    *,
    stamps: Optional[dict] = None,
) -> dict:
    """Assembles the report and evaluates every assertion.

    ``reference``/``gated`` are optional so a quick engine-only run still
    produces a report (the parity/bit-identity assertions then record
    themselves as skipped rather than silently passing).
    """
    config = scenario.config
    outcomes = _outcome_tables(engine)
    by_kind = outcomes["by_kind"]
    assertions: List[dict] = []

    lost = engine.lost_studies()
    errored = engine.errored_studies()
    assertions.append(
        _assert_row(
            "zero_lost_studies",
            not lost and not errored,
            f"lost={lost} errored={errored} of {len(engine.outcomes)} studies",
        )
    )

    expected_kinds = scenario.kinds_present()
    served_kinds = sorted(
        kind
        for kind, row in by_kind.items()
        if row["suggests"] - row["errors"] > 0
    )
    assertions.append(
        _assert_row(
            "all_kinds_served",
            set(expected_kinds) <= set(served_kinds),
            f"expected={expected_kinds} served={served_kinds}",
        )
    )

    fired_ok = [e for e in engine.events_fired if "error" not in e]
    skipped_events = [e for e in engine.events_fired if "skipped" in e]
    assertions.append(
        _assert_row(
            "all_events_fired",
            len(fired_ok) == len(scenario.events) and not skipped_events,
            f"fired={len(fired_ok)}/{len(scenario.events)} "
            f"skipped={len(skipped_events)}",
        )
    )

    kills = [e for e in engine.events_fired if e["kind"] == "kill_replica"]
    if kills:
        failovers = int(engine.serving_stats.get("failovers", 0) or 0)
        assertions.append(
            _assert_row(
                "failover_complete",
                failovers >= 1 and not lost,
                f"failovers={failovers} lost_after_failover={lost}",
            )
        )

    suggests = [r for r in engine.records if r.op == "suggest"]
    served = [r for r in suggests if r.error is None]
    fallbacks = sum(1 for r in served if r.fallback)
    fallback_rate = fallbacks / max(1, len(served))
    assertions.append(
        _assert_row(
            "fallback_rate_bounded",
            fallback_rate <= config.max_fallback_rate,
            f"rate={fallback_rate:.4f} budget={config.max_fallback_rate}",
        )
    )

    speculative_section = {
        "armed": config.planes.speculative,
        "hits": sum(1 for r in served if r.speculative_hit),
        "gp_suggests": sum(
            1 for r in served if r.kind in models.GP_KINDS
        ),
    }
    speculative_section["gp_hit_rate"] = round(
        speculative_section["hits"]
        / max(1, speculative_section["gp_suggests"]),
        4,
    )
    if config.planes.speculative:
        assertions.append(
            _assert_row(
                "speculative_hits",
                speculative_section["hits"] >= config.min_speculative_hits
                and speculative_section["gp_hit_rate"] >= config.min_hit_rate,
                f"hits={speculative_section['hits']} "
                f"(min {config.min_speculative_hits}), gp hit rate "
                f"{speculative_section['gp_hit_rate']} "
                f"(min {config.min_hit_rate})",
            )
        )

    if config.planes.slo:
        if config.target in ("subprocess", "shared_compute"):
            # Each replica process runs its own SLO engine (armed by the
            # inherited env overlay) and dumps it via --obs-dump-dir; the
            # driver has no in-process engine to read, so the roll-up
            # assertion is waived rather than silently passed.
            assertions.append(
                _assert_row(
                    "slo_evaluated",
                    True,
                    "waived: SLO engines run per replica process "
                    "(read them from the fleet observability dumps)",
                )
            )
        else:
            breaching = list(engine.slo.get("breaching", []))
            evaluations = engine.slo.get("evaluations", 0)
            armed = bool(engine.slo) and engine.slo.get("armed", True)
            assertions.append(
                _assert_row(
                    "slo_evaluated",
                    armed
                    and not any(
                        b.startswith("suggest_p99") for b in breaching
                    ),
                    f"armed={armed} evaluations={evaluations} "
                    f"breaching={sorted(breaching)} "
                    f"(p99 budget {config.p99_budget_ms} ms)",
                )
            )

    admission_section = _admission_section(config, engine)
    if config.planes.admission:
        # The plane soaks WITH the traffic: under the scenario's nominal
        # load the controller must not shed past budget (the hot_tenant
        # overload preset raises the budget to 1.0 — shedding there IS
        # the mechanism under test).
        assertions.append(
            _assert_row(
                "shed_rate_bounded",
                admission_section["shed_rate"] <= config.max_shed_rate,
                f"shed_rate={admission_section['shed_rate']} "
                f"budget={config.max_shed_rate} "
                f"(sheds={admission_section['sheds']})",
            )
        )

    # Per-study designer seeding cannot cross a process boundary, so a
    # subprocess tier serves unseeded designers: trajectory-level parity
    # against the in-process reference is structurally meaningless there
    # and is WAIVED (recorded, not silently passed) — the in-process arms
    # carry the parity/bit-identity evidence for the same code paths.
    parity_waived = config.target in ("subprocess", "shared_compute")

    parity = None
    if parity_waived:
        assertions.append(
            _assert_row(
                "regret_parity",
                True,
                "waived: subprocess tier serves unseeded designers "
                "(parity evidence rides the in-process arms)",
            )
        )
    elif reference is not None:
        parity = _parity_section(scenario, engine, reference)
        assertions.append(
            _assert_row(
                "regret_parity",
                parity["ranksum_p"] >= config.parity_alpha
                and not parity["skipped"],
                f"ranksum_p={parity['ranksum_p']} "
                f"(alpha {config.parity_alpha}), cohort "
                f"{len(parity['cohort'])}, skipped {parity['skipped']}",
            )
        )
    else:
        assertions.append(
            _assert_row("regret_parity", False, "reference arm not run")
        )

    bit_identity = None
    if parity_waived:
        assertions.append(
            _assert_row(
                "bit_identical_when_gated",
                True,
                "waived: subprocess tier serves unseeded designers "
                "(bit-identity evidence rides the in-process arms)",
            )
        )
    elif gated is not None and reference is not None:
        bit_identity = _bit_identity_section(gated, reference)
        assertions.append(
            _assert_row(
                "bit_identical_when_gated",
                bit_identity["identical"],
                f"compared={bit_identity['studies_compared']} "
                f"mismatched={bit_identity['mismatched']}",
            )
        )
    else:
        assertions.append(
            _assert_row(
                "bit_identical_when_gated", False, "gated-off arm not run"
            )
        )

    report = {
        "version": REPORT_VERSION,
        "what": (
            "loadgen full-stack soak: production-shaped mixed traffic "
            "(open-loop arrivals, Zipf study sizes, tenant + program-kind "
            "mixes, scripted kill/revive + chaos events) driven through "
            "the real serving fleet, asserted in one report"
        ),
        "scenario": {
            "config": config.as_dict(),
            "fingerprint": engine.scenario_fingerprint,
            "registered_program_kinds": list(models.registered_gp_kinds()),
        },
        "traffic": _traffic_section(scenario, engine),
        "outcomes": outcomes,
        "admission": admission_section,
        "speculative": speculative_section,
        "slo": engine.slo,
        "failover": {
            "events_fired": engine.events_fired,
            "failovers": int(engine.serving_stats.get("failovers", 0) or 0),
            "restored_studies": int(
                engine.serving_stats.get("restored_studies", 0) or 0
            ),
            "recorder_event_kinds": engine.recorder_event_kinds,
            "lost_studies": lost,
            "errored_studies": errored,
            "errors": {
                str(i): engine.outcomes[i].error
                for i in errored
                if engine.outcomes[i].error
            },
        },
        "serving_stats": {
            k: v
            for k, v in sorted(engine.serving_stats.items())
            if isinstance(v, int) and v
        },
        "parity": parity,
        "bit_identity": bit_identity,
        "assertions": assertions,
        "ok": all(a["ok"] for a in assertions),
    }
    if stamps:
        report["stamps"] = stamps
    return report


def diff_reports(
    a: dict,
    b: dict,
    *,
    hit_rate_drop: float = 0.10,
    fallback_rise: float = 0.05,
    shed_rise: float = 0.05,
    latency_ratio: float = 0.0,
) -> dict:
    """Compares two SOAK_REPORTs (A = before, B = after).

    The ROADMAP defaults-ON campaign's before/after gate: per-kind AND
    per-tenant suggest-latency deltas, assertion verdict changes,
    speculative hit-rate, fallback-rate, and admission shed-rate deltas.
    **Regressions** (what flips ``ok`` to False) are: an assertion that
    passed in A and fails in B; a GP hit-rate drop > ``hit_rate_drop``;
    a fallback-rate rise > ``fallback_rise``; an admission shed-rate
    rise > ``shed_rise`` while the plane's armed state is UNCHANGED
    (arming the plane on a saturating scenario legitimately introduces
    sheds — that is not a regression); and, when ``latency_ratio`` > 0,
    any per-kind p99 that grew by more than that factor (off by default
    — wall-clock comparisons across machines are advisory, verdicts are
    the gate). Per-tenant p99 deltas are always reported, and gated by
    the same ``latency_ratio`` knob.
    """

    def _assertions(report: dict) -> Dict[str, bool]:
        return {
            row["name"]: bool(row["ok"])
            for row in report.get("assertions", [])
        }

    regressions: List[str] = []
    a_asserts, b_asserts = _assertions(a), _assertions(b)
    verdict_changes: Dict[str, dict] = {}
    for name in sorted(set(a_asserts) | set(b_asserts)):
        before, after = a_asserts.get(name), b_asserts.get(name)
        if before != after:
            verdict_changes[name] = {"before": before, "after": after}
        if before is True and after is False:
            regressions.append(f"assertion {name}: pass -> FAIL")

    per_kind: Dict[str, dict] = {}
    a_kinds = a.get("outcomes", {}).get("by_kind", {})
    b_kinds = b.get("outcomes", {}).get("by_kind", {})
    for kind in sorted(set(a_kinds) | set(b_kinds)):
        row_a, row_b = a_kinds.get(kind), b_kinds.get(kind)
        entry: Dict[str, object] = {
            "present": {"before": row_a is not None, "after": row_b is not None}
        }
        if row_a and row_b:
            for q in ("p50_ms", "p99_ms"):
                before = row_a.get("latency", {}).get(q)
                after = row_b.get("latency", {}).get(q)
                if before is not None and after is not None:
                    entry[q] = {
                        "before": before,
                        "after": after,
                        "delta": round(after - before, 3),
                        "ratio": round(after / before, 3)
                        if before
                        else None,
                    }
            if (
                latency_ratio > 0
                and isinstance(entry.get("p99_ms"), dict)
                and entry["p99_ms"].get("ratio") is not None
                and entry["p99_ms"]["ratio"] > latency_ratio
            ):
                regressions.append(
                    f"{kind} p99 {entry['p99_ms']['ratio']}x "
                    f"(> {latency_ratio}x budget)"
                )
            entry["fallback_rate"] = {
                "before": row_a.get("fallback_rate", 0.0),
                "after": row_b.get("fallback_rate", 0.0),
            }
            entry["hit_rate"] = {
                "before": row_a.get("hit_rate", 0.0),
                "after": row_b.get("hit_rate", 0.0),
            }
        elif row_a and not row_b:
            regressions.append(f"kind {kind} served in A but absent in B")
        per_kind[kind] = entry

    # Per-tenant p99 deltas + controller-shed deltas (the fair-share
    # regression view: a hot-tenant fix must not quietly cost a light
    # tenant its p99).
    per_tenant: Dict[str, dict] = {}
    a_tenants = a.get("outcomes", {}).get("by_tenant", {})
    b_tenants = b.get("outcomes", {}).get("by_tenant", {})
    for tenant in sorted(set(a_tenants) | set(b_tenants)):
        row_a, row_b = a_tenants.get(tenant), b_tenants.get(tenant)
        entry: Dict[str, object] = {
            "present": {"before": row_a is not None, "after": row_b is not None}
        }
        if row_a and row_b:
            for q in ("p50_ms", "p99_ms"):
                before = (row_a.get("latency") or {}).get(q)
                after = (row_b.get("latency") or {}).get(q)
                if before is not None and after is not None:
                    entry[q] = {
                        "before": before,
                        "after": after,
                        "delta": round(after - before, 3),
                        "ratio": round(after / before, 3) if before else None,
                    }
            entry["sheds"] = {
                "before": row_a.get("sheds", 0),
                "after": row_b.get("sheds", 0),
            }
            if (
                latency_ratio > 0
                and isinstance(entry.get("p99_ms"), dict)
                and entry["p99_ms"].get("ratio") is not None
                and entry["p99_ms"]["ratio"] > latency_ratio
            ):
                regressions.append(
                    f"tenant {tenant} p99 {entry['p99_ms']['ratio']}x "
                    f"(> {latency_ratio}x budget)"
                )
        per_tenant[tenant] = entry

    adm_a = a.get("admission", {}) or {}
    adm_b = b.get("admission", {}) or {}
    shed_section = {
        "armed": {"before": adm_a.get("armed"), "after": adm_b.get("armed")},
        "shed_rate": {
            "before": adm_a.get("shed_rate"),
            "after": adm_b.get("shed_rate"),
        },
    }
    if (
        adm_a.get("armed") == adm_b.get("armed")
        and adm_a.get("shed_rate") is not None
        and adm_b.get("shed_rate") is not None
        and adm_b["shed_rate"] > adm_a["shed_rate"] + shed_rise
    ):
        regressions.append(
            f"admission shed rate {adm_a['shed_rate']} -> "
            f"{adm_b['shed_rate']} (rise > {shed_rise} with the plane "
            "unchanged)"
        )

    spec_a = a.get("speculative", {}) or {}
    spec_b = b.get("speculative", {}) or {}
    speculative = {
        "hits": {"before": spec_a.get("hits"), "after": spec_b.get("hits")},
        "gp_hit_rate": {
            "before": spec_a.get("gp_hit_rate"),
            "after": spec_b.get("gp_hit_rate"),
        },
    }
    if (
        spec_a.get("armed")
        and spec_b.get("armed")
        and spec_a.get("gp_hit_rate") is not None
        and spec_b.get("gp_hit_rate") is not None
        and spec_b["gp_hit_rate"] < spec_a["gp_hit_rate"] - hit_rate_drop
    ):
        regressions.append(
            f"gp hit rate {spec_a['gp_hit_rate']} -> "
            f"{spec_b['gp_hit_rate']} (drop > {hit_rate_drop})"
        )

    def _fallback_rate(report: dict) -> Optional[float]:
        kinds = report.get("outcomes", {}).get("by_kind", {})
        suggests = sum(r.get("suggests", 0) for r in kinds.values())
        fallbacks = sum(r.get("fallbacks", 0) for r in kinds.values())
        return round(fallbacks / suggests, 4) if suggests else None

    fb_a, fb_b = _fallback_rate(a), _fallback_rate(b)
    fallback = {"before": fb_a, "after": fb_b}
    if fb_a is not None and fb_b is not None and fb_b > fb_a + fallback_rise:
        regressions.append(
            f"fallback rate {fb_a} -> {fb_b} (rise > {fallback_rise})"
        )

    return {
        "what": "SOAK_REPORT diff (A = before, B = after)",
        "fingerprints": {
            "before": (a.get("scenario") or {}).get("fingerprint"),
            "after": (b.get("scenario") or {}).get("fingerprint"),
        },
        "same_scenario": (a.get("scenario") or {}).get("fingerprint")
        == (b.get("scenario") or {}).get("fingerprint"),
        "ok_flags": {"before": a.get("ok"), "after": b.get("ok")},
        "assertion_changes": verdict_changes,
        "per_kind": per_kind,
        "per_tenant": per_tenant,
        "admission": shed_section,
        "speculative": speculative,
        "fallback_rate": fallback,
        "regressions": regressions,
        "ok": not regressions,
    }


def render_diff(diff: dict) -> str:
    """Human rendering of :func:`diff_reports` (the --diff stdout)."""
    lines = [
        f"soak diff: {'OK' if diff['ok'] else 'REGRESSED'} "
        f"(same scenario: {diff['same_scenario']})"
    ]
    for name, change in sorted(diff["assertion_changes"].items()):
        lines.append(
            f"  verdict {name}: {change['before']} -> {change['after']}"
        )
    for kind, entry in sorted(diff["per_kind"].items()):
        p99 = entry.get("p99_ms")
        if isinstance(p99, dict):
            lines.append(
                f"  {kind}: p99 {p99['before']} -> {p99['after']} ms "
                f"({p99['ratio']}x)"
            )
    for tenant, entry in sorted(diff.get("per_tenant", {}).items()):
        p99 = entry.get("p99_ms")
        sheds = entry.get("sheds", {})
        if isinstance(p99, dict):
            lines.append(
                f"  tenant {tenant}: p99 {p99['before']} -> {p99['after']} "
                f"ms ({p99['ratio']}x), sheds {sheds.get('before')} -> "
                f"{sheds.get('after')}"
            )
    spec = diff["speculative"]["gp_hit_rate"]
    if spec["before"] is not None or spec["after"] is not None:
        lines.append(
            f"  gp hit rate: {spec['before']} -> {spec['after']}"
        )
    fb = diff["fallback_rate"]
    lines.append(f"  fallback rate: {fb['before']} -> {fb['after']}")
    shed = diff.get("admission", {}).get("shed_rate", {})
    if shed.get("before") is not None or shed.get("after") is not None:
        lines.append(
            f"  admission shed rate: {shed.get('before')} -> "
            f"{shed.get('after')}"
        )
    for regression in diff["regressions"]:
        lines.append(f"  REGRESSION: {regression}")
    return "\n".join(lines)


def render_verdict(report: dict) -> str:
    """The one-screen human verdict (the CLI's stdout tail)."""
    lines = [
        f"soak: {'PASS' if report['ok'] else 'FAIL'} — "
        f"{report['traffic']['studies']} studies / "
        f"{report['traffic']['driven_trials']} trials in "
        f"{report['traffic']['wall_s']}s"
    ]
    for a in report["assertions"]:
        lines.append(
            f"  [{'ok' if a['ok'] else 'FAIL'}] {a['name']}: {a['detail']}"
        )
    return "\n".join(lines)
