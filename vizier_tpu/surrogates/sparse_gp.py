"""Sparse inducing-point GP: SGPR collapsed bound, mask-safe, TPU-first.

The exact GP (``models.gp``) pays O(n³) per ARD loss evaluation and O(n²)
per posterior query — a 72 s device-side suggest at the 1000×20-D
north-star scale (BENCH_CPU_FULLSCALE.json). This module is the
inducing-point alternative ("Scalable Thompson Sampling using Sparse
Gaussian Process Models", arXiv:2006.05356; Titsias' SGPR collapsed
bound): m ≪ n pseudo-inputs Z summarize the data, training costs O(n·m²)
and each posterior query O(m²) — and because the collapsed bound
marginalizes the inducing distribution in closed form, there is no
variational optimization loop: the SAME multi-restart L-BFGS program that
trains the exact GP trains this one (the hyperparameter pytree is
identical, so warm-started ARD restarts keep working across the seam).

Design mirrors ``models.gp`` deliberately:

- **mask-safe everywhere**: padded data rows AND padded inducing slots are
  decoupled (zero cross-covariance, unit diagonal, zero residual), so one
  compiled program serves every (trial-bucket, inducing-bucket) pair —
  fill values cannot leak into either Cholesky;
- **k-center inducing selection** (farthest-point traversal, seeded at the
  incumbent) is deterministic given the data and runs INSIDE the jitted
  program — O(n·m·d), negligible next to training, and vmappable over the
  cross-study batch axis;
- **matmul-only predictions**: like ``GPState.linv``, the two triangular
  inverses are formed once at precompute so the acquisition sweep's
  thousands of posterior queries ride the MXU instead of sequential
  triangular solves.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import flax.struct
import jax
import jax.numpy as jnp

from vizier_tpu.models import gp as gp_lib
from vizier_tpu.models import kernels
from vizier_tpu.models import params as params_lib

Array = jax.Array
Params = params_lib.Params

_LOG_2PI = 1.8378770664093453
# Noise-floor jitter matching the exact GP's Gram stabilizer.
_JITTER = 1e-5
# Kmm jitter: inducing Grams are denser (k-center picks spread points, but
# duplicate training rows can still select twice); a slightly larger
# diagonal keeps the m×m Cholesky conditioned without visibly biasing the
# posterior at SGPR scales.
_KMM_JITTER = 1e-4


@flax.struct.dataclass
class SparseGPData:
    """Training data + the selected (padded, masked) inducing set."""

    data: gp_lib.GPData
    z_continuous: Array  # [M, Dc] float32
    z_categorical: Array  # [M, Ds] int32
    inducing_mask: Array  # [M] bool, True = real inducing point
    inducing_indices: Array  # [M] int32 rows of ``data`` the points came from

    @property
    def num_inducing(self) -> int:
        return self.z_continuous.shape[0]

    def z_features(self) -> kernels.MixedFeatures:
        return kernels.MixedFeatures(self.z_continuous, self.z_categorical)


def select_inducing_kcenter(data: gp_lib.GPData, m: int) -> SparseGPData:
    """Greedy k-center (farthest-point) selection of ``m`` inducing points.

    Deterministic given the data: starts at the best-label valid row (the
    incumbent — the region Thompson/UCB exploitation cares most about),
    then repeatedly takes the valid row farthest from the chosen set under
    the unit-lengthscale mixed metric (squared euclidean on continuous +
    hamming on categorical, both dim-masked). Traceable: fixed [m] output
    shapes, ``fori_loop`` over picks, so it vmaps over the cross-study
    batch axis. When fewer than ``m`` valid rows exist the surplus slots
    repeat already-chosen rows and are masked out of every downstream
    computation by ``inducing_mask``.
    """
    cont, cat = data.continuous, data.categorical
    valid = data.row_mask
    num_valid = jnp.sum(valid.astype(jnp.int32))
    start = jnp.argmax(jnp.where(valid, data.labels, -jnp.inf)).astype(jnp.int32)

    cont_w = data.cont_dim_mask.astype(cont.dtype)
    cat_w = data.cat_dim_mask.astype(cont.dtype)

    def dist_to(idx: Array) -> Array:
        dc = cont - cont[idx][None, :]
        sq = jnp.sum(dc * dc * cont_w[None, :], axis=-1)
        mismatch = (cat != cat[idx][None, :]).astype(cont.dtype)
        return sq + jnp.sum(mismatch * cat_w[None, :], axis=-1)

    def body(i, carry):
        min_d, idxs = carry
        min_d = jnp.minimum(min_d, dist_to(idxs[i - 1]))
        nxt = jnp.argmax(jnp.where(valid, min_d, -jnp.inf)).astype(jnp.int32)
        return min_d, idxs.at[i].set(nxt)

    idxs = jnp.zeros((m,), jnp.int32).at[0].set(start)
    min_d = jnp.full((cont.shape[0],), jnp.inf, dtype=cont.dtype)
    if m > 1:
        _, idxs = jax.lax.fori_loop(1, m, body, (min_d, idxs))
    mask = jnp.arange(m) < jnp.minimum(num_valid, m)
    return SparseGPData(
        data=data,
        z_continuous=cont[idxs],
        z_categorical=cat[idxs],
        inducing_mask=mask,
        inducing_indices=idxs,
    )


def with_pending_capacity(
    sdata: SparseGPData, data: gp_lib.GPData, extra: int
) -> SparseGPData:
    """An all-points twin of a trained posterior's inducing set.

    Carries the SAME inducing rows Z over a different data block (the
    completed+active rows with spare slots for a batch's picks), plus
    ``extra`` masked-off spare inducing slots that per-pick conditioning
    may Nyström-fill (``gp_ucb_pe._append_row_sparse``) when a pick lands
    where Z has no support. Traceable fixed shapes: one compiled program
    per (n-bucket, m-bucket, extra) triple.
    """
    z_cont = jnp.concatenate(
        [
            sdata.z_continuous,
            jnp.zeros(
                (extra, sdata.z_continuous.shape[-1]), sdata.z_continuous.dtype
            ),
        ],
        axis=0,
    )
    z_cat = jnp.concatenate(
        [
            sdata.z_categorical,
            jnp.zeros(
                (extra, sdata.z_categorical.shape[-1]),
                sdata.z_categorical.dtype,
            ),
        ],
        axis=0,
    )
    mask = jnp.concatenate(
        [sdata.inducing_mask, jnp.zeros((extra,), bool)], axis=0
    )
    indices = jnp.concatenate(
        [sdata.inducing_indices, jnp.zeros((extra,), jnp.int32)], axis=0
    )
    return SparseGPData(
        data=data,
        z_continuous=z_cont,
        z_categorical=z_cat,
        inducing_mask=mask,
        inducing_indices=indices,
    )


@dataclasses.dataclass(frozen=True)
class SparseGaussianProcess:
    """Static sparse-model config + pure functions over (params, data).

    Wraps the exact model for its kernel and hyperparameter declaration —
    the parameter pytree is IDENTICAL to the exact GP's, which is what lets
    warm-started ARD restarts and the serving designer-state cache carry
    trained params across suggests without knowing which surrogate is
    active. ``num_inducing`` is the PADDED inducing-slot count (a jit
    static; the designer buckets it via the padding schedule).
    """

    base: gp_lib.VizierGaussianProcess
    num_inducing: int

    def param_collection(self) -> params_lib.ParameterCollection:
        return self.base.param_collection()

    # -- masked covariance blocks ------------------------------------------

    def _masked_kmm(self, p: Params, sdata: SparseGPData) -> Array:
        """K(Z, Z) + jitter·I on valid slots; identity on padded slots."""
        zf = sdata.z_features()
        k = self.base._kernel(p, zf, zf, sdata.data)
        m = sdata.inducing_mask
        pair = m[:, None] & m[None, :]
        k = jnp.where(pair, k, 0.0)
        amp2 = p["amplitude"] * p["amplitude"]
        diag = jnp.where(m, amp2 + _KMM_JITTER, 1.0)
        eye = jnp.eye(k.shape[0], dtype=bool)
        return jnp.where(eye, 0.0, k) + jnp.diag(diag)

    def _masked_knm(self, p: Params, sdata: SparseGPData) -> Array:
        """K(X, Z) zeroed on padded rows and padded inducing slots."""
        k = self.base._kernel(p, sdata.data.features(), sdata.z_features(), sdata.data)
        keep = sdata.data.row_mask[:, None] & sdata.inducing_mask[None, :]
        return jnp.where(keep, k, 0.0)

    def _factorize(self, p: Params, sdata: SparseGPData):
        """The shared SGPR factorization (GPflow notation).

        L  = chol(Kmm)                                  [M, M]
        A  = L⁻¹ Kmn / σ                                [M, N]
        B  = I + A Aᵀ,  LB = chol(B)                    [M, M]
        c  = LB⁻¹ A y / σ                               [M]

        Padded inducing slots have zero A rows ⇒ unit rows of B ⇒ unit LB
        diagonal and zero c entries; padded data rows have zero A columns
        and zero labels — both drop out of every term below.
        """
        kmm = self._masked_kmm(p, sdata)
        knm = self._masked_knm(p, sdata)
        chol = jnp.linalg.cholesky(kmm)
        sigma2 = p["noise_stddev"] * p["noise_stddev"] + _JITTER
        sigma = jnp.sqrt(sigma2)
        a = jax.scipy.linalg.solve_triangular(chol, knm.T, lower=True) / sigma
        b = jnp.eye(a.shape[0], dtype=a.dtype) + a @ a.T
        chol_b = jnp.linalg.cholesky(b)
        c = (
            jax.scipy.linalg.solve_triangular(chol_b, a @ sdata.data.labels, lower=True)
            / sigma
        )
        return chol, chol_b, a, c, sigma2

    # -- collapsed bound (the ARD loss) ------------------------------------

    def neg_log_likelihood(self, unconstrained: Params, sdata: SparseGPData) -> Array:
        """Negated Titsias collapsed bound + the shared ARD regularizer.

        -ELBO = ½[n·log 2π + log|B| + n·log σ² + yᵀy/σ² − cᵀc]
                + ½/σ²·tr(Knn − Qnn)

        with every n-indexed term restricted to valid rows. Minimizing this
        is the drop-in replacement for the exact GP's NLL in the SAME
        multi-restart L-BFGS program.
        """
        coll = self.param_collection()
        p = coll.constrain(unconstrained)
        chol, chol_b, a, c, sigma2 = self._factorize(p, sdata)
        del chol
        data = sdata.data
        y = data.labels
        n_valid = jnp.sum(data.row_mask.astype(y.dtype))
        log_det = n_valid * jnp.log(sigma2) + 2.0 * jnp.sum(
            jnp.where(sdata.inducing_mask, jnp.log(jnp.diagonal(chol_b)), 0.0)
        )
        quad = jnp.dot(y, y) / sigma2 - jnp.dot(c, c)
        amp2 = p["amplitude"] * p["amplitude"]
        # tr(Knn − Qnn)/σ²: diag(Knn) = amplitude² on valid rows; ΣA² is
        # exactly tr(Qnn)/σ² (padded columns are zero).
        trace = n_valid * amp2 / sigma2 - jnp.sum(a * a)
        nll = 0.5 * (n_valid * _LOG_2PI + log_det + quad + trace)
        loss = nll + coll.regularization(p)
        # Guard non-finite (Cholesky blow-ups under extreme params) — the
        # same fail-soft the exact GP's loss applies.
        return jnp.where(jnp.isfinite(loss), loss, jnp.asarray(1e10, loss.dtype))

    # -- predictive --------------------------------------------------------

    def precompute(self, unconstrained: Params, sdata: SparseGPData) -> "SparseGPState":
        """Factorize once; posterior queries are then matmul-only O(m²)."""
        return self.precompute_constrained(
            self.param_collection().constrain(unconstrained), sdata
        )

    def precompute_constrained(self, p: Params, sdata: SparseGPData) -> "SparseGPState":
        """Factorization from already-constrained params.

        The UCB-PE pending-pick re-conditioning path: per pick, the greedy
        batch loop overrides the constrained noise floor and rebuilds the
        posterior over the grown pending set — O(n·m²) per pick, the
        inducing-point replacement for the exact path's O(n³) per-pick
        Cholesky (duck-type parity with
        ``VizierGaussianProcess.precompute_constrained``).
        """
        chol, chol_b, _, c, _ = self._factorize(p, sdata)
        eye = jnp.eye(chol.shape[0], dtype=chol.dtype)
        linv = jax.scipy.linalg.solve_triangular(chol, eye, lower=True)
        lb_inv = jax.scipy.linalg.solve_triangular(chol_b, eye, lower=True)
        # mean(x*) = k*ᵀ L⁻ᵀ LB⁻ᵀ c — fold the two back-substitutions into
        # one [M] weight vector; var needs both inverses separately.
        w = linv.T @ (lb_inv.T @ c)
        return SparseGPState(
            model=self,
            params=p,
            sdata=sdata,
            w=w,
            linv=linv,
            lb_linv=lb_inv @ linv,
        )


@flax.struct.dataclass
class SparseGPState:
    """Factorized SGPR posterior, ready for O(Q·M²) batched predictions."""

    model: SparseGaussianProcess = flax.struct.field(pytree_node=False)
    params: Params
    sdata: SparseGPData
    w: Array  # [M] predictive-mean weights
    linv: Array  # [M, M] = chol(Kmm)^-1
    lb_linv: Array  # [M, M] = chol(B)^-1 @ chol(Kmm)^-1

    @property
    def data(self) -> gp_lib.GPData:
        """The training data (duck-type parity with ``GPState.data``)."""
        return self.sdata.data

    def predict(
        self, query: kernels.MixedFeatures, *, include_noise: bool = False
    ) -> Tuple[Array, Array]:
        """Posterior mean and stddev at query points ([Q], [Q]).

        var(x*) = k** − ‖L⁻¹k*‖² + ‖LB⁻¹L⁻¹k*‖² — strictly the SGPR
        predictive (Qnn-corrected), not the DTC approximation.
        """
        model, p, sdata = self.model, self.params, self.sdata
        k_star = model.base._kernel(p, query, sdata.z_features(), sdata.data)
        k_star = jnp.where(sdata.inducing_mask[None, :], k_star, 0.0)  # [Q, M]
        mean = k_star @ self.w
        t1 = self.linv @ k_star.T  # [M, Q] — matmul-only hot loop
        t2 = self.lb_linv @ k_star.T
        amp2 = p["amplitude"] * p["amplitude"]
        var = amp2 - jnp.sum(t1 * t1, axis=0) + jnp.sum(t2 * t2, axis=0)
        if include_noise:
            var = var + p["noise_stddev"] * p["noise_stddev"]
        return mean, jnp.sqrt(jnp.maximum(var, 1e-12))

    def sample(
        self, query: kernels.MixedFeatures, rng: Array, num_samples: int
    ) -> Array:
        """Marginal posterior samples [num_samples, Q] (diagonal cov)."""
        mean, stddev = self.predict(query)
        eps = jax.random.normal(rng, (num_samples,) + mean.shape, dtype=mean.dtype)
        return mean[None, :] + stddev[None, :] * eps


@flax.struct.dataclass
class SparseEnsemblePredictive:
    """Uniform mixture over a leading ensemble axis of SparseGPStates.

    Same moment-matched combination as ``gp.EnsemblePredictive`` — the
    acquisition layer consumes either interchangeably.
    """

    states: SparseGPState  # leading axis E

    @property
    def ensemble_size(self) -> int:
        return self.states.w.shape[0]

    def predict(self, query: kernels.MixedFeatures) -> Tuple[Array, Array]:
        means, stddevs = jax.vmap(lambda s: s.predict(query))(self.states)
        mean = jnp.mean(means, axis=0)
        second = jnp.mean(stddevs**2 + means**2, axis=0)
        var = jnp.maximum(second - mean**2, 1e-12)
        return mean, jnp.sqrt(var)

    def predict_per_member(self, query: kernels.MixedFeatures) -> Tuple[Array, Array]:
        return jax.vmap(lambda s: s.predict(query))(self.states)
