"""The sparse-GP bandit programs: jitted train / sweep / batched flush.

These mirror the exact-GP programs in ``designers.gp_bandit``
(``_train_gp`` / ``_sweep_one`` / ``_gp_bandit_flush_program``) one-for-one
so the sparse path inherits every serving discipline for free:

- the SAME multi-restart L-BFGS ARD program shape (the collapsed bound
  needs no variational loop), with the SAME warm-seed-as-extra-restart-row
  semantics — a trained sparse optimum seeds the next sparse train exactly
  like the exact path's (PARITY.md "Warm-start ARD seeding");
- the SAME acquisition machinery (ScoringFunction / TrustRegion / eagle
  sweep) over the :class:`~vizier_tpu.surrogates.sparse_gp.SparseEnsemblePredictive`;
- ONE fused flush program per (trial-bucket, inducing-bucket) pair for the
  cross-study batch executor, vmapped over a leading study axis — sparse
  studies batch, prewarm, fail-isolate and trace exactly like exact ones.

Layering: this module sits BELOW the designers (``designers.gp_bandit``
imports it), so it depends only on models/optimizers/acquisitions.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from vizier_tpu import types
from vizier_tpu.designers.gp import acquisitions
from vizier_tpu.models import gp as gp_lib
from vizier_tpu.models import kernels
from vizier_tpu.optimizers import lbfgs as lbfgs_lib
from vizier_tpu.optimizers import vectorized as vectorized_lib
from vizier_tpu.surrogates import sparse_gp

Array = jax.Array


def _heuristic_init(coll) -> gp_lib.Params:
    """A deterministic mid-scale restart seed for the collapsed bound.

    The Titsias trace term 1/(2σ²)·tr(Knn − Qnn) is stiff at small noise:
    a random init with tiny ``noise_stddev`` sees a huge penalty whose
    gradient drives the amplitude to its lower clip before the noise can
    rise, and EVERY random restart can land in that degenerate
    (amp→min, ls→max, noise→max) corner — measured on a 60×3 study, 8/8
    random restarts collapsed there while the exact GP trained fine. One
    always-present init at unit scales (labels are z-scored by the output
    warper, so amplitude=1 / length-scale=1 / noise=0.1 is the
    neutral prior) starts inside the well-behaved basin and reliably
    converges to the non-degenerate optimum; the random restarts keep
    their full exploration role on top.
    """
    constrained = {
        spec.name: jnp.full(
            spec.shape,
            0.1 if spec.name == "noise_stddev" else 1.0,
            jnp.float32,
        )
        for spec in coll.specs
    }
    return coll.unconstrain(constrained)


@functools.partial(
    jax.jit, static_argnames=("model", "optimizer", "num_restarts", "ensemble_size")
)
def _train_sparse_gp(
    model: sparse_gp.SparseGaussianProcess,
    optimizer: lbfgs_lib.LbfgsOptimizer,
    data: gp_lib.GPData,
    rng: Array,
    num_restarts: int,
    ensemble_size: int,
    warm_start: Optional[gp_lib.Params] = None,
) -> sparse_gp.SparseGPState:
    """Sparse ARD: k-center inducing selection → restarts → L-BFGS → top-k.

    The inducing set is selected INSIDE the program (deterministic given
    the data) and shared by every restart; ``warm_start`` is prepended as
    an extra restart row, identical to ``gp_bandit._train_gp``, after the
    deterministic :func:`_heuristic_init` row that anchors the restart
    pool outside the collapsed bound's degenerate basin.
    """
    sdata = sparse_gp.select_inducing_kcenter(data, model.num_inducing)
    coll = model.param_collection()
    inits = coll.batch_random_init_unconstrained(rng, num_restarts)
    rows = [_heuristic_init(coll)]
    if warm_start is not None:
        rows.insert(0, warm_start)
    inits = jax.tree_util.tree_map(
        lambda *xs: jnp.concatenate([x[None] for x in xs[:-1]] + [xs[-1]], axis=0),
        *rows,
        inits,
    )
    loss_fn = lambda p: model.neg_log_likelihood(p, sdata)
    result = optimizer(loss_fn, inits, best_n=ensemble_size)
    return jax.vmap(lambda p: model.precompute(p, sdata))(result.params)


@functools.partial(jax.jit, static_argnames=("vec_opt", "count"))
def _maximize_sparse_acquisition(
    vec_opt: vectorized_lib.VectorizedOptimizer,
    scoring: acquisitions.ScoringFunction,
    rng: Array,
    count: int,
    prior_features: kernels.MixedFeatures,
) -> vectorized_lib.VectorizedOptimizerResult:
    return vec_opt(scoring.score, rng, count=count, prior_features=prior_features)


def _prior_features_from_data(data: gp_lib.GPData) -> kernels.MixedFeatures:
    """Top observed points (by warped label) to seed the eagle pool —
    trace-identical to the exact path's helper (k derives from the padded
    row count, so shapes are stable within a padding bucket)."""
    labels = jnp.where(data.row_mask, data.labels, -jnp.inf)
    k = min(10, data.num_rows)
    _, idx = jax.lax.top_k(labels, k)
    num_valid = jnp.sum(data.row_mask)
    idx = jnp.where(jnp.arange(k) < num_valid, idx, idx[0])
    return kernels.MixedFeatures(data.continuous[idx], data.categorical[idx])


def _sweep_one(vec_opt, acquisition, s, d, k, count, use_trust_region):
    """Per-study scoring + eagle sweep over the SPARSE posterior (the
    sequential suggest and the batched flush share this trace)."""
    best_label = jnp.max(jnp.where(d.row_mask, d.labels, -jnp.inf))
    trust = acquisitions.TrustRegion.from_data(d) if use_trust_region else None
    scoring = acquisitions.ScoringFunction(
        predictive=sparse_gp.SparseEnsemblePredictive(s),
        acquisition=acquisition,
        best_label=best_label,
        trust_region=trust,
    )
    return _maximize_sparse_acquisition(
        vec_opt, scoring, k, count, _prior_features_from_data(d)
    )


def _warm_next_batched(
    model: sparse_gp.SparseGaussianProcess, states: sparse_gp.SparseGPState
) -> gp_lib.Params:
    """Per-slot warm seed for the NEXT sparse train: best member's params
    mapped back through the bijectors, vmapped over the study axis."""
    coll = model.param_collection()
    return jax.vmap(
        lambda p: coll.unconstrain(jax.tree_util.tree_map(lambda a: a[0], p))
    )(states.params)


@functools.partial(
    jax.jit,
    static_argnames=(
        "model", "optimizer", "vec_opt", "acquisition",
        "num_restarts", "ensemble_size", "count", "use_trust_region",
    ),
)
def _sparse_flush_program(
    model: sparse_gp.SparseGaussianProcess,
    optimizer: lbfgs_lib.LbfgsOptimizer,
    vec_opt: vectorized_lib.VectorizedOptimizer,
    acquisition,
    md: types.ModelData,  # stacked host ModelData, leading study axis
    rng_train: Array,  # [B]
    rng_acq: Array,  # [B]
    warm: gp_lib.Params,  # [B]
    num_restarts: int,
    ensemble_size: int,
    count: int,
    use_trust_region: bool,
):
    """ONE device program per sparse-bucket flush: encode → select inducing
    → train collapsed bound → sweep → warm seed. The sparse twin of
    ``gp_bandit._gp_bandit_flush_program``; slot i matches study i run
    alone through the sequential sparse path.
    """
    data = jax.vmap(lambda m: gp_lib.GPData.from_model_data(m))(md)
    states = jax.vmap(
        lambda d, k, w: _train_sparse_gp(
            model, optimizer, d, k, num_restarts, ensemble_size, w
        )
    )(data, rng_train, warm)
    result = jax.vmap(
        lambda s, d, k: _sweep_one(
            vec_opt, acquisition, s, d, k, count, use_trust_region
        )
    )(states, data, rng_acq)
    return states, _warm_next_batched(model, states), result
