"""Scalable surrogate tier: sparse-GP posteriors behind the designer seam.

The exact GP's O(n³) Cholesky makes large, long-lived studies infeasible
(BENCH_CPU_FULLSCALE.json: 72 s device-side suggest p50 at 1000 trials ×
20-D). This package provides the sparse inducing-point alternative —
O(n·m²) training, O(m²) posterior — plus the :class:`SurrogateConfig`
auto-switch that moves a study from the exact to the sparse path at a
trial-count threshold (with hysteresis), serving-tier-wide via
``ServingRuntime.surrogates``.

Modules:

- ``config``        — :class:`SurrogateConfig` + ``VIZIER_SPARSE*`` env reads
  (importable without jax; the analysis CLI and config plumbing need that);
- ``sparse_gp``     — SGPR/Nyström collapsed-bound model, k-center inducing
  selection, mask-safe like the exact GP (``models.gp``);
- ``sparse_bandit`` — the jitted train/sweep/flush programs the GP-bandit
  designer and the cross-study batch executor consume.

Evidence: SPARSE_AB.json (tools/surrogate_ab.py) — device-side suggest
latency at the north-star scale plus rank-sum regret parity vs exact.
"""

from vizier_tpu.surrogates.config import SurrogateConfig  # noqa: F401

__all__ = ["SurrogateConfig"]
