"""SurrogateConfig: the exact↔sparse auto-switch policy.

Stdlib-only (the serving runtime and the analysis CLI import this without
jax). The decision is made per suggest from the study's completed-trial
count:

- below ``sparse_threshold_trials`` the study runs the exact GP — the
  bit-identical seed path;
- at or above it the study switches to the sparse inducing-point surrogate
  (``surrogates.sparse_gp``);
- once sparse, a study only switches back when its trial count drops below
  ``sparse_threshold_trials - hysteresis_trials``, so a study sitting at
  the boundary (e.g. trials being deleted/re-added, or a rebuilt designer
  replaying a truncated study) cannot flap between compiled program
  families on alternate suggests.

Every knob has a ``VIZIER_SPARSE*`` environment override (declared in
``vizier_tpu/analysis/registry.py``, documented in
``docs/guides/performance.md``). ``VIZIER_SPARSE=0`` disables the switch
entirely: every study runs the exact path, bit-identical to the seed.
"""

from __future__ import annotations

import dataclasses
import logging

_logger = logging.getLogger(__name__)

# All VIZIER_* switches are declared in (and read through) the central
# registry; an undeclared name raises instead of silently reading an
# always-unset variable. Enforced by the env_registry analysis pass.
from vizier_tpu.analysis import registry as _registry

MODE_EXACT = "exact"
MODE_SPARSE = "sparse"

# -- crossover invalidation hook ---------------------------------------------
# A crossover drops the designer's warm seed and cached posterior; anything
# the serving tier derived from pre-crossover state (today: the speculative
# pre-computed suggestion batch) is equally stale. Listeners are installed
# as a plain designer attribute — the config object itself stays a frozen
# hashable value (it feeds jit statics) — and fired best-effort from inside
# the designer's mode switch, so invalidation happens the moment the flip
# occurs rather than after the compute returns.

_CROSSOVER_ATTR = "_surrogate_crossover_listener"


def install_crossover_listener(designer, listener) -> None:
    """Attaches ``listener(old_mode, new_mode)`` to ``designer`` (replacing
    any previous listener; idempotent re-installs are the common case)."""
    setattr(designer, _CROSSOVER_ATTR, listener)


def fire_crossover_hook(designer, old_mode: str, new_mode: str) -> None:
    """Invokes the installed crossover listener, swallowing its errors —
    a broken observer must never fail the designer's own compute."""
    listener = getattr(designer, _CROSSOVER_ATTR, None)
    if listener is None:
        return
    try:
        listener(old_mode, new_mode)
    except Exception:
        _logger.warning(
            "Surrogate crossover listener failed (%s -> %s).",
            old_mode,
            new_mode,
            exc_info=True,
        )


@dataclasses.dataclass(frozen=True)
class SurrogateConfig:
    """Knobs for the sparse-surrogate auto-switch."""

    # Master switch: False = exact GP always (the seed path, bit-identical).
    sparse: bool = True
    # Completed trials at which a study crosses exact -> sparse. The default
    # sits where the exact path's O(n³) train starts to dominate suggest
    # latency on every backend (docs/guides/performance.md has the cost
    # model); studies below it keep the seed-exact behavior.
    sparse_threshold_trials: int = 512
    # A sparse study only returns to exact below threshold - hysteresis, so
    # the boundary cannot flap between compiled program families.
    hysteresis_trials: int = 64
    # Inducing-point budget m. The designer pads it up the same bucket grid
    # as trial counts (``padding.trial_bucket_grid``) so every (n-bucket,
    # m-bucket) pair is one compiled program.
    num_inducing: int = 128
    # Extend the auto-switch to the GP-UCB-PE designer (the service
    # DEFAULT): above the threshold its greedy batch conditions on pending
    # picks through the inducing-point posterior (Nyström-augmented)
    # instead of the exact GP's O(n³) per-pick re-factorization. False
    # pins UCB-PE studies exact regardless of size (the pre-PR-9
    # behavior); single-objective independent-GP studies only either way.
    sparse_ucb_pe: bool = True

    def __post_init__(self):
        if self.sparse_threshold_trials < 1:
            raise ValueError(
                f"sparse_threshold_trials must be >= 1, got "
                f"{self.sparse_threshold_trials}."
            )
        if self.hysteresis_trials < 0:
            raise ValueError(
                f"hysteresis_trials must be >= 0, got {self.hysteresis_trials}."
            )
        if self.num_inducing < 1:
            raise ValueError(
                f"num_inducing must be >= 1, got {self.num_inducing}."
            )

    @classmethod
    def from_env(cls) -> "SurrogateConfig":
        """The default config with per-knob environment overrides applied."""
        return cls(
            sparse=_registry.env_on("VIZIER_SPARSE"),
            sparse_threshold_trials=_registry.env_int(
                "VIZIER_SPARSE_THRESHOLD", 512
            ),
            hysteresis_trials=_registry.env_int("VIZIER_SPARSE_HYSTERESIS", 64),
            num_inducing=_registry.env_int("VIZIER_SPARSE_INDUCING", 128),
            sparse_ucb_pe=_registry.env_on("VIZIER_SPARSE_UCB_PE"),
        )

    @classmethod
    def disabled(cls) -> "SurrogateConfig":
        """Exact GP always — the seed path."""
        return cls(sparse=False)

    def mode_for(self, num_trials: int, current: str = MODE_EXACT) -> str:
        """The surrogate mode for a study with ``num_trials`` completed
        trials, given its ``current`` mode (hysteresis needs history)."""
        if not self.sparse:
            return MODE_EXACT
        if current == MODE_SPARSE:
            floor = self.sparse_threshold_trials - self.hysteresis_trials
            return MODE_SPARSE if num_trials >= floor else MODE_EXACT
        return (
            MODE_SPARSE
            if num_trials >= self.sparse_threshold_trials
            else MODE_EXACT
        )

    def as_dict(self) -> dict:
        """JSON-stampable form (bench.py / tools artifacts)."""
        return {
            "sparse": self.sparse,
            "sparse_threshold_trials": self.sparse_threshold_trials,
            "hysteresis_trials": self.hysteresis_trials,
            "num_inducing": self.num_inducing,
            "sparse_ucb_pe": self.sparse_ucb_pe,
        }
