"""The designer-compute program registry.

One process-wide table, two indexes:

- **by kind** — ``get("gp_ucb_pe")`` → the :class:`~vizier_tpu.compute.ir.
  DesignerProgram` whose device body executes that bucket family. The
  batch executor looks a flush's program up here instead of calling a
  per-designer method; tools (obs_report, bench stamps) enumerate
  :func:`kinds` instead of maintaining hardcoded lists.
- **by designer type** — :func:`resolve` walks ``type(designer).__mro__``
  to the most-derived class with registered programs and returns the
  first program whose ``bucket_key`` accepts the designer's current state
  (e.g. the exact GP-bandit program declines a study the surrogate
  auto-switch has flipped sparse, and the sparse program picks it up).

Wrappers and custom designers compose without registering:

- a designer exposing ``compute_program(count) -> (program, key) | None``
  overrides resolution entirely — the chaos harness uses this to wrap the
  resolved program in fault-injecting hooks (slot isolation rides the IR,
  not per-designer method copies);
- a designer with only the legacy duck-typed ``batch_*`` methods resolves
  to a :class:`DuckTypedProgram` adapter, so out-of-tree designers keep
  batching without a registry entry (they forgo prewarm/conformance).

Registration happens at designer-module import: importing
``vizier_tpu.compute.programs`` (or any designer module) populates the
table. The analysis suite's ``compute_ir`` pass statically audits every
``register(...)`` site for prewarm coverage and chaos-test coverage.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple, Type

from vizier_tpu.compute import ir

_LOCK = threading.Lock()
_BY_KIND: Dict[str, ir.DesignerProgram] = {}
_BY_TYPE: Dict[type, List[ir.DesignerProgram]] = {}


def register(designer_type: type, program: ir.DesignerProgram) -> ir.DesignerProgram:
    """Adds ``program`` for designers of ``designer_type`` (idempotent:
    re-registering the same kind replaces it — module reloads in tests)."""
    if not program.kind:
        raise ValueError(f"{type(program).__name__} must declare a kind.")
    with _LOCK:
        existing = _BY_KIND.get(program.kind)
        if existing is not None:
            # Replace in both indexes (same-kind re-registration only).
            for programs in _BY_TYPE.values():
                programs[:] = [p for p in programs if p.kind != program.kind]
        _BY_KIND[program.kind] = program
        _BY_TYPE.setdefault(designer_type, []).append(program)
    return program


def get(kind: str) -> Optional[ir.DesignerProgram]:
    with _LOCK:
        return _BY_KIND.get(kind)


def kinds() -> Tuple[str, ...]:
    """Registered program kinds, sorted (stable for stamps/reports)."""
    _ensure_builtin_programs()
    with _LOCK:
        return tuple(sorted(_BY_KIND))


def programs() -> Tuple[ir.DesignerProgram, ...]:
    _ensure_builtin_programs()
    with _LOCK:
        return tuple(_BY_KIND[k] for k in sorted(_BY_KIND))


def programs_for_algorithm(algorithm: str) -> Tuple[ir.DesignerProgram, ...]:
    """Programs a service prewarm for ``algorithm`` should compile."""
    return tuple(p for p in programs() if p.matches_algorithm(algorithm))


class DuckTypedProgram(ir.DesignerProgram):
    """Adapter over the legacy duck-typed ``batch_*`` designer methods.

    Unregistered designers (test stubs, out-of-tree extensions) keep
    batching through the executor; the adapter is per-resolution so the
    bound designer's own hooks run — including any fault-injection those
    hooks carry.
    """

    surrogate_family = "exact"

    def __init__(self, kind: str, designer: Any):
        self.kind = kind
        self.device_phase = f"{kind}.suggest_batched"
        # The device body dispatches through the RESOLVED designer (not the
        # inner designer an item may record): a wrapper's batch_execute —
        # e.g. a chaos strike — must stay on the dispatch path, exactly as
        # the pre-IR executor's ``live[0].designer.batch_execute`` did.
        self._designer = designer

    def bucket_key(self, designer, count):
        key_fn = getattr(designer, "batch_bucket_key", None)
        return key_fn(count) if key_fn is not None else None

    def prepare(self, designer, count):
        return designer.batch_prepare(count)

    def device_program(self, items, pad_to=None):
        return self._designer.batch_execute(items, pad_to=pad_to)

    def finalize(self, designer, item, output):
        return designer.batch_finalize(item, output)

    def prewarm_factory(self, problem, **kwargs):
        raise NotImplementedError(
            "Duck-typed designers are not prewarmable; register a "
            "DesignerProgram to join the prewarm walk."
        )


def _ensure_builtin_programs() -> None:
    """Imports the in-tree designer modules so their programs are present.

    Resolution by designer type works without this (importing a designer
    class imports its module, which registers); only whole-registry
    enumeration (kinds/programs, the prewarm walk, stamps) needs the full
    set eagerly.
    """
    import vizier_tpu.designers.gp_bandit  # noqa: F401  (registers on import)
    import vizier_tpu.designers.gp_ucb_pe  # noqa: F401


def resolve(
    designer: Any, count: Optional[int] = None
) -> Optional[Tuple[ir.DesignerProgram, ir.BucketKey]]:
    """The designer's program + bucket key for this compute, or None.

    Order: the designer's own ``compute_program`` hook (wrappers), then
    the most-derived registered designer type's programs in registration
    order (first non-None ``bucket_key`` wins), then the duck-typed
    ``batch_*`` fallback. None means unbatchable — the caller runs the
    plain sequential ``suggest``.
    """
    count = count or 1
    hook = getattr(designer, "compute_program", None)
    if hook is not None:
        return hook(count)
    with _LOCK:
        type_programs = None
        for cls in type(designer).__mro__:
            found = _BY_TYPE.get(cls)
            if found:
                type_programs = list(found)
                break
    if type_programs is not None:
        for program in type_programs:
            key = program.bucket_key(designer, count)
            if key is not None:
                return program, key
        return None
    key_fn = getattr(designer, "batch_bucket_key", None)
    if key_fn is None:
        return None
    key = key_fn(count)
    if key is None:
        return None
    return DuckTypedProgram(key.kind, designer), key
