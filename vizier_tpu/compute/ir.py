"""The batched designer-compute IR: one contract, every serving discipline.

Every batchable designer computation in the tree has the same anatomy:

- a **shape/static descriptor** (:class:`BucketKey`) that says which other
  studies' computations it can share a compiled device program with;
- a **host-side encode** run on the submitting thread (trial → padded
  model data + RNG draws, zero device dispatches);
- a **jitted, vmappable device body** (multi-restart ARD train + the
  acquisition sweep) executed once per bucket flush over a leading study
  axis;
- a **host-side decode/demux** that writes the designer's state
  transitions (warm ARD seed, cached posterior, counters) and decodes
  suggestions.

Before this module those four stages were duck-typed methods copied onto
every designer (``batch_bucket_key`` / ``batch_prepare`` /
``batch_execute`` / ``batch_finalize``), and each cross-cutting feature —
the batch executor, the compile-prewarm walker, chaos slot isolation,
``vizier_jax_phase_seconds`` device tracing, the speculative lane — had to
be wired per copy. :class:`DesignerProgram` names the contract once;
programs register in :mod:`vizier_tpu.compute.registry` and every feature
consumes the registry generically. A designer that implements one program
gets batching, prewarm, fail isolation, tracing, and speculation for free
(docs/guides/performance.md "Batched compute IR" is the author guide).

Layering: this module is import-light (no jax at module import) so the
registry stays cheap to consult from host-side serving code and the
stdlib-only analysis suite can reason about it.
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Any, Hashable, List, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class BucketKey:
    """Identity of one shape bucket: equal keys ⇒ batchable together.

    ``kind`` is the registered :class:`DesignerProgram` that executes the
    bucket's device body. ``statics`` carries the hashable jit-static
    objects (model, optimizers, acquisition config, restart budget, …) so
    two studies share a bucket exactly when they would share every
    compiled program — shape AND configuration.
    """

    kind: str  # registered program kind, e.g. "gp_bandit" | "gp_ucb_pe"
    pad_trials: int
    cont_width: int
    cat_width: int
    metric_count: int
    count: int  # suggestions per study (a jit-static of the sweep)
    statics: Tuple[Hashable, ...] = ()

    def label(self) -> str:
        """Low-cardinality metrics/tracing label (one per shape bucket)."""
        return (
            f"{self.kind}/t{self.pad_trials}/f{self.cont_width}"
            f"x{self.cat_width}/m{self.metric_count}/q{self.count}"
        )


class DesignerProgram(abc.ABC):
    """One batched designer computation, named by ``kind``.

    Programs are stateless singletons: all per-study state lives on the
    designer instance each hook receives (the ``prepare``/``finalize``
    pair runs the exact state transitions the designer's sequential
    ``suggest`` performs, so slot i of a batch is bit-identical to study i
    run alone). ``device_program`` is a classless device body: it reads
    per-slot jit statics from ``items[0]`` — the bucket key guarantees
    every slot's statics are equal.
    """

    #: Unique registry key; also the BucketKey.kind this program emits.
    kind: str = ""
    #: ``jax_timing.device_phase`` name the device body times itself under
    #: (feeds ``vizier_jax_phase_seconds{phase}`` and tools/obs_report.py).
    device_phase: str = ""
    #: Which surrogate family the device body trains ("exact" | "sparse");
    #: tools/obs_report.py builds its phase classification from this.
    surrogate_family: str = "exact"
    #: Name of the batch axis ``device_program`` may shard over a device
    #: placement ("" = unshardable: the executor never passes a
    #: ``placement`` and the flush runs on the default device). Every
    #: in-tree program stacks items along a leading per-study axis and
    #: declares ``"study"``; the mesh executor then commits the stacked
    #: pytree onto the placement's submesh (``DevicePlacement.shard``)
    #: before the fused dispatch. Declared as IR metadata — not inferred —
    #: so the ``compute_ir`` analysis pass can audit that every registered
    #: program made the call explicitly.
    shardable_batch_axis: str = ""
    #: Service algorithm names whose prewarm walks should compile this
    #: program's buckets (PythiaServicer.prewarm consults the registry).
    algorithms: Tuple[str, ...] = ()

    @abc.abstractmethod
    def bucket_key(self, designer: Any, count: int) -> Optional[BucketKey]:
        """This designer's shape bucket for a ``count``-suggestion compute,
        or None when the program does not cover its current state (seeding
        stage, multi-objective, priors, wrong surrogate mode, …)."""

    @abc.abstractmethod
    def prepare(self, designer: Any, count: int) -> dict:
        """Host-side encode on the submitting thread: padded model data +
        RNG draws, consuming the designer's RNG stream in exactly the
        sequential order. Must issue zero device dispatches."""

    @abc.abstractmethod
    def device_program(
        self,
        items: Sequence[dict],
        pad_to: Optional[int] = None,
        placement: Any = None,
    ) -> List[dict]:
        """The jitted, vmapped train+acquire body for a whole bucket:
        stacks the items along a leading study axis, runs ONE fused XLA
        dispatch, fetches once, and returns one host-side output dict per
        item (free numpy views after the single ``device_get``).

        ``placement`` (a ``parallel.mesh.DevicePlacement``) is only passed
        when the program declares a ``shardable_batch_axis``: the program
        must then commit the stacked pytree onto the placement's submesh
        (``placement.shard``) so the fused dispatch spans its devices. The
        executor guarantees ``pad_to`` is a multiple of the placement's
        device count."""

    @abc.abstractmethod
    def finalize(self, designer: Any, item: dict, output: dict) -> List[Any]:
        """Host-side decode/demux on the waiting thread: the designer's
        sequential state writeback (warm seed, cached fit, counters) plus
        suggestion decode. Returns the TrialSuggestion batch."""

    @abc.abstractmethod
    def prewarm_factory(self, problem: Any, **kwargs) -> Any:
        """A designer whose computations route to THIS program, for the
        compile-prewarm walker (``BatchExecutor.prewarm``) to train and
        sweep synthetic studies through every padding bucket."""

    def matches_algorithm(self, algorithm: str) -> bool:
        """Whether a service-level prewarm for ``algorithm`` covers this
        program (case-insensitive exact match on ``algorithms``)."""
        return (algorithm or "").upper() in self.algorithms
