"""Batched designer-compute IR: the one seam every designer implements.

``ir.DesignerProgram`` names the four-hook contract (bucket_key / prepare
/ device_program / finalize); ``registry`` holds the process-wide program
table the batch executor, prewarm walker, chaos harness, device-phase
tracing, and speculative lane all consume. See
docs/guides/performance.md "Batched compute IR".
"""

from vizier_tpu.compute.ir import BucketKey, DesignerProgram
from vizier_tpu.compute import registry

__all__ = ["BucketKey", "DesignerProgram", "registry"]
