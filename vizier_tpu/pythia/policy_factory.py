"""PolicyFactory protocol: algorithm string → Policy.

Parity with ``/root/reference/vizier/_src/pythia/policy_factory.py:25``.
The default concrete factory lives in ``vizier_tpu.service.policy_factory``.
"""

from __future__ import annotations

from typing import Protocol, runtime_checkable

from vizier_tpu.pythia import policy as policy_lib
from vizier_tpu.pythia import policy_supporter
from vizier_tpu.pyvizier import base_study_config


@runtime_checkable
class PolicyFactory(Protocol):
    """Creates a Policy for (problem, algorithm, supporter, study_name)."""

    def __call__(
        self,
        problem_statement: base_study_config.ProblemStatement,
        algorithm: str,
        policy_supporter: policy_supporter.PolicySupporter,
        study_name: str,
    ) -> policy_lib.Policy:
        ...
