"""Public Pythia facade: the algorithm-hosting protocol."""

from vizier_tpu.pythia.errors import (
    CachedPolicyIsStaleError,
    CancelComputeError,
    CancelledByVizierError,
    InactivateStudyError,
    PythiaProtocolError,
    TemporaryPythiaError,
    VizierDatabaseError,
)
from vizier_tpu.pythia.local_policy_supporters import InRamPolicySupporter
from vizier_tpu.pythia.policy import (
    EarlyStopDecision,
    EarlyStopDecisions,
    EarlyStopRequest,
    Policy,
    SuggestDecision,
    SuggestRequest,
)
from vizier_tpu.pythia.policy_factory import PolicyFactory
from vizier_tpu.pythia.policy_supporter import PolicySupporter

__all__ = [
    "CachedPolicyIsStaleError",
    "CancelComputeError",
    "CancelledByVizierError",
    "EarlyStopDecision",
    "EarlyStopDecisions",
    "EarlyStopRequest",
    "InRamPolicySupporter",
    "InactivateStudyError",
    "Policy",
    "PolicyFactory",
    "PolicySupporter",
    "PythiaProtocolError",
    "SuggestDecision",
    "SuggestRequest",
    "TemporaryPythiaError",
    "VizierDatabaseError",
]
