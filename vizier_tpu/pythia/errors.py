"""Typed Pythia error protocol.

Parity with ``/root/reference/vizier/_src/pythia/pythia_errors.py:20-84``.
The service maps these onto retry / study-inactivation / cache-rebuild
behaviors.
"""

from __future__ import annotations


class PythiaProtocolError(Exception):
    """A bug in the Pythia protocol implementation itself."""


class TemporaryPythiaError(Exception):
    """Transient failure; the caller should retry the request."""


class InactivateStudyError(Exception):
    """Unrecoverable for this study; the service should mark it aborted."""


class CachedPolicyIsStaleError(Exception):
    """The cached policy no longer matches the study; rebuild and retry."""


class CancelComputeError(Exception):
    """Raised inside a policy when cancellation was requested."""


class VizierDatabaseError(Exception):
    """The Vizier service failed to serve a supporter request."""


class CancelledByVizierError(Exception):
    """The Vizier service asked the policy to stop computing."""
