"""In-RAM policy supporter: a mini service+client for tests and benchmarks.

Parity with
``/root/reference/vizier/_src/pythia/local_policy_supporters.py:36``: holds
trials in memory, assigns ids, applies policy decisions, and stores prior
studies for transfer learning. This is the engine under the benchmark runner
(no gRPC service needed for research loops).
"""

from __future__ import annotations

import copy
from typing import Dict, Iterable, List, Optional, Sequence

from vizier_tpu.pythia import policy as policy_lib
from vizier_tpu.pythia import policy_supporter
from vizier_tpu.pyvizier import study as study_lib
from vizier_tpu.pyvizier import study_config as sc
from vizier_tpu.pyvizier import trial as trial_


class InRamPolicySupporter(policy_supporter.PolicySupporter):
    """Owns one study's trials in RAM and drives policies against them."""

    def __init__(
        self,
        study_config: sc.StudyConfig,
        *,
        study_guid: str = "local",
    ):
        self._study_config = study_config
        self._study_guid = study_guid
        self._trials: List[trial_.Trial] = []
        # Prior studies for transfer learning, guid -> (config, trials).
        self._priors: Dict[str, "InRamPolicySupporter"] = {}

    # -- properties --------------------------------------------------------

    @property
    def study_config(self) -> sc.StudyConfig:
        return self._study_config

    @property
    def study_guid(self) -> str:
        return self._study_guid

    @property
    def trials(self) -> List[trial_.Trial]:
        return list(self._trials)

    def study_descriptor(self) -> study_lib.StudyDescriptor:
        return study_lib.StudyDescriptor(
            config=self._study_config,
            guid=self._study_guid,
            max_trial_id=len(self._trials),
        )

    # -- PolicySupporter interface ----------------------------------------

    def GetStudyConfig(self, study_guid: Optional[str] = None) -> sc.StudyConfig:
        if study_guid is None or study_guid == self._study_guid:
            return self._study_config
        if study_guid in self._priors:
            return self._priors[study_guid].study_config
        raise KeyError(f"Unknown study {study_guid!r}.")

    def GetTrials(
        self,
        *,
        study_guid: Optional[str] = None,
        trial_ids: Optional[Iterable[int]] = None,
        min_trial_id: Optional[int] = None,
        max_trial_id: Optional[int] = None,
        status_matches: Optional[trial_.TrialStatus] = None,
        include_intermediate_measurements: bool = True,
    ) -> List[trial_.Trial]:
        if study_guid is not None and study_guid != self._study_guid:
            return self._priors[study_guid].GetTrials(
                trial_ids=trial_ids,
                min_trial_id=min_trial_id,
                max_trial_id=max_trial_id,
                status_matches=status_matches,
            )
        ids = frozenset(trial_ids) if trial_ids is not None else None
        out = []
        for t in self._trials:
            if ids is not None and t.id not in ids:
                continue
            if min_trial_id is not None and t.id < min_trial_id:
                continue
            if max_trial_id is not None and t.id > max_trial_id:
                continue
            if status_matches is not None and t.status != status_matches:
                continue
            out.append(t)
        return out

    def SendMetadata(self, delta: trial_.MetadataDelta) -> None:
        self._apply_metadata(delta)

    # -- service-like operations ------------------------------------------

    def AddTrials(self, trials: Sequence[trial_.Trial]) -> None:
        """Adds copies of externally-built trials, assigning fresh ids.

        Copies, so transferring a prior study's trials cannot rewrite the
        prior study's ids in place.
        """
        for t in trials:
            t = copy.deepcopy(t)
            t.id = len(self._trials) + 1
            self._trials.append(t)

    def AddSuggestions(
        self, suggestions: Sequence[trial_.TrialSuggestion]
    ) -> List[trial_.Trial]:
        """Materializes suggestions as ACTIVE trials with fresh ids."""
        new_trials = []
        for s in suggestions:
            t = s.to_trial(len(self._trials) + 1)
            self._trials.append(t)
            new_trials.append(t)
        return new_trials

    def SuggestTrials(self, policy: policy_lib.Policy, count: int) -> List[trial_.Trial]:
        """Runs one suggest round and materializes the results as trials."""
        decision = policy.suggest(
            policy_lib.SuggestRequest(study_descriptor=self.study_descriptor(), count=count)
        )
        self._apply_metadata(decision.metadata)
        return self.AddSuggestions(decision.suggestions)

    def EarlyStopTrials(
        self, policy: policy_lib.Policy, trial_ids: Iterable[int] = ()
    ) -> policy_lib.EarlyStopDecisions:
        ids = frozenset(trial_ids)
        if not ids:
            # Empty means "consider everything that could stop" (the
            # EarlyStopRequest contract): all ACTIVE and STOPPING trials.
            ids = frozenset(
                t.id
                for t in self._trials
                if t.status in (trial_.TrialStatus.ACTIVE, trial_.TrialStatus.STOPPING)
            )
        decisions = policy.early_stop(
            policy_lib.EarlyStopRequest(
                study_descriptor=self.study_descriptor(), trial_ids=ids
            )
        )
        self._apply_metadata(decisions.metadata)
        for d in decisions.decisions:
            if d.should_stop:
                for t in self._trials:
                    if t.id == d.id:
                        t.stop(d.reason)
        return decisions

    def SetPriorStudy(
        self, supporter: "InRamPolicySupporter", study_guid: Optional[str] = None
    ) -> str:
        """Registers a prior study for transfer learning; returns its guid."""
        guid = study_guid if study_guid is not None else supporter.study_guid
        self._priors[guid] = supporter
        return guid

    # -- internals ---------------------------------------------------------

    def _apply_metadata(self, delta: trial_.MetadataDelta) -> None:
        self._study_config.metadata.attach(delta.on_study)
        for tid, md in delta.on_trials.items():
            for t in self._trials:
                if t.id == tid:
                    t.metadata.attach(md)
