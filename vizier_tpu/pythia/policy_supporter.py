"""PolicySupporter: the algorithm's read-back channel to the study DB.

Parity with ``/root/reference/vizier/_src/pythia/policy_supporter.py:26-133``.
"""

from __future__ import annotations

import abc
import datetime
from typing import Iterable, List, Optional

from vizier_tpu.pythia import errors
from vizier_tpu.pyvizier import study_config as sc
from vizier_tpu.pyvizier import trial as trial_


class PolicySupporter(abc.ABC):
    """Reads study state on behalf of a running policy."""

    @abc.abstractmethod
    def GetStudyConfig(self, study_guid: Optional[str] = None) -> sc.StudyConfig:
        """Fetches a study's config (defaults to the policy's own study)."""

    @abc.abstractmethod
    def GetTrials(
        self,
        *,
        study_guid: Optional[str] = None,
        trial_ids: Optional[Iterable[int]] = None,
        min_trial_id: Optional[int] = None,
        max_trial_id: Optional[int] = None,
        status_matches: Optional[trial_.TrialStatus] = None,
        include_intermediate_measurements: bool = True,
    ) -> List[trial_.Trial]:
        """Fetches trials matching the filters."""

    def CheckCancelled(self, note: str = "") -> None:
        """Raises CancelComputeError if the RPC was cancelled (default: no-op)."""

    def TimeRemaining(self) -> datetime.timedelta:
        """Time left before the deadline (default: unbounded)."""
        return datetime.timedelta.max

    def SendMetadata(self, delta: trial_.MetadataDelta) -> None:
        """Persists metadata immediately (mid-computation checkpointing)."""
        raise NotImplementedError(f"{type(self).__name__} does not support SendMetadata.")
