"""The Policy protocol: the algorithm-hosting contract.

Parity with ``/root/reference/vizier/_src/pythia/policy.py:40-274``:
``SuggestRequest`` → ``SuggestDecision`` and ``EarlyStopRequest`` →
``EarlyStopDecisions``, plus the abstract ``Policy``. A Policy is the unit
the Pythia service hosts; Designers are wrapped into Policies by
``vizier_tpu.algorithms.designer_policy``.
"""

from __future__ import annotations

import abc
import dataclasses
from typing import FrozenSet, Iterable, List, Optional

from vizier_tpu.pyvizier import study as study_lib
from vizier_tpu.pyvizier import study_config as sc
from vizier_tpu.pyvizier import trial as trial_


@dataclasses.dataclass(frozen=True)
class SuggestRequest:
    """A request for ``count`` new suggestions for one study."""

    study_descriptor: study_lib.StudyDescriptor
    count: int = 1
    checkpoint_dir: Optional[str] = None

    def __post_init__(self):
        if self.count <= 0:
            raise ValueError(f"count must be positive, got {self.count}.")

    @property
    def study_config(self) -> sc.StudyConfig:
        return self.study_descriptor.config

    @property
    def study_guid(self) -> str:
        return self.study_descriptor.guid

    @property
    def max_trial_id(self) -> int:
        return self.study_descriptor.max_trial_id


@dataclasses.dataclass
class SuggestDecision:
    """Suggestions plus any metadata updates to persist."""

    suggestions: List[trial_.TrialSuggestion]
    metadata: trial_.MetadataDelta = dataclasses.field(default_factory=trial_.MetadataDelta)

    def __post_init__(self):
        self.suggestions = list(self.suggestions)


@dataclasses.dataclass(frozen=True)
class EarlyStopRequest:
    """Which trials to consider stopping (empty = all STOPPING+ACTIVE)."""

    study_descriptor: study_lib.StudyDescriptor
    trial_ids: FrozenSet[int] = frozenset()
    checkpoint_dir: Optional[str] = None

    def __post_init__(self):
        object.__setattr__(self, "trial_ids", frozenset(self.trial_ids))

    @property
    def study_config(self) -> sc.StudyConfig:
        return self.study_descriptor.config

    @property
    def study_guid(self) -> str:
        return self.study_descriptor.guid


@dataclasses.dataclass
class EarlyStopDecision:
    """Whether one trial should stop."""

    id: int
    reason: str = ""
    should_stop: bool = True
    metadata: trial_.Metadata = dataclasses.field(default_factory=trial_.Metadata)


@dataclasses.dataclass
class EarlyStopDecisions:
    decisions: List[EarlyStopDecision] = dataclasses.field(default_factory=list)
    metadata: trial_.MetadataDelta = dataclasses.field(default_factory=trial_.MetadataDelta)


class Policy(abc.ABC):
    """An algorithm hosted by the Pythia service."""

    @abc.abstractmethod
    def suggest(self, request: SuggestRequest) -> SuggestDecision:
        """Produces new trial suggestions."""

    def early_stop(self, request: EarlyStopRequest) -> EarlyStopDecisions:
        """Decides which trials should stop early. Default: stop nothing."""
        return EarlyStopDecisions(
            decisions=[
                EarlyStopDecision(id=tid, reason="Policy does not early-stop.", should_stop=False)
                for tid in request.trial_ids
            ]
        )

    @property
    def name(self) -> str:
        return type(self).__name__

    @property
    def should_be_cached(self) -> bool:
        """Whether the service may reuse this policy object across requests."""
        return False
