"""Singleton-parameter stripping.

Parity with ``/root/reference/vizier/_src/pythia/singleton_params.py:28``:
parameters with exactly one feasible value carry no information for the
model — strip them from the problem before handing it to an algorithm, and
re-attach the fixed values to every suggestion.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence

from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import parameter_config as pc
from vizier_tpu.pyvizier import trial as trial_


@dataclasses.dataclass
class SingletonParameterHandler:
    """Splits a problem into (reduced problem, fixed singleton values)."""

    problem: base_study_config.ProblemStatement

    def __post_init__(self):
        self._fixed: Dict[str, pc.ParameterValueTypes] = {}
        kept: List[pc.ParameterConfig] = []
        for config in self.problem.search_space.parameters:
            if not config.children and config.num_feasible_values == 1:
                if config.type == pc.ParameterType.DOUBLE:
                    value = config.bounds[0]
                else:
                    value = config.feasible_values[0]
                self._fixed[config.name] = config.cast_value(value)
            else:
                kept.append(config)
        space = pc.SearchSpace(kept)
        self.reduced_problem = base_study_config.ProblemStatement(
            search_space=space,
            metric_information=self.problem.metric_information,
            metadata=self.problem.metadata,
        )

    @property
    def fixed_parameters(self) -> Dict[str, pc.ParameterValueTypes]:
        return dict(self._fixed)

    def augment(
        self, suggestions: Sequence[trial_.TrialSuggestion]
    ) -> List[trial_.TrialSuggestion]:
        """Re-attaches the stripped singleton values to each suggestion."""
        for s in suggestions:
            for name, value in self._fixed.items():
                if name not in s.parameters:
                    s.parameters[name] = value
        return list(suggestions)

    def strip(self, trials: Sequence[trial_.Trial]) -> List[trial_.Trial]:
        """Removes singleton parameters from trials (for designer updates)."""
        out = []
        for t in trials:
            params = trial_.ParameterDict(
                {k: v for k, v in t.parameters.items() if k not in self._fixed}
            )
            clone = trial_.Trial(
                id=t.id,
                parameters=params,
                metadata=t.metadata,
                measurements=list(t.measurements),
                final_measurement=t.final_measurement,
                infeasibility_reason=t.infeasibility_reason,
            )
            out.append(clone)
        return out
