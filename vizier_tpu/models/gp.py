"""The Vizier Gaussian process: masked training, prediction, ensembles.

TPU-first rebuild of the reference GP stack
(``/root/reference/vizier/_src/jax/models/tuned_gp_models.py:78`` and
``stochastic_process_model.py:205,835,890``): an ARD Matern-5/2 GP over mixed
continuous/categorical features with

- hyperparameters as an unconstrained pytree (see ``models.params``) so ARD
  training is plain unconstrained optimization under jit/vmap;
- *mask-safe* likelihood/Cholesky: padded rows are decoupled (off-diagonal
  zeroed, unit diagonal, zero residual) so one compiled graph serves every
  trial count inside a padding bucket — fill values cannot leak into the
  factorization;
- f32 throughout with a noise floor + jitter instead of the reference's
  forced float64 (``pythia_service.py:50-57``) — TPU-native numerics;
- ensembles as a leading vmapped axis, ready to shard across devices over
  the ``ensemble`` mesh axis (see ``vizier_tpu.parallel``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from vizier_tpu import types
from vizier_tpu.models import kernels
from vizier_tpu.models import params as params_lib

Array = jax.Array
Params = params_lib.Params

_LOG_2PI = 1.8378770664093453
_JITTER = 1e-5


@flax.struct.dataclass
class GPData:
    """Plain-array training data with validity masks (all jit-traceable)."""

    continuous: Array  # [N, Dc] float32 in [0, 1]
    categorical: Array  # [N, Ds] int32
    labels: Array  # [N] float32 (warped; no NaNs among valid rows)
    row_mask: Array  # [N] bool, True = real data
    cont_dim_mask: Array  # [Dc] bool
    cat_dim_mask: Array  # [Ds] bool

    @classmethod
    def from_model_data(cls, data: types.ModelData, metric_index: int = 0) -> "GPData":
        cont = data.features.continuous
        cat = data.features.categorical
        labels = data.labels.padded_array[:, metric_index]
        row_mask = (
            cont.valid_mask(0)
            & data.labels.valid_mask(0)
            & ~jnp.isnan(labels)
        )
        return cls(
            continuous=jnp.asarray(cont.padded_array, jnp.float32),
            categorical=jnp.asarray(cat.padded_array, jnp.int32),
            labels=jnp.where(row_mask, jnp.nan_to_num(labels), 0.0).astype(jnp.float32),
            row_mask=row_mask,
            cont_dim_mask=cont.valid_mask(1),
            cat_dim_mask=cat.valid_mask(1),
        )

    @property
    def num_rows(self) -> int:
        return self.continuous.shape[0]

    def features(self) -> kernels.MixedFeatures:
        return kernels.MixedFeatures(self.continuous, self.categorical)


@dataclasses.dataclass(frozen=True)
class VizierGaussianProcess:
    """Static model config + pure functions over (params, data)."""

    num_continuous: int
    num_categorical: int
    use_linear_mean: bool = False
    # HEBO-style learnable Kumaraswamy input warping of the [0,1] continuous
    # features (parity with the reference's hebo_gp_model.py): u ->
    # 1-(1-u^a)^b with per-dimension a, b — lets the GP adapt to
    # non-stationary objectives (e.g. log-like sensitivity near a boundary).
    use_input_warping: bool = False

    # -- hyperparameter declaration ---------------------------------------

    def param_collection(self) -> params_lib.ParameterCollection:
        sc = params_lib.SoftClip
        specs = [
            params_lib.ParameterSpec(
                "amplitude", (), sc(0.01, 100.0), 0.1, 10.0, prior_mu=0.0, prior_sigma=1.0
            ),
            params_lib.ParameterSpec(
                "noise_stddev", (), sc(1e-3, 1.0), 5e-3, 0.3,
                prior_mu=float(np.log(1e-2)), prior_sigma=1.0,
            ),
        ]
        if self.num_continuous:
            specs.append(
                params_lib.ParameterSpec(
                    "continuous_length_scales",
                    (self.num_continuous,),
                    sc(0.005, 100.0),
                    0.05,
                    2.0,
                    prior_mu=float(np.log(0.3)),
                    prior_sigma=1.0,
                )
            )
        if self.num_categorical:
            # Weak prior centered at ls ~ 0.71, matching the reference's
            # categorical length_scale_squared regularizer
            # 0.01*log(ls^2/0.5)^2 over bounds ls in [0.1, 10]
            # (`tuned_gp_models.py:183-193`). A tight categorical prior is
            # destructive: at ls ~ 0.3 a single category mismatch puts
            # cells ~11 scaled units apart, zeroing all cross-cell
            # correlation — the GP then sees every unobserved cell as
            # prior-mean, the UCB-PE promising region collapses onto
            # observed cells, and batch exploration dies.
            specs.append(
                params_lib.ParameterSpec(
                    "categorical_length_scales",
                    (self.num_categorical,),
                    sc(0.05, 100.0),
                    0.1,
                    10.0,
                    prior_mu=float(np.log(np.sqrt(0.5))),
                    prior_sigma=3.5,
                )
            )
        if self.use_input_warping and self.num_continuous:
            for name in ("warp_a", "warp_b"):
                specs.append(
                    params_lib.ParameterSpec(
                        name,
                        (self.num_continuous,),
                        sc(0.25, 4.0),
                        0.8,
                        1.25,
                        prior_mu=0.0,  # log-normal centered at identity (a=b=1)
                        prior_sigma=0.5,
                    )
                )
        if self.use_linear_mean and self.num_continuous:
            # Linear mean coefficients are unconstrained; modelled via a wide
            # softclip to keep the single-pytree machinery uniform.
            specs.append(
                params_lib.ParameterSpec(
                    "mean_scale", (), sc(1e-3, 10.0), 0.1, 1.0, prior_mu=0.0
                )
            )
        return params_lib.ParameterCollection(tuple(specs))

    # -- kernel & mean -----------------------------------------------------

    def _warp_features(self, p: Params, f: kernels.MixedFeatures) -> kernels.MixedFeatures:
        if not (self.use_input_warping and self.num_continuous):
            return f
        u = jnp.clip(f.continuous, 1e-6, 1.0 - 1e-6)
        warped = 1.0 - (1.0 - u ** p["warp_a"]) ** p["warp_b"]
        return kernels.MixedFeatures(warped, f.categorical)

    def _kernel(
        self, p: Params, f1: kernels.MixedFeatures, f2: kernels.MixedFeatures, data: GPData
    ) -> Array:
        cont_ls = p.get("continuous_length_scales", jnp.ones((self.num_continuous,)))
        cat_ls = p.get("categorical_length_scales", jnp.ones((self.num_categorical,)))
        f1 = self._warp_features(p, f1)
        f2 = self._warp_features(p, f2)
        return kernels.matern52_ard(
            f1,
            f2,
            amplitude=p["amplitude"],
            continuous_length_scales=cont_ls,
            categorical_length_scales=cat_ls,
            continuous_dim_mask=data.cont_dim_mask,
            categorical_dim_mask=data.cat_dim_mask,
        )

    # -- likelihood --------------------------------------------------------

    def _masked_gram(self, p: Params, data: GPData) -> Array:
        """K + (noise²+jitter)·I on valid rows; identity on padded rows."""
        k = self._kernel(p, data.features(), data.features(), data)
        m = data.row_mask
        pair = m[:, None] & m[None, :]
        k = jnp.where(pair, k, 0.0)  # also zeroes padded diagonal entries
        noise = p["noise_stddev"] * p["noise_stddev"] + _JITTER
        return k + jnp.diag(jnp.where(m, noise, 1.0))

    def neg_log_likelihood(self, unconstrained: Params, data: GPData) -> Array:
        """-log p(y | X, θ) + log-normal regularization (the ARD loss)."""
        coll = self.param_collection()
        p = coll.constrain(unconstrained)
        gram = self._masked_gram(p, data)
        chol = jnp.linalg.cholesky(gram)
        y = data.labels
        alpha = jax.scipy.linalg.cho_solve((chol, True), y)
        n_valid = jnp.sum(data.row_mask.astype(jnp.float32))
        # Padded rows: y = 0 and unit diag ⇒ zero contribution to each term.
        data_fit = 0.5 * jnp.dot(y, alpha)
        log_det = jnp.sum(
            jnp.where(data.row_mask, jnp.log(jnp.diagonal(chol)), 0.0)
        )
        nll = data_fit + log_det + 0.5 * n_valid * _LOG_2PI
        loss = nll + coll.regularization(p)
        # Guard non-finite (Cholesky blow-ups under extreme params).
        return jnp.where(jnp.isfinite(loss), loss, jnp.asarray(1e10, loss.dtype))

    # -- predictive --------------------------------------------------------

    def precompute(self, unconstrained: Params, data: GPData) -> "GPState":
        p = self.param_collection().constrain(unconstrained)
        return self.precompute_constrained(p, data)

    def precompute_constrained(self, p: Params, data: GPData) -> "GPState":
        """Precompute from already-constrained params (e.g. after a noise
        override for pure-exploration conditioning, gp_ucb_pe.py).

        Also forms L^-1 explicitly: the acquisition sweep calls predict()
        thousands of times per suggest, and a precomputed inverse turns each
        per-query triangular solve (sequential, slow on TPU) into a plain
        matmul that rides the MXU. One extra O(N^3) solve here is amortized
        over ~3000 sweep iterations.
        """
        gram = self._masked_gram(p, data)
        chol = jnp.linalg.cholesky(gram)
        alpha = jax.scipy.linalg.cho_solve((chol, True), data.labels)
        eye = jnp.eye(chol.shape[0], dtype=chol.dtype)
        linv = jax.scipy.linalg.solve_triangular(chol, eye, lower=True)
        return GPState(
            model=self, params=p, data=data, chol=chol, alpha=alpha, linv=linv
        )


@flax.struct.dataclass
class GPState:
    """Cholesky-precomputed posterior, ready for O(N·M) predictions."""

    model: VizierGaussianProcess = flax.struct.field(pytree_node=False)
    params: Params
    data: GPData
    chol: Array  # [N, N]
    alpha: Array  # [N]
    linv: Array  # [N, N] = chol^-1 (matmul-only predicts; MXU-friendly)

    def predict(
        self, query: kernels.MixedFeatures, *, include_noise: bool = False
    ) -> Tuple[Array, Array]:
        """Posterior mean and stddev at query points ([M], [M])."""
        model, p, data = self.model, self.params, self.data
        k_star = model._kernel(p, query, data.features(), data)  # [M, N]
        k_star = jnp.where(data.row_mask[None, :], k_star, 0.0)
        mean = k_star @ self.alpha
        v = self.linv @ k_star.T  # [N, M] — pure matmul in the hot loop
        prior_var = p["amplitude"] * p["amplitude"]
        var = prior_var - jnp.sum(v * v, axis=0)
        if include_noise:
            var = var + p["noise_stddev"] * p["noise_stddev"]
        return mean, jnp.sqrt(jnp.maximum(var, 1e-12))

    def predict_joint(self, query: kernels.MixedFeatures) -> Tuple[Array, Array]:
        """Posterior mean [M] and full covariance [M, M] at query points.

        Needed by joint q-batch acquisitions: duplicated batch members are
        perfectly correlated, which marginal-only sampling cannot express.
        """
        model, p, data = self.model, self.params, self.data
        k_star = model._kernel(p, query, data.features(), data)  # [M, N]
        k_star = jnp.where(data.row_mask[None, :], k_star, 0.0)
        mean = k_star @ self.alpha
        v = self.linv @ k_star.T  # [N, M]
        k_qq = model._kernel(p, query, query, data)  # [M, M]
        cov = k_qq - v.T @ v
        # Symmetrize + jitter for downstream Cholesky.
        cov = 0.5 * (cov + cov.T) + 1e-6 * jnp.eye(cov.shape[0], dtype=cov.dtype)
        return mean, cov

    def sample(
        self, query: kernels.MixedFeatures, rng: Array, num_samples: int
    ) -> Array:
        """Marginal posterior samples [num_samples, M] (diagonal covariance)."""
        mean, stddev = self.predict(query)
        eps = jax.random.normal(rng, (num_samples,) + mean.shape, dtype=mean.dtype)
        return mean[None, :] + stddev[None, :] * eps


@flax.struct.dataclass
class EnsemblePredictive:
    """Uniform mixture over a leading ensemble axis of GPStates.

    Parity with ``UniformEnsemblePredictive``
    (``stochastic_process_model.py:835``): predictions vmap over members and
    combine as a uniform Gaussian mixture (moment-matched).
    """

    states: GPState  # leading axis E on params/chol/alpha/data

    @property
    def ensemble_size(self) -> int:
        return self.states.alpha.shape[0]

    def predict(self, query: kernels.MixedFeatures) -> Tuple[Array, Array]:
        means, stddevs = jax.vmap(lambda s: s.predict(query))(self.states)
        mean = jnp.mean(means, axis=0)
        second = jnp.mean(stddevs**2 + means**2, axis=0)
        var = jnp.maximum(second - mean**2, 1e-12)
        return mean, jnp.sqrt(var)

    def predict_per_member(self, query: kernels.MixedFeatures) -> Tuple[Array, Array]:
        return jax.vmap(lambda s: s.predict(query))(self.states)
