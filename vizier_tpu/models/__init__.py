"""JAX stochastic-process models: GP, kernels, warpers, transfer learning."""

from vizier_tpu.models.gp import (
    EnsemblePredictive,
    GPData,
    GPState,
    VizierGaussianProcess,
)
from vizier_tpu.models.kernels import MixedFeatures, matern52, matern52_ard
from vizier_tpu.models.multitask_gp import MultiTaskGaussianProcess
from vizier_tpu.models.output_warpers import (
    WarperPipeline,
    create_default_warper,
    create_warp_outliers_warper,
)
from vizier_tpu.models.params import ParameterCollection, ParameterSpec, SoftClip
from vizier_tpu.models.stacked_residual import StackedResidualGP
