"""Multi-task GP: a separable task kernel over correlated metrics.

Parity with
``/root/reference/vizier/_src/jax/models/multitask_tuned_gp_models.py``
(``MultiTaskType``: INDEPENDENT / SEPARABLE task-kernel priors): the
covariance factorizes as ``K((x,i),(x',j)) = k_x(x,x') · B[i,j]`` with
``B = L Lᵀ + d·I`` Cholesky-parameterized. The joint Gram is the Kronecker
product ``B ⊗ K_x`` over flattened (task-major) observations, mask-safe the
same way as the single-task GP. INDEPENDENT multi-task is served by the
per-metric vmapped training in ``designers.gp_bandit``.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Tuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from vizier_tpu.models import gp as gp_lib
from vizier_tpu.models import kernels
from vizier_tpu.models import params as params_lib

Array = jax.Array
_JITTER = 1e-5
_LOG_2PI = 1.8378770664093453


class MultiTaskType(enum.Enum):
    INDEPENDENT = "INDEPENDENT"
    SEPARABLE = "SEPARABLE"


@flax.struct.dataclass
class MultiTaskData:
    """Shared features, per-task labels [M, N] with a joint mask."""

    features_data: gp_lib.GPData  # labels field unused; masks/features shared
    task_labels: Array  # [M, N]
    task_mask: Array  # [M, N] bool (valid observation of task m at row n)

    @classmethod
    def from_gp_datas(cls, datas: Tuple[gp_lib.GPData, ...]) -> "MultiTaskData":
        labels = jnp.stack([d.labels for d in datas])
        masks = jnp.stack([d.row_mask for d in datas])
        return cls(features_data=datas[0], task_labels=labels, task_mask=masks)


@dataclasses.dataclass(frozen=True)
class MultiTaskGaussianProcess:
    """Separable multi-task GP over ``num_tasks`` correlated metrics."""

    num_continuous: int
    num_categorical: int
    num_tasks: int

    def _base(self) -> gp_lib.VizierGaussianProcess:
        return gp_lib.VizierGaussianProcess(
            num_continuous=self.num_continuous, num_categorical=self.num_categorical
        )

    def param_collection(self) -> params_lib.ParameterCollection:
        specs = list(self._base().param_collection().specs)
        m = self.num_tasks
        # Task covariance: lower-triangular factor entries, soft-clipped to
        # keep B well-scaled; diagonal entries strictly positive.
        specs.append(
            params_lib.ParameterSpec(
                "task_chol_diag", (m,), params_lib.SoftClip(0.05, 5.0), 0.3, 2.0
            )
        )
        if m > 1:
            ntril = m * (m - 1) // 2
            # Off-diagonal factor magnitudes (sign handled via two halves is
            # unnecessary for PSD B; positive couplings cover the common
            # "metrics agree" case and keep the single-pytree machinery).
            specs.append(
                params_lib.ParameterSpec(
                    "task_chol_offdiag", (ntril,), params_lib.SoftClip(1e-3, 5.0),
                    0.01, 0.5,
                )
            )
        return params_lib.ParameterCollection(tuple(specs))

    def _task_cov(self, p: params_lib.Params) -> Array:
        m = self.num_tasks
        chol = jnp.diag(p["task_chol_diag"])
        if m > 1:
            rows, cols = jnp.tril_indices(m, k=-1)
            chol = chol.at[rows, cols].set(p["task_chol_offdiag"])
        return chol @ chol.T + 1e-6 * jnp.eye(m)

    def _joint_gram(self, p: params_lib.Params, data: MultiTaskData) -> Array:
        base = self._base()
        fd = data.features_data
        kx = base._kernel(p, fd.features(), fd.features(), fd)  # [N, N]
        b = self._task_cov(p)  # [M, M]
        gram = jnp.kron(b, kx)  # [MN, MN], task-major
        mask = data.task_mask.reshape(-1)  # [MN]
        pair = mask[:, None] & mask[None, :]
        gram = jnp.where(pair, gram, 0.0)
        noise = p["noise_stddev"] * p["noise_stddev"] + _JITTER
        return gram + jnp.diag(jnp.where(mask, noise, 1.0))

    def neg_log_likelihood(
        self, unconstrained: params_lib.Params, data: MultiTaskData
    ) -> Array:
        coll = self.param_collection()
        p = coll.constrain(unconstrained)
        gram = self._joint_gram(p, data)
        y = jnp.where(data.task_mask, data.task_labels, 0.0).reshape(-1)
        chol = jnp.linalg.cholesky(gram)
        alpha = jax.scipy.linalg.cho_solve((chol, True), y)
        mask = data.task_mask.reshape(-1)
        n_valid = jnp.sum(mask.astype(jnp.float32))
        nll = (
            0.5 * jnp.dot(y, alpha)
            + jnp.sum(jnp.where(mask, jnp.log(jnp.diagonal(chol)), 0.0))
            + 0.5 * n_valid * _LOG_2PI
        )
        loss = nll + coll.regularization(p)
        return jnp.where(jnp.isfinite(loss), loss, jnp.asarray(1e10, loss.dtype))

    def precompute(
        self, unconstrained: params_lib.Params, data: MultiTaskData
    ) -> "MultiTaskGPState":
        return self.precompute_constrained(
            self.param_collection().constrain(unconstrained), data
        )

    def precompute_constrained(
        self, p: params_lib.Params, data: MultiTaskData
    ) -> "MultiTaskGPState":
        gram = self._joint_gram(p, data)
        y = jnp.where(data.task_mask, data.task_labels, 0.0).reshape(-1)
        chol = jnp.linalg.cholesky(gram)
        alpha = jax.scipy.linalg.cho_solve((chol, True), y)
        return MultiTaskGPState(
            model=self, params=p, data=data, chol=chol, alpha=alpha
        )


@flax.struct.dataclass
class MultiTaskGPState:
    model: MultiTaskGaussianProcess = flax.struct.field(pytree_node=False)
    params: params_lib.Params
    data: MultiTaskData
    chol: Array  # [MN, MN]
    alpha: Array  # [MN]

    def predict(self, query: kernels.MixedFeatures) -> Tuple[Array, Array]:
        """Posterior per task: mean [M, Q], stddev [M, Q]."""
        model, p, data = self.model, self.params, self.data
        base = model._base()
        fd = data.features_data
        kx_star = base._kernel(p, query, fd.features(), fd)  # [Q, N]
        b = model._task_cov(p)  # [M, M]
        # Cross-covariance of task m at query q with all (task, row) obs:
        # kron(b[m], kx_star[q]) → build [M, Q, M*N].
        k_star = jnp.einsum("mt,qn->mqtn", b, kx_star).reshape(
            model.num_tasks, query.continuous.shape[0], -1
        )
        mask = data.task_mask.reshape(-1)
        k_star = jnp.where(mask[None, None, :], k_star, 0.0)
        mean = k_star @ self.alpha  # [M, Q]
        flat = k_star.reshape(-1, k_star.shape[-1])  # [MQ, MN]
        v = jax.scipy.linalg.solve_triangular(self.chol, flat.T, lower=True)
        prior_var = (
            p["amplitude"] * p["amplitude"] * jnp.diag(b)[:, None]
        )  # [M, 1]
        var = prior_var - jnp.sum(v * v, axis=0).reshape(mean.shape)
        return mean, jnp.sqrt(jnp.maximum(var, 1e-12))
