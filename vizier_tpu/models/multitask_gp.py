"""Multi-task GP: a separable task kernel over correlated metrics.

Parity with
``/root/reference/vizier/_src/jax/models/multitask_tuned_gp_models.py``
(``MultiTaskType``: INDEPENDENT plus three SEPARABLE task-kernel priors,
``:41-60``): the covariance factorizes as
``K((x,i),(x',j)) = k_x(x,x') · B[i,j]`` and the joint Gram is the Kronecker
product ``B ⊗ K_x`` over flattened (task-major) observations, mask-safe the
same way as the single-task GP. INDEPENDENT multi-task is served by the
per-metric vmapped training in ``designers.gp_bandit``.

Task-covariance parameterizations (all SIGNED — off-diagonal Cholesky
entries can go negative, so anti-correlated objectives, the common case for
multi-objective trade-offs, are representable):

- ``SEPARABLE`` (= reference ``SEPARABLE_NORMAL_TASK_KERNEL_PRIOR``,
  ``:144-170``): free lower-triangular Cholesky; positive diagonal, signed
  off-diagonals with a Normal(0, 1) prior centered at the identity.
- ``SEPARABLE_LKJ`` (``:93-137``): correlation Cholesky via row
  normalization of signed entries (the ``CorrelationCholesky`` bijector's
  construction) scaled by a per-task sqrt-diagonal in (1e-6, 1); an
  LKJ(concentration=1) log-density on the correlation factor joins the
  regularizer.
- ``SEPARABLE_DIAG`` (``:77-92``): diagonal-only B (no cross-task
  coupling, but a learned per-task scale).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Tuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from vizier_tpu.models import gp as gp_lib
from vizier_tpu.models import kernels
from vizier_tpu.models import params as params_lib

Array = jax.Array
_JITTER = 1e-5
_LOG_2PI = 1.8378770664093453


class MultiTaskType(enum.Enum):
    INDEPENDENT = "INDEPENDENT"
    # Normal-prior signed Cholesky (reference SEPARABLE_NORMAL_TASK_KERNEL_PRIOR).
    SEPARABLE = "SEPARABLE"
    SEPARABLE_NORMAL = "SEPARABLE"  # alias of SEPARABLE
    SEPARABLE_LKJ = "SEPARABLE_LKJ"
    SEPARABLE_DIAG = "SEPARABLE_DIAG"


def _corr_cholesky(vec: Array, m: int) -> Array:
    """Signed lower-tri entries → unit-diagonal correlation Cholesky.

    The ``CorrelationCholesky`` bijector's construction: fill the strict
    lower triangle, put 1 on the diagonal, L2-normalize each row. Rows of
    the result have unit norm, so ``LLᵀ`` is a correlation matrix.
    """
    l = jnp.eye(m, dtype=jnp.float32)
    if m > 1:
        rows, cols = jnp.tril_indices(m, k=-1)
        l = l.at[rows, cols].set(vec)
    return l / jnp.linalg.norm(l, axis=-1, keepdims=True)


@flax.struct.dataclass
class MultiTaskData:
    """Shared features, per-task labels [M, N] with a joint mask."""

    features_data: gp_lib.GPData  # labels field unused; masks/features shared
    task_labels: Array  # [M, N]
    task_mask: Array  # [M, N] bool (valid observation of task m at row n)

    @classmethod
    def from_gp_datas(cls, datas: Tuple[gp_lib.GPData, ...]) -> "MultiTaskData":
        labels = jnp.stack([d.labels for d in datas])
        masks = jnp.stack([d.row_mask for d in datas])
        return cls(features_data=datas[0], task_labels=labels, task_mask=masks)


@dataclasses.dataclass(frozen=True)
class MultiTaskGaussianProcess:
    """Separable multi-task GP over ``num_tasks`` correlated metrics."""

    num_continuous: int
    num_categorical: int
    num_tasks: int
    multitask_type: MultiTaskType = MultiTaskType.SEPARABLE

    def __post_init__(self):
        if self.multitask_type is MultiTaskType.INDEPENDENT:
            raise ValueError(
                "INDEPENDENT multi-task is the per-metric vmapped path in "
                "designers.gp_bandit; MultiTaskGaussianProcess models the "
                "SEPARABLE* variants."
            )

    def _base(self) -> gp_lib.VizierGaussianProcess:
        return gp_lib.VizierGaussianProcess(
            num_continuous=self.num_continuous, num_categorical=self.num_categorical
        )

    def param_collection(self) -> params_lib.ParameterCollection:
        specs = list(self._base().param_collection().specs)
        m = self.num_tasks
        ntril = m * (m - 1) // 2
        t = self.multitask_type
        if t is MultiTaskType.SEPARABLE_DIAG:
            # Diagonal-only B: per-task sqrt-scale in (1e-6, 1), uniform
            # init and a Uniform prior — zero penalty (reference
            # correlation_diag, Sigmoid-constrained, Uniform prior).
            specs.append(
                params_lib.ParameterSpec(
                    "task_sqrt_diag", (m,),
                    params_lib.SoftClip(1e-6, 1.0, log_space=False),
                    0.3, 0.95, linear=True, regularize=False,
                )
            )
        elif t is MultiTaskType.SEPARABLE_LKJ:
            # Correlation Cholesky from SIGNED entries (row-normalized) x a
            # per-task sqrt-diagonal. The ONLY prior on the correlation
            # entries is the LKJ density in _extra_regularization, and the
            # sqrt-diagonal's reference prior is Uniform (zero penalty) —
            # per-spec Gaussian penalties are disabled so task coupling is
            # not shrunk beyond the reference's priors
            # (multitask_tuned_gp_models.py:100-127).
            if m > 1:
                specs.append(
                    params_lib.ParameterSpec(
                        "task_corr_chol_vec", (ntril,),
                        params_lib.SoftClip(-5.0, 5.0, log_space=False),
                        -0.5, 0.5, linear=True, regularize=False,
                    )
                )
            specs.append(
                params_lib.ParameterSpec(
                    "task_sqrt_diag", (m,),
                    params_lib.SoftClip(1e-6, 1.0, log_space=False),
                    0.3, 0.95, linear=True, regularize=False,
                )
            )
        else:  # SEPARABLE (normal prior on Cholesky entries)
            # Positive diagonal with a log-normal prior at 1 (the reference
            # centers the Cholesky prior at the identity).
            specs.append(
                params_lib.ParameterSpec(
                    "task_chol_diag", (m,), params_lib.SoftClip(0.05, 5.0),
                    0.3, 2.0,
                )
            )
            if m > 1:
                # SIGNED off-diagonals with a Normal(0, 1) prior: negative
                # task correlations (anti-correlated objectives — the common
                # multi-objective trade-off case) are representable, matching
                # the reference's signed Normal prior
                # (multitask_tuned_gp_models.py:144-151).
                specs.append(
                    params_lib.ParameterSpec(
                        "task_chol_offdiag", (ntril,),
                        params_lib.SoftClip(-5.0, 5.0, log_space=False),
                        -0.5, 0.5, prior_mu=0.0, prior_sigma=1.0, linear=True,
                    )
                )
        return params_lib.ParameterCollection(tuple(specs))

    def _task_cholesky(self, p: params_lib.Params) -> Array:
        """Lower-triangular factor L with B = LLᵀ (+ jitter)."""
        m = self.num_tasks
        t = self.multitask_type
        if t is MultiTaskType.SEPARABLE_DIAG:
            return jnp.diag(p["task_sqrt_diag"])
        if t is MultiTaskType.SEPARABLE_LKJ:
            vec = p.get("task_corr_chol_vec", jnp.zeros((0,), jnp.float32))
            corr = _corr_cholesky(vec, m)
            return corr * p["task_sqrt_diag"][:, None]
        chol = jnp.diag(p["task_chol_diag"])
        if m > 1:
            rows, cols = jnp.tril_indices(m, k=-1)
            chol = chol.at[rows, cols].set(p["task_chol_offdiag"])
        return chol

    def _task_cov(self, p: params_lib.Params) -> Array:
        chol = self._task_cholesky(p)
        return chol @ chol.T + 1e-6 * jnp.eye(self.num_tasks)

    def _extra_regularization(self, p: params_lib.Params) -> Array:
        """Model-level prior terms beyond the per-spec regularizers.

        LKJ(concentration=1) Cholesky log-density on the correlation factor:
        -log p(L) = -Σ_i (m - i - 1)·log L_ii (0-indexed diagonal).
        """
        if self.multitask_type is MultiTaskType.SEPARABLE_LKJ and self.num_tasks > 1:
            vec = p.get("task_corr_chol_vec", jnp.zeros((0,), jnp.float32))
            corr = _corr_cholesky(vec, self.num_tasks)
            i = jnp.arange(self.num_tasks, dtype=jnp.float32)
            exponents = self.num_tasks - i - 1.0
            return -jnp.sum(
                exponents * jnp.log(jnp.diagonal(corr) + 1e-12)
            )
        return jnp.asarray(0.0, jnp.float32)

    def _joint_gram(self, p: params_lib.Params, data: MultiTaskData) -> Array:
        base = self._base()
        fd = data.features_data
        kx = base._kernel(p, fd.features(), fd.features(), fd)  # [N, N]
        b = self._task_cov(p)  # [M, M]
        gram = jnp.kron(b, kx)  # [MN, MN], task-major
        mask = data.task_mask.reshape(-1)  # [MN]
        pair = mask[:, None] & mask[None, :]
        gram = jnp.where(pair, gram, 0.0)
        noise = p["noise_stddev"] * p["noise_stddev"] + _JITTER
        return gram + jnp.diag(jnp.where(mask, noise, 1.0))

    def neg_log_likelihood(
        self, unconstrained: params_lib.Params, data: MultiTaskData
    ) -> Array:
        coll = self.param_collection()
        p = coll.constrain(unconstrained)
        gram = self._joint_gram(p, data)
        y = jnp.where(data.task_mask, data.task_labels, 0.0).reshape(-1)
        chol = jnp.linalg.cholesky(gram)
        alpha = jax.scipy.linalg.cho_solve((chol, True), y)
        mask = data.task_mask.reshape(-1)
        n_valid = jnp.sum(mask.astype(jnp.float32))
        nll = (
            0.5 * jnp.dot(y, alpha)
            + jnp.sum(jnp.where(mask, jnp.log(jnp.diagonal(chol)), 0.0))
            + 0.5 * n_valid * _LOG_2PI
        )
        loss = nll + coll.regularization(p) + self._extra_regularization(p)
        return jnp.where(jnp.isfinite(loss), loss, jnp.asarray(1e10, loss.dtype))

    def precompute(
        self, unconstrained: params_lib.Params, data: MultiTaskData
    ) -> "MultiTaskGPState":
        return self.precompute_constrained(
            self.param_collection().constrain(unconstrained), data
        )

    def precompute_constrained(
        self, p: params_lib.Params, data: MultiTaskData
    ) -> "MultiTaskGPState":
        gram = self._joint_gram(p, data)
        y = jnp.where(data.task_mask, data.task_labels, 0.0).reshape(-1)
        chol = jnp.linalg.cholesky(gram)
        alpha = jax.scipy.linalg.cho_solve((chol, True), y)
        return MultiTaskGPState(
            model=self, params=p, data=data, chol=chol, alpha=alpha
        )


@flax.struct.dataclass
class MultiTaskGPState:
    model: MultiTaskGaussianProcess = flax.struct.field(pytree_node=False)
    params: params_lib.Params
    data: MultiTaskData
    chol: Array  # [MN, MN]
    alpha: Array  # [MN]

    def predict(self, query: kernels.MixedFeatures) -> Tuple[Array, Array]:
        """Posterior per task: mean [M, Q], stddev [M, Q]."""
        model, p, data = self.model, self.params, self.data
        base = model._base()
        fd = data.features_data
        kx_star = base._kernel(p, query, fd.features(), fd)  # [Q, N]
        b = model._task_cov(p)  # [M, M]
        # Cross-covariance of task m at query q with all (task, row) obs:
        # kron(b[m], kx_star[q]) → build [M, Q, M*N].
        k_star = jnp.einsum("mt,qn->mqtn", b, kx_star).reshape(
            model.num_tasks, query.continuous.shape[0], -1
        )
        mask = data.task_mask.reshape(-1)
        k_star = jnp.where(mask[None, None, :], k_star, 0.0)
        mean = k_star @ self.alpha  # [M, Q]
        flat = k_star.reshape(-1, k_star.shape[-1])  # [MQ, MN]
        v = jax.scipy.linalg.solve_triangular(self.chol, flat.T, lower=True)
        prior_var = (
            p["amplitude"] * p["amplitude"] * jnp.diag(b)[:, None]
        )  # [M, 1]
        var = prior_var - jnp.sum(v * v, axis=0).reshape(mean.shape)
        return mean, jnp.sqrt(jnp.maximum(var, 1e-12))
