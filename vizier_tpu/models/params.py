"""GP hyperparameter specs, constraints, and bijectors.

TPU-first replacement for the reference's TFP-based ``ModelParameter``
coroutine machinery
(``/root/reference/vizier/_src/jax/stochastic_process_model.py:56-144``):
instead of Flax coroutines yielding TFP bijectors, a model declares a flat
list of ``ParameterSpec``s; hyperparameters live as an unconstrained pytree
(dict of arrays) that optimizers can treat as a plain vector, and
``constrain``/``unconstrain`` map through smooth sigmoid soft-clip bijectors.
Everything is f32 and jit/vmap-safe (TPU native — no x64 requirement).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array
Params = Dict[str, Array]


@dataclasses.dataclass(frozen=True)
class SoftClip:
    """Smooth bijector from R onto (low, high) via a scaled sigmoid.

    ``forward(0)`` lands at the geometric (log-space) midpoint for positive
    ranges, which keeps default inits well-scaled.
    """

    low: float
    high: float
    log_space: bool = True  # interpolate in log space (positive ranges)

    def forward(self, x: Array) -> Array:
        s = jax.nn.sigmoid(x)
        if self.log_space and self.low > 0:
            lo, hi = np.log(self.low), np.log(self.high)
            return jnp.exp(lo + (hi - lo) * s)
        return self.low + (self.high - self.low) * s

    def inverse(self, y: Array) -> Array:
        eps = 1e-6
        if self.log_space and self.low > 0:
            lo, hi = np.log(self.low), np.log(self.high)
            s = (jnp.log(y) - lo) / (hi - lo)
        else:
            s = (y - self.low) / (self.high - self.low)
        s = jnp.clip(s, eps, 1.0 - eps)
        return jnp.log(s) - jnp.log1p(-s)


@dataclasses.dataclass(frozen=True)
class ParameterSpec:
    """One hyperparameter: shape, constraint, init distribution, regularizer.

    ``init_low/high``: constrained-space log-uniform init range for random
    restarts. ``prior_mu/sigma``: log-normal regularizer
    0.5*((log(v) - mu)/sigma)^2 summed over elements (the reference's
    log-squared regularizers, ``tuned_gp_models.py:132-220``).

    ``linear=True`` switches to linear-space sampling and a plain Gaussian
    regularizer 0.5*((v - mu)/sigma)^2 — required for SIGNED parameters
    (e.g. task-covariance Cholesky off-diagonals, which must be able to
    learn negative task correlations; reference
    ``multitask_tuned_gp_models.py:144-151`` uses signed Normal priors).
    """

    name: str
    shape: Tuple[int, ...]
    bijector: SoftClip
    init_low: float
    init_high: float
    prior_mu: float = 0.0
    prior_sigma: float = 1.0
    linear: bool = False
    # False = no per-spec prior penalty (e.g. reference Uniform priors, or
    # parameters whose prior lives in a model-level term instead).
    regularize: bool = True

    def sample_constrained(self, rng: Array) -> Array:
        u = jax.random.uniform(rng, self.shape, dtype=jnp.float32)
        if self.linear:
            return self.init_low + (self.init_high - self.init_low) * u
        lo, hi = np.log(self.init_low), np.log(self.init_high)
        return jnp.exp(lo + (hi - lo) * u)

    def regularizer(self, constrained_value: Array) -> Array:
        if not self.regularize:
            return jnp.asarray(0.0, jnp.float32)
        if self.linear:
            z = (constrained_value - self.prior_mu) / self.prior_sigma
        else:
            z = (jnp.log(constrained_value) - self.prior_mu) / self.prior_sigma
        return 0.5 * jnp.sum(z * z)


@dataclasses.dataclass(frozen=True)
class ParameterCollection:
    """A model's full hyperparameter declaration."""

    specs: Tuple[ParameterSpec, ...]

    def spec(self, name: str) -> ParameterSpec:
        for s in self.specs:
            if s.name == name:
                return s
        raise KeyError(name)

    def random_init_unconstrained(self, rng: Array) -> Params:
        """One random init (unconstrained space) for restart seeding."""
        keys = jax.random.split(rng, len(self.specs))
        out = {}
        for s, k in zip(self.specs, keys):
            out[s.name] = s.bijector.inverse(s.sample_constrained(k))
        return out

    def batch_random_init_unconstrained(self, rng: Array, batch: int) -> Params:
        """[batch, ...]-leading random inits (for vmapped restarts)."""
        keys = jax.random.split(rng, batch)
        return jax.vmap(self.random_init_unconstrained)(keys)

    def constrain(self, unconstrained: Params) -> Params:
        return {
            s.name: s.bijector.forward(unconstrained[s.name]) for s in self.specs
        }

    def unconstrain(self, constrained: Params) -> Params:
        return {
            s.name: s.bijector.inverse(jnp.asarray(constrained[s.name], jnp.float32))
            for s in self.specs
        }

    def regularization(self, constrained: Params) -> Array:
        total = jnp.asarray(0.0, jnp.float32)
        for s in self.specs:
            total = total + s.regularizer(constrained[s.name])
        return total
