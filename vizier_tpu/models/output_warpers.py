"""Label (output) warping for GP robustness.

Parity with
``/root/reference/vizier/_src/algorithms/designers/gp/output_warpers.py``:
half-rank gaussianization of the bad tail, z-scoring, and infeasibility
imputation. Host-side numpy (runs once per suggest on a small vector, before
padding/device transfer); the GP then sees ~N(0,1) labels, which is what its
log-normal hyperparameter priors assume.
"""

from __future__ import annotations

import abc
import dataclasses
from typing import List, Optional, Sequence

import numpy as np
from scipy import special


class OutputWarper(abc.ABC):
    """Maps a [N, 1] label column (NaN = infeasible) to warped values."""

    @abc.abstractmethod
    def warp(self, labels: np.ndarray) -> np.ndarray:
        ...

    def __call__(self, labels: np.ndarray) -> np.ndarray:
        labels = np.asarray(labels, dtype=np.float64)
        squeeze = labels.ndim == 1
        if squeeze:
            labels = labels[:, None]
        out = self.warp(labels)
        return out[:, 0] if squeeze else out


@dataclasses.dataclass
class HalfRankWarper(OutputWarper):
    """Gaussianizes the below-median half by rank (robust to bad outliers).

    Values >= median are kept; values below are replaced by
    ``median + std * Phi^{-1}(quantile)`` so a catastrophically bad trial
    cannot stretch the GP's length scales. MAXIMIZE convention.
    """

    def warp(self, labels: np.ndarray) -> np.ndarray:
        out = labels.copy()
        for j in range(labels.shape[1]):
            y = labels[:, j]
            finite = np.isfinite(y)
            vals = y[finite]
            if len(vals) < 2:
                continue
            med = np.median(vals)
            upper = vals[vals >= med]
            # Robust scale from the good half; fall back to overall std.
            std = np.std(upper - med)
            if std <= 1e-12:
                std = np.std(vals) + 1e-12
            ranks = np.argsort(np.argsort(vals))  # 0..n-1
            quantiles = (ranks + 0.5) / len(vals)
            bad = vals < med
            mapped = vals.copy()
            mapped[bad] = med + std * np.sqrt(2.0) * special.erfinv(
                2.0 * quantiles[bad] - 1.0
            )
            out[finite, j] = mapped
        return out


@dataclasses.dataclass
class ZScoreWarper(OutputWarper):
    def warp(self, labels: np.ndarray) -> np.ndarray:
        out = labels.copy()
        for j in range(labels.shape[1]):
            y = labels[:, j]
            finite = np.isfinite(y)
            if finite.sum() == 0:
                continue
            mu = np.mean(y[finite])
            sigma = np.std(y[finite])
            if sigma <= 1e-12:
                sigma = 1.0
            out[finite, j] = (y[finite] - mu) / sigma
        return out


@dataclasses.dataclass
class InfeasibleWarper(OutputWarper):
    """Imputes NaN (infeasible) labels with a value worse than every real one."""

    margin: float = 0.5

    def warp(self, labels: np.ndarray) -> np.ndarray:
        out = labels.copy()
        for j in range(labels.shape[1]):
            y = out[:, j]
            finite = np.isfinite(y)
            if finite.sum() == 0:
                out[:, j] = 0.0
                continue
            lo, hi = np.min(y[finite]), np.max(y[finite])
            span = max(hi - lo, 1.0)
            out[~finite, j] = lo - self.margin * span
        return out


@dataclasses.dataclass
class WarperPipeline(OutputWarper):
    warpers: Sequence[OutputWarper] = ()

    def warp(self, labels: np.ndarray) -> np.ndarray:
        for w in self.warpers:
            labels = w.warp(labels)
        return labels


def create_default_warper(*, infeasible: bool = True) -> OutputWarper:
    """The reference's default pipeline: half-rank → z-score → infeasible."""
    warpers: List[OutputWarper] = [HalfRankWarper(), ZScoreWarper()]
    if infeasible:
        warpers.append(InfeasibleWarper())
    return WarperPipeline(warpers)


@dataclasses.dataclass
class YeoJohnsonWarper(OutputWarper):
    """Yeo-Johnson power transform with per-column lambda fit by grid MLE.

    Parity with the reference's ``yjt.py``: gaussianizes skewed label
    distributions; lambda chosen to maximize the normal log-likelihood over
    a grid (robust, derivative-free, a handful of vectorized passes).
    """

    lambdas_grid: Sequence[float] = tuple(np.linspace(-2.0, 4.0, 25))

    @staticmethod
    def _transform(y: np.ndarray, lmbda: float) -> np.ndarray:
        out = np.empty_like(y)
        pos = y >= 0
        if abs(lmbda) > 1e-9:
            out[pos] = ((y[pos] + 1.0) ** lmbda - 1.0) / lmbda
        else:
            out[pos] = np.log1p(y[pos])
        if abs(lmbda - 2.0) > 1e-9:
            out[~pos] = -(((1.0 - y[~pos]) ** (2.0 - lmbda)) - 1.0) / (2.0 - lmbda)
        else:
            out[~pos] = -np.log1p(-y[~pos])
        return out

    def warp(self, labels: np.ndarray) -> np.ndarray:
        out = labels.copy()
        for j in range(labels.shape[1]):
            y = labels[:, j]
            finite = np.isfinite(y)
            vals = y[finite]
            if len(vals) < 3:
                continue
            best_ll, best_t = -np.inf, vals
            for lmbda in self.lambdas_grid:
                t = self._transform(vals, float(lmbda))
                var = np.var(t)
                if var <= 1e-12 or not np.isfinite(var):
                    continue
                # Normal log-likelihood + Jacobian term.
                ll = -0.5 * len(t) * np.log(var) + (lmbda - 1.0) * np.sum(
                    np.sign(vals) * np.log1p(np.abs(vals))
                )
                if ll > best_ll:
                    best_ll, best_t = ll, t
            out[finite, j] = best_t
        return out
