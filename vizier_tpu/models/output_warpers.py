"""Label (output) warping for GP robustness.

Parity with
``/root/reference/vizier/_src/algorithms/designers/gp/output_warpers.py``
(half-rank :289, log :381, infeasible :419, z-score :496, normalize :530,
outlier detection :578, gaussianization :666, pipelines :118-230): real
objective scales are pathological (huge outliers, NaN infeasibles, heavy
skew), and the default GP pipeline's robustness depends on taming them.
Host-side numpy (runs once per suggest on a small vector, before padding /
device transfer); the GP then sees bounded, roughly-gaussian labels, which
is what its log-normal hyperparameter priors assume. MAXIMIZE convention.

Warpers are stateful: ``warp`` fits whatever statistics it needs and
``unwarp`` inverts the most recent ``warp`` (used to report predictions in
the original metric scale, e.g. ``VizierGPUCBPEBandit.sample``).
"""

from __future__ import annotations

import abc
import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np
from scipy import special


def _validate(labels: np.ndarray) -> np.ndarray:
    """Casts to float [N, 1]-compatible, maps -inf to NaN, rejects +inf."""
    labels = np.array(labels, dtype=np.float64)
    if np.isposinf(labels).any():
        raise ValueError("+inf label values are not valid (MAXIMIZE convention).")
    labels[np.isneginf(labels)] = np.nan
    return labels


class OutputWarper(abc.ABC):
    """Maps a [N, 1] label column (NaN = infeasible) to warped values."""

    @abc.abstractmethod
    def warp(self, labels: np.ndarray) -> np.ndarray:
        ...

    def unwarp(self, labels: np.ndarray) -> np.ndarray:
        raise NotImplementedError(f"{type(self).__name__} has no unwarp.")

    def __call__(self, labels: np.ndarray) -> np.ndarray:
        labels = _validate(labels)
        squeeze = labels.ndim == 1
        if squeeze:
            labels = labels[:, None]
        out = self.warp(labels)
        return out[:, 0] if squeeze else out


@dataclasses.dataclass
class _HalfRankColumnState:
    """Monotone warped→original lookup for one column's below-median half."""

    original: np.ndarray  # sorted unique original values
    warped: np.ndarray  # their images under the warp (sorted, same order)
    median: float

    def unwarp(self, v: np.ndarray) -> np.ndarray:
        out = np.array(v, dtype=np.float64)
        below = out < self.median
        if not below.any() or len(self.original) < 2:
            return out
        # Piecewise-linear inverse; linear extrapolation below the image.
        lo_w, hi_w = self.warped[0], self.warped[-1]
        lo_o, hi_o = self.original[0], self.original[-1]
        interp = np.interp(out[below], self.warped, self.original)
        span_w = max(hi_w - lo_w, 1e-12)
        extrapolated = lo_o - (np.abs(out[below] - lo_w) / span_w) * (hi_o - lo_o)
        interp = np.where(out[below] < lo_w, extrapolated, interp)
        out[below] = interp
        return out


@dataclasses.dataclass
class HalfRankWarper(OutputWarper):
    """Gaussianizes the below-median half by rank (robust to bad outliers).

    Values >= median are kept; values below are replaced by
    ``median + std * Phi^{-1}(quantile)`` so a catastrophically bad trial
    cannot stretch the GP's length scales. MAXIMIZE convention. NaNs pass
    through untouched.
    """

    _states: Optional[List[Optional[_HalfRankColumnState]]] = None

    def warp(self, labels: np.ndarray) -> np.ndarray:
        out = labels.copy()
        self._states = []
        for j in range(labels.shape[1]):
            y = labels[:, j]
            finite = np.isfinite(y)
            vals = y[finite]
            if len(vals) < 2:
                self._states.append(None)
                continue
            med = np.median(vals)
            upper = vals[vals >= med]
            # Robust scale from the good half; fall back to overall std.
            std = np.sqrt(np.mean((upper - med) ** 2))
            if std <= 1e-12:
                std = np.std(vals) + 1e-12
            ranks = np.argsort(np.argsort(vals))  # 0..n-1
            quantiles = (ranks + 0.5) / len(vals)
            bad = vals < med
            mapped = vals.copy()
            mapped[bad] = med + std * np.sqrt(2.0) * special.erfinv(
                2.0 * quantiles[bad] - 1.0
            )
            out[finite, j] = mapped
            uniq, idx = np.unique(vals, return_index=True)
            self._states.append(
                _HalfRankColumnState(
                    original=uniq, warped=mapped[idx], median=float(med)
                )
            )
        return out

    def unwarp(self, labels: np.ndarray) -> np.ndarray:
        if self._states is None:
            raise ValueError("warp() must be called before unwarp().")
        out = labels.copy()
        for j, state in enumerate(self._states):
            if state is None:
                continue
            finite = np.isfinite(out[:, j])
            out[finite, j] = state.unwarp(out[finite, j])
        return out


@dataclasses.dataclass
class LogWarper(OutputWarper):
    """Compresses the range so differences between *good* values dominate.

    Maps finite labels into [-0.5, 0.5] via
    ``0.5 - log1p(norm_diff * (offset-1)) / log(offset)`` where ``norm_diff``
    is the normalized distance from the max — a log scale anchored at the
    best observed value. NaNs pass through.
    """

    offset: float = 1.5
    _mins: Optional[np.ndarray] = None
    _maxs: Optional[np.ndarray] = None

    def warp(self, labels: np.ndarray) -> np.ndarray:
        if self.offset <= 0:
            raise ValueError("offset must be positive.")
        out = labels.copy()
        self._mins = np.nanmin(labels, axis=0)
        self._maxs = np.nanmax(labels, axis=0)
        for j in range(labels.shape[1]):
            y = out[:, j]
            finite = np.isfinite(y)
            if not finite.any():
                continue
            span = max(self._maxs[j] - self._mins[j], 1e-12)
            norm_diff = (self._maxs[j] - y[finite]) / span
            out[finite, j] = 0.5 - np.log1p(
                norm_diff * (self.offset - 1.0)
            ) / np.log(self.offset)
        return out

    def unwarp(self, labels: np.ndarray) -> np.ndarray:
        if self._maxs is None:
            raise ValueError("warp() must be called before unwarp().")
        out = labels.copy()
        for j in range(labels.shape[1]):
            y = out[:, j]
            finite = np.isfinite(y)
            if not finite.any():
                continue
            span = max(self._maxs[j] - self._mins[j], 1e-12)
            norm_diff = np.expm1(np.log(self.offset) * (0.5 - y[finite])) / (
                self.offset - 1.0
            )
            out[finite, j] = self._maxs[j] - norm_diff * span
        return out


@dataclasses.dataclass
class ZScoreWarper(OutputWarper):
    """Standardizes finite labels to mean 0 / std 1; invertible."""

    _mu: Optional[np.ndarray] = None
    _sigma: Optional[np.ndarray] = None

    def warp(self, labels: np.ndarray) -> np.ndarray:
        out = labels.copy()
        m = labels.shape[1]
        self._mu = np.zeros(m)
        self._sigma = np.ones(m)
        for j in range(m):
            y = labels[:, j]
            finite = np.isfinite(y)
            if finite.sum() == 0:
                continue
            mu = np.mean(y[finite])
            sigma = np.std(y[finite])
            if sigma <= 1e-12 or not np.isfinite(sigma):
                sigma = 1.0
            self._mu[j], self._sigma[j] = mu, sigma
            out[finite, j] = (y[finite] - mu) / sigma
        return out

    def unwarp(self, labels: np.ndarray) -> np.ndarray:
        if self._mu is None:
            raise ValueError("warp() must be called before unwarp().")
        return labels * self._sigma[None, :] + self._mu[None, :]


@dataclasses.dataclass
class NormalizeLabels(OutputWarper):
    """Affine map of finite labels onto ``target_interval`` (invertible).

    All-equal finite labels map to the interval midpoint; NaNs untouched.
    """

    target_interval: Tuple[float, float] = (0.0, 1.0)
    _source: Optional[List[Optional[Tuple[float, float]]]] = None

    def warp(self, labels: np.ndarray) -> np.ndarray:
        lo_t, hi_t = self.target_interval
        if lo_t > hi_t:
            raise ValueError(f"Invalid target interval {self.target_interval}.")
        out = labels.copy()
        self._source = []
        for j in range(labels.shape[1]):
            y = labels[:, j]
            finite = np.isfinite(y)
            if not finite.any():
                self._source.append(None)
                continue
            lo, hi = np.min(y[finite]), np.max(y[finite])
            self._source.append((float(lo), float(hi)))
            if lo == hi:
                out[finite, j] = 0.5 * (lo_t + hi_t)
            else:
                out[finite, j] = lo_t + (y[finite] - lo) * (hi_t - lo_t) / (hi - lo)
        return out

    def unwarp(self, labels: np.ndarray) -> np.ndarray:
        if self._source is None:
            raise ValueError("warp() must be called before unwarp().")
        lo_t, hi_t = self.target_interval
        out = labels.copy()
        for j, src in enumerate(self._source):
            if src is None:
                continue
            lo, hi = src
            finite = np.isfinite(out[:, j])
            if lo == hi or hi_t == lo_t:
                out[finite, j] = lo
            else:
                out[finite, j] = lo + (out[finite, j] - lo_t) * (hi - lo) / (
                    hi_t - lo_t
                )
        return out


@dataclasses.dataclass
class InfeasibleWarper(OutputWarper):
    """Imputes NaN (infeasible) labels with a value worse than every real one.

    The imputed value sits half a range below the worst observed label, and
    all feasible labels are shifted so the frequency-weighted mean of the
    warped column is zero — matching a zero-mean GP prior: far from support,
    the posterior reverts to the blended feasible/infeasible expectation
    (reference ``InfeasibleWarperComponent`` docstring, Jeffreys-smoothed
    feasibility frequency).
    """

    _shift: Optional[np.ndarray] = None

    def warp(self, labels: np.ndarray) -> np.ndarray:
        out = labels.copy()
        m = labels.shape[1]
        self._shift = np.zeros(m)
        for j in range(m):
            y = out[:, j]
            finite = np.isfinite(y)
            if finite.sum() == 0:
                self._shift[j] = np.nan
                out[:, j] = 0.0
                continue
            lo, hi = np.min(y[finite]), np.max(y[finite])
            bad_value = lo - (0.5 * (hi - lo) + 1.0)
            # Jeffreys-smoothed feasible frequency: rare feasibles should pull
            # the zero point (GP prior mean) toward the infeasible value.
            p_feasible = (0.5 + finite.sum()) / (1.0 + len(y))
            shift = -np.mean(y[finite]) * p_feasible - bad_value * (1.0 - p_feasible)
            self._shift[j] = shift
            # Shift applies to ALL rows, imputed included, so the
            # frequency-weighted mean of the warped column is exactly zero
            # and unwarp (labels - shift) inverts every row.
            out[~finite, j] = bad_value
            out[:, j] = out[:, j] + shift
        return out

    def unwarp(self, labels: np.ndarray) -> np.ndarray:
        if self._shift is None:
            raise ValueError("warp() must be called before unwarp().")
        shift = np.where(np.isnan(self._shift), 0.0, self._shift)
        return labels - shift[None, :]


@dataclasses.dataclass
class DetectOutliers(OutputWarper):
    """Marks unreasonably-bad labels as NaN (outlier → infeasible).

    A label more than ``min_zscore`` estimated stds below the median is an
    outlier (e.g. a -1e76 sentinel in a [1, 10] metric). The std is estimated
    from (median, max, N) only — the bad tail itself must not inflate it —
    using the sample-size-dependent estimator of Hozo et al. (BMC Med. Res.
    Method. 2005) that the reference uses.
    """

    min_zscore: float = 6.0
    max_zscore: Optional[float] = None

    def _estimate_variance(self, vals: np.ndarray) -> float:
        n = len(vals)
        med = float(np.median(vals))
        hi = float(np.max(vals))
        if self.max_zscore:
            return ((hi - med) / self.min_zscore) ** 2
        if n >= 70:
            return ((hi - med) / 3.0) ** 2
        if n >= 15:
            return ((hi - med) / 2.0) ** 2
        # Small-sample range-based estimator (Hozo et al., eq. 12) with the
        # min hallucinated at zero after shifting.
        a = med - hi
        if a < 0:
            a = 0.0
        m, b = med, hi
        out = a**2 + m**2 + b**2
        out += ((n - 3) / 2.0) * ((a + m) ** 2 + (b + m) ** 2) / 4.0
        out -= n * ((a + 2 * m + b) / 4.0 + (a - 2 * m + b) / (4.0 * n)) ** 2
        return out / max(n - 1, 1)

    def warp(self, labels: np.ndarray) -> np.ndarray:
        out = labels.copy()
        for j in range(labels.shape[1]):
            y = out[:, j]
            finite = np.isfinite(y)
            if finite.sum() < 2:
                continue
            vals = y[finite]
            med = np.median(vals)
            std = np.sqrt(max(self._estimate_variance(vals), 1e-24))
            threshold = med - self.min_zscore * std
            vals = np.where(vals < threshold, np.nan, vals)
            out[finite, j] = vals
        return out


def _softclip(x: np.ndarray, low: float, high: float, softness: float) -> np.ndarray:
    """Smooth (differentiable, strictly monotone) clip of x into (low, high)."""
    # Chained softplus hinges: approaches identity away from the bounds.
    y = low + softness * np.logaddexp(0.0, (x - low) / softness)
    return high - softness * np.logaddexp(0.0, (high - y) / softness)


@dataclasses.dataclass
class TransformToGaussian(OutputWarper):
    """Quantile-transforms labels toward N(0, 1).

    Normalizes values (or ranks, with ``use_rank``) to [0, 1], soft-clips
    away from the endpoints, and applies the normal PPF — a non-parametric
    gaussianization suited to GP priors. NaNs pass through.
    """

    softclip_low: float = 1e-10
    softclip_high: float = 1.0 - 1e-10
    softclip_hinge_softness: float = 0.01
    use_rank: bool = False

    def warp(self, labels: np.ndarray) -> np.ndarray:
        out = labels.copy()
        for j in range(labels.shape[1]):
            y = out[:, j]
            finite = np.isfinite(y)
            vals = y[finite]
            if len(vals) < 2:
                continue
            base = np.argsort(np.argsort(vals)).astype(np.float64) if self.use_rank else vals
            span = np.max(base) - np.min(base)
            if span <= 0:
                out[finite, j] = 0.0
                continue
            normalized = (base - np.min(base)) / span
            clipped = _softclip(
                normalized,
                self.softclip_low,
                self.softclip_high,
                self.softclip_hinge_softness,
            )
            out[finite, j] = special.ndtri(np.clip(clipped, 1e-12, 1.0 - 1e-12))
        return out


@dataclasses.dataclass
class WarperPipeline(OutputWarper):
    """Sequential warping with the reference pipeline's edge-case contract.

    All-identical finite labels warp to zeros; all-infeasible labels warp to
    -1s (and those two cases unwarp back to themselves / NaNs).
    """

    warpers: Sequence[OutputWarper] = ()
    # Edge-case fit state: 'normal' | 'constant' (all labels equal; stores
    # the constant) | 'all_nan'. The sub-warpers are NOT fitted in the edge
    # modes, so unwarp must invert from this state, not from them.
    _mode: str = "normal"
    _constant: float = 0.0

    def warp(self, labels: np.ndarray) -> np.ndarray:
        labels = _validate(labels)
        if labels.size == 0:
            self._mode = "normal"
            return labels
        if np.isfinite(labels).all() and len(np.unique(labels)) == 1:
            self._mode = "constant"
            self._constant = float(labels.flat[0])
            return np.zeros_like(labels)
        if np.isnan(labels).all():
            self._mode = "all_nan"
            return -np.ones_like(labels)
        self._mode = "normal"
        for w in self.warpers:
            labels = w.warp(labels)
        return labels

    def unwarp(self, labels: np.ndarray) -> np.ndarray:
        labels = _validate(labels)
        if self._mode == "constant":
            # Warped space was 0 = the constant; shift arbitrary inputs
            # (e.g. GP samples around 0) back by it.
            return labels + self._constant
        if self._mode == "all_nan":
            return np.full_like(labels, np.nan)
        for w in reversed(list(self.warpers)):
            labels = w.unwarp(labels)
        return labels


def create_default_warper(
    *,
    half_rank_warp: bool = True,
    log_warp: bool = True,
    infeasible_warp: bool = True,
) -> WarperPipeline:
    """The reference's default pipeline: half-rank → log → infeasible."""
    if not (half_rank_warp or log_warp or infeasible_warp):
        raise ValueError("At least one warper must be enabled.")
    warpers: List[OutputWarper] = []
    if half_rank_warp:
        warpers.append(HalfRankWarper())
    if log_warp:
        warpers.append(LogWarper())
    if infeasible_warp:
        warpers.append(InfeasibleWarper())
    return WarperPipeline(warpers)


def create_warp_outliers_warper(
    *,
    warp_outliers: bool = True,
    infeasible_warp: bool = True,
    transform_gaussian: bool = True,
) -> WarperPipeline:
    """Outlier-robust pipeline: detect-outliers → infeasible → gaussianize."""
    warpers: List[OutputWarper] = []
    if warp_outliers:
        warpers.append(DetectOutliers())
    if infeasible_warp:
        warpers.append(InfeasibleWarper())
    if transform_gaussian:
        warpers.append(TransformToGaussian())
    return WarperPipeline(warpers)


@dataclasses.dataclass
class YeoJohnsonWarper(OutputWarper):
    """Yeo-Johnson power transform with per-column lambda fit by grid MLE.

    Parity with the reference's ``yjt.py``: gaussianizes skewed label
    distributions; lambda chosen to maximize the normal log-likelihood over
    a grid (robust, derivative-free, a handful of vectorized passes).
    """

    lambdas_grid: Sequence[float] = tuple(np.linspace(-2.0, 4.0, 25))

    @staticmethod
    def _transform(y: np.ndarray, lmbda: float) -> np.ndarray:
        out = np.empty_like(y)
        pos = y >= 0
        if abs(lmbda) > 1e-9:
            out[pos] = ((y[pos] + 1.0) ** lmbda - 1.0) / lmbda
        else:
            out[pos] = np.log1p(y[pos])
        if abs(lmbda - 2.0) > 1e-9:
            out[~pos] = -(((1.0 - y[~pos]) ** (2.0 - lmbda)) - 1.0) / (2.0 - lmbda)
        else:
            out[~pos] = -np.log1p(-y[~pos])
        return out

    def warp(self, labels: np.ndarray) -> np.ndarray:
        out = labels.copy()
        for j in range(labels.shape[1]):
            y = labels[:, j]
            finite = np.isfinite(y)
            vals = y[finite]
            if len(vals) < 3:
                continue
            best_ll, best_t = -np.inf, vals
            for lmbda in self.lambdas_grid:
                t = self._transform(vals, float(lmbda))
                var = np.var(t)
                if var <= 1e-12 or not np.isfinite(var):
                    continue
                # Normal log-likelihood + Jacobian term.
                ll = -0.5 * len(t) * np.log(var) + (lmbda - 1.0) * np.sum(
                    np.sign(vals) * np.log1p(np.abs(vals))
                )
                if ll > best_ll:
                    best_ll, best_t = ll, t
            out[finite, j] = best_t
        return out
