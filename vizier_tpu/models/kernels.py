"""ARD Matern-5/2 kernel over mixed continuous + categorical features.

TPU-first replacement for the reference's TFP kernel stack
(``FeatureScaledWithCategorical`` over Matern-5/2,
``/root/reference/vizier/_src/jax/models/tuned_gp_models.py:132-220``):
pure jax.numpy, batched [N, D] x [M, D] → [N, M]. The squared distance
uses the exact-difference form for typical dims (D ≤ 64) — XLA fuses the
broadcast-subtract-square-reduce into one pass, and f32 stays accurate
enough for the downstream Cholesky — and switches to the MXU
||a||² - 2a·b + ||b||² matmul expansion only for wide feature spaces.

Categorical features are integer category indices; the ARD distance adds
(mismatch / lengthscale²) per categorical dimension (the exact-match kernel
the reference builds from one-hot + feature scaling, but without
materializing one-hots).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

Array = jax.Array

_SQRT5 = 2.2360679774997896


def matern52(sq_dist: Array) -> Array:
    """Matern-5/2 of a *squared* scaled distance."""
    d = jnp.sqrt(jnp.maximum(sq_dist, 1e-20))
    return (1.0 + _SQRT5 * d + (5.0 / 3.0) * sq_dist) * jnp.exp(-_SQRT5 * d)


_DIRECT_DIST_MAX_DIM = 64


def scaled_sq_distance_continuous(
    x1: Array, x2: Array, length_scales: Array, *, dim_mask: Optional[Array] = None
) -> Array:
    """[N, D], [M, D] -> [N, M] sum_d ((x1-x2)/l)^2, optionally dim-masked.

    For D <= 64 (the typical Vizier regime) uses exact elementwise diffs —
    the ||a||²-2a·b+||b||² MXU expansion suffers f32 cancellation (~1e-3
    absolute on near-duplicate points), which poisons the Cholesky diagonal.
    Wide feature spaces fall back to the matmul expansion with clamping.
    """
    inv = 1.0 / length_scales
    if dim_mask is not None:
        inv = jnp.where(dim_mask, inv, 0.0)
    a = x1 * inv
    b = x2 * inv
    if x1.shape[-1] <= _DIRECT_DIST_MAX_DIM:
        diff = a[:, None, :] - b[None, :, :]
        return jnp.sum(diff * diff, axis=-1)
    a2 = jnp.sum(a * a, axis=-1, keepdims=True)  # [N, 1]
    b2 = jnp.sum(b * b, axis=-1, keepdims=True).T  # [1, M]
    cross = jax.lax.dot_general(
        a, b, (((1,), (1,)), ((), ())), precision=jax.lax.Precision.HIGHEST
    )
    return jnp.maximum(a2 + b2 - 2.0 * cross, 0.0)


def categorical_sq_distance(
    z1: Array, z2: Array, length_scales: Array, *, dim_mask: Optional[Array] = None
) -> Array:
    """[N, S] int, [M, S] int -> [N, M] sum_s mismatch/l_s^2."""
    if z1.shape[-1] == 0:
        return jnp.zeros((z1.shape[0], z2.shape[0]), dtype=jnp.float32)
    inv_sq = 1.0 / (length_scales * length_scales)
    if dim_mask is not None:
        inv_sq = jnp.where(dim_mask, inv_sq, 0.0)
    mismatch = (z1[:, None, :] != z2[None, :, :]).astype(jnp.float32)  # [N, M, S]
    return jnp.einsum("nms,s->nm", mismatch, inv_sq)


class MixedFeatures(NamedTuple):
    """Plain-array view of model inputs (already scaled/indexed)."""

    continuous: Array  # [N, Dc] float
    categorical: Array  # [N, Ds] int


def matern52_ard(
    f1: MixedFeatures,
    f2: MixedFeatures,
    *,
    amplitude: Array,
    continuous_length_scales: Array,
    categorical_length_scales: Array,
    continuous_dim_mask: Optional[Array] = None,
    categorical_dim_mask: Optional[Array] = None,
) -> Array:
    """Full mixed-feature ARD Matern-5/2 kernel matrix [N, M].

    XLA fuses the exact-difference distance (broadcast-subtract-square-
    reduce over D) into a single pass — no [N, M, D] intermediate reaches
    HBM. A hand-written Pallas kernel for this op was measured at
    0.4-0.93x the XLA-fused path on TPU v5e across 512..16k point counts
    (round 2) and removed: the op is bandwidth/dispatch-bound and the
    compiler already schedules it optimally.
    """
    sq = scaled_sq_distance_continuous(
        f1.continuous, f2.continuous, continuous_length_scales, dim_mask=continuous_dim_mask
    )
    sq = sq + categorical_sq_distance(
        f1.categorical, f2.categorical, categorical_length_scales,
        dim_mask=categorical_dim_mask,
    )
    return (amplitude * amplitude) * matern52(sq)
