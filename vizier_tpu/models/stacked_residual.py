"""Stacked residual GPs: transfer learning across studies.

Parity with the reference's transfer-learning stack
(``/root/reference/vizier/_src/algorithms/designers/gp/gp_models.py:245``
``train_stacked_residual_gp`` and ``transfer_learning.py``): a base GP is
trained on prior-study data; each subsequent level is trained on the
*residuals* of the level below at its own data; prediction sums means and
combines variances. Every level reuses the mask-safe f32 GP and the
vmapped-restart ARD of ``models.gp`` / ``optimizers.lbfgs``.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import flax.struct
import jax
import jax.numpy as jnp

from vizier_tpu.models import gp as gp_lib
from vizier_tpu.models import kernels
from vizier_tpu.optimizers import lbfgs as lbfgs_lib

Array = jax.Array


@flax.struct.dataclass
class StackedResidualGP:
    """A tuple of per-level posteriors, base level first."""

    levels: Tuple[gp_lib.GPState, ...]

    def predict(self, query: kernels.MixedFeatures) -> Tuple[Array, Array]:
        mean = None
        var = None
        for state in self.levels:
            m, s = state.predict(query)
            mean = m if mean is None else mean + m
            var = s * s if var is None else var + s * s
        return mean, jnp.sqrt(jnp.maximum(var, 1e-12))


def train_stacked_residual_gp(
    model: gp_lib.VizierGaussianProcess,
    optimizer: lbfgs_lib.Optimizer,
    datasets: Sequence[gp_lib.GPData],
    rng: Array,
    *,
    num_restarts: int = lbfgs_lib.DEFAULT_RANDOM_RESTARTS,
) -> StackedResidualGP:
    """Trains one GP per dataset, each on the residuals of the stack so far.

    ``datasets[0]`` is the oldest prior; the last entry is the current
    study's data. All datasets must share feature dimensions (the caller
    aligns search spaces; mismatched spaces are the caller's converter
    problem, as in the reference's ``ProblemAndTrialsScaler``).
    """
    levels: List[gp_lib.GPState] = []
    coll = model.param_collection()
    for data in datasets:
        if levels:
            stack = StackedResidualGP(levels=tuple(levels))
            prior_mean, _ = stack.predict(data.features())
            data = gp_lib.GPData(
                continuous=data.continuous,
                categorical=data.categorical,
                labels=jnp.where(
                    data.row_mask, data.labels - prior_mean, data.labels
                ),
                row_mask=data.row_mask,
                cont_dim_mask=data.cont_dim_mask,
                cat_dim_mask=data.cat_dim_mask,
            )
        rng, train_rng = jax.random.split(rng)
        inits = coll.batch_random_init_unconstrained(train_rng, num_restarts)
        loss_fn = lambda p, d=data: model.neg_log_likelihood(p, d)
        result = optimizer(loss_fn, inits)
        levels.append(model.precompute(result.params, data))
    return StackedResidualGP(levels=tuple(levels))
