"""Stateful serving runtime: designer cache, request coalescing, stats.

The reference serves every ``SuggestTrials`` with a cold-constructed
designer and a from-scratch ARD train (``designer_policy.py``'s stateless
``DesignerPolicy``). This package keeps per-study designer state alive
between requests instead:

- :class:`DesignerStateCache` — live designer + last trained unconstrained
  ARD params per study, TTL/LRU-evicted, invalidated on study deletion;
- :class:`RequestCoalescer` — concurrent identical suggest computations
  collapse onto one in-flight designer run;
- :class:`CachedDesignerStatePolicy` — the Pythia policy that routes
  through the cache with incremental trial updates and warm-started ARD;
- :class:`ServingStats` — cache hit/miss, warm/cold train, and coalescing
  counters behind a small snapshot API;
- :class:`ServingConfig` — the knobs (all on by default; env-overridable);
- :class:`SpeculativeEngine` — opt-in background pre-compute of the next
  suggestion batch after each completion, served from the cache entry
  when the frontier fingerprint still matches (``VIZIER_SPECULATIVE=1``);
- :class:`AdmissionController` — opt-in multi-tenant overload protection
  (fair-share admission, load shedding, deadline-aware backpressure,
  graceful degradation) at the Pythia dispatch boundary
  (``VIZIER_ADMISSION=1``; docs/guides/reliability.md).

The runtime also owns the cross-study batch executor
(``vizier_tpu.parallel.batch_executor``): concurrent designer computations
from different studies that share a padding bucket execute as one vmapped
device program (``docs/guides/performance.md``).

See ``docs/guides/serving.md`` for semantics and the intentional deviation
from the reference's per-request cold train (PARITY.md).
"""

from vizier_tpu.serving.admission import AdmissionConfig
from vizier_tpu.serving.admission import AdmissionController
from vizier_tpu.serving.config import ServingConfig
from vizier_tpu.serving.coalescer import RequestCoalescer
from vizier_tpu.serving.designer_cache import CachedDesignerEntry
from vizier_tpu.serving.designer_cache import DesignerStateCache
from vizier_tpu.serving.policy import CachedDesignerStatePolicy
from vizier_tpu.serving.runtime import ServingRuntime
from vizier_tpu.serving.speculative import SpeculativeConfig
from vizier_tpu.serving.speculative import SpeculativeEngine
from vizier_tpu.serving.stats import ServingStats

__all__ = [
    "AdmissionConfig",
    "AdmissionController",
    "CachedDesignerEntry",
    "CachedDesignerStatePolicy",
    "DesignerStateCache",
    "RequestCoalescer",
    "ServingConfig",
    "ServingRuntime",
    "ServingStats",
    "SpeculativeConfig",
    "SpeculativeEngine",
]
