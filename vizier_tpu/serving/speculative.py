"""Speculative suggestion pre-compute: make the suggest p99 a cache hit.

After each trial completion the serving runtime already has everything a
steady-state suggest needs — the live designer, warm ARD params, and the
new measurement — yet the next ``SuggestTrials`` still pays the full GP
train + acquisition on the request path. This module moves that compute
off the request path: a completion enqueues a *speculative job* keyed by
the study's **frontier fingerprint** (completed-trial set + active-trial
set + study-config hash); a bounded worker pool runs the job through the
SAME policy / designer-cache / batch-executor / surrogate path as a live
request (at low flush priority, so live traffic is never delayed), and
parks the resulting suggestion batch in a speculative slot on the study's
designer-cache entry. A live suggest whose frontier fingerprint matches
serves the parked batch in microseconds; any frontier change, study
deletion, surrogate crossover, or config change invalidates the slot, and
``max_speculation_age_s`` bounds staleness in time. This is the
serving-granularity analogue of the parallel-BO throughput argument in
GP-UCB-PE (arXiv:1206.6402): compute suggestions concurrently with
evaluation, with staleness bounded the way ensemble work
(arXiv:2205.14090) bounds model risk — invalidate and fall back, never
block.

Correctness model — a hit IS the live compute, run early:

- The speculative job executes the identical ``update → suggest`` sequence
  on the identical cached designer the live request would have used, so a
  hit is **bit-equal** to what live compute would have produced for the
  same frontier (asserted in ``tests/serving/test_speculative.py``).
- Designers advance a persistent RNG per suggest, so an *unserved*
  speculation shifts the stream for later computes. The engine therefore
  speculates only frontiers the workload will serve (completion-triggered
  by default; the post-fill trigger is opt-in) and discards — never
  serves — results whose frontier moved mid-flight.
- Speculative failures never surface to clients: a failed, superseded,
  fallback-stamped, or shutdown-cancelled job simply leaves the slot
  empty and the next request decays to a live compute.

Thread/lock model: the queue condition (``_cond``) and the slot-swap lock
(``_serve_lock``) are leaves — no device compute, RPC, or foreign lock is
ever taken under them. Workers pop a job under ``_cond``, release it, and
run the compute bare; the compute path itself takes the ordinary serving
locks (cache map, entry, coalescer) exactly as a live request does.

``VIZIER_SPECULATIVE=0`` (the default — speculation is opt-in) leaves the
request path bit-identical to the non-speculative tree: no engine object,
no threads, no extra designer computes.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import logging
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

# All VIZIER_* switches are declared in (and read through) the central
# registry; an undeclared name raises instead of silently reading an
# always-unset variable. Enforced by the env_registry analysis pass.
from vizier_tpu.analysis import registry as _registry
from vizier_tpu.observability import flight_recorder as recorder_lib
from vizier_tpu.observability import tracing as tracing_lib

_logger = logging.getLogger(__name__)

# Metadata stamp on served speculative suggestions (the serve-path twin of
# reliability's fallback stamp): ns "serving", key "speculative" = "hit".
SPECULATIVE_NAMESPACE = "serving"
SPECULATIVE_KEY = "speculative"
SPECULATIVE_HIT_VALUE = "hit"

# The speculative-compute flag rides a thread-local, not the request proto:
# the engine's worker runs the whole compute stack synchronously on its own
# thread (policy → batch executor), so every layer can ask "am I inside a
# speculative job?" without a wire-schema change.
_STATE = threading.local()


def in_speculative_compute() -> bool:
    """True on a thread currently executing a speculative job's compute."""
    return getattr(_STATE, "speculative", False)


class speculative_scope:
    """Marks the current thread as running a speculative compute."""

    def __enter__(self):
        self._prev = getattr(_STATE, "speculative", False)
        _STATE.speculative = True
        return self

    def __exit__(self, *exc):
        _STATE.speculative = self._prev
        return False


@dataclasses.dataclass(frozen=True)
class SpeculativeConfig:
    """Knobs for the speculative pre-compute pipeline."""

    # Master switch. Default OFF: speculation trades idle compute (and, on
    # a count-mismatch miss, an extra designer RNG advance) for request
    # latency — an opt-in, like VIZIER_BATCHING_PREWARM. Off = no engine,
    # no threads, bit-identical request path.
    speculative: bool = False
    # Bounded worker pool size. One worker serializes speculative device
    # compute behind live traffic naturally; more only helps multi-study
    # completion bursts.
    workers: int = 1
    # A parked batch older than this is served to nobody: the evaluation
    # that should have consumed it evidently stalled, and hyperparameters
    # may have drifted meaningfully by the time traffic returns.
    max_speculation_age_s: float = 300.0
    # Also speculate when a live suggest fills/refreshes the cache entry
    # (pre-computes the batch a SECOND client at the post-suggest frontier
    # would get). Off by default: in single-client loops that batch is
    # never served, and an unserved speculation advances the designer's
    # RNG stream away from the non-speculative path.
    speculate_on_fill: bool = False
    # Idle-window admission gate: a job is only handed to the compute path
    # while the batch executor's LIVE queue depth is <= this; otherwise the
    # worker backs off (admission_backoff_s per probe, admission_max_wait_s
    # total) and then drops the job rather than contend with live traffic.
    max_live_queue_depth: int = 0
    admission_backoff_s: float = 0.01
    admission_max_wait_s: float = 0.25
    # Count speculated for a study before its first live suggest reveals
    # the client's real batch size.
    default_count: int = 1
    # Distinct recent request counts remembered per study. A job
    # speculates the LARGEST of them: smaller requests serve a prefix of
    # the parked batch (the serve path already reconciles down), so a
    # client alternating suggest(1)/suggest(5) hits on both — under the
    # old last-seen-only policy every larger-count request was a
    # guaranteed miss (ROADMAP PR 8 residual).
    count_memory: int = 4
    # Trigger debounce for high-completion-rate studies: a completion
    # burst (parallel workers reporting back-to-back) coalesces into ONE
    # pre-compute once the study has been quiet this long, instead of
    # starting-and-superseding a job per completion. 0 = immediate (the
    # PR 8 behavior).
    debounce_ms: float = 0.0

    def __post_init__(self):
        if self.workers < 1:
            raise ValueError(f"workers must be >= 1, got {self.workers}.")
        if self.max_speculation_age_s <= 0:
            raise ValueError(
                f"max_speculation_age_s must be > 0, got "
                f"{self.max_speculation_age_s}."
            )
        if self.default_count < 1:
            raise ValueError(
                f"default_count must be >= 1, got {self.default_count}."
            )
        if self.count_memory < 1:
            raise ValueError(
                f"count_memory must be >= 1, got {self.count_memory}."
            )
        if self.debounce_ms < 0:
            raise ValueError(
                f"debounce_ms must be >= 0, got {self.debounce_ms}."
            )

    @classmethod
    def from_env(cls) -> "SpeculativeConfig":
        """The default config with per-knob environment overrides applied."""
        return cls(
            speculative=_registry.env_set("VIZIER_SPECULATIVE"),
            workers=_registry.env_int("VIZIER_SPECULATIVE_WORKERS", 1),
            max_speculation_age_s=_registry.env_float(
                "VIZIER_SPECULATIVE_MAX_AGE_S", 300.0
            ),
            speculate_on_fill=_registry.env_set("VIZIER_SPECULATIVE_ON_FILL"),
            count_memory=_registry.env_int(
                "VIZIER_SPECULATIVE_COUNT_MEMORY", 4
            ),
            debounce_ms=_registry.env_float(
                "VIZIER_SPECULATIVE_DEBOUNCE_MS", 0.0
            ),
        )

    @classmethod
    def disabled(cls) -> "SpeculativeConfig":
        """No speculation — the seed request path."""
        return cls(speculative=False)

    def as_dict(self) -> dict:
        """JSON-stampable form (bench.py / tools artifacts)."""
        return {
            "speculative": self.speculative,
            "workers": self.workers,
            "max_speculation_age_s": self.max_speculation_age_s,
            "speculate_on_fill": self.speculate_on_fill,
            "count_memory": self.count_memory,
            "debounce_ms": self.debounce_ms,
        }


@dataclasses.dataclass(frozen=True)
class FrontierFingerprint:
    """Identity of the designer-visible study state.

    Two requests with equal fingerprints would feed the designer identical
    inputs: the same completed-trial set (what ``update`` incorporates),
    the same active-trial set (what batch designers condition on as
    pending points), and the same study config (search space, metrics,
    algorithm — hashed, since the spec can be KBs). Measurement *content*
    on active trials is intentionally excluded: no shipped designer reads
    it, and ``AddMeasurement`` re-speculates anyway.
    """

    config_digest: str
    completed_ids: Tuple[int, ...]
    active_ids: Tuple[int, ...]


def config_digest(spec_bytes: bytes) -> str:
    return hashlib.sha256(spec_bytes).hexdigest()[:16]


def make_fingerprint(
    spec_bytes: bytes,
    completed_ids: Iterable[int],
    active_ids: Iterable[int],
) -> FrontierFingerprint:
    return FrontierFingerprint(
        config_digest=config_digest(spec_bytes),
        completed_ids=tuple(sorted(int(i) for i in completed_ids)),
        active_ids=tuple(sorted(int(i) for i in active_ids)),
    )


@dataclasses.dataclass
class SpeculativeSlot:
    """One parked pre-computed suggestion batch (designer-cache entry)."""

    study_name: str
    fingerprint: FrontierFingerprint
    response: Any  # PythiaSuggestResponse (opaque to the engine)
    count: int
    created_at: float  # engine-clock (monotonic) timestamp


class _Job:
    """One queued speculative pre-compute for a study."""

    __slots__ = ("study_name", "epoch", "trigger_ctx", "reason", "not_before")

    def __init__(
        self,
        study_name: str,
        epoch: int,
        trigger_ctx: Optional[tracing_lib.SpanContext],
        reason: str,
        not_before: float = 0.0,
    ):
        self.study_name = study_name
        self.epoch = epoch
        self.trigger_ctx = trigger_ctx
        self.reason = reason
        # Engine-clock debounce deadline: a worker leaves the job queued
        # until this time, so a completion burst supersedes in place and
        # costs one compute instead of one per completion.
        self.not_before = not_before


class SpeculativeEngine:
    """Background pre-compute pipeline over the designer cache.

    The engine is proto-agnostic: the Pythia servicer binds three
    callables —

    - ``fingerprint_fn(study_name) -> (FrontierFingerprint, max_trial_id)``
      reads the study's current frontier;
    - ``compute_fn(study_name, count, max_trial_id) -> response`` runs the
      live suggest path (coalescer → policy → designer → batch executor)
      and returns the response proto, or ``None``;
    - ``accept_fn(response) -> Optional[int]`` vets a response for
      parking (no error, non-empty, not a reliability fallback) and
      returns its batch size.

    Everything else — supersede-on-new-completion epochs, the admission
    gate against live batch-executor traffic, slot staleness, one-shot
    consumption — is engine-internal.
    """

    def __init__(
        self,
        config: SpeculativeConfig,
        cache,  # serving.designer_cache.DesignerStateCache
        stats=None,  # serving.stats.ServingStats
        metrics=None,  # observability.metrics.MetricsRegistry
        executor=None,  # parallel.batch_executor.BatchExecutor
        time_fn: Callable[[], float] = time.monotonic,
    ):
        self.config = config
        self._cache = cache
        self._stats = stats
        self._executor = executor
        self._time = time_fn
        self._fingerprint_fn: Optional[Callable] = None
        self._compute_fn: Optional[Callable] = None
        self._accept_fn: Optional[Callable] = None
        # Queue state under _cond: newest job per study (a fresh completion
        # supersedes the queued job for the same study), per-study epochs
        # (bumped by every notify/invalidate; a finished job only parks its
        # result if its epoch is still current), last-seen live counts, and
        # the in-flight study set (wait_idle).
        self._cond = threading.Condition()
        self._jobs: "collections.OrderedDict[str, _Job]" = (
            collections.OrderedDict()
        )
        self._epochs: Dict[str, int] = {}
        # study -> OrderedDict of its last count_memory DISTINCT request
        # counts (insertion order = recency; values unused).
        self._counts: Dict[str, "collections.OrderedDict"] = {}
        self._inflight: set = set()
        self._closed = False
        self._threads: List[threading.Thread] = []
        # Slot swaps (park / one-shot pop) serialize on their own leaf lock
        # so two concurrent suggests can never both serve one batch.
        self._serve_lock = threading.Lock()
        self._events = None
        self._latency = None
        if metrics is not None:
            self._events = metrics.counter(
                "vizier_speculative_events",
                help="Speculative pipeline events by outcome "
                "(hit | miss | stale | cancelled | stored | error).",
            )
            self._latency = metrics.histogram(
                "vizier_speculative_suggest_latency_seconds",
                help="Pythia suggest wall time split by whether the "
                "speculative slot served it (result=hit|miss).",
            )

    # -- wiring --------------------------------------------------------------

    def bind(
        self,
        *,
        fingerprint_fn: Callable,
        compute_fn: Callable,
        accept_fn: Callable,
    ) -> None:
        """Connects the engine to a Pythia servicer's compute path."""
        self._fingerprint_fn = fingerprint_fn
        self._compute_fn = compute_fn
        self._accept_fn = accept_fn

    @property
    def bound(self) -> bool:
        return self._compute_fn is not None

    # -- triggers ------------------------------------------------------------

    def notify_completion(self, study_name: str) -> bool:
        """CompleteTrial/AddMeasurement: frontier moved — invalidate the
        parked slot and enqueue a pre-compute for the new frontier."""
        return self._enqueue(study_name, reason="completion")

    def notify_fill(self, study_name: str) -> bool:
        """A live compute just filled/refreshed the cache entry; with
        ``speculate_on_fill`` pre-compute for the post-suggest frontier."""
        if not self.config.speculate_on_fill:
            return False
        return self._enqueue(study_name, reason="fill")

    def note_live_suggest(self, study_name: str, count: int) -> None:
        """Records the client's batch size in the study's recent-count set.

        The last ``count_memory`` DISTINCT counts are kept; jobs speculate
        the largest of them (smaller requests serve a batch prefix), so a
        workload mixing batch sizes no longer misses on the bigger ones.
        """
        if count < 1:
            return
        with self._cond:
            counts = self._counts.setdefault(
                study_name, collections.OrderedDict()
            )
            counts[count] = None
            counts.move_to_end(count)
            while len(counts) > self.config.count_memory:
                counts.popitem(last=False)

    def invalidate(self, study_name: str, reason: str = "") -> None:
        """Drops the parked slot and supersedes any queued/in-flight job
        (DeleteStudy, surrogate crossover, external frontier surgery)."""
        dropped_job = False
        with self._cond:
            self._epochs[study_name] = self._epochs.get(study_name, 0) + 1
            dropped_job = self._jobs.pop(study_name, None) is not None
            self._counts.pop(study_name, None)
        if dropped_job:
            self._record("cancelled", reason=reason or "invalidated")
        self._clear_slot(study_name)
        tracing_lib.add_current_event(
            "speculative.invalidated", study=study_name, reason=reason
        )

    def _enqueue(self, study_name: str, reason: str) -> bool:
        if not self.bound:
            return False
        trigger_ctx = tracing_lib.get_tracer().current_context()
        # The old slot (if any) was computed for a frontier that no longer
        # exists; drop it eagerly rather than letting it fail the serve-time
        # fingerprint check. BEFORE the enqueue: a worker may pick the new
        # job the instant it lands, and clearing afterwards could wipe the
        # fresh batch it just parked.
        self._clear_slot(study_name)
        superseded = False
        with self._cond:
            if self._closed:
                return False
            epoch = self._epochs.get(study_name, 0) + 1
            self._epochs[study_name] = epoch
            superseded = study_name in self._jobs
            self._jobs[study_name] = _Job(
                study_name,
                epoch,
                trigger_ctx,
                reason,
                not_before=self._time() + self.config.debounce_ms / 1000.0,
            )
            self._jobs.move_to_end(study_name)
            self._ensure_workers()
            self._cond.notify_all()
        if superseded:
            self._record("cancelled", reason="superseded")
        return True

    # -- serve path ----------------------------------------------------------

    def try_serve(
        self, study_name: str, count: int, fingerprint: FrontierFingerprint
    ) -> Tuple[Optional[Any], str]:
        """One-shot pop of the parked batch when it matches the request.

        Returns ``(response, outcome)`` with outcome in
        ``hit | miss | stale``; the response is only non-None on a hit and
        the slot is consumed (two racing suggests can never both serve one
        parked batch — the loser decays to live compute).
        """
        entry = self._cache.peek(study_name)
        slot = getattr(entry, "speculative", None) if entry is not None else None
        if slot is None:
            self._record("miss", study=study_name)
            return None, "miss"
        now = self._time()
        with self._serve_lock:
            slot = entry.speculative
            if slot is None:
                self._record("miss", study=study_name)
                return None, "miss"
            if now - slot.created_at > self.config.max_speculation_age_s:
                entry.speculative = None
                self._record("stale", study=study_name)
                return None, "stale"
            if slot.fingerprint != fingerprint:
                # The frontier moved since the job ran; the batch can never
                # be served (fingerprints don't come back) — drop it.
                entry.speculative = None
                self._record("miss", study=study_name, reason="fingerprint")
                return None, "miss"
            if count > slot.count:
                # The client wants more than was speculated: the whole
                # request falls through to live compute (the parked batch
                # stays for a matching-count peer; the live compute's new
                # trials will invalidate it naturally).
                self._record("miss", study=study_name, reason="count")
                return None, "miss"
            entry.speculative = None
        self._record("hit", study=study_name)
        return slot.response, "hit"

    def observe_suggest_latency(self, result: str, seconds: float) -> None:
        """The request-path latency histogram split by hit/miss."""
        if self._latency is not None:
            self._latency.observe(seconds, result=result)

    # -- worker pool ---------------------------------------------------------

    def _ensure_workers(self) -> None:
        """Starts workers lazily (caller holds ``_cond``)."""
        self._threads = [t for t in self._threads if t.is_alive()]
        while len(self._threads) < self.config.workers:
            thread = threading.Thread(
                target=self._worker_loop,
                name=f"vizier-speculative-{len(self._threads)}",
                daemon=True,  # joined in close(); daemon guards teardown
            )
            self._threads.append(thread)
            thread.start()

    def _pop_due_job_locked(self):
        """(job, wait): the first debounce-expired job (popped), or the
        seconds until the earliest becomes due (None = queue empty).
        Caller holds ``_cond``."""
        if not self._jobs:
            return None, None
        now = self._time()
        earliest: Optional[float] = None
        for name, job in self._jobs.items():
            if job.not_before <= now:
                return self._jobs.pop(name), None
            wait = job.not_before - now
            earliest = wait if earliest is None else min(earliest, wait)
        return None, earliest

    def _worker_loop(self) -> None:
        while True:
            with self._cond:
                while True:
                    if self._closed:
                        return
                    job, wait = self._pop_due_job_locked()
                    if job is not None:
                        break
                    self._cond.wait(timeout=wait)
                study_name = job.study_name
                self._inflight.add(study_name)
            try:
                self._run_job(job)
            except Exception:  # must never kill the pool
                _logger.warning(
                    "Speculative job for %s died.", job.study_name, exc_info=True
                )
                self._record("error", study=job.study_name)
            finally:
                with self._cond:
                    self._inflight.discard(study_name)
                    self._cond.notify_all()

    def _epoch_current(self, job: _Job) -> bool:
        with self._cond:
            return (
                not self._closed
                and self._epochs.get(job.study_name, 0) == job.epoch
            )

    def _admission_wait(self, job: _Job) -> bool:
        """Blocks until the live flush buckets are quiet (True) or the
        admission budget runs out / the job is superseded (False)."""
        if self._executor is None:
            return True
        deadline = self._time() + self.config.admission_max_wait_s
        while True:
            if self._executor.live_pending() <= self.config.max_live_queue_depth:
                return True
            if self._time() >= deadline:
                return False
            if not self._epoch_current(job):
                return False
            time.sleep(self.config.admission_backoff_s)

    def _run_job(self, job: _Job) -> None:
        study = job.study_name
        if not self._epoch_current(job):
            self._record("cancelled", study=study, reason="superseded")
            return
        if self._cache.peek(study, touch=False) is None:
            # No designer entry ⇒ the study has never been served through
            # the cache (bulk trial loading before the first suggest, an
            # evicted entry, or a non-cached policy like RANDOM_SEARCH).
            # The hit path needs the entry to park on, so computing now
            # would burn designer RNG state for a batch nobody can serve.
            self._record("cancelled", study=study, reason="no_entry")
            return
        if not self._admission_wait(job):
            if self._epoch_current(job):
                self._record("cancelled", study=study, reason="busy")
            else:
                self._record("cancelled", study=study, reason="superseded")
            return
        tracer = tracing_lib.get_tracer()
        with tracer.span(
            "speculative.precompute", study=study, trigger=job.reason
        ) as span:
            # Link (not parent) the triggering completion: the pre-compute
            # is its own trace, but a completion's trace shows what work it
            # set in motion and vice versa.
            if span is not None and job.trigger_ctx is not None:
                span.add_link(job.trigger_ctx, name="trigger")
            with self._cond:
                recent = self._counts.get(study)
                # The largest recent count covers every smaller request as
                # a served prefix; only a count above every recent one
                # still falls through to live compute.
                count = max(recent) if recent else self.config.default_count
            outcome = self._compute_and_park(job, count)
            if span is not None:
                span.set_attribute("outcome", outcome)
                span.set_attribute("count", count)

    def _compute_and_park(self, job: _Job, count: int) -> str:
        study = job.study_name
        try:
            fingerprint, max_trial_id = self._fingerprint_fn(study)
        except Exception:
            _logger.warning(
                "Speculative fingerprint for %s failed.", study, exc_info=True
            )
            self._record("error", study=study, reason="fingerprint")
            return "error"
        self._record("precompute", study=study)
        try:
            with speculative_scope():
                response = self._compute_fn(study, count, max_trial_id)
        except Exception:
            # A speculative failure must never surface anywhere: no slot is
            # parked and the next live request simply computes as usual.
            _logger.warning(
                "Speculative compute for %s failed.", study, exc_info=True
            )
            self._record("error", study=study, reason="compute")
            return "error"
        batch_size = self._accept_fn(response) if response is not None else None
        if not batch_size:
            self._record("error", study=study, reason="rejected")
            return "rejected"
        if not self._epoch_current(job):
            # A completion (or invalidation, or shutdown) landed while the
            # job was mid-flight: the batch was computed for a frontier
            # that is already history — discard, never serve.
            self._record("cancelled", study=study, reason="superseded")
            return "superseded"
        entry = self._cache.peek(study)
        if entry is None:
            self._record("cancelled", study=study, reason="evicted")
            return "evicted"
        slot = SpeculativeSlot(
            study_name=study,
            fingerprint=fingerprint,
            response=response,
            count=batch_size,
            created_at=self._time(),
        )
        with self._serve_lock:
            entry.speculative = slot
        self._record("stored", study=study)
        return "stored"

    # -- lifecycle / inspection ---------------------------------------------

    def wait_idle(self, timeout: float = 30.0) -> bool:
        """Blocks until no job is queued or in flight (tests, A/B tools —
        models an evaluation that outlasts the pre-compute)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._jobs or self._inflight:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._closed:
                    return not (self._jobs or self._inflight)
                self._cond.wait(timeout=remaining)
            return True

    def pending_jobs(self) -> int:
        with self._cond:
            return len(self._jobs) + len(self._inflight)

    def close(self, timeout: float = 30.0) -> None:
        """Cancels queued jobs, lets in-flight computes finish (their
        results are discarded via the epoch bump), joins the pool."""
        with self._cond:
            if self._closed:
                threads = list(self._threads)
            else:
                self._closed = True
                cancelled = len(self._jobs)
                self._jobs.clear()
                # Bump every epoch so an in-flight job can never park its
                # result into a half-shut-down runtime.
                for study in list(self._epochs):
                    self._epochs[study] += 1
                threads = list(self._threads)
                self._cond.notify_all()
                if cancelled:
                    self._record("cancelled", amount=cancelled, reason="shutdown")
        for thread in threads:
            thread.join(timeout=timeout)

    def _clear_slot(self, study_name: str) -> None:
        entry = self._cache.peek(study_name)
        if entry is None:
            return
        with self._serve_lock:
            entry.speculative = None

    _STAT_FIELDS = {
        "hit": "speculative_hits",
        "miss": "speculative_misses",
        "stale": "speculative_stale",
        "cancelled": "speculative_cancelled",
        "precompute": "speculative_precomputes",
        "error": "speculative_errors",
    }

    def _record(self, outcome: str, amount: int = 1, **attrs) -> None:
        field = self._STAT_FIELDS.get(outcome)
        if self._stats is not None and field is not None:
            self._stats.increment(field, amount)
        if self._events is not None:
            self._events.inc(amount, outcome=outcome)
        tracing_lib.add_current_event(
            f"speculative.{outcome}", **{k: v for k, v in attrs.items() if v}
        )
        recorder = recorder_lib.get_recorder()
        if recorder.enabled:
            clean = {k: v for k, v in attrs.items() if v and k != "study"}
            recorder.record(
                attrs.get("study"), "speculation", outcome=outcome, **clean
            )
