"""Multi-tenant overload protection: admission, shedding, degradation.

The production Vizier service survives fleet-scale traffic because no
single hot study or tenant can starve everyone else (arXiv:2408.11527
describes the service defaults at Google scale). This module is that
defense for our serving tier, applied at the Pythia dispatch boundary —
the last hop before a designer computation burns real device time:

- **per-tenant accounting** — the tenant id is the ``owners/{owner}``
  segment of the study resource name (:func:`tenant_of`), so it rides
  every request for free and is fleet-wide by construction (all replicas
  share ONE Pythia, hence one controller);
- **bounded in-flight admission** — a global cap plus a per-tenant cap on
  concurrent designer computations. A request over either cap is SHED
  with a typed ``TRANSIENT: RESOURCE_EXHAUSTED`` error carrying a
  ``retry_after_ms=`` hint that :class:`~vizier_tpu.reliability.retry.
  RetryPolicy` honors as a backoff floor. **A shed is not a failure**: it
  never reaches the per-study circuit breaker (the study's designer did
  nothing wrong) and never burns a designer run;
- **deadline-aware rejection** — a request whose remaining
  ``deadline_secs`` cannot cover the estimated queue wait plus the
  compute p50 (from the live latency histogram) is shed immediately:
  never dispatch a computation whose caller has already given up;
- **an overload state machine** — ``healthy → shedding → degraded`` over
  a sliding decision window. Under sustained saturation (windowed shed
  rate over ``degrade_rate``) the controller enters DEGRADED and serves
  *low-priority* tenants (weight below ``degraded_floor``) the existing
  seeded quasi-random fallback (stamped in trial metadata) while
  reserving GP compute for in-SLO tenants; recovery is hysteretic
  (windowed shed rate under ``recover_rate`` AND in-flight pressure
  relieved, sustained for a full window).

The same controller drives the batch executor's weighted fair-share
plane: per-tenant weights feed the deficit-round-robin slot selection
inside the live lane (see ``parallel.batch_executor``), and the tenant
travels from the admission gate to the executor on a contextvar
(:func:`tenant_scope`) so no layer in between needs a new parameter.

Everything is opt-in: ``VIZIER_ADMISSION=0`` (the default) builds no
controller — the serving path is bit-identical to the pre-admission
tree.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import dataclasses
import threading
import time
from typing import Callable, Deque, Dict, Optional, Tuple

# All VIZIER_* switches are declared in (and read through) the central
# registry; enforced by the env_registry analysis pass.
from vizier_tpu.analysis import registry as _registry
from vizier_tpu.reliability import errors as errors_lib

# Overload states, in escalation order.
HEALTHY = "healthy"
SHEDDING = "shedding"
DEGRADED = "degraded"
_STATE_LEVEL = {HEALTHY: 0, SHEDDING: 1, DEGRADED: 2}

# Decision outcomes.
ADMIT = "admit"
SHED = "shed"
DEGRADE = "degrade"

# Shed reasons (the ``reason=`` token in the typed error, the metric
# label, and the snapshot key).
REASON_TOTAL = "inflight_total"
REASON_TENANT = "inflight_tenant"
REASON_DEADLINE = "deadline_infeasible"

# Trial-metadata stamp for degraded-mode quasi-random serves (next to the
# reliability fallback stamp, so degraded trials stay auditable).
ADMISSION_NAMESPACE = "admission"
ADMISSION_KEY = "degraded"
ADMISSION_VALUE = "quasi_random"

# The tenant the admission gate admitted on this thread of execution;
# the batch executor reads it for fair-share slot accounting.
_TENANT: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "vizier_admission_tenant", default=None
)

DEFAULT_TENANT = "default"


def tenant_of(study_name: str) -> str:
    """The tenant id carried by a study resource name.

    The ``owners/{owner}`` segment (``owners/prod/studies/s1`` → ``prod``)
    — the same identity the loadgen tenant mix stamps and the rendezvous
    router hashes. Unparseable names fall into one shared default tenant
    rather than erroring: admission must never fail a request over a
    naming convention.
    """
    if study_name.startswith("owners/"):
        owner = study_name[len("owners/"):].split("/", 1)[0]
        if owner:
            return owner
    return DEFAULT_TENANT


def current_tenant() -> Optional[str]:
    """The tenant admitted on this thread (None outside an admission
    scope — e.g. speculative jobs, or with admission off)."""
    return _TENANT.get()


@contextlib.contextmanager
def tenant_scope(tenant: str):
    token = _TENANT.set(tenant)
    try:
        yield
    finally:
        _TENANT.reset(token)


class AdmissionShedError(errors_lib.TransientError):
    """A request refused by the admission controller (not a failure:
    carries the RESOURCE_EXHAUSTED + retry-after markers, and must never
    count against a study's circuit breaker)."""


def shed_error(
    tenant: str, reason: str, retry_after_ms: float
) -> AdmissionShedError:
    return AdmissionShedError(
        errors_lib.mark_transient(
            f"{errors_lib.RESOURCE_EXHAUSTED_MARKER}: admission shed "
            f"(tenant={tenant}, reason={reason}, "
            f"{errors_lib.RETRY_AFTER_KEY}{retry_after_ms:g})"
        )
    )


def _parse_weights(raw: str) -> Tuple[Tuple[str, float], ...]:
    """``"prod:8,batch:3,dev:1"`` → weight pairs (bad entries skipped)."""
    out = []
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, value = part.rpartition(":")
        try:
            weight = float(value)
        except ValueError:
            continue
        if name and weight > 0:
            out.append((name, weight))
    return tuple(out)


@dataclasses.dataclass(frozen=True)
class AdmissionConfig:
    """Knobs for the overload-protection plane (``VIZIER_ADMISSION*``).

    Off by default: the serving path with ``enabled=False`` is
    bit-identical to the pre-admission tree (no controller object, no
    fair-share reordering, no tenant metric labels).
    """

    enabled: bool = False
    # Concurrent designer computations admitted fleet-wide / per tenant.
    max_inflight: int = 16
    tenant_inflight: int = 8
    # Fair-share weights ((tenant, weight) pairs); unlisted tenants get
    # weight 1.0. Weights drive BOTH the executor's deficit-round-robin
    # quantum and the degraded-mode priority split.
    weights: Tuple[Tuple[str, float], ...] = ()
    # The retry-after hint stamped into shed errors (RetryPolicy backoff
    # floor).
    retry_after_ms: float = 50.0
    # Deadline-aware rejection: shed when remaining deadline < estimated
    # queue wait + compute p50.
    deadline_shed: bool = True
    # Graceful degradation: under sustained saturation, serve tenants
    # with weight < degraded_floor the quasi-random fallback instead of
    # shedding or computing.
    degraded: bool = True
    degraded_floor: float = 1.0
    # State machine: windowed shed rate >= degrade_rate escalates
    # SHEDDING -> DEGRADED; rate <= recover_rate (with in-flight pressure
    # relieved) sustained for window_s de-escalates.
    degrade_rate: float = 0.5
    recover_rate: float = 0.1
    window_s: float = 5.0
    # Minimum windowed decisions before the state machine may escalate.
    min_decisions: int = 10

    def weight(self, tenant: str) -> float:
        for name, weight in self.weights:
            if name == tenant:
                return weight
        return 1.0

    def low_priority(self, tenant: str) -> bool:
        return self.weight(tenant) < self.degraded_floor

    @classmethod
    def from_env(cls) -> "AdmissionConfig":
        return cls(
            enabled=_registry.env_set("VIZIER_ADMISSION"),
            max_inflight=_registry.env_int("VIZIER_ADMISSION_MAX_INFLIGHT", 16),
            tenant_inflight=_registry.env_int(
                "VIZIER_ADMISSION_TENANT_INFLIGHT", 8
            ),
            weights=_parse_weights(
                _registry.env_str("VIZIER_ADMISSION_WEIGHTS")
            ),
            retry_after_ms=_registry.env_float(
                "VIZIER_ADMISSION_RETRY_AFTER_MS", 50.0
            ),
            deadline_shed=_registry.env_on("VIZIER_ADMISSION_DEADLINE"),
            degraded=_registry.env_on("VIZIER_ADMISSION_DEGRADED"),
            degraded_floor=_registry.env_float(
                "VIZIER_ADMISSION_DEGRADED_FLOOR", 1.0
            ),
            degrade_rate=_registry.env_float(
                "VIZIER_ADMISSION_DEGRADE_RATE", 0.5
            ),
            recover_rate=_registry.env_float(
                "VIZIER_ADMISSION_RECOVER_RATE", 0.1
            ),
            window_s=_registry.env_float("VIZIER_ADMISSION_WINDOW_S", 5.0),
        )

    @classmethod
    def disabled(cls) -> "AdmissionConfig":
        return cls(enabled=False)

    def as_dict(self) -> Dict[str, object]:
        out = dataclasses.asdict(self)
        out["weights"] = {name: weight for name, weight in self.weights}
        return out


@dataclasses.dataclass
class Decision:
    """One admission verdict. An ADMIT reserves an in-flight slot that
    the caller must release (use :meth:`AdmissionController.in_flight`)."""

    outcome: str  # ADMIT | SHED | DEGRADE
    tenant: str
    reason: str = ""
    retry_after_ms: float = 0.0
    state: str = HEALTHY

    @property
    def admitted(self) -> bool:
        return self.outcome == ADMIT

    def error(self) -> AdmissionShedError:
        return shed_error(self.tenant, self.reason, self.retry_after_ms)


class AdmissionController:
    """The fleet-wide admission gate + overload state machine.

    Thread model: one leaf lock guards the in-flight counts, the sliding
    decision window, and the state; stats/metric/recorder emissions run
    OUTSIDE it (the lock-order pass's metrics-are-leaves rule), and the
    injected estimate callables (histogram p50, executor queue depth) are
    called before the lock is taken.
    """

    def __init__(
        self,
        config: AdmissionConfig,
        *,
        stats=None,  # serving.stats.ServingStats
        metrics=None,  # observability.metrics.MetricsRegistry
        recorder=None,  # observability.flight_recorder recorder
        compute_p50_fn: Optional[Callable[[], Optional[float]]] = None,
        queue_depth_fn: Optional[Callable[[], int]] = None,
        time_fn: Callable[[], float] = time.monotonic,
    ):
        self.config = config
        self._stats = stats
        self._recorder = recorder
        self._compute_p50 = compute_p50_fn
        self._queue_depth = queue_depth_fn
        self._time = time_fn
        self._lock = threading.Lock()
        self._state = HEALTHY
        self._inflight: Dict[str, int] = {}
        self._inflight_total = 0
        # Sliding decision window: (monotonic time, was_shed) pairs.
        self._window: Deque[Tuple[float, bool]] = collections.deque(
            maxlen=4096
        )
        # Hysteresis anchor: the last instant the recovery condition did
        # NOT hold (recovery requires a full window_s of calm after it).
        self._last_pressure_t = time_fn()
        self._sheds_by_tenant: Dict[str, Dict[str, int]] = {}
        self._degraded_by_tenant: Dict[str, int] = {}
        self._admits_by_tenant: Dict[str, int] = {}
        self._transitions: list = []
        self._decisions_gauge = self._inflight_gauge = self._state_gauge = None
        if metrics is not None:
            self._decisions_gauge = metrics.counter(
                "vizier_admission_decisions",
                help="Admission verdicts by tenant and outcome.",
            )
            self._inflight_gauge = metrics.gauge(
                "vizier_admission_inflight",
                help="Admitted in-flight designer computations per tenant.",
            )
            self._state_gauge = metrics.gauge(
                "vizier_admission_state",
                help="Overload state (0 healthy, 1 shedding, 2 degraded).",
            )
            self._state_gauge.set(0.0)

    # -- introspection -------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self.config.enabled

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def weight(self, tenant: Optional[str]) -> float:
        """The fair-share weight for DRR quanta (None → default 1.0)."""
        if tenant is None:
            return 1.0
        return self.config.weight(tenant)

    def inflight(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._inflight)

    def shed_rate(self) -> float:
        """Windowed shed fraction (0.0 when the window is empty)."""
        now = self._time()
        with self._lock:
            self._trim_window_locked(now)
            if not self._window:
                return 0.0
            return sum(1 for _, shed in self._window if shed) / len(
                self._window
            )

    def snapshot(self) -> Dict[str, object]:
        """The JSON-ready controller state (soak reports, serving_stats)."""
        with self._lock:
            sheds = {
                tenant: dict(reasons)
                for tenant, reasons in sorted(self._sheds_by_tenant.items())
            }
            out = {
                "enabled": self.config.enabled,
                "state": self._state,
                "inflight": dict(sorted(self._inflight.items())),
                "admits_by_tenant": dict(sorted(self._admits_by_tenant.items())),
                "sheds_by_tenant": sheds,
                "degraded_by_tenant": dict(
                    sorted(self._degraded_by_tenant.items())
                ),
                "transitions": list(self._transitions),
            }
        out["shed_rate"] = self.shed_rate()
        out["total_sheds"] = sum(
            count
            for reasons in out["sheds_by_tenant"].values()
            for count in reasons.values()
        )
        return out

    # -- the decision --------------------------------------------------------

    def decide(
        self,
        tenant: str,
        *,
        deadline_secs: float = 0.0,
        study: str = "",
    ) -> Decision:
        """One admission verdict for a live designer computation.

        ``deadline_secs`` is the request's remaining wire budget (0 = no
        deadline, negative = already expired — the deadline layer rejects
        those before admission runs). ADMIT reserves the in-flight slot.
        """
        config = self.config
        # Estimate inputs come from foreign locks (histogram, executor):
        # read them before taking the controller lock.
        wait_estimate = None
        if config.deadline_shed and deadline_secs > 0:
            wait_estimate = self._estimate_wait_secs()
        now = self._time()
        decision: Optional[Decision] = None
        transition = None
        with self._lock:
            self._trim_window_locked(now)
            if (
                config.degraded
                and self._state == DEGRADED
                and config.low_priority(tenant)
            ):
                # Degraded mode: low-priority tenants skip the designer
                # entirely (quasi-random fallback at the caller) so the
                # remaining compute budget serves in-SLO tenants.
                decision = Decision(DEGRADE, tenant, state=self._state)
                self._degraded_by_tenant[tenant] = (
                    self._degraded_by_tenant.get(tenant, 0) + 1
                )
            elif (
                wait_estimate is not None
                and deadline_secs > 0
                and wait_estimate > deadline_secs
            ):
                decision = self._shed_locked(tenant, REASON_DEADLINE, now)
            elif self._inflight_total >= max(1, config.max_inflight):
                decision = self._shed_locked(tenant, REASON_TOTAL, now)
            elif self._inflight.get(tenant, 0) >= max(
                1, config.tenant_inflight
            ):
                decision = self._shed_locked(tenant, REASON_TENANT, now)
            else:
                self._inflight[tenant] = self._inflight.get(tenant, 0) + 1
                self._inflight_total += 1
                self._admits_by_tenant[tenant] = (
                    self._admits_by_tenant.get(tenant, 0) + 1
                )
                self._window.append((now, False))
                decision = Decision(ADMIT, tenant, state=self._state)
            transition = self._advance_state_locked(now)
        self._emit(decision, study, transition)
        return decision

    def release(self, decision: Decision) -> None:
        """Returns an ADMIT's in-flight slot (idempotence is the caller's
        job — use :meth:`in_flight`)."""
        if not decision.admitted:
            return
        with self._lock:
            remaining = self._inflight.get(decision.tenant, 0) - 1
            if remaining > 0:
                self._inflight[decision.tenant] = remaining
            else:
                self._inflight.pop(decision.tenant, None)
            self._inflight_total = max(0, self._inflight_total - 1)
        if self._inflight_gauge is not None:
            self._inflight_gauge.set(max(0, remaining), tenant=decision.tenant)

    @contextlib.contextmanager
    def in_flight(self, decision: Decision):
        """Holds the admitted slot for the compute's duration and exposes
        the tenant to the batch executor via the contextvar."""
        try:
            with tenant_scope(decision.tenant):
                yield decision
        finally:
            self.release(decision)

    # -- internals -----------------------------------------------------------

    def _estimate_wait_secs(self) -> Optional[float]:
        """Expected queue wait + compute time for a new live computation.

        ``compute_p50`` comes from the pythia-hop latency histogram;
        queued-ahead work adds one compute per expected flush the request
        must wait behind. None (no latency data yet) disables the
        deadline shed — conservative by construction.
        """
        p50 = self._compute_p50() if self._compute_p50 is not None else None
        if p50 is None or p50 <= 0:
            return None
        queued = self._queue_depth() if self._queue_depth is not None else 0
        # Queued live slots drain in flush-sized groups; each group ahead
        # costs roughly one compute p50.
        flushes_ahead = 1.0 + float(max(0, queued)) / 8.0
        return p50 * flushes_ahead

    def _shed_locked(self, tenant: str, reason: str, now: float) -> Decision:
        self._window.append((now, True))
        self._last_pressure_t = now
        per_tenant = self._sheds_by_tenant.setdefault(tenant, {})
        per_tenant[reason] = per_tenant.get(reason, 0) + 1
        return Decision(
            SHED,
            tenant,
            reason=reason,
            retry_after_ms=self.config.retry_after_ms,
            state=self._state,
        )

    def _trim_window_locked(self, now: float) -> None:
        horizon = now - max(0.1, self.config.window_s)
        while self._window and self._window[0][0] < horizon:
            self._window.popleft()

    def _advance_state_locked(self, now: float):
        """The healthy → shedding → degraded automaton; returns the
        ``(old, new)`` transition or None."""
        config = self.config
        total = len(self._window)
        sheds = sum(1 for _, shed in self._window if shed)
        rate = sheds / total if total else 0.0
        pressured = self._inflight_total >= max(1, config.max_inflight)
        if rate > config.recover_rate or pressured:
            self._last_pressure_t = now
        calm_for = now - self._last_pressure_t
        old = self._state
        if old == HEALTHY:
            if sheds > 0:
                self._state = SHEDDING
        elif old == SHEDDING:
            if (
                config.degraded
                and total >= config.min_decisions
                and rate >= config.degrade_rate
            ):
                self._state = DEGRADED
            elif sheds == 0 and calm_for >= config.window_s:
                self._state = HEALTHY
        elif old == DEGRADED:
            if rate <= config.recover_rate and calm_for >= config.window_s:
                self._state = SHEDDING
        if self._state != old:
            self._last_pressure_t = now  # re-arm hysteresis on every move
            self._transitions.append(
                {"from": old, "to": self._state, "shed_rate": round(rate, 4)}
            )
            return (old, self._state)
        return None

    def _emit(self, decision: Decision, study: str, transition) -> None:
        """Stats/metrics/recorder updates, outside the controller lock."""
        stats = self._stats
        if stats is not None:
            if decision.outcome == SHED:
                stats.increment("admission_sheds")
                if decision.reason == REASON_DEADLINE:
                    stats.increment("admission_deadline_sheds")
            elif decision.outcome == DEGRADE:
                stats.increment("admission_degraded")
            if transition is not None:
                stats.increment("admission_transitions")
        if self._decisions_gauge is not None:
            self._decisions_gauge.inc(
                tenant=decision.tenant,
                outcome=(
                    f"shed_{decision.reason}"
                    if decision.outcome == SHED
                    else decision.outcome
                ),
            )
        if self._inflight_gauge is not None and decision.admitted:
            with self._lock:
                current = self._inflight.get(decision.tenant, 0)
            self._inflight_gauge.set(current, tenant=decision.tenant)
        if self._state_gauge is not None and transition is not None:
            self._state_gauge.set(float(_STATE_LEVEL[transition[1]]))
        recorder = self._recorder
        if recorder is not None and getattr(recorder, "enabled", False):
            if decision.outcome == SHED:
                recorder.record(
                    study or None,
                    "admission_shed",
                    tenant=decision.tenant,
                    reason=decision.reason,
                    retry_after_ms=decision.retry_after_ms,
                    state=decision.state,
                )
            elif decision.outcome == DEGRADE:
                recorder.record(
                    study or None,
                    "admission_degraded",
                    tenant=decision.tenant,
                )
            if transition is not None:
                recorder.record(
                    None,
                    "admission_state",
                    old=transition[0],
                    new=transition[1],
                )
