"""The serving policy: cached designer + incremental updates + warm ARD.

Stateless per-request object over shared state: the policy itself is
rebuilt per Pythia request (cheap), while the designer, its trained ARD
params, and the incorporated-trial-id set live in the process-wide
:class:`~vizier_tpu.serving.designer_cache.DesignerStateCache`. Contrast
with ``algorithms.designer_policy.DesignerPolicy`` (fresh designer + full
trial replay per request — the reference shape) and
``InRamDesignerPolicy`` (lives only as long as the policy object the
Pythia servicer happens to cache, no TTL/LRU/invalidation).
"""

from __future__ import annotations

import logging
from typing import Any, Callable, List, Optional, Sequence

from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.algorithms import designer_policy
from vizier_tpu.observability import flight_recorder as recorder_lib
from vizier_tpu.observability import tracing as tracing_lib
from vizier_tpu.pythia import policy as policy_lib
from vizier_tpu.pythia import policy_supporter as supporter_lib
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import trial as trial_
from vizier_tpu.serving import designer_cache as cache_lib
from vizier_tpu.serving import runtime as runtime_lib
from vizier_tpu.serving import speculative as speculative_lib
from vizier_tpu.surrogates import config as surrogate_config_lib

_logger = logging.getLogger(__name__)


class CachedDesignerStatePolicy(policy_lib.Policy):
    """Routes suggests through the shared per-study designer cache."""

    def __init__(
        self,
        supporter: supporter_lib.PolicySupporter,
        designer_factory: Callable[[base_study_config.ProblemStatement], Any],
        runtime: runtime_lib.ServingRuntime,
        study_name: str,
        *,
        use_seeding: bool = False,
    ):
        self._supporter = supporter
        self._designer_factory = designer_factory
        self._runtime = runtime
        self._study_name = study_name
        self._use_seeding = use_seeding

    def suggest(self, request: policy_lib.SuggestRequest) -> policy_lib.SuggestDecision:
        if self._use_seeding and request.max_trial_id == 0:
            seed = designer_policy.default_suggestion(
                request.study_config.to_problem()
            )
            rest: Sequence[trial_.TrialSuggestion] = []
            if request.count > 1:
                rest = self._run_designer(request, request.count - 1)
            return policy_lib.SuggestDecision(suggestions=[seed] + list(rest))
        return policy_lib.SuggestDecision(
            suggestions=list(self._run_designer(request, request.count))
        )

    def _run_designer(
        self, request: policy_lib.SuggestRequest, count: int
    ) -> List[trial_.TrialSuggestion]:
        problem = request.study_config.to_problem()
        cache = self._runtime.designer_cache
        entry = cache.get_or_create(
            self._study_name, lambda: self._designer_factory(problem)
        )
        # Surrogate-crossover invalidation hook: a parked speculative batch
        # predates the crossover's warm/posterior reset, so the designer
        # reports the flip straight into the engine the moment it happens
        # (mid-compute), not after the policy's post-hoc stats diff.
        if self._runtime.speculative_engine is not None:
            surrogate_config_lib.install_crossover_listener(
                entry.designer, self._on_surrogate_crossover
            )
        with entry.lock:
            try:
                return self._update_and_suggest(entry, count)
            except Exception:
                # A designer whose live state went bad (e.g. an update that
                # died halfway) must not poison every later suggest for the
                # study: drop the entry so the next request rebuilds from a
                # clean full replay, then surface this request's error.
                cache.invalidate(self._study_name)
                _logger.warning(
                    "Serving designer for %s failed; cache entry invalidated.",
                    self._study_name,
                )
                raise

    def _update_and_suggest(
        self, entry: cache_lib.CachedDesignerEntry, count: int
    ) -> List[trial_.TrialSuggestion]:
        designer = entry.designer
        tracer = tracing_lib.get_tracer()
        completed = self._supporter.GetTrials(
            status_matches=trial_.TrialStatus.COMPLETED
        )
        new_completed = [
            t for t in completed if t.id not in entry.incorporated_trial_ids
        ]
        active = self._supporter.GetTrials(status_matches=trial_.TrialStatus.ACTIVE)
        before = self._train_counts(designer)
        surrogate_before = self._surrogate_counts(designer)
        with tracer.span(
            "designer.update",
            designer=type(designer).__name__,
            new_completed=len(new_completed),
            incremental=True,
        ):
            designer.update(
                core_lib.CompletedTrials(new_completed),
                core_lib.ActiveTrials(active),
            )
        entry.incorporated_trial_ids.update(t.id for t in new_completed)
        with tracer.span(
            "designer.suggest",
            designer=type(designer).__name__,
            count=count,
        ):
            # Cross-study batching: concurrent same-bucket computations from
            # different studies share one vmapped device program. The
            # executor runs unbatchable paths (and batching off) inline —
            # the exact per-study call below.
            executor = getattr(self._runtime, "batch_executor", None)
            if executor is not None:
                # A speculative job's compute rides the low-priority lane:
                # it shares vmapped flush buckets with live traffic when
                # one is already forming, but never delays a live flush.
                suggestions = list(
                    executor.suggest(
                        designer,
                        count,
                        speculative=speculative_lib.in_speculative_compute(),
                    )
                )
            else:
                suggestions = list(designer.suggest(count))
        self._account_trains(before, self._train_counts(designer))
        self._account_surrogate(
            surrogate_before, self._surrogate_counts(designer)
        )
        # Mirror the trained unconstrained ARD params into the entry: the
        # stats/inspection surface for "what would seed the next train",
        # and the hand-off if the designer is ever rebuilt around them.
        get_state = getattr(designer, "warm_start_state", None)
        if get_state is not None:
            entry.warm_params = get_state()
        # Scalable-surrogate mirrors: the active mode and the cached
        # inducing-point state (None on the exact path — a crossover back
        # to exact clears it here too, so no stale sparse state lingers).
        entry.surrogate_mode = getattr(designer, "surrogate_mode", None)
        get_sparse = getattr(designer, "sparse_inducing_state", None)
        entry.sparse_state = get_sparse() if get_sparse is not None else None
        entry.num_suggests += 1
        return suggestions

    def _on_surrogate_crossover(self, old_mode: str, new_mode: str) -> None:
        """The designer's exact↔sparse flip invalidates the parked batch."""
        self._runtime.speculative_invalidate(
            self._study_name, reason=f"crossover:{old_mode}->{new_mode}"
        )

    @staticmethod
    def _train_counts(designer: Any) -> Optional[dict]:
        counts = getattr(designer, "ard_train_counts", None)
        return dict(counts) if counts is not None else None

    @staticmethod
    def _surrogate_counts(designer: Any) -> Optional[dict]:
        counts = getattr(designer, "surrogate_counts", None)
        return dict(counts) if counts is not None else None

    def _account_surrogate(
        self, before: Optional[dict], after: Optional[dict]
    ) -> None:
        if before is None or after is None:
            return
        stats = self._runtime.stats
        sparse = after.get("sparse_suggests", 0) - before.get("sparse_suggests", 0)
        crossed = after.get("crossovers", 0) - before.get("crossovers", 0)
        if sparse > 0:
            stats.increment("sparse_suggests", sparse)
        if crossed > 0:
            stats.increment("surrogate_crossovers", crossed)
            recorder_lib.get_recorder().record(
                self._study_name, "surrogate_crossover", count=crossed,
                mode=after.get("mode"),
            )

    def _account_trains(self, before: Optional[dict], after: Optional[dict]) -> None:
        if before is None or after is None:
            return
        stats = self._runtime.stats
        warm = after.get("warm", 0) - before.get("warm", 0)
        cold = after.get("cold", 0) - before.get("cold", 0)
        if warm > 0:
            stats.increment("warm_trains", warm)
        if cold > 0:
            stats.increment("cold_trains", cold)
