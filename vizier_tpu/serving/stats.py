"""Serving-level counters: cache, warm/cold ARD trains, coalescing.

Backed by the :mod:`vizier_tpu.observability` metrics registry (one
``Counter`` per field, prefixed ``vizier_serving_``) so the serving
vocabulary shows up in the same Prometheus dump as the latency histograms,
while keeping the original ``FIELDS``/``increment``/``snapshot``/``reset``
API — counters are core serving behavior and stay on even with
``VIZIER_OBSERVABILITY=0``.

Thread safety: the field→counter map is built once in ``__init__`` and
never mutated, so the vocabulary membership check is race-free by
construction (no lock needed to read an immutable dict); each counter
serializes its own increments.
"""

from __future__ import annotations

from typing import Dict, Optional

from vizier_tpu.observability import metrics as metrics_lib


class ServingStats:
    """Thread-safe monotonic counters with a dict snapshot API."""

    # The fixed counter vocabulary: a typo'd increment should fail loudly
    # rather than mint a new counter nobody reads.
    FIELDS = (
        "cache_hits",
        "cache_misses",
        "cache_evictions_ttl",
        "cache_evictions_lru",
        "cache_invalidations",
        # Config-hash turnover drops (shared compute tier: a frontend's
        # delete/recreate detected via the request's StudySpec hash).
        "cache_invalidations_config",
        "coalesced_requests",  # followers served from a shared computation
        "coalesced_computations",  # leader runs that had >= 1 follower
        "warm_trains",
        "cold_trains",
        # Reliability (vizier_tpu.reliability): retry/fallback/breaker/deadline.
        "retries",  # client-side RPC / suggest retries
        "designer_failures",  # designer computations that raised
        "fallbacks",  # suggestions served by the quasi-random fallback
        "breaker_open_transitions",
        "breaker_half_open_transitions",
        "breaker_close_transitions",
        "breaker_short_circuits",  # suggests skipped because a circuit was open
        "deadline_exceeded",  # ops completed with TRANSIENT: DEADLINE_EXCEEDED
        # Multi-tenant overload protection (vizier_tpu.serving.admission).
        "admission_sheds",  # requests shed with TRANSIENT: RESOURCE_EXHAUSTED
        "admission_deadline_sheds",  # sheds because the deadline was infeasible
        "admission_degraded",  # degraded-mode quasi-random serves
        "admission_transitions",  # overload state-machine transitions
        # Cross-study batching (vizier_tpu.parallel.batch_executor).
        "batch_flushes",  # bucket flushes (full / timeout / drain)
        "batched_suggests",  # slots served from a shared vmapped program
        "batch_fallbacks",  # slots rerun sequentially after a batch failure
        "batch_slot_errors",  # slot-isolated prepare/finalize/NaN failures
        "mesh_flushes",  # flushes executed on a mesh placement worker
        # Scalable surrogates (vizier_tpu.surrogates).
        "sparse_suggests",  # suggests served by the sparse-GP posterior
        "surrogate_crossovers",  # exact<->sparse auto-switch transitions
        # Speculative pre-compute (vizier_tpu.serving.speculative).
        "speculative_hits",  # suggests served from a parked pre-computed batch
        "speculative_misses",  # slot empty / frontier moved / count mismatch
        "speculative_stale",  # slots expired by max_speculation_age_s
        "speculative_cancelled",  # jobs superseded / dropped busy / shutdown
        "speculative_precomputes",  # speculative designer computations run
        "speculative_errors",  # speculative failures swallowed off-path
        "speculative_rearms",  # pre-computes re-armed by replica failover
    )

    def __init__(self, registry: Optional[metrics_lib.MetricsRegistry] = None):
        # A private registry by default so each stats object starts from
        # zero; the serving runtime passes its shared registry so the
        # counters land in the same Prometheus dump as the histograms.
        self._registry = registry or metrics_lib.MetricsRegistry()
        self._counters = {
            f: self._registry.counter(
                f"vizier_serving_{f}", help=f"Serving counter: {f}."
            )
            for f in self.FIELDS
        }

    @property
    def registry(self) -> metrics_lib.MetricsRegistry:
        """The backing registry (histogram co-location, Prometheus dump)."""
        return self._registry

    def increment(self, field: str, amount: int = 1) -> None:
        counter = self._counters.get(field)
        if counter is None:
            raise KeyError(f"Unknown serving counter: {field!r}")
        counter.inc(amount)

    def get(self, field: str) -> int:
        return int(self._counters[field].value())

    def snapshot(self) -> Dict[str, int]:
        """A point-in-time copy of every counter."""
        return {f: int(c.value()) for f, c in self._counters.items()}

    def reset(self) -> None:
        for counter in self._counters.values():
            counter.reset()
