"""Serving-level counters: cache, warm/cold ARD trains, coalescing.

One process-wide mutex guards all counters; increments happen on the
suggest control path (microseconds against a multi-ms designer run), so a
finer-grained scheme buys nothing.
"""

from __future__ import annotations

import threading
from typing import Dict


class ServingStats:
    """Thread-safe monotonic counters with a dict snapshot API."""

    # The fixed counter vocabulary: a typo'd increment should fail loudly
    # rather than mint a new counter nobody reads.
    FIELDS = (
        "cache_hits",
        "cache_misses",
        "cache_evictions_ttl",
        "cache_evictions_lru",
        "cache_invalidations",
        "coalesced_requests",  # followers served from a shared computation
        "coalesced_computations",  # leader runs that had >= 1 follower
        "warm_trains",
        "cold_trains",
        # Reliability (vizier_tpu.reliability): retry/fallback/breaker/deadline.
        "retries",  # client-side RPC / suggest retries
        "designer_failures",  # designer computations that raised
        "fallbacks",  # suggestions served by the quasi-random fallback
        "breaker_open_transitions",
        "breaker_half_open_transitions",
        "breaker_close_transitions",
        "breaker_short_circuits",  # suggests skipped because a circuit was open
        "deadline_exceeded",  # ops completed with TRANSIENT: DEADLINE_EXCEEDED
    )

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {f: 0 for f in self.FIELDS}

    def increment(self, field: str, amount: int = 1) -> None:
        if field not in self._counts:
            raise KeyError(f"Unknown serving counter: {field!r}")
        with self._lock:
            self._counts[field] += amount

    def get(self, field: str) -> int:
        with self._lock:
            return self._counts[field]

    def snapshot(self) -> Dict[str, int]:
        """A point-in-time copy of every counter."""
        with self._lock:
            return dict(self._counts)

    def reset(self) -> None:
        with self._lock:
            for f in self._counts:
                self._counts[f] = 0
