"""Serving runtime knobs.

Everything defaults ON; each knob can be forced off per-process via the
environment (useful for A/B runs and for restoring the reference's
cold-train-per-request behavior without code changes):

- ``VIZIER_SERVING_CACHE=0``      — no designer-state cache (stateless
  ``DesignerPolicy`` per request, the reference shape);
- ``VIZIER_SERVING_WARM_START=0`` — cache designers but cold-train ARD on
  every suggest (full restart budget from random inits);
- ``VIZIER_SERVING_COALESCING=0`` — every Pythia suggest computes its own
  designer run;
- ``VIZIER_BATCHING=0``           — no cross-study batch executor: every
  study's computation dispatches alone (today's per-study path,
  bit-identical suggestions);
- ``VIZIER_BATCHING_PREWARM=1``   — AOT-compile the batched programs over
  the padding-bucket grid when the first study of a shape arrives
  (default off: prewarm is explicit via ``ServingRuntime.prewarm_batching``).
- ``VIZIER_COMPILE_CACHE_DIR=/path`` — persist XLA compilations across
  process restarts (``jax_compilation_cache_dir``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

# All VIZIER_* switches are declared in (and read through) the central
# registry; an undeclared name raises instead of silently reading an
# always-unset variable. Enforced by the env_registry analysis pass.
from vizier_tpu.analysis import registry as _registry


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Knobs for the stateful serving runtime."""

    # Keep live designers + trained ARD params per study.
    designer_cache: bool = True
    # Inject the previous suggest's trained params as restart seed 0 and
    # shrink the restart budget to ``warm_ard_restarts``.
    warm_start: bool = True
    # Collapse concurrent identical Pythia suggest computations.
    coalescing: bool = True
    # Cache sizing: LRU beyond max_entries, TTL on idle entries.
    cache_max_entries: int = 64
    cache_ttl_seconds: float = 3600.0
    # Restart budget for a warm-started ARD train (cold trains keep the
    # designer's full ``ard_restarts``). The A/B evidence for 1 restart is
    # WARM_START_AB.json (latency + regret parity).
    warm_ard_restarts: int = 1

    # -- cross-study batching (vizier_tpu.parallel.batch_executor) ----------
    # Collect concurrent designer computations from different studies into
    # shape-bucket queues and run each bucket as ONE vmapped device program.
    # The A/B evidence is BATCHING_AB.json (tools/batching_ab.py).
    batching: bool = True
    # Flush a bucket at this many studies ("full") ...
    batch_max_size: int = 8
    # ... or when its oldest request has waited this long ("timeout"), so
    # single-study latency is bounded by the micro-batch window.
    batch_max_wait_ms: float = 4.0
    # Pad partial batches to batch_max_size with masked copies of slot 0:
    # one compiled program shape per bucket regardless of occupancy.
    batch_pad_partial: bool = True
    # AOT-compile the batched programs over the padding-bucket grid when
    # the first study of a shape arrives (background thread). Explicit
    # prewarm via ServingRuntime.prewarm_batching works either way.
    batching_prewarm: bool = False
    # The padding-grid ceiling the prewarm walks (study sizes 1..N).
    batching_prewarm_max_trials: int = 32

    # JAX persistent compilation cache directory (applied at runtime init
    # via ``jax_compilation_cache_dir``); None leaves jax's default alone.
    compilation_cache_dir: Optional[str] = None

    @classmethod
    def from_env(cls) -> "ServingConfig":
        """The default config with per-knob environment overrides applied."""
        return cls(
            designer_cache=_registry.env_on("VIZIER_SERVING_CACHE"),
            warm_start=_registry.env_on("VIZIER_SERVING_WARM_START"),
            coalescing=_registry.env_on("VIZIER_SERVING_COALESCING"),
            batching=_registry.env_on("VIZIER_BATCHING"),
            batch_max_size=_registry.env_int("VIZIER_BATCH_MAX_SIZE", 8),
            batch_max_wait_ms=_registry.env_float("VIZIER_BATCH_MAX_WAIT_MS", 4.0),
            batching_prewarm=_registry.env_on("VIZIER_BATCHING_PREWARM"),
            compilation_cache_dir=(
                _registry.env_str("VIZIER_COMPILE_CACHE_DIR") or None
            ),
        )

    @classmethod
    def disabled(cls) -> "ServingConfig":
        """Reference behavior: stateless, cold, uncoalesced, unbatched."""
        return cls(
            designer_cache=False,
            warm_start=False,
            coalescing=False,
            batching=False,
        )
