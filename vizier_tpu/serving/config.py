"""Serving runtime knobs.

Everything defaults ON; each knob can be forced off per-process via the
environment (useful for A/B runs and for restoring the reference's
cold-train-per-request behavior without code changes):

- ``VIZIER_SERVING_CACHE=0``      — no designer-state cache (stateless
  ``DesignerPolicy`` per request, the reference shape);
- ``VIZIER_SERVING_WARM_START=0`` — cache designers but cold-train ARD on
  every suggest (full restart budget from random inits);
- ``VIZIER_SERVING_COALESCING=0`` — every Pythia suggest computes its own
  designer run.
"""

from __future__ import annotations

import dataclasses
import os


def _env_on(name: str) -> bool:
    return os.environ.get(name, "1") not in ("0", "false", "False", "")


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Knobs for the stateful serving runtime."""

    # Keep live designers + trained ARD params per study.
    designer_cache: bool = True
    # Inject the previous suggest's trained params as restart seed 0 and
    # shrink the restart budget to ``warm_ard_restarts``.
    warm_start: bool = True
    # Collapse concurrent identical Pythia suggest computations.
    coalescing: bool = True
    # Cache sizing: LRU beyond max_entries, TTL on idle entries.
    cache_max_entries: int = 64
    cache_ttl_seconds: float = 3600.0
    # Restart budget for a warm-started ARD train (cold trains keep the
    # designer's full ``ard_restarts``). The A/B evidence for 1 restart is
    # WARM_START_AB.json (latency + regret parity).
    warm_ard_restarts: int = 1

    @classmethod
    def from_env(cls) -> "ServingConfig":
        """The default config with per-knob environment overrides applied."""
        return cls(
            designer_cache=_env_on("VIZIER_SERVING_CACHE"),
            warm_start=_env_on("VIZIER_SERVING_WARM_START"),
            coalescing=_env_on("VIZIER_SERVING_COALESCING"),
        )

    @classmethod
    def disabled(cls) -> "ServingConfig":
        """Reference behavior: stateless, cold, uncoalesced."""
        return cls(designer_cache=False, warm_start=False, coalescing=False)
