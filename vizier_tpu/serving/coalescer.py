"""Request coalescing: concurrent identical computations share one run.

The service already deduplicates at the *operation* level (an unfinished
op for the same client is returned as-is); this lifts deduplication to the
*compute* level: N concurrent suggest computations for the same study
state run ONE designer computation, and the result is fanned back out to
every waiter.

Correctness hinges on the key: callers must include everything the
computation depends on (study name, algorithm, ``max_trial_id``, count) so
only requests that would produce an identical answer coalesce. A request
arriving after the leader finished starts a fresh computation — results
are never cached beyond the in-flight window, only shared within it.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Hashable, Optional, Tuple, TypeVar

from vizier_tpu.serving import stats as stats_lib

T = TypeVar("T")


class _Inflight:
    def __init__(self):
        self.done = threading.Event()
        self.result: Any = None
        self.error: Optional[BaseException] = None
        self.followers = 0


class RequestCoalescer:
    """Collapses concurrent calls with equal keys onto one computation."""

    def __init__(self, stats: Optional[stats_lib.ServingStats] = None):
        self._stats = stats or stats_lib.ServingStats()
        self._lock = threading.Lock()
        self._inflight: Dict[Hashable, _Inflight] = {}

    def coalesce(
        self,
        key: Hashable,
        compute: Callable[[], T],
        clone: Optional[Callable[[T], T]] = None,
    ) -> T:
        """Runs ``compute`` once per concurrent key; fans the result out.

        The first caller for a key becomes the leader and runs ``compute``;
        callers arriving while it is in flight block until it finishes and
        receive the same result (``clone`` applied for followers when the
        result is mutable — proto responses must not be shared across
        servicer threads). A leader's exception propagates to every waiter.
        """
        with self._lock:
            entry = self._inflight.get(key)
            if entry is not None:
                entry.followers += 1
                leader = False
            else:
                entry = _Inflight()
                self._inflight[key] = entry
                leader = True
        if not leader:
            entry.done.wait()
            self._stats.increment("coalesced_requests")
            if entry.error is not None:
                raise entry.error
            return clone(entry.result) if clone is not None else entry.result
        try:
            entry.result = compute()
        except BaseException as e:
            entry.error = e
            raise
        finally:
            # Unregister BEFORE waking waiters: a new request arriving after
            # the computation finished must start fresh, not adopt a result
            # computed against stale study state.
            with self._lock:
                del self._inflight[key]
                if entry.followers:
                    self._stats.increment("coalesced_computations")
            entry.done.set()
        return entry.result

    def inflight_keys(self) -> Tuple[Hashable, ...]:
        with self._lock:
            return tuple(self._inflight)
