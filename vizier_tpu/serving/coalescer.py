"""Request coalescing: concurrent identical computations share one run.

The service already deduplicates at the *operation* level (an unfinished
op for the same client is returned as-is); this lifts deduplication to the
*compute* level: N concurrent suggest computations for the same study
state run ONE designer computation, and the result is fanned back out to
every waiter.

Correctness hinges on the key: callers must include everything the
computation depends on (study name, algorithm, ``max_trial_id``, count) so
only requests that would produce an identical answer coalesce. A request
arriving after the leader finished starts a fresh computation — results
are never cached beyond the in-flight window, only shared within it.

Observability: leader compute time and follower wait time land in the
``vizier_coalescer_wait_seconds{role=...}`` histogram; with tracing on, a
``span_name`` wraps the leader's computation in its own span and each
follower's active span links to it (so a coalesced trace shows *which*
computation actually served it).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Hashable, Optional, Tuple, TypeVar

from vizier_tpu.observability import tracing as tracing_lib
from vizier_tpu.serving import stats as stats_lib

T = TypeVar("T")


class _Inflight:
    def __init__(self):
        self.done = threading.Event()
        self.result: Any = None
        self.error: Optional[BaseException] = None
        self.followers = 0
        # The leader's computation span context: followers link to it.
        self.leader_ctx: Optional[tracing_lib.SpanContext] = None


class RequestCoalescer:
    """Collapses concurrent calls with equal keys onto one computation."""

    def __init__(
        self,
        stats: Optional[stats_lib.ServingStats] = None,
        observe_latency: bool = True,
    ):
        self._stats = stats or stats_lib.ServingStats()
        self._lock = threading.Lock()
        self._inflight: Dict[Hashable, _Inflight] = {}
        registry = getattr(self._stats, "registry", None)
        self._wait_hist = (
            registry.histogram(
                "vizier_coalescer_wait_seconds",
                help="Coalescer wall time: role=leader is the shared "
                "computation, role=follower the wait for it.",
            )
            if observe_latency and registry is not None
            else None
        )

    def _observe(self, role: str, t0: float) -> None:
        if self._wait_hist is not None:
            self._wait_hist.observe(time.perf_counter() - t0, role=role)

    def coalesce(
        self,
        key: Hashable,
        compute: Callable[[], T],
        clone: Optional[Callable[[T], T]] = None,
        span_name: str = "",
    ) -> T:
        """Runs ``compute`` once per concurrent key; fans the result out.

        The first caller for a key becomes the leader and runs ``compute``;
        callers arriving while it is in flight block until it finishes and
        receive the same result (``clone`` applied for followers when the
        result is mutable — proto responses must not be shared across
        servicer threads). A leader's exception propagates to every waiter.
        """
        with self._lock:
            entry = self._inflight.get(key)
            if entry is not None:
                entry.followers += 1
                leader = False
            else:
                entry = _Inflight()
                self._inflight[key] = entry
                leader = True
        t0 = time.perf_counter()
        if not leader:
            entry.done.wait()
            self._observe("follower", t0)
            self._stats.increment("coalesced_requests")
            # Link the follower's active span (its own pythia.suggest) to
            # the computation that actually produced its answer.
            span = tracing_lib.get_tracer().current_span()
            if span is not None and entry.leader_ctx is not None:
                span.add_link(entry.leader_ctx, name="coalesced_leader")
                span.set_attribute("coalesced", True)
            if entry.error is not None:
                raise entry.error
            return clone(entry.result) if clone is not None else entry.result
        try:
            tracer = tracing_lib.get_tracer()
            if span_name and tracer.enabled:
                with tracer.span(span_name, coalescer_leader=True) as span:
                    entry.leader_ctx = span.context()
                    entry.result = compute()
            else:
                entry.result = compute()
        except BaseException as e:
            entry.error = e
            raise
        finally:
            self._observe("leader", t0)
            # Unregister BEFORE waking waiters: a new request arriving after
            # the computation finished must start fresh, not adopt a result
            # computed against stale study state. The counter update runs
            # outside the map lock (it takes the metrics lock; this mutex
            # stays a leaf of the serving lock graph).
            with self._lock:
                del self._inflight[key]
                had_followers = bool(entry.followers)
            if had_followers:
                self._stats.increment("coalesced_computations")
            entry.done.set()
        return entry.result

    def inflight_keys(self) -> Tuple[Hashable, ...]:
        with self._lock:
            return tuple(self._inflight)
