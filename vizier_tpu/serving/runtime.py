"""ServingRuntime: one object bundling cache + coalescer + stats + config.

The Pythia servicer owns one runtime per process; the policy factory and
the serving policy share it so every counter lands in one place and
``DeleteStudy`` invalidation reaches the real cache. The reliability layer
(per-study circuit breakers + its config) lives here too, so breaker
transitions land in the same stats sink and study invalidation drops the
breaker along with the designer state. The observability layer hangs off
the same object: one metrics registry backs the serving counters AND the
latency histograms (cache lookups, coalescer waits, per-hop suggest
latency), all dumped together by :meth:`prometheus_text`.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

from vizier_tpu.observability import config as obs_config_lib
from vizier_tpu.observability import flight_recorder as recorder_lib
from vizier_tpu.observability import metrics as metrics_lib
from vizier_tpu.observability import slo as slo_lib
from vizier_tpu.reliability import breaker as breaker_lib
from vizier_tpu.reliability import config as reliability_config_lib
from vizier_tpu.serving import admission as admission_lib
from vizier_tpu.serving import coalescer as coalescer_lib
from vizier_tpu.serving import config as config_lib
from vizier_tpu.serving import designer_cache as cache_lib
from vizier_tpu.serving import speculative as speculative_lib
from vizier_tpu.serving import stats as stats_lib
from vizier_tpu.surrogates import config as surrogate_config_lib

_logger = logging.getLogger(__name__)


def _apply_compilation_cache(cache_dir: str) -> bool:
    """Points jax's persistent compilation cache at ``cache_dir``.

    Best-effort: an older jax without the option must not take serving
    down. The min-compile-time floor is dropped to 0 so the small per-bucket
    GP programs (often < 1s compiles on CPU) are cached too.
    """
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        try:
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        except Exception:  # option renamed/missing: dir alone still helps
            pass
        return True
    except Exception:
        _logger.warning(
            "Could not enable the JAX compilation cache at %r.", cache_dir
        )
        return False


class ServingRuntime:
    """Shared serving state for one Pythia servicer."""

    def __init__(
        self,
        config: Optional[config_lib.ServingConfig] = None,
        stats: Optional[stats_lib.ServingStats] = None,
        reliability: Optional[reliability_config_lib.ReliabilityConfig] = None,
        observability: Optional[obs_config_lib.ObservabilityConfig] = None,
        surrogates: Optional[surrogate_config_lib.SurrogateConfig] = None,
        speculative: Optional[speculative_lib.SpeculativeConfig] = None,
        mesh: Optional[Any] = None,  # parallel.mesh.MeshConfig
        slo: Optional[slo_lib.SloConfig] = None,
        admission: Optional[admission_lib.AdmissionConfig] = None,
    ):
        self.config = config or config_lib.ServingConfig.from_env()
        self.observability = (
            observability or obs_config_lib.ObservabilityConfig.from_env()
        )
        # Scalable-surrogate auto-switch (vizier_tpu.surrogates): threaded
        # into every GP designer the policy factory builds, so the whole
        # serving tier shares one exact↔sparse policy. VIZIER_SPARSE=0
        # keeps every study on the exact path (the seed behavior).
        self.surrogates = (
            surrogates or surrogate_config_lib.SurrogateConfig.from_env()
        )
        self.stats = stats or stats_lib.ServingStats()
        # One registry for this runtime's whole metric surface. A caller
        # passing pre-existing stats brings its registry along so counters
        # and histograms still land in one dump.
        self.metrics: metrics_lib.MetricsRegistry = self.stats.registry
        self.reliability = (
            reliability or reliability_config_lib.ReliabilityConfig.from_env()
        )
        self.designer_cache = cache_lib.DesignerStateCache(
            max_entries=self.config.cache_max_entries,
            ttl_seconds=self.config.cache_ttl_seconds,
            stats=self.stats,
            observe_latency=self.observability.metrics_on,
        )
        self.coalescer = coalescer_lib.RequestCoalescer(
            stats=self.stats,
            observe_latency=self.observability.metrics_on,
        )
        self.breakers = breaker_lib.CircuitBreakerRegistry(
            failure_threshold=self.reliability.breaker_failure_threshold,
            window_secs=self.reliability.breaker_window_secs,
            cooldown_secs=self.reliability.breaker_cooldown_secs,
            half_open_probes=self.reliability.breaker_half_open_probes,
            stats=self.stats,
        )
        self._suggest_latency = self.metrics.histogram(
            "vizier_suggest_latency_seconds",
            help="SuggestTrials wall time per hop (service, pythia).",
        )
        # Multi-tenant overload protection (vizier_tpu.serving.admission):
        # bounded in-flight admission + deadline-aware shedding + the
        # healthy→shedding→degraded state machine at the Pythia dispatch
        # boundary, and the weighted fair-share plane inside the batch
        # executor. Off by default (VIZIER_ADMISSION=0): no controller,
        # the bit-identical pre-admission path.
        self.flight_recorder = recorder_lib.get_recorder()
        admission_config = admission or admission_lib.AdmissionConfig.from_env()
        self.admission = None
        if admission_config.enabled:
            self.admission = admission_lib.AdmissionController(
                admission_config,
                stats=self.stats,
                metrics=(self.metrics if self.observability.metrics_on else None),
                recorder=self.flight_recorder,
                compute_p50_fn=lambda: self._suggest_latency.percentile(
                    50, hop="pythia"
                ),
                queue_depth_fn=self._live_queue_depth,
            )
        # JAX persistent compilation cache: survive process restarts so a
        # restarted server pays zero XLA compiles for known buckets.
        self.compilation_cache_active = False
        if self.config.compilation_cache_dir:
            self.compilation_cache_active = _apply_compilation_cache(
                self.config.compilation_cache_dir
            )
        # Cross-study batch executor: concurrent same-bucket designer
        # computations share ONE vmapped device program. None = batching
        # off (VIZIER_BATCHING=0): the exact per-study path. The mesh
        # execution plane (VIZIER_MESH=1, parallel.mesh.MeshConfig) carves
        # the process's devices into placements the executor schedules
        # buckets over; off (the default) = the single-device seed path.
        self.batch_executor = None
        if self.config.batching:
            from vizier_tpu.parallel import batch_executor as batch_executor_lib
            from vizier_tpu.parallel import mesh as mesh_lib

            self.mesh = mesh or mesh_lib.MeshConfig.from_env()
            self.batch_executor = batch_executor_lib.BatchExecutor(
                max_batch_size=self.config.batch_max_size,
                max_wait_ms=self.config.batch_max_wait_ms,
                pad_partial=self.config.batch_pad_partial,
                stats=self.stats,
                metrics=(
                    self.metrics if self.observability.metrics_on else None
                ),
                mesh=self.mesh,
                admission=self.admission,
            )
        else:
            self.mesh = mesh
        # Speculative pre-compute pipeline (vizier_tpu.serving.speculative):
        # after each completion, the NEXT suggestion batch is computed in
        # the background and served from the designer-cache entry. Requires
        # the cache (the slot lives on its entries); None = off (the
        # default, VIZIER_SPECULATIVE=0): the exact request path.
        self.speculative = (
            speculative or speculative_lib.SpeculativeConfig.from_env()
        )
        self.speculative_engine = None
        if self.speculative.speculative and self.config.designer_cache:
            self.speculative_engine = speculative_lib.SpeculativeEngine(
                config=self.speculative,
                cache=self.designer_cache,
                stats=self.stats,
                metrics=(self.metrics if self.observability.metrics_on else None),
                executor=self.batch_executor,
            )
        # Fleet observability plane: the process-global flight recorder
        # (grabbed above, no-op unless VIZIER_FLIGHT_RECORDER=1) and the
        # SLO engine (VIZIER_SLO=1) evaluating declarative objectives over
        # sliding windows of this runtime's metrics registry, with
        # breach-triggered black-box dumps. Both off by default = today's
        # behavior.
        self.slo = slo or slo_lib.SloConfig.from_env()
        self.slo_engine = None
        if self.slo.enabled:
            self.slo_engine = slo_lib.SloEngine(
                config=self.slo,
                registry=self.metrics,
                recorder=self.flight_recorder,
            )
            self.slo_engine.start()
        self._prewarmed_shapes: set = set()
        self._prewarm_lock = threading.Lock()
        self._prewarm_threads: List[threading.Thread] = []

    # -- compile prewarm ----------------------------------------------------

    def prewarm_batching(
        self,
        problem: Any,
        designer_factory: Callable[..., Any],
        *,
        max_trials: Optional[int] = None,
        counts: Sequence[int] = (1,),
    ) -> List[dict]:
        """Walks the padding-bucket grid for ``problem`` and AOT-compiles the
        batched suggest programs at batch sizes {1, max} so first-request
        latency pays no XLA compile. Returns the per-bucket compile report."""
        if self.batch_executor is None:
            return []
        return self.batch_executor.prewarm(
            problem,
            designer_factory,
            max_trials=max_trials or self.config.batching_prewarm_max_trials,
            counts=counts,
        )

    def maybe_prewarm_batching_async(
        self, problem: Any, designer_factory: Callable[..., Any]
    ) -> bool:
        """Background prewarm, once per distinct search-space shape; used by
        the policy factory when ``config.batching_prewarm`` is on. Returns
        True when a prewarm thread was started."""
        if self.batch_executor is None or not self.config.batching_prewarm:
            return False
        shape_key = tuple(
            sorted((p.name, str(p.type)) for p in problem.search_space.parameters)
        )
        with self._prewarm_lock:
            if shape_key in self._prewarmed_shapes:
                return False
            self._prewarmed_shapes.add(shape_key)
        thread = threading.Thread(
            target=lambda: self.prewarm_batching(problem, designer_factory),
            name="vizier-batch-prewarm",
            daemon=True,
        )
        with self._prewarm_lock:
            self._prewarm_threads.append(thread)
        thread.start()
        return True

    def shutdown(self) -> None:
        """Joins in-flight prewarm compiles (an XLA compile aborted by
        interpreter teardown SIGABRTs the process), cancels speculative
        jobs and joins their worker pool, and drains the batch executor —
        in that order, so no speculative job can submit into a closing
        executor. Idempotent."""
        if self.slo_engine is not None:
            self.slo_engine.close()
        if self.speculative_engine is not None:
            self.speculative_engine.close()
        with self._prewarm_lock:
            threads, self._prewarm_threads = self._prewarm_threads, []
        for thread in threads:
            thread.join(timeout=120.0)
        if self.batch_executor is not None:
            self.batch_executor.close()

    def _live_queue_depth(self) -> int:
        """Queued live executor slots (0 with batching off) — the
        admission controller's deadline-shed wait estimator input."""
        executor = self.batch_executor
        if executor is None:
            return 0
        return executor.live_pending()

    def observe_suggest_latency(
        self,
        hop: str,
        seconds: float,
        trace_id: Optional[str] = None,
        tenant: Optional[str] = None,
    ) -> None:
        """Records one suggest's wall time at a hop (no-op when metrics are
        off — the off switch must cost nothing). ``trace_id`` makes the
        observation an exemplar candidate: the hop's top-latency samples
        keep their trace ids so an SLO breach links to real traces.
        ``tenant`` (set by the service hop only while admission is armed)
        splits the series per tenant so the SLO engine can hold a
        per-tenant p99 objective; None keeps the seed series unchanged."""
        if self.observability.metrics_on:
            labels = {"hop": hop}
            if tenant is not None:
                labels["tenant"] = tenant
            self._suggest_latency.observe(seconds, trace_id=trace_id, **labels)

    def slo_report(self) -> Dict[str, Any]:
        """Evaluates the armed SLOs now and returns the JSON-ready report
        (``{"armed": False}`` when VIZIER_SLO is off)."""
        if self.slo_engine is None:
            return {"armed": False}
        return self.slo_engine.report()

    def suggest_latency_histogram(self) -> metrics_lib.Histogram:
        return self._suggest_latency

    def invalidate_study(self, study_name: str) -> bool:
        """Drops the study's designer state + breaker + speculative job
        (study deleted)."""
        self.breakers.invalidate(study_name)
        if self.speculative_engine is not None:
            self.speculative_engine.invalidate(study_name, reason="delete_study")
        self.flight_recorder.invalidate(study_name)
        return self.designer_cache.invalidate(study_name)

    def note_study_config(self, study_name: str, config_hash: str) -> bool:
        """Pins per-study serving state to one StudyConfig incarnation.

        Called by the servicer with every request's parsed-config hash.
        On a hash turnover — the shared-compute-tier delete/recreate race,
        where another frontend's ``DeleteStudy`` invalidation cannot reach
        this process — everything TRAINED against the previous incarnation
        (designer entry, breaker, speculative slot) is dropped so it is
        never served again. The flight-recorder ring survives: it is
        forensic history keyed by time, not derived state, and a metadata
        update (a legitimate hash turnover — e.g. the budget-policy knobs
        ride metadata) must not erase the study's earlier events. Returns
        True when a turnover was detected.
        """
        changed = self.designer_cache.note_config_hash(study_name, config_hash)
        if changed:
            # note_config_hash already dropped the designer entry itself.
            self.breakers.invalidate(study_name)
            if self.speculative_engine is not None:
                self.speculative_engine.invalidate(
                    study_name, reason="config_turnover"
                )
        return changed

    def speculative_invalidate(self, study_name: str, reason: str = "") -> None:
        """Drops only the study's speculative slot/job (frontier surgery,
        surrogate crossover); the designer entry itself stays live."""
        if self.speculative_engine is not None:
            self.speculative_engine.invalidate(study_name, reason=reason)

    def snapshot(self) -> Dict[str, int]:
        """All counters plus the current cache/breaker population."""
        out = self.stats.snapshot()
        out["cached_studies"] = len(self.designer_cache)
        out["open_breakers"] = self.breakers.open_count()
        return out

    def admission_snapshot(self) -> Dict[str, Any]:
        """The admission controller's JSON-ready state (per-tenant
        sheds/admits, overload state, transitions); ``{"enabled": False}``
        with the plane off."""
        if self.admission is None:
            return {"enabled": False}
        return self.admission.snapshot()

    def prometheus_text(self) -> str:
        """Every serving counter + latency histogram, Prometheus format."""
        return self.metrics.prometheus_text()
