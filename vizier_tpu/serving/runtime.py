"""ServingRuntime: one object bundling cache + coalescer + stats + config.

The Pythia servicer owns one runtime per process; the policy factory and
the serving policy share it so every counter lands in one place and
``DeleteStudy`` invalidation reaches the real cache. The reliability layer
(per-study circuit breakers + its config) lives here too, so breaker
transitions land in the same stats sink and study invalidation drops the
breaker along with the designer state. The observability layer hangs off
the same object: one metrics registry backs the serving counters AND the
latency histograms (cache lookups, coalescer waits, per-hop suggest
latency), all dumped together by :meth:`prometheus_text`.
"""

from __future__ import annotations

from typing import Dict, Optional

from vizier_tpu.observability import config as obs_config_lib
from vizier_tpu.observability import metrics as metrics_lib
from vizier_tpu.reliability import breaker as breaker_lib
from vizier_tpu.reliability import config as reliability_config_lib
from vizier_tpu.serving import coalescer as coalescer_lib
from vizier_tpu.serving import config as config_lib
from vizier_tpu.serving import designer_cache as cache_lib
from vizier_tpu.serving import stats as stats_lib


class ServingRuntime:
    """Shared serving state for one Pythia servicer."""

    def __init__(
        self,
        config: Optional[config_lib.ServingConfig] = None,
        stats: Optional[stats_lib.ServingStats] = None,
        reliability: Optional[reliability_config_lib.ReliabilityConfig] = None,
        observability: Optional[obs_config_lib.ObservabilityConfig] = None,
    ):
        self.config = config or config_lib.ServingConfig.from_env()
        self.observability = (
            observability or obs_config_lib.ObservabilityConfig.from_env()
        )
        self.stats = stats or stats_lib.ServingStats()
        # One registry for this runtime's whole metric surface. A caller
        # passing pre-existing stats brings its registry along so counters
        # and histograms still land in one dump.
        self.metrics: metrics_lib.MetricsRegistry = self.stats.registry
        self.reliability = (
            reliability or reliability_config_lib.ReliabilityConfig.from_env()
        )
        self.designer_cache = cache_lib.DesignerStateCache(
            max_entries=self.config.cache_max_entries,
            ttl_seconds=self.config.cache_ttl_seconds,
            stats=self.stats,
            observe_latency=self.observability.metrics_on,
        )
        self.coalescer = coalescer_lib.RequestCoalescer(
            stats=self.stats,
            observe_latency=self.observability.metrics_on,
        )
        self.breakers = breaker_lib.CircuitBreakerRegistry(
            failure_threshold=self.reliability.breaker_failure_threshold,
            window_secs=self.reliability.breaker_window_secs,
            cooldown_secs=self.reliability.breaker_cooldown_secs,
            half_open_probes=self.reliability.breaker_half_open_probes,
            stats=self.stats,
        )
        self._suggest_latency = self.metrics.histogram(
            "vizier_suggest_latency_seconds",
            help="SuggestTrials wall time per hop (service, pythia).",
        )

    def observe_suggest_latency(self, hop: str, seconds: float) -> None:
        """Records one suggest's wall time at a hop (no-op when metrics are
        off — the off switch must cost nothing)."""
        if self.observability.metrics_on:
            self._suggest_latency.observe(seconds, hop=hop)

    def suggest_latency_histogram(self) -> metrics_lib.Histogram:
        return self._suggest_latency

    def invalidate_study(self, study_name: str) -> bool:
        """Drops the study's designer state + breaker (study deleted)."""
        self.breakers.invalidate(study_name)
        return self.designer_cache.invalidate(study_name)

    def snapshot(self) -> Dict[str, int]:
        """All counters plus the current cache/breaker population."""
        out = self.stats.snapshot()
        out["cached_studies"] = len(self.designer_cache)
        out["open_breakers"] = self.breakers.open_count()
        return out

    def prometheus_text(self) -> str:
        """Every serving counter + latency histogram, Prometheus format."""
        return self.metrics.prometheus_text()
