"""Per-study designer-state cache with TTL/LRU eviction.

Each entry holds the LIVE designer (jit caches, trained GP fit, rng state
and all) plus the last trained unconstrained ARD params, so a steady-state
suggest pays an incremental update + warm-started train instead of a full
replay + cold multi-restart ARD. Entries are keyed by study resource name.

Eviction:
- **TTL** — an entry idle longer than ``ttl_seconds`` is dropped on the
  next cache access (lazy; there is no background reaper thread to leak);
- **LRU** — inserting beyond ``max_entries`` evicts the least recently
  used entry;
- **invalidation** — ``DeleteStudy`` calls :meth:`invalidate` so a reused
  study name never sees a predecessor's designer state.

Thread safety: the cache dict is guarded by one mutex; each entry carries
its own lock that callers hold across the designer's update→suggest
critical section, so suggests for *different* studies run concurrently
while suggests for one study serialize on its entry (the designer is
stateful).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, List, Optional, Set

from vizier_tpu.observability import tracing as tracing_lib
from vizier_tpu.serving import stats as stats_lib


class CachedDesignerEntry:
    """One study's live serving state."""

    def __init__(self, study_name: str, designer: Any, now: float):
        self.study_name = study_name
        self.designer = designer
        # Last trained unconstrained ARD params (whatever pytree the
        # designer's ``warm_start_state()`` returns); None until the first
        # trained suggest.
        self.warm_params: Any = None
        # Scalable-surrogate mirrors (vizier_tpu.surrogates): the active
        # exact/sparse mode and the last trained sparse posterior (inducing
        # set + factorization) — the inspection/hand-off surface, kept in
        # lock-step with the live designer by the serving policy. Both die
        # with the entry: DeleteStudy invalidation drops cached inducing
        # state along with everything else.
        self.surrogate_mode: Any = None
        self.sparse_state: Any = None
        # Speculative pre-compute slot (vizier_tpu.serving.speculative): a
        # parked next-suggestion batch for one exact frontier fingerprint,
        # swapped atomically under the engine's serve lock (never under
        # this entry's designer lock — a slot pop must not wait behind an
        # in-flight live compute). Dies with the entry on invalidation.
        self.speculative: Any = None
        # Completed-trial ids already fed to the designer (incremental
        # updates only hand over the delta).
        self.incorporated_trial_ids: Set[int] = set()
        self.lock = threading.Lock()
        self.created_at = now
        self.last_used_at = now
        self.num_suggests = 0


class DesignerStateCache:
    """TTL/LRU cache: study resource name → :class:`CachedDesignerEntry`."""

    def __init__(
        self,
        max_entries: int = 64,
        ttl_seconds: float = 3600.0,
        stats: Optional[stats_lib.ServingStats] = None,
        time_fn: Callable[[], float] = time.monotonic,
        observe_latency: bool = True,
    ):
        if max_entries < 1:
            raise ValueError(f"max_entries must be >= 1, got {max_entries}.")
        self._max_entries = max_entries
        self._ttl = ttl_seconds
        self._stats = stats or stats_lib.ServingStats()
        self._time = time_fn
        # Lookup latency histogram: a miss pays designer construction (jit
        # compile caches and all) — exactly the cost the cache exists to
        # amortize, so it is worth a distribution, not just a counter.
        registry = getattr(self._stats, "registry", None)
        self._lookup_hist = (
            registry.histogram(
                "vizier_designer_cache_lookup_seconds",
                help="Designer-cache lookup wall time; a miss includes "
                "designer construction.",
            )
            if observe_latency and registry is not None
            else None
        )
        self._lock = threading.Lock()
        # Ordered oldest-used first; move_to_end on every hit.
        self._entries: "collections.OrderedDict[str, CachedDesignerEntry]" = (
            collections.OrderedDict()
        )
        # study name -> last-seen StudyConfig hash (note_config_hash).
        # Bounded independently of the entry map: the hash is what DETECTS
        # a delete/recreate turnover, so it must outlive the entry's own
        # TTL/LRU eviction, but million-study churn must not grow it
        # without bound.
        self._config_hashes: "collections.OrderedDict[str, str]" = (
            collections.OrderedDict()
        )
        self._max_hashes = max(1024, 16 * max_entries)

    @property
    def stats(self) -> stats_lib.ServingStats:
        return self._stats

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, study_name: str) -> bool:
        with self._lock:
            return study_name in self._entries

    def get_or_create(
        self, study_name: str, designer_factory: Callable[[], Any]
    ) -> CachedDesignerEntry:
        """The study's entry, creating (and possibly evicting) as needed.

        The designer factory runs OUTSIDE the cache mutex — constructing a
        GP designer compiles converters and optimizers, and holding the
        map lock through that would serialize unrelated studies' misses.
        The small race (two threads miss the same study concurrently) is
        resolved by a second lookup before insert: the loser's designer is
        discarded and the winner's entry returned.
        """
        t0 = time.perf_counter()
        now = self._time()
        # Counter/histogram updates run OUTSIDE the map mutex throughout:
        # they take the metrics registry's own locks, and nesting those
        # under the cache mutex would serialize unrelated studies' lookups
        # on metric bookkeeping (the lock_order pass keeps this mutex a
        # leaf of the serving lock graph).
        ttl_evicted = False
        with self._lock:
            entry = self._entries.get(study_name)
            if entry is not None and self._expired(entry, now):
                del self._entries[study_name]
                ttl_evicted = True
                entry = None
            if entry is not None:
                entry.last_used_at = now
                self._entries.move_to_end(study_name)
        if ttl_evicted:
            self._stats.increment("cache_evictions_ttl")
        if entry is not None:
            self._stats.increment("cache_hits")
            self._observe_lookup("hit", t0)
            return entry
        designer = designer_factory()
        lru_evictions = 0
        race_hit = False
        with self._lock:
            entry = self._entries.get(study_name)
            if entry is not None and not self._expired(entry, self._time()):
                # Lost the miss race; serve the winner's entry as a hit.
                entry.last_used_at = self._time()
                self._entries.move_to_end(study_name)
                race_hit = True
            else:
                entry = CachedDesignerEntry(study_name, designer, self._time())
                self._entries[study_name] = entry
                self._entries.move_to_end(study_name)
                while len(self._entries) > self._max_entries:
                    self._entries.popitem(last=False)
                    lru_evictions += 1
        if race_hit:
            self._stats.increment("cache_hits")
            self._observe_lookup("hit", t0)
            return entry
        self._stats.increment("cache_misses")
        if lru_evictions:
            self._stats.increment("cache_evictions_lru", lru_evictions)
        self._observe_lookup("miss", t0)
        return entry

    def _observe_lookup(self, result: str, t0: float) -> None:
        seconds = time.perf_counter() - t0
        if self._lookup_hist is not None:
            self._lookup_hist.observe(seconds, result=result)
        tracing_lib.add_current_event(
            "designer_cache", result=result, seconds=round(seconds, 6)
        )

    def peek(
        self, study_name: str, touch: bool = True
    ) -> Optional[CachedDesignerEntry]:
        """The study's live entry, or None — never constructs a designer.

        The speculative engine's lookup shape: parking or popping a
        pre-computed batch must not build designer state for a study
        nobody is serving. ``touch`` refreshes TTL/LRU (a served hit is a
        real use); ``touch=False`` is a pure inspection read.
        """
        now = self._time()
        with self._lock:
            entry = self._entries.get(study_name)
            if entry is None:
                return None
            if self._expired(entry, now):
                del self._entries[study_name]
                expired = True
            else:
                expired = False
                if touch:
                    entry.last_used_at = now
                    self._entries.move_to_end(study_name)
        if expired:
            self._stats.increment("cache_evictions_ttl")
            return None
        return entry

    def note_config_hash(self, study_name: str, config_hash: str) -> bool:
        """Pins the study's cached designer state to one config incarnation.

        A shared compute tier serves MANY frontends: a study can be
        deleted and recreated (same resource name, different search space)
        through a frontend whose ``DeleteStudy`` invalidation never
        reaches this process — there is no invalidation RPC on the Pythia
        surface. The servicer calls this with the request's parsed-config
        hash on every suggest; a hash TURNOVER (a different hash for a
        name we have seen) drops the stale entry so the next lookup
        builds a designer for the current incarnation. Returns True when
        a turnover was detected.
        """
        turned_over = False
        removed = None
        with self._lock:
            previous = self._config_hashes.get(study_name)
            self._config_hashes[study_name] = config_hash
            self._config_hashes.move_to_end(study_name)
            while len(self._config_hashes) > self._max_hashes:
                self._config_hashes.popitem(last=False)
            if previous is not None and previous != config_hash:
                turned_over = True
                removed = self._entries.pop(study_name, None)
        if removed is not None:
            self._stats.increment("cache_invalidations_config")
        return turned_over

    def invalidate(self, study_name: str) -> bool:
        """Drops the study's entry (study deleted / state known stale)."""
        with self._lock:
            removed = self._entries.pop(study_name, None)
        if removed is not None:
            self._stats.increment("cache_invalidations")
        return removed is not None

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def study_names(self) -> List[str]:
        """Cached studies, least recently used first (for inspection)."""
        with self._lock:
            return list(self._entries)

    def _expired(self, entry: CachedDesignerEntry, now: float) -> bool:
        return self._ttl > 0 and (now - entry.last_used_at) > self._ttl
