"""User-facing client: ``Study`` / ``Trial``.

Parity with ``/root/reference/vizier/_src/service/clients.py:39,126,236``:
``Study.from_study_config`` implicitly creates/loads the study (spinning an
in-process service when no endpoint is configured); trials round-trip
through the platform-independent ``client_abc`` interfaces.
"""

from __future__ import annotations

import secrets
from typing import Any, Collection, Dict, List, Optional

from vizier_tpu import pyvizier as vz
from vizier_tpu.client import client_abc
from vizier_tpu.service import vizier_client

NO_ENDPOINT = vizier_client.NO_ENDPOINT
environment_variables = vizier_client.environment_variables


def list_studies(owner: str, *, endpoint: Optional[str] = None) -> List["Study"]:
    """All studies under an owner (parity with the ListStudies RPC)."""
    from vizier_tpu.service import resources
    from vizier_tpu.service.protos import vizier_service_pb2

    service = vizier_client.create_service_stub(endpoint)
    response = service.ListStudies(
        vizier_service_pb2.ListStudiesRequest(
            parent=resources.OwnerResource(owner).name
        )
    )
    return [
        Study(vizier_client.VizierClient(service, s.name, "default_client_id"))
        for s in response.studies
    ]


class Trial(client_abc.TrialInterface):
    def __init__(
        self,
        client: vizier_client.VizierClient,
        uid: int,
        snapshot: Optional[vz.Trial] = None,
    ):
        self._client = client
        self._uid = uid
        # Trial parameters are immutable after creation, so a creation-time
        # snapshot (e.g. the proto ``suggest`` already received) answers
        # ``.parameters`` with zero RPCs; measurements/state always
        # re-materialize.
        self._snapshot = snapshot
        self._params: Optional[Dict[str, Any]] = None

    @property
    def id(self) -> int:
        return self._uid

    @property
    def parameters(self) -> Dict[str, Any]:
        if self._params is None:
            config = self._client.cached_study_config()
            trial = self._snapshot if self._snapshot is not None else self.materialize()
            self._params = config.trial_parameters(trial)
        # Fresh dict per access: a caller mutating the returned mapping must
        # not poison later reads through the cache.
        return dict(self._params)

    def add_measurement(self, measurement: vz.Measurement) -> None:
        self._client.report_intermediate_objective_value(self._uid, measurement)

    def complete(
        self,
        measurement: Optional[vz.Measurement] = None,
        *,
        infeasible_reason: Optional[str] = None,
    ) -> Optional[vz.Measurement]:
        trial = self._client.complete_trial(
            self._uid, measurement, infeasibility_reason=infeasible_reason
        )
        return trial.final_measurement

    def check_early_stopping(self) -> bool:
        return self._client.should_trial_stop(self._uid)

    def stop(self) -> None:
        self._client.stop_trial(self._uid)

    def delete(self) -> None:
        self._client.delete_trial(self._uid)

    def materialize(self) -> vz.Trial:
        return self._client.get_trial(self._uid)

    def update_metadata(self, delta: vz.Metadata) -> None:
        md = vz.MetadataDelta(on_trials={self._uid: delta})
        self._client.update_metadata(md)

    @property
    def status(self) -> vz.TrialStatus:
        return self.materialize().status


class Study(client_abc.StudyInterface):
    def __init__(self, client: vizier_client.VizierClient):
        self._client = client
        # client_id -> VizierClient scoped to it. Building a VizierClient
        # is not free (RetryPolicy + jitter RNG construction), and the
        # multi-worker stress shape calls suggest(client_id=...) per trial;
        # clients are stateless wrappers over the shared service handle, so
        # caching per worker id is safe.
        self._scoped_clients: Dict[str, vizier_client.VizierClient] = {}

    # -- factories ---------------------------------------------------------

    @classmethod
    def from_study_config(
        cls,
        config: vz.StudyConfig,
        *,
        owner: str = "owner",
        study_id: str = "",
        client_id: str = "default_client_id",
        endpoint: Optional[str] = None,
    ) -> "Study":
        study_id = study_id or f"study-{secrets.token_hex(4)}"
        return cls(
            vizier_client.VizierClient.create_or_load_study(
                owner, study_id, config, client_id=client_id, endpoint=endpoint
            )
        )

    @classmethod
    def from_resource_name(
        cls,
        name: str,
        *,
        client_id: str = "default_client_id",
        endpoint: Optional[str] = None,
    ) -> "Study":
        try:
            return cls(
                vizier_client.VizierClient.load_study(
                    name, client_id=client_id, endpoint=endpoint
                )
            )
        except KeyError as e:
            raise client_abc.ResourceNotFoundError(str(e))

    # -- StudyInterface ----------------------------------------------------

    @property
    def resource_name(self) -> str:
        return self._client.study_name

    def suggest(
        self, *, count: Optional[int] = None, client_id: Optional[str] = None
    ) -> List[Trial]:
        if client_id is not None and client_id != self._client.client_id:
            scoped = self._scoped_clients.get(client_id)
            if scoped is None:
                scoped = self._scoped_clients[client_id] = vizier_client.VizierClient(
                    self._client._service, self._client.study_name, client_id
                )
        else:
            scoped = self._client
        trials = scoped.get_suggestions(count or 1)
        return [Trial(self._client, t.id, snapshot=t) for t in trials]

    def delete(self) -> None:
        self._client.delete_study()

    def trials(
        self, trial_filter: Optional[vz.TrialFilter] = None
    ) -> Collection[Trial]:
        all_trials = self._client.list_trials()
        if trial_filter is not None:
            all_trials = [t for t in all_trials if trial_filter(t)]
        return [Trial(self._client, t.id) for t in all_trials]

    def get_trial(self, uid: int) -> Trial:
        try:
            self._client.get_trial(uid)
        except KeyError as e:
            raise client_abc.ResourceNotFoundError(str(e))
        return Trial(self._client, uid)

    def optimal_trials(self, count: Optional[int] = None) -> Collection[Trial]:
        optimal = self._client.list_optimal_trials()
        if count is not None:
            optimal = optimal[:count]
        return [Trial(self._client, t.id) for t in optimal]

    def materialize_study_config(self) -> vz.StudyConfig:
        return self._client.get_study_config()

    def materialize_state(self) -> vz.StudyState:
        from vizier_tpu.service.protos import study_pb2, vizier_service_pb2

        study = self._client._service.GetStudy(
            vizier_service_pb2.GetStudyRequest(name=self._client.study_name)
        )
        state_map = {
            study_pb2.Study.ACTIVE: vz.StudyState.ACTIVE,
            study_pb2.Study.INACTIVE: vz.StudyState.ABORTED,
            study_pb2.Study.COMPLETED: vz.StudyState.COMPLETED,
        }
        return state_map.get(study.state, vz.StudyState.ACTIVE)

    def set_state(self, state: vz.StudyState) -> None:
        self._client.set_study_state(state)

    def update_metadata(self, delta: vz.Metadata) -> None:
        self._client.update_metadata(vz.MetadataDelta(on_study=delta))
