"""PolicySupporter reading trials back from the Vizier service.

Parity with ``/root/reference/vizier/_src/service/service_policy_supporter.py``.
"""

from __future__ import annotations

from typing import Iterable, List, Optional

from vizier_tpu import pyvizier as vz
from vizier_tpu.pythia import policy_supporter
from vizier_tpu.service import proto_converters as pc
from vizier_tpu.service.protos import vizier_service_pb2


class ServicePolicySupporter(policy_supporter.PolicySupporter):
    """Reads study/trial state via the Vizier servicer (or stub)."""

    def __init__(self, study_name: str, vizier_service):
        self._study_name = study_name
        self._vizier = vizier_service

    def GetStudyConfig(self, study_guid: Optional[str] = None) -> vz.StudyConfig:
        name = study_guid or self._study_name
        study = self._vizier.GetStudy(vizier_service_pb2.GetStudyRequest(name=name))
        return pc.study_config_from_proto(study.study_spec)

    def GetTrials(
        self,
        *,
        study_guid: Optional[str] = None,
        trial_ids: Optional[Iterable[int]] = None,
        min_trial_id: Optional[int] = None,
        max_trial_id: Optional[int] = None,
        status_matches: Optional[vz.TrialStatus] = None,
        include_intermediate_measurements: bool = True,
    ) -> List[vz.Trial]:
        name = study_guid or self._study_name
        response = self._vizier.ListTrials(
            vizier_service_pb2.ListTrialsRequest(parent=name)
        )
        trials = [pc.trial_from_proto(t) for t in response.trials]
        ids = frozenset(trial_ids) if trial_ids is not None else None
        out = []
        for t in trials:
            if ids is not None and t.id not in ids:
                continue
            if min_trial_id is not None and t.id < min_trial_id:
                continue
            if max_trial_id is not None and t.id > max_trial_id:
                continue
            if status_matches is not None and t.status != status_matches:
                continue
            out.append(t)
        return out
