"""PythiaServicer: hosts suggestion policies.

Parity with ``/root/reference/vizier/_src/service/pythia_service.py:36``:
builds a ``ServicePolicySupporter`` for the study, asks the policy factory
for the algorithm's policy, converts proto⇄pythia types, and captures policy
errors into the response. (No forced float64 — our GP stack is f32/TPU-native
by design, unlike the reference's ``jax_enable_x64`` at ``:50-57``.)
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
import time
import traceback
from typing import Optional

from vizier_tpu import pyvizier as vz
from vizier_tpu.observability import tracing as tracing_lib
from vizier_tpu.pythia import policy as policy_lib
from vizier_tpu.reliability import deadline as deadline_lib
from vizier_tpu.reliability import errors as errors_lib
from vizier_tpu.reliability import fallback as fallback_lib
from vizier_tpu.service import policy_factory as policy_factory_lib
from vizier_tpu.service import proto_converters as pc
from vizier_tpu.service import service_policy_supporter
from vizier_tpu.service.protos import pythia_service_pb2, study_pb2
from vizier_tpu.service.protos import vizier_service_pb2
from vizier_tpu.serving import admission as admission_lib
from vizier_tpu.serving import speculative as speculative_lib

_logger = logging.getLogger(__name__)


class PythiaServicer:
    def __init__(
        self,
        vizier_service=None,
        policy_factory=None,
        serving_config=None,
        reliability_config=None,
        surrogate_config=None,
        mesh_config=None,
        admission_config=None,
    ):
        from vizier_tpu.serving import runtime as serving_runtime_lib

        self._vizier = vizier_service
        # The stateful serving runtime (designer cache + coalescer + stats +
        # per-study circuit breakers); ``serving_config`` (a
        # vizier_tpu.serving.ServingConfig) and ``reliability_config`` (a
        # vizier_tpu.reliability.ReliabilityConfig) disable parts or all of
        # it; ``surrogate_config`` (a vizier_tpu.surrogates.SurrogateConfig)
        # sets the exact↔sparse auto-switch every GP designer shares;
        # ``mesh_config`` (a vizier_tpu.parallel.mesh.MeshConfig) carves
        # the devices into batch-executor placements (VIZIER_MESH*; off =
        # the single-device seed path); ``admission_config`` (a
        # vizier_tpu.serving.admission.AdmissionConfig) arms the
        # multi-tenant overload-protection plane (VIZIER_ADMISSION*; off =
        # the bit-identical pre-admission path). None -> defaults with
        # env-var overrides.
        self._serving = serving_runtime_lib.ServingRuntime(
            serving_config,
            reliability=reliability_config,
            surrogates=surrogate_config,
            mesh=mesh_config,
            admission=admission_config,
        )
        self._policy_factory = policy_factory or policy_factory_lib.DefaultPolicyFactory(
            serving_runtime=self._serving
        )
        # Cache for policies that declare should_be_cached, keyed by
        # (study_name, algorithm, config_hash).
        self._policy_cache = {}
        # study_name -> (config hash, parsed StudyConfig). The hash (over
        # the serialized StudySpec) catches metadata updates AND the
        # shared-compute-tier delete/recreate turnover — so the hot path
        # skips a full Python proto->pyvizier parse per suggest without
        # ever serving a stale search space (see _parsed_study_config).
        self._config_cache = {}
        # Early-stopping policies cached per study (regression rule holds a
        # trained GBM; see EarlyStop dispatch).
        self._stopping_policies = {}
        self._bind_speculative()

    def connect_to_vizier(self, vizier_service) -> None:
        self._vizier = vizier_service
        self._bind_speculative()

    def _bind_speculative(self) -> None:
        """Connects the runtime's speculative engine to THIS servicer's
        compute path (needs a Vizier service to read frontiers from)."""
        engine = self._serving.speculative_engine
        if engine is None or self._vizier is None:
            return
        engine.bind(
            fingerprint_fn=self._speculative_fingerprint,
            compute_fn=self._speculative_compute,
            accept_fn=self._speculative_accept,
        )

    @property
    def serving_runtime(self):
        return self._serving

    def serving_stats(self) -> dict:
        """Snapshot of the serving counters + current cache population."""
        return self._serving.snapshot()

    def prometheus_text(self) -> str:
        """Serving counters + latency histograms, Prometheus text format."""
        return self._serving.prometheus_text()

    def prewarm(
        self,
        study_config: vz.StudyConfig,
        algorithm: str = "DEFAULT",
        counts=(1,),
        max_trials=None,
    ) -> list:
        """AOT-compiles the (batched) suggest programs for this study shape.

        Walks the padding-bucket grid at batch sizes {1, max}: a server
        prewarmed for its expected study shapes pays no XLA compile on the
        first real request. The designer factory comes from the compute-IR
        program registry (``vizier_tpu.compute.registry``): every
        registered program claiming ``algorithm`` contributes its
        ``prewarm_factory``, so a new DesignerProgram joins the prewarm
        walk by registering — no servicer edit. Returns the per-bucket
        compile report (empty when batching is off or no registered
        program covers the algorithm).
        """
        from vizier_tpu.compute import registry as compute_registry

        problem = study_config.to_problem()
        kwargs_fn = getattr(self._policy_factory, "_gp_designer_kwargs", None)
        kwargs = kwargs_fn() if kwargs_fn is not None else {}
        programs = compute_registry.programs_for_algorithm(algorithm or "DEFAULT")
        report = []
        seen_factories = set()
        for program in programs:
            # Same-designer programs (e.g. exact + sparse families) share
            # one walk: the factory's auto-switch decides which program
            # each synthetic bucket compiles, exactly like live studies.
            factory_key = type(program.prewarm_factory(problem, **kwargs))
            if factory_key in seen_factories:
                continue
            seen_factories.add(factory_key)
            report.extend(
                self._serving.prewarm_batching(
                    problem,
                    lambda p, _program=program: _program.prewarm_factory(
                        p, **kwargs
                    ),
                    counts=counts,
                    max_trials=max_trials,
                )
            )
        return report

    def shutdown(self) -> None:
        """Drains the serving runtime's batch executor (idempotent)."""
        self._serving.shutdown()

    def invalidate_study(self, study_name: str) -> None:
        """Drops every piece of per-study serving state (study deleted)."""
        self._serving.invalidate_study(study_name)
        self._stopping_policies.pop(study_name, None)
        self._config_cache.pop(study_name, None)
        for key in [k for k in self._policy_cache if k[0] == study_name]:
            del self._policy_cache[key]

    def _parsed_study_config(self, request) -> vz.StudyConfig:
        """The request's StudyConfig, cached by (study name, config hash).

        The hash (over the serialized StudySpec) is the cache's identity
        check AND the shared compute tier's staleness detector: against
        one shared Pythia, two frontends racing ``DeleteStudy``/
        ``CreateStudy`` for the same resource name have no invalidation
        RPC to this process, so a hash TURNOVER is the only signal that
        the name now means a different study. On turnover every per-study
        cache pinned to the previous incarnation is dropped — the parsed
        config, the policy cache, the stopping policies, and (through the
        runtime) the designer-state cache.
        """
        spec = request.study_descriptor.config
        spec_bytes = spec.SerializeToString()
        config_hash = hashlib.sha1(spec_bytes).hexdigest()[:16]
        study_name = request.study_name
        cached = self._config_cache.get(study_name)
        if cached is not None and cached[0] == config_hash:
            return cached[1]
        if cached is not None:
            # Same resource name, different config bytes: a delete/
            # recreate (or a metadata update, which can change policy
            # construction — e.g. the acquisition-budget override) from
            # ANY frontend. Drop state keyed to the stale incarnation.
            self._stopping_policies.pop(study_name, None)
            for key in [k for k in self._policy_cache if k[0] == study_name]:
                del self._policy_cache[key]
        config = pc.study_config_from_proto(spec)
        if study_name:
            self._config_cache[study_name] = (config_hash, config)
            self._serving.note_study_config(study_name, config_hash)
        return config

    def _request_config_hash(self, request) -> str:
        """The request's own config hash — NOT a read-back from the parse
        cache: two frontends racing different incarnations of one study
        name interleave freely here, and keying a policy by the OTHER
        request's hash would serve incarnation A under B's key."""
        spec_bytes = request.study_descriptor.config.SerializeToString()
        return hashlib.sha1(spec_bytes).hexdigest()[:16]

    def _get_policy(
        self,
        study_config: vz.StudyConfig,
        algorithm: str,
        study_name: str,
        config_hash: str = "",
    ) -> policy_lib.Policy:
        supporter = service_policy_supporter.ServicePolicySupporter(
            study_name, self._vizier
        )
        # Keyed by (study, algorithm, config hash): a cached policy must
        # die with the config incarnation it was constructed from.
        key = (study_name, algorithm, config_hash)
        cached = self._policy_cache.get(key)
        if cached is not None:
            return cached
        policy = self._policy_factory(
            study_config.to_problem(), algorithm, supporter, study_name
        )
        if policy.should_be_cached:
            self._policy_cache[key] = policy
        return policy

    def Suggest(
        self, request: pythia_service_pb2.PythiaSuggestRequest, context=None
    ) -> pythia_service_pb2.PythiaSuggestResponse:
        # Trace parentage comes from the request's wire context, NOT the
        # ambient contextvar: the deadline-bounded dispatch runs this method
        # on a fresh worker thread (ResponseWaiter), and a remote stub
        # crosses a process boundary — the proto field survives both.
        tracer = tracing_lib.get_tracer()
        parent = tracing_lib.parse_context(request.trace_context)
        t0 = time.perf_counter()
        with tracer.span(
            "pythia.suggest",
            parent=parent,
            study=request.study_name,
            algorithm=request.algorithm,
            count=int(request.count),
            deadline_remaining_secs=float(request.deadline_secs),
        ) as span:
            response = self._suggest_coalesced(request)
            if response.error:
                span.set_attribute("error", response.error.splitlines()[0][:200])
            trace_id = getattr(span, "trace_id", None)
        self._serving.observe_suggest_latency(
            "pythia", time.perf_counter() - t0, trace_id=trace_id
        )
        return response

    def _suggest_coalesced(
        self, request: pythia_service_pb2.PythiaSuggestRequest
    ) -> pythia_service_pb2.PythiaSuggestResponse:
        if not self._serving.config.coalescing:
            return self._suggest_compute(request)
        # Compute-level request coalescing: concurrent suggests against the
        # SAME study state (name, config incarnation, algorithm, trial
        # frontier, count) collapse onto one designer computation;
        # followers receive their own copy of the response (protos are
        # mutable and cross servicer threads). The config hash keeps two
        # frontends racing a delete/recreate of one study name from
        # coalescing onto the OTHER incarnation's computation.
        key = (
            "suggest",
            request.study_name,
            self._request_config_hash(request),
            request.algorithm,
            int(request.study_descriptor.max_trial_id),
            int(request.count),
        )

        def clone(resp):
            out = pythia_service_pb2.PythiaSuggestResponse()
            out.CopyFrom(resp)
            return out

        return self._serving.coalescer.coalesce(
            key,
            lambda: self._suggest_compute(request),
            clone=clone,
            span_name="pythia.suggest_compute",
        )

    # -- speculative pre-compute (vizier_tpu.serving.speculative) -----------

    def notify_trial_event(self, study_name: str) -> None:
        """A completion/measurement moved the study's frontier: drop the
        parked batch and enqueue a pre-compute for the new frontier."""
        engine = self._serving.speculative_engine
        if engine is not None and engine.bound:
            engine.notify_completion(study_name)

    def _trial_frontier(self, study_name: str):
        """``(completed_ids, active_ids, max_trial_id)`` via the connected
        Vizier service (copy-free fast path when in-process)."""
        frontier = getattr(self._vizier, "trial_frontier", None)
        if frontier is not None:
            return frontier(study_name)
        listing = self._vizier.ListTrials(
            vizier_service_pb2.ListTrialsRequest(parent=study_name)
        )
        completed, active, max_id = [], [], 0
        for t in listing.trials:
            max_id = max(max_id, int(t.id))
            if t.state in (study_pb2.Trial.SUCCEEDED, study_pb2.Trial.INFEASIBLE):
                completed.append(int(t.id))
            elif t.state == study_pb2.Trial.ACTIVE:
                active.append(int(t.id))
        return completed, active, max_id

    def _speculative_fingerprint(self, study_name: str):
        """Job-side frontier read: the fingerprint the parked batch will be
        served under, captured BEFORE the compute (conservative: anything
        landing after this point makes the slot a serve-time mismatch)."""
        study = self._vizier.GetStudy(
            vizier_service_pb2.GetStudyRequest(name=study_name)
        )
        completed, active, max_id = self._trial_frontier(study_name)
        fingerprint = speculative_lib.make_fingerprint(
            study.study_spec.SerializeToString(), completed, active
        )
        return fingerprint, max_id

    def _speculative_compute(
        self, study_name: str, count: int, max_trial_id: int
    ) -> Optional[pythia_service_pb2.PythiaSuggestResponse]:
        """Runs one speculative job through the EXACT live suggest path
        (coalescer → policy → designer cache → batch executor), so a hit
        is the live compute run early — same designer state mutations,
        same RNG order, same batching buckets (at low flush priority via
        the speculative-scope thread flag the engine sets)."""
        study = self._vizier.GetStudy(
            vizier_service_pb2.GetStudyRequest(name=study_name)
        )
        if study.state != study_pb2.Study.ACTIVE:
            return None
        preq = pythia_service_pb2.PythiaSuggestRequest(
            count=count,
            algorithm=study.study_spec.algorithm,
            study_name=study_name,
        )
        preq.study_descriptor.config.CopyFrom(study.study_spec)
        preq.study_descriptor.guid = study_name
        preq.study_descriptor.max_trial_id = max_trial_id
        return self._suggest_coalesced(preq)

    def _speculative_accept(
        self, response: pythia_service_pb2.PythiaSuggestResponse
    ) -> Optional[int]:
        """Batch size when the response is servable, else None. A response
        carrying an error, no suggestions, or the reliability fallback
        stamp must never be parked: serving cached quasi-random picks when
        a live compute might succeed would silently degrade the study."""
        if response is None or response.error or not response.suggestions:
            return None
        for suggestion in response.suggestions:
            for kv in suggestion.metadata:
                if (
                    kv.key == fallback_lib.FALLBACK_KEY
                    and kv.string_value == fallback_lib.FALLBACK_VALUE
                ):
                    return None
        return len(response.suggestions)

    def _try_speculative_serve(
        self, engine, request: pythia_service_pb2.PythiaSuggestRequest
    ) -> Optional[pythia_service_pb2.PythiaSuggestResponse]:
        """The microsecond path: pop the parked batch when the request's
        frontier fingerprint (current completed/active sets + config hash)
        matches the one it was computed for. Any failure here decays to
        the live compute — the speculative layer must never break a
        suggest."""
        study_name = request.study_name
        if not study_name:
            return None
        count = max(1, int(request.count))
        try:
            engine.note_live_suggest(study_name, count)
            completed, active, _ = self._trial_frontier(study_name)
            fingerprint = speculative_lib.make_fingerprint(
                request.study_descriptor.config.SerializeToString(),
                completed,
                active,
            )
            response, outcome = engine.try_serve(study_name, count, fingerprint)
        except Exception:
            _logger.warning(
                "Speculative serve check failed for %s; computing live.",
                study_name,
                exc_info=True,
            )
            return None
        if response is None:
            return None
        del outcome  # "hit" — the only outcome with a response
        return self._stamp_speculative(response, count)

    @staticmethod
    def _stamp_speculative(
        response: pythia_service_pb2.PythiaSuggestResponse, count: int
    ) -> pythia_service_pb2.PythiaSuggestResponse:
        """A private copy of the parked response, reconciled to ``count``
        (serving the batch prefix when the client asked for fewer) and
        stamped ``ns "serving": speculative=hit`` per suggestion so served
        speculative picks stay auditable in trial metadata."""
        out = pythia_service_pb2.PythiaSuggestResponse()
        out.CopyFrom(response)
        if count < len(out.suggestions):
            del out.suggestions[count:]
        stamp = vz.Metadata()
        stamp.ns(speculative_lib.SPECULATIVE_NAMESPACE)[
            speculative_lib.SPECULATIVE_KEY
        ] = speculative_lib.SPECULATIVE_HIT_VALUE
        key_values = pc.metadata_to_key_values(stamp)
        for suggestion in out.suggestions:
            suggestion.metadata.extend(key_values)
        return out

    def _suggest_compute(
        self, request: pythia_service_pb2.PythiaSuggestRequest
    ) -> pythia_service_pb2.PythiaSuggestResponse:
        """Speculative serve check wrapped around the live compute.

        With no engine (VIZIER_SPECULATIVE=0, the default) this is a
        direct tail call into the live path — bit-identical to the
        pre-speculation tree. Inside a speculative job's own compute the
        check is skipped too (a job must compute, not self-serve)."""
        engine = self._serving.speculative_engine
        if (
            engine is None
            or not engine.bound
            or speculative_lib.in_speculative_compute()
        ):
            return self._suggest_compute_admitted(request)
        t0 = time.perf_counter()
        served = self._try_speculative_serve(engine, request)
        if served is not None:
            engine.observe_suggest_latency("hit", time.perf_counter() - t0)
            return served
        response = self._suggest_compute_admitted(request)
        engine.observe_suggest_latency("miss", time.perf_counter() - t0)
        if not response.error:
            # "Cache fill" trigger (opt-in): the live compute just
            # refreshed the designer entry; pre-compute the batch a second
            # client at the post-suggest frontier would receive.
            engine.notify_fill(request.study_name)
        return response

    # -- multi-tenant admission (vizier_tpu.serving.admission) ---------------

    def _suggest_compute_admitted(
        self, request: pythia_service_pb2.PythiaSuggestRequest
    ) -> pythia_service_pb2.PythiaSuggestResponse:
        """The admission gate around the live designer computation.

        With no controller (VIZIER_ADMISSION=0, the default) this is a
        direct tail call — bit-identical to the pre-admission tree.
        Speculative jobs bypass it too: the speculative engine has its own
        executor-backed admission gate, and a background pre-compute must
        never consume a live in-flight slot.

        A SHED verdict returns the typed ``TRANSIENT: RESOURCE_EXHAUSTED``
        error (retry-after hint included) WITHOUT touching the study's
        circuit breaker — shed is a capacity condition, not a designer
        failure. A DEGRADE verdict (sustained-overload state machine,
        low-priority tenant) serves the seeded quasi-random fallback,
        stamped in metadata, so the remaining compute budget goes to
        in-SLO tenants.
        """
        admission = self._serving.admission
        if admission is None or speculative_lib.in_speculative_compute():
            return self._suggest_compute_live(request)
        tenant = admission_lib.tenant_of(request.study_name)
        decision = admission.decide(
            tenant,
            deadline_secs=float(request.deadline_secs),
            study=request.study_name,
        )
        if decision.outcome == admission_lib.SHED:
            tracing_lib.add_current_event(
                "admission.shed", tenant=tenant, reason=decision.reason
            )
            response = pythia_service_pb2.PythiaSuggestResponse()
            response.error = errors_lib.format_op_error(decision.error())
            return response
        if decision.outcome == admission_lib.DEGRADE:
            tracing_lib.add_current_event("admission.degraded", tenant=tenant)
            try:
                config = self._parsed_study_config(request)
            except Exception as e:  # permanent, same contract as setup
                response = pythia_service_pb2.PythiaSuggestResponse()
                response.error = errors_lib.format_op_error(e)
                return response
            response = self._fallback_response(
                config, request, "admission_degraded"
            )
            self._stamp_degraded(response)
            return response
        with admission.in_flight(decision):
            return self._suggest_compute_live(request)

    @staticmethod
    def _stamp_degraded(
        response: pythia_service_pb2.PythiaSuggestResponse,
    ) -> None:
        """``ns "admission": degraded=quasi_random`` on every suggestion,
        next to the reliability fallback stamp — degraded-mode serves stay
        auditable in trial metadata."""
        stamp = vz.Metadata()
        stamp.ns(admission_lib.ADMISSION_NAMESPACE)[
            admission_lib.ADMISSION_KEY
        ] = admission_lib.ADMISSION_VALUE
        key_values = pc.metadata_to_key_values(stamp)
        for suggestion in response.suggestions:
            suggestion.metadata.extend(key_values)

    def _suggest_compute_live(
        self, request: pythia_service_pb2.PythiaSuggestRequest
    ) -> pythia_service_pb2.PythiaSuggestResponse:
        response = pythia_service_pb2.PythiaSuggestResponse()
        reliability = self._serving.reliability
        stats = self._serving.stats

        # Config parsing and policy construction fail HARD: an invalid
        # search space or unknown algorithm is permanent — retrying or
        # falling back would serve a misconfigured study forever.
        try:
            config = self._parsed_study_config(request)
            algorithm = request.algorithm or config.algorithm
            if algorithm != config.algorithm:
                # The cached config is shared across requests (and threads):
                # a per-request algorithm override goes on a shallow copy so
                # it never leaks into later requests for the same study.
                config = dataclasses.replace(config, algorithm=algorithm)
            policy = self._get_policy(
                config,
                algorithm,
                request.study_name,
                self._request_config_hash(request),
            )
            descriptor = vz.StudyDescriptor(
                config=config,
                guid=request.study_descriptor.guid,
                max_trial_id=int(request.study_descriptor.max_trial_id),
            )
        except Exception as e:
            _logger.warning("Pythia Suggest setup failed: %s", traceback.format_exc())
            response.error = errors_lib.format_op_error(e)
            return response

        # from_wire, not from_budget: a NEGATIVE wire budget means the
        # caller's deadline already expired at the sender — the dispatch
        # check below then sheds before any designer computation runs,
        # instead of reading "expired" as "no deadline".
        deadline = (
            deadline_lib.Deadline.from_wire(request.deadline_secs)
            if reliability.deadlines_on
            else deadline_lib.Deadline.none()
        )
        breaker = (
            self._serving.breakers.get(request.study_name)
            if reliability.breaker_on
            else None
        )

        # Open circuit: skip the designer computation entirely (it would
        # very likely fail and burn the client's budget) and degrade.
        if breaker is not None and not breaker.allow():
            stats.increment("breaker_short_circuits")
            tracing_lib.add_current_event(
                "breaker.short_circuit", study=request.study_name
            )
            if reliability.fallback_on:
                return self._fallback_response(config, request, "circuit_open")
            response.error = errors_lib.format_op_error(
                errors_lib.CircuitOpenError(
                    errors_lib.mark_transient(
                        f"CIRCUIT_OPEN: breaker for study "
                        f"{request.study_name!r} is open; designer "
                        "computation skipped."
                    )
                )
            )
            return response

        try:
            # Budget already burned upstream (queueing, drain, transport):
            # not a designer failure, so no breaker record.
            deadline.check(f"suggest dispatch for {request.study_name!r}")
        except errors_lib.DeadlineExceededError as e:
            stats.increment("deadline_exceeded")
            tracing_lib.add_current_event("deadline.exceeded", at="dispatch")
            response.error = errors_lib.format_op_error(e)
            return response

        try:
            decision = policy.suggest(
                policy_lib.SuggestRequest(
                    study_descriptor=descriptor, count=int(request.count)
                )
            )
            # The over-budget computation completes the op with a typed
            # error: the client stopped waiting at its deadline, so
            # returning suggestions now would hand out trials nobody runs.
            # A chronically slow designer also counts against the breaker.
            deadline.check(
                f"suggest computation for {request.study_name!r}"
            )
        except errors_lib.DeadlineExceededError as e:
            stats.increment("deadline_exceeded")
            tracing_lib.add_current_event("deadline.exceeded", at="computation")
            if breaker is not None:
                breaker.record_failure()
            response.error = errors_lib.format_op_error(e)
            return response
        except Exception as e:
            _logger.warning("Pythia Suggest failed: %s", traceback.format_exc())
            stats.increment("designer_failures")
            tracing_lib.add_current_event(
                "designer.failure", error_type=type(e).__name__
            )
            if breaker is not None:
                breaker.record_failure()
            if reliability.fallback_on:
                return self._fallback_response(
                    config, request, f"designer_error:{type(e).__name__}"
                )
            response.error = errors_lib.format_op_error(e)
            return response

        if breaker is not None:
            breaker.record_success()
        for s in decision.suggestions:
            response.suggestions.add().CopyFrom(pc.trial_suggestion_to_proto(s))
        self._append_metadata_deltas(response, decision.metadata)
        return response

    def _fallback_response(
        self,
        config: vz.StudyConfig,
        request: pythia_service_pb2.PythiaSuggestRequest,
        reason: str,
    ) -> pythia_service_pb2.PythiaSuggestResponse:
        """Graceful degradation: seeded quasi-random, stamped + counted."""
        response = pythia_service_pb2.PythiaSuggestResponse()
        try:
            suggestions = fallback_lib.suggest_fallback(
                config.to_problem(),
                max(1, int(request.count)),
                study_name=request.study_name,
                max_trial_id=int(request.study_descriptor.max_trial_id),
                reason=reason,
            )
        except Exception as e:  # fallback itself failed: surface as transient
            _logger.warning(
                "Quasi-random fallback failed: %s", traceback.format_exc()
            )
            response.error = errors_lib.format_op_error(
                errors_lib.TransientError(
                    errors_lib.mark_transient(
                        f"FALLBACK_FAILED ({reason}): {type(e).__name__}: {e}"
                    )
                )
            )
            return response
        self._serving.stats.increment("fallbacks", len(suggestions))
        tracing_lib.add_current_event(
            "fallback.served", reason=reason, count=len(suggestions)
        )
        self._serving.flight_recorder.record(
            request.study_name, "fallback", reason=reason,
            count=len(suggestions),
        )
        _logger.warning(
            "Serving %d quasi-random fallback suggestion(s) for %s (%s).",
            len(suggestions),
            request.study_name,
            reason,
        )
        for s in suggestions:
            response.suggestions.add().CopyFrom(pc.trial_suggestion_to_proto(s))
        return response

    def EarlyStop(
        self, request: pythia_service_pb2.PythiaEarlyStopRequest, context=None
    ) -> pythia_service_pb2.PythiaEarlyStopResponse:
        response = pythia_service_pb2.PythiaEarlyStopResponse()
        try:
            # Through the parse cache (not a fresh proto->pyvizier parse):
            # EarlyStop polls ride the same (study, config-hash) identity
            # as Suggest, so a delete/recreate turnover also drops the
            # cached stopping policies below.
            config = self._parsed_study_config(request)
            if config.automated_stopping_config is not None:
                # Studies with a stopping spec pick their rule (median curve
                # or curve-regression); otherwise the algorithm's own policy
                # decides.
                from vizier_tpu.algorithms import early_stopping

                stopping = config.automated_stopping_config
                if stopping.rule == "regression":
                    # Cached per study: the policy holds a trained GBM that
                    # repeated polls between completions must reuse.
                    policy = self._stopping_policies.get(request.study_name)
                    if policy is None:
                        policy = early_stopping.RegressionEarlyStopPolicy(
                            supporter=service_policy_supporter.ServicePolicySupporter(
                                request.study_name, self._vizier
                            ),
                            min_num_trials=stopping.min_num_trials,
                        )
                        self._stopping_policies[request.study_name] = policy
                else:
                    policy = early_stopping.MedianEarlyStopPolicy(
                        supporter=service_policy_supporter.ServicePolicySupporter(
                            request.study_name, self._vizier
                        ),
                        use_steps=stopping.use_steps,
                        min_num_trials=stopping.min_num_trials,
                    )
            else:
                policy = self._get_policy(
                    config,
                    request.algorithm or config.algorithm,
                    request.study_name,
                    self._request_config_hash(request),
                )
            descriptor = vz.StudyDescriptor(
                config=config,
                guid=request.study_descriptor.guid,
                max_trial_id=int(request.study_descriptor.max_trial_id),
            )
            decisions = policy.early_stop(
                policy_lib.EarlyStopRequest(
                    study_descriptor=descriptor,
                    trial_ids=frozenset(int(i) for i in request.trial_ids),
                )
            )
            for d in decisions.decisions:
                dp = response.decisions.add()
                dp.id = d.id
                dp.should_stop = d.should_stop
                dp.reason = d.reason
        except Exception as e:
            _logger.warning("Pythia EarlyStop failed: %s", traceback.format_exc())
            response.error = errors_lib.format_op_error(e)
        return response

    def Ping(
        self, request: pythia_service_pb2.PingRequest, context=None
    ) -> pythia_service_pb2.PingResponse:
        return pythia_service_pb2.PingResponse()

    @staticmethod
    def _append_metadata_deltas(
        response: pythia_service_pb2.PythiaSuggestResponse, delta: vz.MetadataDelta
    ) -> None:
        if delta.on_study.namespaces():
            dp = response.metadata_deltas.add()
            dp.trial_id = 0
            dp.key_values.extend(pc.metadata_to_key_values(delta.on_study))
        for trial_id, md in delta.on_trials.items():
            if md.namespaces():
                dp = response.metadata_deltas.add()
                dp.trial_id = trial_id
                dp.key_values.extend(pc.metadata_to_key_values(md))
