"""Default service policy factory: algorithm string → Policy.

Parity with ``/root/reference/vizier/_src/service/policy_factory.py:28-115``
(lazy imports; DEFAULT resolves to the GP bandit stack).
"""

from __future__ import annotations

from typing import Optional

from vizier_tpu import pyvizier as vz
from vizier_tpu.pythia import policy as policy_lib
from vizier_tpu.pythia import policy_supporter as supporter_lib


class DefaultPolicyFactory:
    """Maps well-known algorithm names to policies."""

    def __call__(
        self,
        problem_statement: vz.ProblemStatement,
        algorithm: str,
        policy_supporter: supporter_lib.PolicySupporter,
        study_name: str,
    ) -> policy_lib.Policy:
        from vizier_tpu.algorithms import designer_policy
        from vizier_tpu.algorithms import random_policy

        algorithm = (algorithm or "DEFAULT").upper()
        if algorithm in ("DEFAULT", "GP_UCB_PE", "ALGORITHM_UNSPECIFIED"):
            try:
                from vizier_tpu.designers import gp_ucb_pe

                def factory(p, **kw):
                    # gRPC clients can request reference acquisition
                    # semantics (a full budget on EVERY pick) without a
                    # code path to the designer kwarg: study metadata
                    # ns 'gp_ucb_pe' key 'acquisition_budget_policy' =
                    # per_pick | per_batch | first_pick_full (default).
                    kwargs = {}
                    requested = p.metadata.ns("gp_ucb_pe").get(
                        "acquisition_budget_policy", cls=str
                    )
                    if requested:
                        kwargs["acquisition_budget_policy"] = requested
                    return gp_ucb_pe.VizierGPUCBPEBandit(p, **kwargs)

            except ImportError:  # pragma: no cover - transitional fallback
                from vizier_tpu.designers import gp_bandit

                factory = lambda p, **kw: gp_bandit.VizierGPBandit(p)
            return designer_policy.DesignerPolicy(
                policy_supporter, factory, use_seeding=True
            )
        if algorithm in ("GAUSSIAN_PROCESS_BANDIT",):
            from vizier_tpu.designers import gp_bandit

            return designer_policy.DesignerPolicy(
                policy_supporter,
                lambda p, **kw: gp_bandit.VizierGPBandit(p),
                use_seeding=True,
            )
        if algorithm == "RANDOM_SEARCH":
            return random_policy.RandomPolicy(policy_supporter)
        if algorithm == "QUASI_RANDOM_SEARCH":
            from vizier_tpu.designers import quasi_random

            return designer_policy.PartiallySerializableDesignerPolicy(
                policy_supporter,
                lambda p, **kw: quasi_random.QuasiRandomDesigner(p.search_space),
            )
        if algorithm in ("GRID_SEARCH", "SHUFFLED_GRID_SEARCH"):
            from vizier_tpu.designers import grid

            shuffle = 0 if algorithm == "SHUFFLED_GRID_SEARCH" else None
            return designer_policy.PartiallySerializableDesignerPolicy(
                policy_supporter,
                lambda p, **kw: grid.GridSearchDesigner(p.search_space, shuffle_seed=shuffle),
            )
        if algorithm == "NSGA2":
            from vizier_tpu.designers import evolution

            return designer_policy.PartiallySerializableDesignerPolicy(
                policy_supporter, lambda p, **kw: evolution.NSGA2Designer(p)
            )
        if algorithm == "EAGLE_STRATEGY":
            from vizier_tpu.designers import eagle_strategy

            return designer_policy.PartiallySerializableDesignerPolicy(
                policy_supporter,
                lambda p, **kw: eagle_strategy.EagleStrategyDesigner(p),
            )
        if algorithm == "CMA_ES":
            from vizier_tpu.designers import cmaes

            return designer_policy.DesignerPolicy(
                policy_supporter, lambda p, **kw: cmaes.CMAESDesigner(p)
            )
        if algorithm == "BOCS":
            from vizier_tpu.designers import bocs

            return designer_policy.DesignerPolicy(
                policy_supporter, lambda p, **kw: bocs.BOCSDesigner(p)
            )
        if algorithm == "HARMONICA":
            from vizier_tpu.designers import harmonica

            return designer_policy.DesignerPolicy(
                policy_supporter, lambda p, **kw: harmonica.HarmonicaDesigner(p)
            )
        if algorithm == "PYGLOVE":
            from vizier_tpu.pyglove import backend as pyglove_backend

            registered = pyglove_backend.get_registered_generator(study_name)
            if registered is None:
                raise ValueError(
                    f"No PyGlove generator registered for study {study_name!r}; "
                    "construct VizierBackend with dna_spec and algorithm in the "
                    "primary tuner process first."
                )
            dna_spec, generator = registered
            return pyglove_backend.TunerPolicy(policy_supporter, dna_spec, generator)
        raise ValueError(f"Unknown algorithm: {algorithm!r}")
