"""Default service policy factory: algorithm string → Policy.

Parity with ``/root/reference/vizier/_src/service/policy_factory.py:28-115``
(lazy imports; DEFAULT resolves to the GP bandit stack).
"""

from __future__ import annotations

from typing import Optional

from vizier_tpu import pyvizier as vz
from vizier_tpu.pythia import policy as policy_lib
from vizier_tpu.pythia import policy_supporter as supporter_lib


_ALLOWED_BUDGET_POLICIES = ("first_pick_full", "per_batch", "per_pick")


def _validated_acq_evals(problem_statement) -> int:
    """Study-metadata acquisition-sweep budget (0 = designer default).

    Namespace ``gp_ucb_pe``, key ``max_acquisition_evaluations``: like
    ``acquisition_budget_policy`` this is the remote client's only path
    to a designer kwarg — the value travels inside the StudySpec, so a
    shared compute server applies the requesting study's budget without
    any per-process configuration. Raises on non-integer or negative
    values so a typo surfaces on the first suggest.
    """
    ns = problem_statement.metadata.ns("gp_ucb_pe")
    raw = ns.get("max_acquisition_evaluations")
    if raw is None:
        return 0
    try:
        evals = int(raw)
    except (TypeError, ValueError):
        evals = -1
    if evals < 0:
        raise ValueError(
            "Invalid study metadata ns 'gp_ucb_pe' key "
            f"'max_acquisition_evaluations': {raw!r}. "
            "Expected a non-negative integer (0 = designer default)."
        )
    return evals


class DefaultPolicyFactory:
    """Maps well-known algorithm names to policies.

    With a ``serving_runtime`` (``vizier_tpu.serving.ServingRuntime``), the
    GP algorithms route through the per-study designer-state cache
    (``CachedDesignerStatePolicy``) instead of the stateless
    fresh-designer-per-request ``DesignerPolicy``, and the designers are
    configured for warm-started ARD per the runtime's config.
    """

    def __init__(self, serving_runtime=None):
        self._serving = serving_runtime

    def _gp_designer_kwargs(self) -> dict:
        """Serving-config-driven designer knobs for the GP algorithms."""
        if self._serving is None:
            return {}
        cfg = self._serving.config
        kwargs = {"use_warm_start_ard": cfg.warm_start}
        if cfg.warm_start:
            kwargs["warm_ard_restarts"] = cfg.warm_ard_restarts
        # The process-wide exact↔sparse surrogate policy
        # (vizier_tpu.surrogates): every GP designer the factory builds
        # shares the runtime's auto-switch config.
        surrogates = getattr(self._serving, "surrogates", None)
        if surrogates is not None:
            kwargs["surrogate"] = surrogates
        return kwargs

    def _gp_policy(
        self, policy_supporter, factory, study_name: str, problem=None
    ) -> policy_lib.Policy:
        """Cache-backed policy when serving is on; stateless otherwise."""
        from vizier_tpu.algorithms import designer_policy

        if self._serving is not None and self._serving.config.designer_cache:
            from vizier_tpu.serving import policy as serving_policy

            if problem is not None:
                # Background AOT compile of the batched programs for this
                # search-space shape (no-op unless batching_prewarm is on).
                self._serving.maybe_prewarm_batching_async(problem, factory)
            return serving_policy.CachedDesignerStatePolicy(
                policy_supporter,
                factory,
                self._serving,
                study_name,
                use_seeding=True,
            )
        return designer_policy.DesignerPolicy(
            policy_supporter, factory, use_seeding=True
        )

    def __call__(
        self,
        problem_statement: vz.ProblemStatement,
        algorithm: str,
        policy_supporter: supporter_lib.PolicySupporter,
        study_name: str,
    ) -> policy_lib.Policy:
        from vizier_tpu.algorithms import designer_policy
        from vizier_tpu.algorithms import random_policy

        algorithm = (algorithm or "DEFAULT").upper()
        if algorithm in ("DEFAULT", "GP_UCB_PE", "ALGORITHM_UNSPECIFIED"):
            # Validate the metadata override HERE, at policy construction:
            # a client typo must surface as one descriptive error on the
            # first suggest, not a deep ValueError inside every designer
            # construction for the study's lifetime.
            requested_policy = problem_statement.metadata.ns("gp_ucb_pe").get(
                "acquisition_budget_policy", cls=str
            )
            if requested_policy and requested_policy not in _ALLOWED_BUDGET_POLICIES:
                raise ValueError(
                    "Invalid study metadata ns 'gp_ucb_pe' key "
                    f"'acquisition_budget_policy': {requested_policy!r}. "
                    f"Allowed values: {', '.join(_ALLOWED_BUDGET_POLICIES)}."
                )
            _validated_acq_evals(problem_statement)
            try:
                from vizier_tpu.designers import gp_ucb_pe

                serving_kwargs = self._gp_designer_kwargs()

                def factory(p, **kw):
                    # gRPC clients can request reference acquisition
                    # semantics (a full budget on EVERY pick) without a
                    # code path to the designer kwarg: study metadata
                    # ns 'gp_ucb_pe' key 'acquisition_budget_policy' =
                    # per_pick | per_batch | first_pick_full (default).
                    kwargs = dict(serving_kwargs)
                    requested = p.metadata.ns("gp_ucb_pe").get(
                        "acquisition_budget_policy", cls=str
                    )
                    if requested:
                        kwargs["acquisition_budget_policy"] = requested
                    # Same remote-client contract for the acquisition
                    # sweep size: the key rides the StudySpec through the
                    # Pythia surface, so a disaggregated compute server
                    # honors it with no out-of-band configuration.
                    evals = _validated_acq_evals(p)
                    if evals:
                        kwargs["max_acquisition_evaluations"] = evals
                    return gp_ucb_pe.VizierGPUCBPEBandit(p, **kwargs)

            except ImportError:  # pragma: no cover - transitional fallback
                from vizier_tpu.designers import gp_bandit

                factory = lambda p, **kw: gp_bandit.VizierGPBandit(p)
            return self._gp_policy(
                policy_supporter, factory, study_name, problem=problem_statement
            )
        if algorithm in ("GAUSSIAN_PROCESS_BANDIT",):
            from vizier_tpu.designers import gp_bandit

            serving_kwargs = self._gp_designer_kwargs()
            return self._gp_policy(
                policy_supporter,
                lambda p, **kw: gp_bandit.VizierGPBandit(p, **serving_kwargs),
                study_name,
                problem=problem_statement,
            )
        if algorithm == "RANDOM_SEARCH":
            return random_policy.RandomPolicy(policy_supporter)
        if algorithm == "QUASI_RANDOM_SEARCH":
            from vizier_tpu.designers import quasi_random

            return designer_policy.PartiallySerializableDesignerPolicy(
                policy_supporter,
                lambda p, **kw: quasi_random.QuasiRandomDesigner(p.search_space),
            )
        if algorithm in ("GRID_SEARCH", "SHUFFLED_GRID_SEARCH"):
            from vizier_tpu.designers import grid

            shuffle = 0 if algorithm == "SHUFFLED_GRID_SEARCH" else None
            return designer_policy.PartiallySerializableDesignerPolicy(
                policy_supporter,
                lambda p, **kw: grid.GridSearchDesigner(p.search_space, shuffle_seed=shuffle),
            )
        if algorithm == "NSGA2":
            from vizier_tpu.designers import evolution

            return designer_policy.PartiallySerializableDesignerPolicy(
                policy_supporter, lambda p, **kw: evolution.NSGA2Designer(p)
            )
        if algorithm == "EAGLE_STRATEGY":
            from vizier_tpu.designers import eagle_strategy

            return designer_policy.PartiallySerializableDesignerPolicy(
                policy_supporter,
                lambda p, **kw: eagle_strategy.EagleStrategyDesigner(p),
            )
        if algorithm == "CMA_ES":
            from vizier_tpu.designers import cmaes

            return designer_policy.DesignerPolicy(
                policy_supporter, lambda p, **kw: cmaes.CMAESDesigner(p)
            )
        if algorithm == "BOCS":
            from vizier_tpu.designers import bocs

            return designer_policy.DesignerPolicy(
                policy_supporter, lambda p, **kw: bocs.BOCSDesigner(p)
            )
        if algorithm == "HARMONICA":
            from vizier_tpu.designers import harmonica

            return designer_policy.DesignerPolicy(
                policy_supporter, lambda p, **kw: harmonica.HarmonicaDesigner(p)
            )
        if algorithm == "PYGLOVE":
            from vizier_tpu.pyglove import backend as pyglove_backend

            registered = pyglove_backend.get_registered_generator(study_name)
            if registered is None:
                raise ValueError(
                    f"No PyGlove generator registered for study {study_name!r}; "
                    "construct VizierBackend with dna_spec and algorithm in the "
                    "primary tuner process first."
                )
            dna_spec, generator = registered
            return pyglove_backend.TunerPolicy(policy_supporter, dna_spec, generator)
        raise ValueError(f"Unknown algorithm: {algorithm!r}")
