"""Low-level Vizier client: RPC wrappers + suggestion-operation polling.

Parity with ``/root/reference/vizier/_src/service/vizier_client.py:94,127``
(polling loop ``:166-179``): the client targets either a remote gRPC
endpoint or an in-process ``VizierServicer`` through the same interface (the
reference's in-process/stub Union trick, ``types.py:24-33``).
"""

from __future__ import annotations

import atexit
import dataclasses
import time
from typing import Any, Dict, List, Optional, Union

from vizier_tpu import pyvizier as vz
from vizier_tpu.observability import tracing as tracing_lib
from vizier_tpu.reliability import config as reliability_config_lib
from vizier_tpu.reliability import deadline as deadline_lib
from vizier_tpu.reliability import errors as errors_lib
from vizier_tpu.reliability import retry as retry_lib
from vizier_tpu.service import proto_converters as pc
from vizier_tpu.service import resources
from vizier_tpu.service.protos import study_pb2, vizier_service_pb2

NO_ENDPOINT = "NO_ENDPOINT"

# Hard ceiling on retry-after-paced shed retries per get_suggestions call
# (the overall polling deadline is the real bound; this stops a pathological
# zero-hint loop from spinning).
_MAX_SHED_RETRIES = 100


@dataclasses.dataclass
class EnvironmentVariables:
    """Process-global client defaults (reference ``vizier_client.py:46-72``)."""

    server_endpoint: str = NO_ENDPOINT
    # Sharded tier: the replica endpoints, in replica-id order (position i
    # is "replica-i"). When set, clients route each study to its owning
    # replica through a RoutedVizierStub — VizierClient code is unchanged.
    # Takes precedence over ``server_endpoint``.
    server_endpoints: Optional[List[str]] = None
    servicer_kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # Initial GetOperation poll delay; grows by bounded exponential backoff
    # (doubling with jitter, capped at 8x) while an op stays not-done.
    polling_delay_secs: float = 0.1
    polling_timeout_secs: float = 600.0


environment_variables = EnvironmentVariables()

_local_servicer = None
_routed_stubs: Dict[tuple, Any] = {}


def _get_local_servicer():
    """Lazily creates one in-process service shared by local clients."""
    global _local_servicer
    if _local_servicer is None:
        from vizier_tpu.service import pythia_service, vizier_service

        servicer = vizier_service.VizierServicer(
            **environment_variables.servicer_kwargs
        )
        pythia = pythia_service.PythiaServicer(servicer)
        servicer.set_pythia(pythia)
        _local_servicer = servicer
        # The serving runtime's background threads (speculative workers,
        # prewarm compiles, the batch-executor scheduler) must be joined
        # before interpreter teardown — an XLA compile aborted mid-flight
        # SIGABRTs the process. Explicit servers shut down through their
        # own lifecycle; the implicit in-process service gets an atexit
        # hook (shutdown is idempotent).
        atexit.register(pythia.shutdown)
    return _local_servicer


def _routed_stub(endpoints) -> Any:
    """One RoutedVizierStub per endpoint list (shared, like gRPC channels)."""
    key = tuple(endpoints)
    stub = _routed_stubs.get(key)
    if stub is None:
        from vizier_tpu.analysis import registry as _registry
        from vizier_tpu.distributed import router_stub
        from vizier_tpu.observability import metrics as metrics_lib
        from vizier_tpu.service import grpc_stubs

        stub = router_stub.RoutedVizierStub(
            {
                f"replica-{i}": (lambda ep=ep: grpc_stubs.create_vizier_stub(ep))
                for i, ep in enumerate(key)
            },
            routing_enabled=_registry.env_on("VIZIER_DISTRIBUTED"),
            registry=metrics_lib.default_registry(),
        )
        _routed_stubs[key] = stub
    return stub


def create_service_stub(endpoint: Optional[str] = None):
    """Returns a gRPC stub, a routed multi-replica stub, or the in-process
    servicer — all duck-typed alike, so callers cannot tell them apart."""
    if endpoint is None and environment_variables.server_endpoints:
        return _routed_stub(environment_variables.server_endpoints)
    if isinstance(endpoint, (list, tuple)):
        return _routed_stub(endpoint)
    endpoint = endpoint or environment_variables.server_endpoint
    if endpoint == NO_ENDPOINT:
        return _get_local_servicer()
    from vizier_tpu.service import grpc_stubs

    return grpc_stubs.create_vizier_stub(endpoint)


class VizierClient:
    """Study-scoped RPC wrapper.

    Every RPC goes through a :class:`~vizier_tpu.reliability.RetryPolicy`
    (exponential backoff + full jitter over transient transport errors),
    and ``get_suggestions`` attaches a deadline budget to the request,
    polls with bounded exponential backoff, and retries ops that failed
    with a ``TRANSIENT:``-marked error. ``VIZIER_RELIABILITY=0`` (or a
    ``reliability`` config with everything off) restores the seed's
    fail-hard, fixed-sleep behavior.
    """

    def __init__(
        self,
        service,
        study_name: str,
        client_id: str,
        *,
        reliability: Optional[reliability_config_lib.ReliabilityConfig] = None,
    ):
        self._service = service
        self._study_name = study_name
        self._client_id = client_id
        self._reliability = (
            reliability or reliability_config_lib.ReliabilityConfig.from_env()
        )
        self._retry = retry_lib.RetryPolicy.from_config(self._reliability)

    # -- reliability plumbing ----------------------------------------------

    def _count_retry(self, error: BaseException, attempt: int) -> None:
        del error, attempt
        # Surfaces in serving_stats() when the service is in-process; a
        # remote stub has no retry-accounting RPC, so this is best-effort.
        record = getattr(self._service, "record_client_retry", None)
        if record is not None:
            try:
                record(1)
            except Exception:
                pass

    def _call(self, method_name: str, request, deadline=None):
        """One RPC with transient-error retries (when reliability is on).

        At-least-once semantics: a transient failure on the response path
        of a mutating RPC can re-apply it (a duplicated measurement, or a
        "already completed" error on a replayed CompleteTrial). The
        service's idempotent paths (op dedup, ACTIVE-trial reuse) absorb
        the suggest-side cases; the rest is the standard retry tradeoff.
        """
        method = getattr(self._service, method_name)
        if not self._reliability.retries_on:
            return method(request)
        return self._retry.call(
            lambda: method(request), on_retry=self._count_retry, deadline=deadline
        )

    @property
    def study_name(self) -> str:
        return self._study_name

    @property
    def client_id(self) -> str:
        return self._client_id

    # -- factory -----------------------------------------------------------

    @classmethod
    def create_or_load_study(
        cls,
        owner_id: str,
        study_id: str,
        study_config: vz.StudyConfig,
        *,
        client_id: str = "default_client_id",
        endpoint: Optional[str] = None,
    ) -> "VizierClient":
        service = create_service_stub(endpoint)
        study_name = resources.StudyResource(owner_id, study_id).name
        study = pc.study_to_proto(study_config, study_name, display_name=study_id)
        service.CreateStudy(
            vizier_service_pb2.CreateStudyRequest(
                parent=resources.OwnerResource(owner_id).name, study=study
            )
        )
        return cls(service, study_name, client_id)

    @classmethod
    def load_study(
        cls,
        study_name: str,
        *,
        client_id: str = "default_client_id",
        endpoint: Optional[str] = None,
    ) -> "VizierClient":
        service = create_service_stub(endpoint)
        service.GetStudy(vizier_service_pb2.GetStudyRequest(name=study_name))
        return cls(service, study_name, client_id)

    # -- suggestions -------------------------------------------------------

    def get_suggestions(
        self, suggestion_count: int, *, deadline_secs: Optional[float] = None
    ) -> List[vz.Trial]:
        """Requests suggestions, polling the long-running operation.

        The whole exchange — RPCs, polling, and op-level retries — is
        bounded by ``polling_timeout_secs``. With deadlines on, a budget
        (``deadline_secs`` or the config default, never more than the
        remaining polling window) rides on each request so the service can
        complete an over-budget computation with a typed
        ``TRANSIENT: DEADLINE_EXCEEDED:`` error instead of silently burning
        this client's polling timeout. Ops that fail with a
        ``TRANSIENT:``-marked error are retried with backoff; permanent
        errors raise immediately.
        """
        cfg = self._reliability
        overall = deadline_lib.Deadline.from_budget(
            environment_variables.polling_timeout_secs
        )
        attempts = max(1, cfg.retry_max_attempts) if cfg.retries_on else 1
        op = None
        # The trace root: every downstream hop (service, Pythia dispatch,
        # designer compute) parents onto this span via the request's
        # trace_context field.
        with tracing_lib.get_tracer().span(
            "client.suggest",
            study=self._study_name,
            client_id=self._client_id,
            count=int(suggestion_count),
        ) as span:
            attempt = 0
            shed_retries = 0
            while True:
                op = self._poll_suggest_op(
                    suggestion_count, overall, deadline_secs
                )
                if not op.error:
                    return [pc.trial_from_proto(t) for t in op.response.trials]
                if not errors_lib.has_transient_marker(op.error):
                    break
                # An admission shed carrying a retry-after hint is
                # BACKPRESSURE, not failure: the service is pacing this
                # client, so honoring the hint must not burn the fixed
                # retry budget (a saturated-but-recovering fleet would
                # otherwise fail exactly the clients it asked to wait).
                # Shed retries are bounded by the overall polling deadline
                # and a hard ceiling instead.
                hint = (
                    errors_lib.retry_after_secs(op.error)
                    if cfg.retries_on
                    else None
                )
                if hint is not None and shed_retries < _MAX_SHED_RETRIES:
                    shed_retries += 1
                    delay = max(self._retry.delay_for_attempt(attempt), hint)
                    if overall.remaining() <= delay:
                        break
                    self._count_retry(RuntimeError(op.error), attempt)
                    span.add_event("shed_retry", shed=shed_retries)
                    self._retry.sleep_fn(delay)
                    continue
                attempt += 1
                if attempt >= attempts:
                    break
                delay = self._retry.delay_for_attempt(attempt - 1)
                if overall.remaining() <= delay:
                    break
                self._count_retry(RuntimeError(op.error), attempt - 1)
                span.add_event("transient_retry", attempt=attempt - 1)
                self._retry.sleep_fn(delay)
            span.set_attribute("error", op.error.splitlines()[0][:200])
        raise RuntimeError(f"SuggestTrials failed: {op.error}")

    def _poll_suggest_op(
        self,
        suggestion_count: int,
        overall: deadline_lib.Deadline,
        deadline_secs: Optional[float],
    ) -> vizier_service_pb2.Operation:
        """One SuggestTrials round-trip: issue the op, poll it to done."""
        budget = 0.0
        if self._reliability.deadlines_on:
            budget = (
                deadline_secs
                if deadline_secs is not None
                else self._reliability.default_deadline_secs
            )
            # Never promise the service more budget than this client will
            # actually wait.
            budget = min(budget, overall.remaining())
            if budget <= 0.0:
                # The budget is already gone at send time. 0 on the wire
                # means "no deadline", so an expired budget travels as a
                # NEGATIVE value — the service ingress sheds it with the
                # typed deadline error instead of computing unbounded.
                budget = min(budget, -1e-3)
        op = self._call(
            "SuggestTrials",
            vizier_service_pb2.SuggestTrialsRequest(
                parent=self._study_name,
                suggestion_count=suggestion_count,
                client_id=self._client_id,
                deadline_secs=budget,
                # Carries the client.suggest span across the RPC ('' when
                # tracing is off — the service then starts its own trace).
                trace_context=tracing_lib.format_context(
                    tracing_lib.get_tracer().current_context()
                ),
            ),
            deadline=overall,
        )
        # Bounded exponential backoff on the poll (satellite of the fixed
        # 100 ms sleep): doubles per not-done poll, jittered, capped at 8x
        # the base delay — cutting idle GetOperation load at scale while
        # keeping first-response latency identical.
        base = environment_variables.polling_delay_secs
        delay = base
        while not op.done:
            if overall.expired:
                raise TimeoutError(f"Suggestion operation timed out: {op.name}")
            jittered = (
                self._retry.rng.uniform(0.5 * delay, delay)
                if self._retry.jitter
                else delay
            )
            time.sleep(min(jittered, max(0.0, overall.remaining())))
            op = self._call(
                "GetOperation",
                vizier_service_pb2.GetOperationRequest(name=op.name),
                deadline=overall,
            )
            delay = min(delay * 2.0, base * 8.0)
        return op

    # -- trials ------------------------------------------------------------

    def _trial_name(self, trial_id: int) -> str:
        return resources.StudyResource.from_name(self._study_name).trial_resource(
            trial_id
        ).name

    def create_trial(self, trial: vz.Trial) -> vz.Trial:
        proto = pc.trial_to_proto(trial)
        out = self._call("CreateTrial",
            vizier_service_pb2.CreateTrialRequest(parent=self._study_name, trial=proto)
        )
        return pc.trial_from_proto(out)

    def get_trial(self, trial_id: int) -> vz.Trial:
        return pc.trial_from_proto(
            self._call("GetTrial",
                vizier_service_pb2.GetTrialRequest(name=self._trial_name(trial_id))
            )
        )

    def list_trials(self) -> List[vz.Trial]:
        response = self._call("ListTrials",
            vizier_service_pb2.ListTrialsRequest(parent=self._study_name)
        )
        return [pc.trial_from_proto(t) for t in response.trials]

    def report_intermediate_objective_value(
        self, trial_id: int, measurement: vz.Measurement
    ) -> vz.Trial:
        out = self._call("AddTrialMeasurement",
            vizier_service_pb2.AddTrialMeasurementRequest(
                trial_name=self._trial_name(trial_id),
                measurement=pc.measurement_to_proto(measurement),
            )
        )
        return pc.trial_from_proto(out)

    def complete_trial(
        self,
        trial_id: int,
        final_measurement: Optional[vz.Measurement] = None,
        *,
        infeasibility_reason: Optional[str] = None,
    ) -> vz.Trial:
        request = vizier_service_pb2.CompleteTrialRequest(
            name=self._trial_name(trial_id),
            trial_infeasible=infeasibility_reason is not None,
            infeasible_reason=infeasibility_reason or "",
        )
        if final_measurement is not None:
            request.final_measurement.CopyFrom(
                pc.measurement_to_proto(final_measurement)
            )
        return pc.trial_from_proto(self._call("CompleteTrial", request))

    def should_trial_stop(self, trial_id: int) -> bool:
        response = self._call("CheckTrialEarlyStoppingState",
            vizier_service_pb2.CheckTrialEarlyStoppingStateRequest(
                trial_name=self._trial_name(trial_id)
            )
        )
        return response.should_stop

    def stop_trial(self, trial_id: int) -> vz.Trial:
        return pc.trial_from_proto(
            self._call("StopTrial",
                vizier_service_pb2.StopTrialRequest(name=self._trial_name(trial_id))
            )
        )

    def delete_trial(self, trial_id: int) -> None:
        self._call("DeleteTrial",
            vizier_service_pb2.DeleteTrialRequest(name=self._trial_name(trial_id))
        )

    # -- study -------------------------------------------------------------

    def get_study_config(self, study_name: Optional[str] = None) -> vz.StudyConfig:
        study = self._call("GetStudy",
            vizier_service_pb2.GetStudyRequest(name=study_name or self._study_name)
        )
        return pc.study_config_from_proto(study.study_spec)

    def cached_study_config(self) -> vz.StudyConfig:
        """This study's config, fetched once per client — for SPEC decoding.

        The service has no RPC that edits a study's search space or metric
        configuration after creation (``SetStudyState`` touches state only),
        so spec-derived uses — e.g. decoding trial parameters — can reuse
        one fetch instead of a ``GetStudy`` round-trip per access. Study
        METADATA is mutable via ``UpdateMetadata`` and may be stale here;
        metadata readers must use :meth:`get_study_config`.
        """
        cached = getattr(self, "_study_config_cache", None)
        if cached is None:
            cached = self._study_config_cache = self.get_study_config()
        return cached

    def set_study_state(self, state: vz.StudyState, reason: str = "") -> None:
        state_map = {
            vz.StudyState.ACTIVE: study_pb2.Study.ACTIVE,
            vz.StudyState.ABORTED: study_pb2.Study.INACTIVE,
            vz.StudyState.COMPLETED: study_pb2.Study.COMPLETED,
        }
        self._call("SetStudyState",
            vizier_service_pb2.SetStudyStateRequest(
                name=self._study_name, state=state_map[state], reason=reason
            )
        )

    def delete_study(self) -> None:
        self._call("DeleteStudy",
            vizier_service_pb2.DeleteStudyRequest(name=self._study_name)
        )

    def list_optimal_trials(self) -> List[vz.Trial]:
        response = self._call("ListOptimalTrials",
            vizier_service_pb2.ListOptimalTrialsRequest(parent=self._study_name)
        )
        return [pc.trial_from_proto(t) for t in response.optimal_trials]

    def update_metadata(self, delta: vz.MetadataDelta) -> None:
        request = vizier_service_pb2.UpdateMetadataRequest(name=self._study_name)
        for kv in pc.metadata_to_key_values(delta.on_study):
            unit = request.deltas.add()
            unit.trial_id = 0
            unit.key_value.CopyFrom(kv)
        for trial_id, md in delta.on_trials.items():
            for kv in pc.metadata_to_key_values(md):
                unit = request.deltas.add()
                unit.trial_id = trial_id
                unit.key_value.CopyFrom(kv)
        response = self._call("UpdateMetadata", request)
        if response.error_details:
            raise KeyError(response.error_details)
