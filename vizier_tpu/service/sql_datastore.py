"""SQLite datastore: the same contract over stdlib sqlite3.

Parity with ``/root/reference/vizier/_src/service/sql_datastore.py:40``
(SQLAlchemy there; plain sqlite3 here — the environment ships no SQLAlchemy,
and a zero-dependency store with proto-blob columns has identical
semantics). Supports ``sqlite:///:memory:`` and ``sqlite:////path/to.db``
URLs. Thread-safe via one connection guarded by a lock (the service layer
serializes per-study writes anyway).
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Callable, Iterable, List, Optional

from vizier_tpu.service import datastore
from vizier_tpu.service import resources
from vizier_tpu.service.protos import key_value_pb2, study_pb2, vizier_service_pb2

SQL_MEMORY_URL = "sqlite:///:memory:"


def _path_from_url(url: str) -> str:
    if not url.startswith("sqlite:///"):
        raise ValueError(f"Only sqlite:/// URLs are supported, got {url!r}")
    return url[len("sqlite:///") :]


_SCHEMA = """
CREATE TABLE IF NOT EXISTS studies (
  name TEXT PRIMARY KEY,
  owner TEXT NOT NULL,
  blob BLOB NOT NULL
);
CREATE TABLE IF NOT EXISTS trials (
  name TEXT PRIMARY KEY,
  study TEXT NOT NULL,
  trial_id INTEGER NOT NULL,
  state INTEGER NOT NULL DEFAULT 0,
  blob BLOB NOT NULL
);
CREATE INDEX IF NOT EXISTS trials_by_study ON trials (study, trial_id);
CREATE TABLE IF NOT EXISTS suggestion_ops (
  name TEXT PRIMARY KEY,
  study TEXT NOT NULL,
  client_id TEXT NOT NULL,
  op_number INTEGER NOT NULL,
  done INTEGER NOT NULL DEFAULT 0,
  blob BLOB NOT NULL
);
CREATE INDEX IF NOT EXISTS ops_by_client ON suggestion_ops (study, client_id, op_number);
CREATE TABLE IF NOT EXISTS early_stopping_ops (
  name TEXT PRIMARY KEY,
  study TEXT NOT NULL,
  blob BLOB NOT NULL
);
"""


class SQLDataStore(datastore.DataStore):
    def __init__(self, url: str = SQL_MEMORY_URL):
        self._lock = threading.Lock()
        self._conn = sqlite3.connect(_path_from_url(url), check_same_thread=False)
        with self._lock:
            self._conn.executescript(_SCHEMA)
            # Migration for databases created before the `done` column.
            # Completion is tracked by PRAGMA user_version (>= 1), NOT by
            # column presence: the ALTER autocommits immediately in the
            # sqlite3 module, so a crash mid-backfill would otherwise leave
            # the column present with every flag stuck at 0 — and done=True
            # ops misread as orphans. The backfill is idempotent, and
            # user_version flips inside the same transaction as its last
            # UPDATE, so an interrupted run simply re-runs.
            cols = {
                row[1]
                for row in self._conn.execute(
                    "PRAGMA table_info(suggestion_ops)"
                )
            }
            if "done" not in cols:
                self._conn.execute(
                    "ALTER TABLE suggestion_ops ADD COLUMN done INTEGER NOT NULL DEFAULT 0"
                )
            trial_cols = {
                row[1]
                for row in self._conn.execute("PRAGMA table_info(trials)")
            }
            if "state" not in trial_cols:
                self._conn.execute(
                    "ALTER TABLE trials ADD COLUMN state INTEGER NOT NULL DEFAULT 0"
                )
            version = self._conn.execute("PRAGMA user_version").fetchone()[0]
            if version < 1:
                for name, blob in self._conn.execute(
                    "SELECT name, blob FROM suggestion_ops"
                ).fetchall():
                    op = vizier_service_pb2.Operation.FromString(blob)
                    if op.done:
                        self._conn.execute(
                            "UPDATE suggestion_ops SET done = 1 WHERE name = ?",
                            (name,),
                        )
            if version < 2:
                for name, blob in self._conn.execute(
                    "SELECT name, blob FROM trials"
                ).fetchall():
                    t = study_pb2.Trial.FromString(blob)
                    self._conn.execute(
                        "UPDATE trials SET state = ? WHERE name = ?",
                        (int(t.state), name),
                    )
                self._conn.execute("PRAGMA user_version = 2")
            # After the column is guaranteed (fresh schema or migration).
            # Covers the dedup query's filter AND its op_number ordering.
            self._conn.execute(
                "CREATE INDEX IF NOT EXISTS ops_by_done"
                " ON suggestion_ops (study, client_id, done, op_number)"
            )
            self._conn.commit()

    # -- studies -----------------------------------------------------------

    def create_study(self, study: study_pb2.Study) -> str:
        r = resources.StudyResource.from_name(study.name)
        with self._lock:
            try:
                self._conn.execute(
                    "INSERT INTO studies (name, owner, blob) VALUES (?, ?, ?)",
                    (study.name, r.owner_id, study.SerializeToString()),
                )
                self._conn.commit()
            except sqlite3.IntegrityError:
                raise datastore.AlreadyExistsError(f"Study exists: {study.name}")
        return study.name

    def load_study(self, study_name: str) -> study_pb2.Study:
        with self._lock:
            row = self._conn.execute(
                "SELECT blob FROM studies WHERE name = ?", (study_name,)
            ).fetchone()
        if row is None:
            raise datastore.NotFoundError(f"No such study: {study_name}")
        return study_pb2.Study.FromString(row[0])

    def update_study(self, study: study_pb2.Study) -> str:
        with self._lock:
            cur = self._conn.execute(
                "UPDATE studies SET blob = ? WHERE name = ?",
                (study.SerializeToString(), study.name),
            )
            self._conn.commit()
        if cur.rowcount == 0:
            raise datastore.NotFoundError(f"No such study: {study.name}")
        return study.name

    def delete_study(self, study_name: str) -> None:
        with self._lock:
            cur = self._conn.execute("DELETE FROM studies WHERE name = ?", (study_name,))
            self._conn.execute("DELETE FROM trials WHERE study = ?", (study_name,))
            self._conn.execute(
                "DELETE FROM suggestion_ops WHERE study = ?", (study_name,)
            )
            self._conn.execute(
                "DELETE FROM early_stopping_ops WHERE study = ?", (study_name,)
            )
            self._conn.commit()
        if cur.rowcount == 0:
            raise datastore.NotFoundError(f"No such study: {study_name}")

    def list_studies(self, owner_name: str) -> List[study_pb2.Study]:
        r = resources.OwnerResource.from_name(owner_name)
        with self._lock:
            rows = self._conn.execute(
                "SELECT blob FROM studies WHERE owner = ? ORDER BY name", (r.owner_id,)
            ).fetchall()
        return [study_pb2.Study.FromString(b) for (b,) in rows]

    def _require_study(self, study_name: str) -> None:
        row = self._conn.execute(
            "SELECT 1 FROM studies WHERE name = ?", (study_name,)
        ).fetchone()
        if row is None:
            raise datastore.NotFoundError(f"No such study: {study_name}")

    # -- trials ------------------------------------------------------------

    def create_trial(self, trial: study_pb2.Trial) -> str:
        r = resources.TrialResource.from_name(trial.name)
        with self._lock:
            self._require_study(r.study_resource.name)
            try:
                self._conn.execute(
                    "INSERT INTO trials (name, study, trial_id, state, blob)"
                    " VALUES (?, ?, ?, ?, ?)",
                    (
                        trial.name,
                        r.study_resource.name,
                        r.trial_id,
                        int(trial.state),
                        trial.SerializeToString(),
                    ),
                )
                self._conn.commit()
            except sqlite3.IntegrityError:
                raise datastore.AlreadyExistsError(f"Trial exists: {trial.name}")
        return trial.name

    def get_trial(self, trial_name: str) -> study_pb2.Trial:
        with self._lock:
            row = self._conn.execute(
                "SELECT blob FROM trials WHERE name = ?", (trial_name,)
            ).fetchone()
        if row is None:
            raise datastore.NotFoundError(f"No such trial: {trial_name}")
        return study_pb2.Trial.FromString(row[0])

    def update_trial(self, trial: study_pb2.Trial) -> str:
        with self._lock:
            cur = self._conn.execute(
                "UPDATE trials SET blob = ?, state = ? WHERE name = ?",
                (trial.SerializeToString(), int(trial.state), trial.name),
            )
            self._conn.commit()
        if cur.rowcount == 0:
            raise datastore.NotFoundError(f"No such trial: {trial.name}")
        return trial.name

    def delete_trial(self, trial_name: str) -> None:
        with self._lock:
            cur = self._conn.execute("DELETE FROM trials WHERE name = ?", (trial_name,))
            self._conn.commit()
        if cur.rowcount == 0:
            raise datastore.NotFoundError(f"No such trial: {trial_name}")

    def list_trials(
        self, study_name: str, *, states: Optional[tuple] = None
    ) -> List[study_pb2.Trial]:
        query = "SELECT blob FROM trials WHERE study = ?"
        params: tuple = (study_name,)
        if states is not None:
            # Storage-level state filter (see datastore.DataStore contract):
            # the suggest path must not deserialize completed history.
            placeholders = ",".join("?" * len(states))
            query += f" AND state IN ({placeholders})"
            params += tuple(int(s) for s in states)
        with self._lock:
            self._require_study(study_name)
            rows = self._conn.execute(
                query + " ORDER BY trial_id", params
            ).fetchall()
        return [study_pb2.Trial.FromString(b) for (b,) in rows]

    def max_trial_id(self, study_name: str) -> int:
        with self._lock:
            self._require_study(study_name)
            row = self._conn.execute(
                "SELECT MAX(trial_id) FROM trials WHERE study = ?", (study_name,)
            ).fetchone()
        return int(row[0]) if row and row[0] is not None else 0

    # -- suggestion operations --------------------------------------------

    def create_suggestion_operation(
        self, operation: vizier_service_pb2.Operation
    ) -> str:
        r = resources.SuggestionOperationResource.from_name(operation.name)
        study_name = resources.StudyResource(r.owner_id, r.study_id).name
        with self._lock:
            self._require_study(study_name)
            try:
                self._conn.execute(
                    "INSERT INTO suggestion_ops"
                    " (name, study, client_id, op_number, done, blob)"
                    " VALUES (?, ?, ?, ?, ?, ?)",
                    (
                        operation.name,
                        study_name,
                        r.client_id,
                        r.operation_number,
                        int(operation.done),
                        operation.SerializeToString(),
                    ),
                )
                self._conn.commit()
            except sqlite3.IntegrityError:
                raise datastore.AlreadyExistsError(f"Operation exists: {operation.name}")
        return operation.name

    def get_suggestion_operation(
        self, operation_name: str
    ) -> vizier_service_pb2.Operation:
        with self._lock:
            row = self._conn.execute(
                "SELECT blob FROM suggestion_ops WHERE name = ?", (operation_name,)
            ).fetchone()
        if row is None:
            raise datastore.NotFoundError(f"No such operation: {operation_name}")
        return vizier_service_pb2.Operation.FromString(row[0])

    def update_suggestion_operation(
        self, operation: vizier_service_pb2.Operation
    ) -> str:
        with self._lock:
            cur = self._conn.execute(
                "UPDATE suggestion_ops SET blob = ?, done = ? WHERE name = ?",
                (
                    operation.SerializeToString(),
                    int(operation.done),
                    operation.name,
                ),
            )
            self._conn.commit()
        if cur.rowcount == 0:
            raise datastore.NotFoundError(f"No such operation: {operation.name}")
        return operation.name

    def list_suggestion_operations(
        self,
        study_name: str,
        client_id: str,
        filter_fn: Optional[Callable[[vizier_service_pb2.Operation], bool]] = None,
        *,
        done: Optional[bool] = None,
    ) -> List[vizier_service_pb2.Operation]:
        # The `done` pre-filter runs in SQL over the indexed column so the
        # hot dedup check never deserializes a session's full op history.
        query = (
            "SELECT blob FROM suggestion_ops WHERE study = ? AND client_id = ?"
        )
        params: tuple = (study_name, client_id)
        if done is not None:
            query += " AND done = ?"
            params += (int(done),)
        with self._lock:
            self._require_study(study_name)
            rows = self._conn.execute(
                query + " ORDER BY op_number", params
            ).fetchall()
        ops = [vizier_service_pb2.Operation.FromString(b) for (b,) in rows]
        if filter_fn is not None:
            ops = [op for op in ops if filter_fn(op)]
        return ops

    def max_suggestion_operation_number(self, study_name: str, client_id: str) -> int:
        with self._lock:
            row = self._conn.execute(
                "SELECT MAX(op_number) FROM suggestion_ops WHERE study = ? AND client_id = ?",
                (study_name, client_id),
            ).fetchone()
        return int(row[0]) if row and row[0] is not None else 0

    # -- early stopping operations ----------------------------------------

    def create_early_stopping_operation(
        self, operation: vizier_service_pb2.EarlyStoppingOperation
    ) -> str:
        r = resources.EarlyStoppingOperationResource.from_name(operation.name)
        study_name = resources.StudyResource(r.owner_id, r.study_id).name
        with self._lock:
            self._require_study(study_name)
            self._conn.execute(
                "INSERT OR REPLACE INTO early_stopping_ops (name, study, blob)"
                " VALUES (?, ?, ?)",
                (operation.name, study_name, operation.SerializeToString()),
            )
            self._conn.commit()
        return operation.name

    def get_early_stopping_operation(
        self, operation_name: str
    ) -> vizier_service_pb2.EarlyStoppingOperation:
        with self._lock:
            row = self._conn.execute(
                "SELECT blob FROM early_stopping_ops WHERE name = ?", (operation_name,)
            ).fetchone()
        if row is None:
            raise datastore.NotFoundError(f"No such operation: {operation_name}")
        return vizier_service_pb2.EarlyStoppingOperation.FromString(row[0])

    def update_early_stopping_operation(
        self, operation: vizier_service_pb2.EarlyStoppingOperation
    ) -> str:
        with self._lock:
            cur = self._conn.execute(
                "UPDATE early_stopping_ops SET blob = ? WHERE name = ?",
                (operation.SerializeToString(), operation.name),
            )
            self._conn.commit()
        if cur.rowcount == 0:
            raise datastore.NotFoundError(f"No such operation: {operation.name}")
        return operation.name

    # -- metadata ----------------------------------------------------------

    def update_metadata(
        self,
        study_name: str,
        study_metadata: Iterable[key_value_pb2.KeyValue],
        trial_metadata: Iterable,
    ) -> None:
        from vizier_tpu.service.ram_datastore import _merge_key_values

        with self._lock:
            row = self._conn.execute(
                "SELECT blob FROM studies WHERE name = ?", (study_name,)
            ).fetchone()
            if row is None:
                raise datastore.NotFoundError(f"No such study: {study_name}")
            study = study_pb2.Study.FromString(row[0])
            _merge_key_values(study.study_spec.metadata, study_metadata)
            self._conn.execute(
                "UPDATE studies SET blob = ? WHERE name = ?",
                (study.SerializeToString(), study_name),
            )
            r = resources.StudyResource.from_name(study_name)
            for trial_id, kv in trial_metadata:
                trial_name = r.trial_resource(trial_id).name
                trow = self._conn.execute(
                    "SELECT blob FROM trials WHERE name = ?", (trial_name,)
                ).fetchone()
                if trow is None:
                    raise datastore.NotFoundError(
                        f"No such trial {trial_id} in {study_name}"
                    )
                trial = study_pb2.Trial.FromString(trow[0])
                _merge_key_values(trial.metadata, [kv])
                self._conn.execute(
                    "UPDATE trials SET blob = ? WHERE name = ?",
                    (trial.SerializeToString(), trial_name),
                )
            self._conn.commit()
