"""Hand-written gRPC stubs and servicer registration.

grpcio-tools (the service-stub generator) is not in this image; messages are
protoc-generated (``protos/``) and the thin method tables below provide what
``*_pb2_grpc.py`` would. Parity with the generated-stub layer the reference
compiles in ``build_protos.sh``.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Tuple

import grpc

from vizier_tpu.service.protos import (
    pythia_service_pb2,
    replication_service_pb2,
    study_pb2,
    vizier_service_pb2,
)

_V = vizier_service_pb2
_P = pythia_service_pb2
_R = replication_service_pb2

# method name -> (request class, response class)
VIZIER_METHODS: Dict[str, Tuple[Any, Any]] = {
    "CreateStudy": (_V.CreateStudyRequest, study_pb2.Study),
    "GetStudy": (_V.GetStudyRequest, study_pb2.Study),
    "ListStudies": (_V.ListStudiesRequest, _V.ListStudiesResponse),
    "DeleteStudy": (_V.DeleteStudyRequest, _V.Empty),
    "SetStudyState": (_V.SetStudyStateRequest, study_pb2.Study),
    "SuggestTrials": (_V.SuggestTrialsRequest, _V.Operation),
    "GetOperation": (_V.GetOperationRequest, _V.Operation),
    "CreateTrial": (_V.CreateTrialRequest, study_pb2.Trial),
    "GetTrial": (_V.GetTrialRequest, study_pb2.Trial),
    "ListTrials": (_V.ListTrialsRequest, _V.ListTrialsResponse),
    "AddTrialMeasurement": (_V.AddTrialMeasurementRequest, study_pb2.Trial),
    "CompleteTrial": (_V.CompleteTrialRequest, study_pb2.Trial),
    "DeleteTrial": (_V.DeleteTrialRequest, _V.Empty),
    "CheckTrialEarlyStoppingState": (
        _V.CheckTrialEarlyStoppingStateRequest,
        _V.CheckTrialEarlyStoppingStateResponse,
    ),
    "StopTrial": (_V.StopTrialRequest, study_pb2.Trial),
    "ListOptimalTrials": (_V.ListOptimalTrialsRequest, _V.ListOptimalTrialsResponse),
    "UpdateMetadata": (_V.UpdateMetadataRequest, _V.UpdateMetadataResponse),
}

PYTHIA_METHODS: Dict[str, Tuple[Any, Any]] = {
    "Suggest": (_P.PythiaSuggestRequest, _P.PythiaSuggestResponse),
    "EarlyStop": (_P.PythiaEarlyStopRequest, _P.PythiaEarlyStopResponse),
    "Ping": (_P.PingRequest, _P.PingResponse),
}

# The cross-process replication plane (standby-log streaming, lease
# heartbeats, recovery plumbing — vizier_tpu.distributed).
REPLICATION_METHODS: Dict[str, Tuple[Any, Any]] = {
    "DeliverAppends": (_R.DeliverAppendsRequest, _R.DeliverAppendsResponse),
    "Baseline": (_R.DeliverAppendsRequest, _R.DeliverAppendsResponse),
    "Fence": (_R.FenceRequest, _R.FenceResponse),
    "Heartbeat": (_R.HeartbeatRequest, _R.HeartbeatResponse),
    "ExportStandby": (_R.ExportStandbyRequest, _R.ExportStandbyResponse),
    "ExportState": (_R.ExportStateRequest, _R.ExportStateResponse),
    "ApplyRecords": (_R.ApplyRecordsRequest, _R.ApplyRecordsResponse),
    "Resync": (_R.ResyncRequest, _R.ResyncResponse),
    "FlushStream": (_R.FlushStreamRequest, _R.FlushStreamResponse),
}

VIZIER_SERVICE_NAME = "vizier_tpu.VizierService"
PYTHIA_SERVICE_NAME = "vizier_tpu.PythiaService"
REPLICATION_SERVICE_NAME = "vizier_tpu.ReplicationService"


def _wrap(servicer, method_name: str):
    fn = getattr(servicer, method_name)

    def handler(request, context):
        try:
            return fn(request, context)
        except KeyError as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        except ValueError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))

    return handler


def _add_servicer(servicer, server, service_name: str, methods: Dict[str, Tuple[Any, Any]]):
    handlers = {
        name: grpc.unary_unary_rpc_method_handler(
            _wrap(servicer, name),
            request_deserializer=req_cls.FromString,
            response_serializer=lambda msg: msg.SerializeToString(),
        )
        for name, (req_cls, _) in methods.items()
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(service_name, handlers),)
    )


def add_vizier_servicer_to_server(servicer, server) -> None:
    _add_servicer(servicer, server, VIZIER_SERVICE_NAME, VIZIER_METHODS)


def add_pythia_servicer_to_server(servicer, server) -> None:
    _add_servicer(servicer, server, PYTHIA_SERVICE_NAME, PYTHIA_METHODS)


def add_replication_servicer_to_server(servicer, server) -> None:
    _add_servicer(servicer, server, REPLICATION_SERVICE_NAME, REPLICATION_METHODS)


class _Stub:
    """Callable-per-method stub: ``stub.GetStudy(request) -> Study``.

    Status codes are translated back into the exceptions the in-process
    servicer raises (NOT_FOUND → datastore NotFoundError, INVALID_ARGUMENT →
    ValueError), so the network and in-process transports are
    indistinguishable to callers — the substitutability contract the client
    conformance suite checks on both.
    """

    def __init__(self, channel: grpc.Channel, service_name: str, methods):
        from vizier_tpu.service import datastore as datastore_lib

        def bind(callable_):
            def call(request):
                try:
                    return callable_(request)
                except grpc.RpcError as e:  # pragma: no branch
                    code = e.code() if hasattr(e, "code") else None
                    if code == grpc.StatusCode.NOT_FOUND:
                        raise datastore_lib.NotFoundError(e.details()) from e
                    if code == grpc.StatusCode.INVALID_ARGUMENT:
                        raise ValueError(e.details()) from e
                    raise

            return call

        for name, (req_cls, resp_cls) in methods.items():
            setattr(
                self,
                name,
                bind(
                    channel.unary_unary(
                        f"/{service_name}/{name}",
                        request_serializer=req_cls.SerializeToString,
                        response_deserializer=resp_cls.FromString,
                    )
                ),
            )


class VizierServiceStub(_Stub):
    def __init__(self, channel: grpc.Channel):
        super().__init__(channel, VIZIER_SERVICE_NAME, VIZIER_METHODS)


class PythiaServiceStub(_Stub):
    def __init__(self, channel: grpc.Channel):
        super().__init__(channel, PYTHIA_SERVICE_NAME, PYTHIA_METHODS)


class ReplicationServiceStub(_Stub):
    def __init__(self, channel: grpc.Channel):
        super().__init__(channel, REPLICATION_SERVICE_NAME, REPLICATION_METHODS)


# One channel per endpoint for the process lifetime. Stub creation sits on
# every client constructor (`vizier_client.create_or_load_study`), and a
# fresh `grpc.insecure_channel` per call leaks its sockets + watcher
# threads for the life of the process — enough accumulated channels
# eventually wedge grpc-core's connectivity subscription (observed as a
# hang inside `channel.subscribe` after ~900 tests). gRPC channels are
# thread-safe and auto-reconnect, so sharing per endpoint is the intended
# usage.
#
# The ready-wait runs ONLY on first creation (every channel_ready_future
# subscribes a connectivity-watcher thread; re-subscribing per stub churns
# threads and races channel.close() at server stop). Concurrent callers
# share the creator's outcome via the entry's event, and a failed
# ready-wait evicts the entry so retries re-attempt readiness instead of
# receiving a never-connected channel.
_CHANNEL_LOCK = threading.Lock()


class _ChannelEntry:
    def __init__(self, channel: grpc.Channel):
        self.channel = channel
        self.ready = threading.Event()
        self.error: Any = None
        # Liveness flag kept fresh by one connectivity watcher per CHANNEL
        # (not per stub call, so no thread churn): a server that dies
        # without close_channel() flips it, and the next cache hit evicts
        # and reconnects instead of handing back a dead channel whose
        # failure would only surface at first RPC.
        self.broken = False
        channel.subscribe(self._watch, try_to_connect=False)

    def _watch(self, state: grpc.ChannelConnectivity) -> None:
        # ONLY SHUTDOWN marks a channel broken. TRANSIENT_FAILURE is a
        # normal intermediate state (a failed connect attempt during a
        # server restart, before gRPC's auto-reconnect succeeds); treating
        # it as broken made a _shared_channel call racing a brief outage
        # evict-and-close() the channel underneath every stub already
        # sharing it — permanently killing stubs gRPC would have recovered.
        if state is grpc.ChannelConnectivity.SHUTDOWN:
            self.broken = True


_CHANNELS: Dict[str, _ChannelEntry] = {}


def _shared_channel(endpoint: str, timeout: float) -> grpc.Channel:
    # Lock order: _CHANNEL_LOCK is a LEAF lock — only dict bookkeeping runs
    # under it. channel.close() re-enters grpc-core (connectivity watchers,
    # completion queues) and is deferred to after release; enforced by the
    # lock_order static-analysis pass.
    stale = None
    with _CHANNEL_LOCK:
        entry = _CHANNELS.get(endpoint)
        if entry is not None and entry.broken and entry.ready.is_set():
            # Stale cache hit: evict, close (outside the lock), fall
            # through to a fresh connect (which re-runs the ready-wait).
            del _CHANNELS[endpoint]
            stale = entry
            entry = None
        fresh = entry is None
        if fresh:
            entry = _ChannelEntry(grpc.insecure_channel(endpoint))
            _CHANNELS[endpoint] = entry
    if stale is not None:
        stale.channel.close()
    if fresh:
        try:
            grpc.channel_ready_future(entry.channel).result(timeout=timeout)
        except Exception as e:  # timeout or connectivity failure
            entry.error = e
            with _CHANNEL_LOCK:
                if _CHANNELS.get(endpoint) is entry:
                    del _CHANNELS[endpoint]
            entry.ready.set()  # release concurrent waiters with the error
            entry.channel.close()
            raise
        entry.ready.set()
        return entry.channel
    # Cached: wait for the creator's ready outcome (usually already set).
    if not entry.ready.wait(timeout=timeout):
        raise grpc.FutureTimeoutError(
            f"Channel to {endpoint} not ready within {timeout}s."
        )
    if entry.error is not None:
        raise entry.error
    return entry.channel


def close_channel(endpoint: str) -> None:
    """Closes and evicts the shared channel for ``endpoint`` (if any).

    Servers call this from ``stop()`` so channels to dead endpoints do not
    accumulate for the process lifetime (each test-scoped server would
    otherwise leave one live channel behind forever).
    """
    with _CHANNEL_LOCK:
        entry = _CHANNELS.pop(endpoint, None)
    if entry is not None:
        entry.channel.close()


def create_vizier_stub(endpoint: str, timeout: float = 10.0) -> VizierServiceStub:
    """Creates a stub on the shared per-endpoint channel once it is ready."""
    return VizierServiceStub(_shared_channel(endpoint, timeout))


def create_pythia_stub(endpoint: str, timeout: float = 10.0) -> PythiaServiceStub:
    return PythiaServiceStub(_shared_channel(endpoint, timeout))


def create_replication_stub(
    endpoint: str, timeout: float = 10.0
) -> ReplicationServiceStub:
    """Replication-surface stub on the shared per-endpoint channel."""
    return ReplicationServiceStub(_shared_channel(endpoint, timeout))
