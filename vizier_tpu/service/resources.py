"""Resource-name parsing and formatting.

Parity with ``/root/reference/vizier/_src/service/resources.py:38-199``:
``owners/{owner}``, ``owners/{o}/studies/{s}``, ``.../trials/{id}``,
``.../earlyStoppingOperations/{op}``, ``.../clients/{c}/operations/{n}``.

``from_name`` parses are memoized: the service hot path re-parses the same
handful of study/trial names ~20x per suggest (measured), and the parsed
resources are frozen (hashable, immutable) so returning a shared instance
is safe. Invalid names still raise every time — ``lru_cache`` does not
cache exceptions.
"""

from __future__ import annotations

import dataclasses
import functools
import re

_SEGMENT = r"[^/]+"

_PARSE_CACHE_SIZE = 16384


def _memoized_parser(fn):
    """Caches a ``from_name`` classmethod per (class, name)."""
    return classmethod(functools.lru_cache(maxsize=_PARSE_CACHE_SIZE)(fn))


@dataclasses.dataclass(frozen=True)
class OwnerResource:
    owner_id: str

    @property
    def name(self) -> str:
        return f"owners/{self.owner_id}"

    @_memoized_parser
    def from_name(cls, name: str) -> "OwnerResource":
        m = re.fullmatch(rf"owners/({_SEGMENT})", name)
        if not m:
            raise ValueError(f"Invalid owner resource name: {name!r}")
        return cls(m.group(1))


@dataclasses.dataclass(frozen=True)
class StudyResource:
    owner_id: str
    study_id: str

    @property
    def name(self) -> str:
        return f"owners/{self.owner_id}/studies/{self.study_id}"

    @_memoized_parser
    def from_name(cls, name: str) -> "StudyResource":
        m = re.fullmatch(rf"owners/({_SEGMENT})/studies/({_SEGMENT})", name)
        if not m:
            raise ValueError(f"Invalid study resource name: {name!r}")
        return cls(m.group(1), m.group(2))

    def trial_resource(self, trial_id: int) -> "TrialResource":
        return TrialResource(self.owner_id, self.study_id, trial_id)


@dataclasses.dataclass(frozen=True)
class TrialResource:
    owner_id: str
    study_id: str
    trial_id: int

    @property
    def name(self) -> str:
        return f"owners/{self.owner_id}/studies/{self.study_id}/trials/{self.trial_id}"

    @_memoized_parser
    def from_name(cls, name: str) -> "TrialResource":
        m = re.fullmatch(
            rf"owners/({_SEGMENT})/studies/({_SEGMENT})/trials/(\d+)", name
        )
        if not m:
            raise ValueError(f"Invalid trial resource name: {name!r}")
        return cls(m.group(1), m.group(2), int(m.group(3)))

    @property
    def study_resource(self) -> StudyResource:
        return StudyResource(self.owner_id, self.study_id)


@dataclasses.dataclass(frozen=True)
class EarlyStoppingOperationResource:
    owner_id: str
    study_id: str
    trial_id: int

    @property
    def name(self) -> str:
        return (
            f"owners/{self.owner_id}/studies/{self.study_id}/trials/"
            f"{self.trial_id}/earlyStoppingOperations/{self.operation_id}"
        )

    @property
    def operation_id(self) -> str:
        return f"earlystopping-{self.trial_id}"

    @_memoized_parser
    def from_name(cls, name: str) -> "EarlyStoppingOperationResource":
        m = re.fullmatch(
            rf"owners/({_SEGMENT})/studies/({_SEGMENT})/trials/(\d+)/"
            rf"earlyStoppingOperations/earlystopping-(\d+)",
            name,
        )
        if not m:
            raise ValueError(f"Invalid early-stopping operation name: {name!r}")
        return cls(m.group(1), m.group(2), int(m.group(3)))

    @property
    def trial_resource(self) -> TrialResource:
        return TrialResource(self.owner_id, self.study_id, self.trial_id)


@dataclasses.dataclass(frozen=True)
class SuggestionOperationResource:
    owner_id: str
    study_id: str
    client_id: str
    operation_number: int

    @property
    def name(self) -> str:
        return (
            f"owners/{self.owner_id}/studies/{self.study_id}/clients/"
            f"{self.client_id}/operations/{self.operation_number}"
        )

    @_memoized_parser
    def from_name(cls, name: str) -> "SuggestionOperationResource":
        m = re.fullmatch(
            rf"owners/({_SEGMENT})/studies/({_SEGMENT})/clients/({_SEGMENT})/operations/(\d+)",
            name,
        )
        if not m:
            raise ValueError(f"Invalid suggestion operation name: {name!r}")
        return cls(m.group(1), m.group(2), m.group(3), int(m.group(4)))
