"""The Vizier study service: datastores, servicers, servers, clients."""

from vizier_tpu.service import clients
from vizier_tpu.service.vizier_server import DefaultVizierServer, DistributedPythiaVizierServer
