"""DataStore ABC + custom errors.

Parity with ``/root/reference/vizier/_src/service/datastore.py:34`` (19
abstract methods over studies/trials/operations/metadata) and
``custom_errors.py:20-38``. Implementations: ``ram_datastore`` (dict-based)
and ``sql_datastore`` (stdlib sqlite3; the environment has no SQLAlchemy —
plain SQL keeps the dependency surface zero and the semantics identical).
"""

from __future__ import annotations

import abc
from typing import Callable, Iterable, List, Optional

from vizier_tpu.service.protos import study_pb2, vizier_service_pb2


class NotFoundError(KeyError):
    """Resource does not exist."""


class AlreadyExistsError(ValueError):
    """Resource already exists."""


class DataStore(abc.ABC):
    """Storage interface for the Vizier service."""

    # -- studies -----------------------------------------------------------

    @abc.abstractmethod
    def create_study(self, study: study_pb2.Study) -> str:
        """Stores a new study; returns its resource name."""

    @abc.abstractmethod
    def load_study(self, study_name: str) -> study_pb2.Study:
        ...

    @abc.abstractmethod
    def update_study(self, study: study_pb2.Study) -> str:
        ...

    @abc.abstractmethod
    def delete_study(self, study_name: str) -> None:
        """Deletes the study and all its trials/operations."""

    @abc.abstractmethod
    def list_studies(self, owner_name: str) -> List[study_pb2.Study]:
        ...

    # -- trials ------------------------------------------------------------

    @abc.abstractmethod
    def create_trial(self, trial: study_pb2.Trial) -> str:
        ...

    @abc.abstractmethod
    def get_trial(self, trial_name: str) -> study_pb2.Trial:
        ...

    @abc.abstractmethod
    def update_trial(self, trial: study_pb2.Trial) -> str:
        ...

    @abc.abstractmethod
    def delete_trial(self, trial_name: str) -> None:
        ...

    @abc.abstractmethod
    def list_trials(
        self, study_name: str, *, states: Optional[tuple] = None
    ) -> List[study_pb2.Trial]:
        """Trials of a study, id order.

        ``states`` (a tuple of ``study_pb2.Trial.State`` values) filters at
        the STORAGE layer: the suggest hot path needs only
        ACTIVE/REQUESTED rows, and copying a long study's completed
        history per suggest is a measured linear slowdown.
        """
        ...

    def trial_states(self, study_name: str) -> List[tuple]:
        """``(trial_id, state)`` pairs for every trial of a study, id order.

        The frontier-fingerprint read shape (serving.speculative): the
        speculative serve check needs only ids and states, not proto
        copies of a long study's measurement history. This default derives
        it from :meth:`list_trials`; stores with a cheaper index (the RAM
        store) override it copy-free.
        """
        return [(t.id, t.state) for t in self.list_trials(study_name)]

    @abc.abstractmethod
    def max_trial_id(self, study_name: str) -> int:
        ...

    # -- suggestion operations --------------------------------------------

    @abc.abstractmethod
    def create_suggestion_operation(
        self, operation: vizier_service_pb2.Operation
    ) -> str:
        ...

    @abc.abstractmethod
    def get_suggestion_operation(
        self, operation_name: str
    ) -> vizier_service_pb2.Operation:
        ...

    @abc.abstractmethod
    def update_suggestion_operation(
        self, operation: vizier_service_pb2.Operation
    ) -> str:
        ...

    @abc.abstractmethod
    def list_suggestion_operations(
        self,
        study_name: str,
        client_id: str,
        filter_fn: Optional[Callable[[vizier_service_pb2.Operation], bool]] = None,
        *,
        done: Optional[bool] = None,
    ) -> List[vizier_service_pb2.Operation]:
        """Ops for (study, client), oldest first.

        ``done`` pre-filters on completion status at the STORAGE layer —
        the hot dedup check (``done=False``) must not deserialize/copy a
        session's whole operation history. ``filter_fn`` runs afterwards
        for arbitrary predicates.

        CONTRACT (all implementations): ``filter_fn`` may be invoked on
        live storage-owned records while the implementation's internal
        (possibly non-reentrant) lock is held. It must be a pure
        predicate: it must NOT mutate its argument and must NOT call back
        into this datastore — violating either corrupts stored state or
        deadlocks. Implementations are free to copy records only AFTER
        filtering (the RAM datastore does, measured 2.3x dedup-throughput
        difference at 200 trials).
        """
        ...

    @abc.abstractmethod
    def max_suggestion_operation_number(self, study_name: str, client_id: str) -> int:
        ...

    # -- early stopping operations ----------------------------------------

    @abc.abstractmethod
    def create_early_stopping_operation(self, operation) -> str:
        """operation: an EarlyStoppingOperation record (see ram_datastore)."""

    @abc.abstractmethod
    def get_early_stopping_operation(self, operation_name: str):
        ...

    @abc.abstractmethod
    def update_early_stopping_operation(self, operation) -> str:
        ...

    # -- metadata ----------------------------------------------------------

    @abc.abstractmethod
    def update_metadata(
        self,
        study_name: str,
        study_metadata: Iterable,
        trial_metadata: Iterable,  # iterable of (trial_id, KeyValue)
    ) -> None:
        """Merges metadata into the stored study spec and trials."""
