"""Cross-thread response handoff.

Parity with ``/root/reference/vizier/_src/service/pythia_util.py:32``
(``ResponseWaiter``): one thread computes a response while another blocks
waiting for it, with error propagation.
"""

from __future__ import annotations

import threading
from typing import Generic, Optional, TypeVar

_T = TypeVar("_T")


class ResponseWaiter(Generic[_T]):
    def __init__(self):
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._response: Optional[_T] = None
        self._error: Optional[BaseException] = None

    def Report(self, response: _T) -> None:
        with self._lock:
            if self._event.is_set():
                raise RuntimeError("ResponseWaiter already completed.")
            self._response = response
            self._event.set()

    def ReportError(self, error: BaseException) -> None:
        with self._lock:
            if self._event.is_set():
                raise RuntimeError("ResponseWaiter already completed.")
            self._error = error
            self._event.set()

    def WaitForResponse(self, timeout: Optional[float] = None) -> _T:
        if not self._event.wait(timeout):
            raise TimeoutError("Timed out waiting for response.")
        if self._error is not None:
            raise self._error
        return self._response  # type: ignore[return-value]
