"""Cross-thread response handoff.

Parity with ``/root/reference/vizier/_src/service/pythia_util.py:32``
(``ResponseWaiter``): one thread computes a response while another blocks
waiting for it, with error propagation. Used by the Vizier service to bound
a Pythia dispatch with the request's deadline budget — the waiter times out
(naming the operation it was waiting on) while the abandoned computation
finishes on its daemon thread.
"""

from __future__ import annotations

import threading
import traceback
from typing import Generic, Optional, TypeVar

_T = TypeVar("_T")


class ResponseWaiter(Generic[_T]):
    def __init__(self, operation_name: str = ""):
        self._operation_name = operation_name
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._response: Optional[_T] = None
        self._error: Optional[BaseException] = None
        self._error_tb: Optional[str] = None

    def Report(self, response: _T) -> None:
        with self._lock:
            if self._event.is_set():
                raise RuntimeError("ResponseWaiter already completed.")
            self._response = response
            self._event.set()

    def ReportError(self, error: BaseException) -> None:
        with self._lock:
            if self._event.is_set():
                raise RuntimeError("ResponseWaiter already completed.")
            self._error = error
            # Format NOW, on the reporting thread: once re-raised on the
            # waiting thread the traceback would be rewritten and the
            # compute-side frames lost.
            self._error_tb = "".join(
                traceback.format_exception(type(error), error, error.__traceback__)
            ).strip()
            self._event.set()

    def WaitForResponse(self, timeout: Optional[float] = None) -> _T:
        if not self._event.wait(timeout):
            suffix = (
                f" for operation {self._operation_name!r}"
                if self._operation_name
                else ""
            )
            raise TimeoutError(f"Timed out waiting for response{suffix}.")
        if self._error is not None:
            err = self._error
            # Cross-thread re-raise: ``from None`` (the waiting thread's
            # context is noise), with the original traceback text folded
            # into the message so it survives the thread hop. Guarded: a
            # second waiter must not append twice, and exceptions with
            # exotic args must still propagate.
            if self._error_tb is not None and self._error_tb not in str(err):
                try:
                    err.args = (
                        f"{err}\n--- original traceback (cross-thread) ---\n"
                        f"{self._error_tb}",
                    )
                except Exception:
                    pass
            raise err from None
        return self._response  # type: ignore[return-value]
