"""Server bootstrap: single-process and split-Pythia topologies.

Parity with ``/root/reference/vizier/_src/service/vizier_server.py:42,101``.
"""

from __future__ import annotations

from concurrent import futures
from typing import Optional

import grpc


def _pick_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class DefaultVizierServer:
    """Vizier + Pythia servicers in one process behind one gRPC server."""

    def __init__(
        self,
        host: str = "localhost",
        database_url: Optional[str] = None,
        policy_factory=None,
        port: Optional[int] = None,
        serving_config=None,
        datastore=None,
    ):
        from vizier_tpu.service import grpc_stubs
        from vizier_tpu.service import pythia_service
        from vizier_tpu.service import vizier_service

        self._port = port or _pick_port()
        # ``datastore`` injects a storage backend (e.g. the sharded tier's
        # snapshot+WAL PersistentDataStore — vizier_tpu.distributed);
        # mutually exclusive with database_url.
        self._servicer = vizier_service.VizierServicer(
            database_url=database_url, datastore=datastore
        )
        # ``serving_config`` (vizier_tpu.serving.ServingConfig) tunes or
        # disables the stateful serving runtime — designer cache, warm ARD
        # starts, request coalescing. None -> defaults + env overrides
        # (VIZIER_SERVING_CACHE / _WARM_START / _COALESCING = 0);
        # ServingConfig.disabled() restores the reference's stateless
        # cold-train-per-request behavior.
        self._pythia_servicer = pythia_service.PythiaServicer(
            self._servicer, policy_factory, serving_config=serving_config
        )
        self._servicer.set_pythia(self._pythia_servicer)
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=30))
        grpc_stubs.add_vizier_servicer_to_server(self._servicer, self._server)
        grpc_stubs.add_pythia_servicer_to_server(self._pythia_servicer, self._server)
        self._endpoint = f"{host}:{self._port}"
        self._server.add_insecure_port(self._endpoint)
        self._server.start()

    @property
    def endpoint(self) -> str:
        return self._endpoint

    @property
    def servicer(self):
        """The in-process servicer (for no-network clients)."""
        return self._servicer

    @property
    def pythia_servicer(self):
        return self._pythia_servicer

    def serving_stats(self) -> dict:
        """Serving counters: cache hits/misses, warm/cold trains, coalescing."""
        return self._pythia_servicer.serving_stats()

    def stop(self, grace: Optional[float] = None) -> None:
        # grpc.Server.stop is non-blocking (returns an event); wait for the
        # grace window to drain in-flight RPCs BEFORE closing the shared
        # client channel, else the close cancels the very RPCs the grace
        # period protects. Stubs created before stop() are invalidated.
        self._server.stop(grace).wait()
        from vizier_tpu.service import grpc_stubs

        grpc_stubs.close_channel(self._endpoint)

    def __del__(self):
        try:
            # grace=0, NOT None: grace=None blocks until every in-flight RPC
            # completes, which deadlocks interpreter shutdown if a handler
            # thread is still parked (observed after early-stopping RPCs).
            self._server.stop(0)
            from vizier_tpu.service import grpc_stubs

            grpc_stubs.close_channel(self._endpoint)
        except Exception:
            pass


class DistributedPythiaVizierServer:
    """Separate gRPC servers for Vizier and Pythia, cross-connected.

    Pythia runs max_workers=1 — one policy computation at a time, matching
    the reference topology (one accelerator-bound computation per host).
    """

    def __init__(
        self,
        host: str = "localhost",
        database_url: Optional[str] = None,
        policy_factory=None,
        serving_config=None,
    ):
        from vizier_tpu.service import grpc_stubs
        from vizier_tpu.service import pythia_service
        from vizier_tpu.service import vizier_service

        # Vizier server.
        self._servicer = vizier_service.VizierServicer(database_url=database_url)
        self._vizier_server = grpc.server(futures.ThreadPoolExecutor(max_workers=30))
        grpc_stubs.add_vizier_servicer_to_server(self._servicer, self._vizier_server)
        self._vizier_endpoint = f"{host}:{_pick_port()}"
        self._vizier_server.add_insecure_port(self._vizier_endpoint)
        self._vizier_server.start()

        # Pythia server (reads trials back through the Vizier stub). Note
        # DeleteStudy invalidation cannot reach a remote Pythia's designer
        # cache (no invalidation RPC); its TTL bounds staleness there.
        vizier_stub = grpc_stubs.create_vizier_stub(self._vizier_endpoint)
        self._pythia_servicer = pythia_service.PythiaServicer(
            vizier_stub, policy_factory, serving_config=serving_config
        )
        self._pythia_server = grpc.server(futures.ThreadPoolExecutor(max_workers=1))
        grpc_stubs.add_pythia_servicer_to_server(
            self._pythia_servicer, self._pythia_server
        )
        self._pythia_endpoint = f"{host}:{_pick_port()}"
        self._pythia_server.add_insecure_port(self._pythia_endpoint)
        self._pythia_server.start()

        # Vizier dispatches suggestion work to Pythia over gRPC.
        self._servicer.set_pythia(grpc_stubs.create_pythia_stub(self._pythia_endpoint))

    @property
    def endpoint(self) -> str:
        return self._vizier_endpoint

    @property
    def pythia_endpoint(self) -> str:
        return self._pythia_endpoint

    def stop(self, grace: Optional[float] = None) -> None:
        # Drain both servers through the grace window first (stop() is
        # non-blocking), THEN close the cross-connect channels.
        pythia_done = self._pythia_server.stop(grace)
        vizier_done = self._vizier_server.stop(grace)
        pythia_done.wait()
        vizier_done.wait()
        from vizier_tpu.service import grpc_stubs

        grpc_stubs.close_channel(self._pythia_endpoint)
        grpc_stubs.close_channel(self._vizier_endpoint)
