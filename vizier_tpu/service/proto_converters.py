"""PyVizier ⇄ protobuf converters.

Functional parity with the reference converter module
(``/root/reference/vizier/_src/pyvizier/oss/proto_converters.py`` and
``metadata_util.py``), written against our own wire schema
(``vizier_tpu/service/protos``).
"""

from __future__ import annotations

import time
from typing import Iterable, List, Optional, Sequence, Tuple

from vizier_tpu import pyvizier as vz
from vizier_tpu.service.protos import key_value_pb2, study_pb2

# ---------------------------------------------------------------------------
# Parameter values
# ---------------------------------------------------------------------------


def parameter_value_to_proto(value: vz.ParameterValueTypes) -> study_pb2.ParameterValue:
    proto = study_pb2.ParameterValue()
    if isinstance(value, bool):
        proto.bool_value = value
    elif isinstance(value, int):
        proto.int_value = value
    elif isinstance(value, float):
        proto.double_value = value
    else:
        proto.string_value = str(value)
    return proto


def parameter_value_from_proto(proto: study_pb2.ParameterValue) -> vz.ParameterValueTypes:
    which = proto.WhichOneof("value")
    if which == "double_value":
        return proto.double_value
    if which == "int_value":
        return int(proto.int_value)
    if which == "bool_value":
        return proto.bool_value
    return proto.string_value


# ---------------------------------------------------------------------------
# Parameter configs / search space
# ---------------------------------------------------------------------------

_SCALE_TO_PROTO = {
    None: study_pb2.ParameterSpec.SCALE_UNSPECIFIED,
    vz.ScaleType.LINEAR: study_pb2.ParameterSpec.LINEAR,
    vz.ScaleType.LOG: study_pb2.ParameterSpec.LOG,
    vz.ScaleType.REVERSE_LOG: study_pb2.ParameterSpec.REVERSE_LOG,
    vz.ScaleType.UNIFORM_DISCRETE: study_pb2.ParameterSpec.UNIFORM_DISCRETE,
}
_SCALE_FROM_PROTO = {v: k for k, v in _SCALE_TO_PROTO.items()}

_EXTERNAL_TO_PROTO = {
    vz.ExternalType.INTERNAL: study_pb2.ParameterSpec.INTERNAL,
    vz.ExternalType.BOOLEAN: study_pb2.ParameterSpec.BOOLEAN,
    vz.ExternalType.INTEGER: study_pb2.ParameterSpec.INTEGER,
    vz.ExternalType.FLOAT: study_pb2.ParameterSpec.FLOAT,
}
_EXTERNAL_FROM_PROTO = {v: k for k, v in _EXTERNAL_TO_PROTO.items()}


def parameter_config_to_proto(config: vz.ParameterConfig) -> study_pb2.ParameterSpec:
    proto = study_pb2.ParameterSpec(name=config.name)
    proto.scale_type = _SCALE_TO_PROTO[config.scale_type]
    proto.external_type = _EXTERNAL_TO_PROTO[config.external_type]
    if config.type == vz.ParameterType.DOUBLE:
        lo, hi = config.bounds
        proto.double_range.min_value = lo
        proto.double_range.max_value = hi
    elif config.type == vz.ParameterType.INTEGER:
        lo, hi = config.bounds
        proto.integer_range.min_value = int(lo)
        proto.integer_range.max_value = int(hi)
    elif config.type == vz.ParameterType.DISCRETE:
        proto.discrete_values.values.extend(float(v) for v in config.feasible_values)
    elif config.type == vz.ParameterType.CATEGORICAL:
        proto.categorical_values.values.extend(str(v) for v in config.feasible_values)
    else:
        raise ValueError(f"Cannot serialize parameter type {config.type}.")
    if config.default_value is not None:
        proto.default_value.CopyFrom(parameter_value_to_proto(config.default_value))
    for child in config.children:
        child_proto = proto.children.add()
        child_proto.spec.CopyFrom(parameter_config_to_proto(child))
        for pv in child.matching_parent_values:
            child_proto.matching_parent_values.append(parameter_value_to_proto(pv))
    return proto


def parameter_config_from_proto(proto: study_pb2.ParameterSpec) -> vz.ParameterConfig:
    which = proto.WhichOneof("domain")
    kwargs = {}
    if which == "double_range":
        kwargs["bounds"] = (proto.double_range.min_value, proto.double_range.max_value)
    elif which == "integer_range":
        kwargs["bounds"] = (
            int(proto.integer_range.min_value),
            int(proto.integer_range.max_value),
        )
    elif which == "discrete_values":
        kwargs["feasible_values"] = list(proto.discrete_values.values)
    elif which == "categorical_values":
        kwargs["feasible_values"] = list(proto.categorical_values.values)
    else:
        raise ValueError(f"ParameterSpec {proto.name!r} has no domain.")
    default = None
    if proto.HasField("default_value"):
        default = parameter_value_from_proto(proto.default_value)
    children = [
        (
            [parameter_value_from_proto(pv) for pv in child.matching_parent_values],
            parameter_config_from_proto(child.spec),
        )
        for child in proto.children
    ]
    return vz.ParameterConfig.factory(
        proto.name,
        scale_type=_SCALE_FROM_PROTO.get(proto.scale_type),
        default_value=default,
        external_type=_EXTERNAL_FROM_PROTO.get(proto.external_type, vz.ExternalType.INTERNAL),
        children=children,
        **kwargs,
    )


# ---------------------------------------------------------------------------
# Metrics
# ---------------------------------------------------------------------------


def metric_information_to_proto(info: vz.MetricInformation) -> study_pb2.MetricSpec:
    proto = study_pb2.MetricSpec(name=info.name)
    proto.goal = (
        study_pb2.MetricSpec.MAXIMIZE if info.goal.is_maximize else study_pb2.MetricSpec.MINIMIZE
    )
    if info.safety_threshold is not None:
        proto.safety_config.safety_threshold = info.safety_threshold
        if info.desired_min_safe_trials_fraction is not None:
            proto.safety_config.desired_min_safe_trials_fraction = (
                info.desired_min_safe_trials_fraction
            )
    import math

    if math.isfinite(info.min_value):
        proto.min_value = info.min_value
    if math.isfinite(info.max_value):
        proto.max_value = info.max_value
    return proto


def metric_information_from_proto(proto: study_pb2.MetricSpec) -> vz.MetricInformation:
    import math

    goal = (
        vz.ObjectiveMetricGoal.MAXIMIZE
        if proto.goal != study_pb2.MetricSpec.MINIMIZE
        else vz.ObjectiveMetricGoal.MINIMIZE
    )
    safety_threshold = None
    frac = None
    if proto.HasField("safety_config"):
        safety_threshold = proto.safety_config.safety_threshold
        if proto.safety_config.HasField("desired_min_safe_trials_fraction"):
            frac = proto.safety_config.desired_min_safe_trials_fraction
    return vz.MetricInformation(
        name=proto.name,
        goal=goal,
        safety_threshold=safety_threshold,
        desired_min_safe_trials_fraction=frac,
        min_value=proto.min_value if proto.HasField("min_value") else -math.inf,
        max_value=proto.max_value if proto.HasField("max_value") else math.inf,
    )


# ---------------------------------------------------------------------------
# Metadata
# ---------------------------------------------------------------------------


def metadata_to_key_values(metadata: vz.Metadata) -> List[key_value_pb2.KeyValue]:
    out = []
    for ns, key, value in metadata.all_items():
        kv = key_value_pb2.KeyValue(key=key, ns=ns.encode())
        if isinstance(value, str):
            kv.string_value = value
        elif isinstance(value, bytes):
            kv.bytes_value = value
        elif isinstance(value, (int, float)):
            kv.double_value = float(value)
        elif hasattr(value, "SerializeToString"):
            kv.bytes_value = value.SerializeToString()
        else:
            kv.string_value = str(value)
        out.append(kv)
    return out


def metadata_from_key_values(key_values: Iterable[key_value_pb2.KeyValue]) -> vz.Metadata:
    md = vz.Metadata()
    for kv in key_values:
        ns = vz.Namespace.decode(kv.ns)
        which = kv.WhichOneof("value")
        if which == "double_value":
            value = kv.double_value
        elif which == "bytes_value":
            value = kv.bytes_value
        else:
            value = kv.string_value
        md.abs_ns(ns)[kv.key] = value
    return md


# ---------------------------------------------------------------------------
# Measurements / trials
# ---------------------------------------------------------------------------


def measurement_to_proto(m: vz.Measurement) -> study_pb2.Measurement:
    proto = study_pb2.Measurement(elapsed_secs=m.elapsed_secs, steps=m.steps)
    for name, metric in m.metrics.items():
        mp = proto.metrics.add()
        mp.name = name
        mp.value = metric.value
        if metric.std is not None:
            mp.std = metric.std
    return proto


def measurement_from_proto(proto: study_pb2.Measurement) -> vz.Measurement:
    return vz.Measurement(
        metrics={
            mp.name: vz.Metric(mp.value, std=mp.std if mp.HasField("std") else None)
            for mp in proto.metrics
        },
        elapsed_secs=proto.elapsed_secs,
        steps=proto.steps,
    )


def trial_to_proto(trial: vz.Trial, name: str = "") -> study_pb2.Trial:
    proto = study_pb2.Trial(name=name, id=trial.id)
    status = trial.status
    if status == vz.TrialStatus.REQUESTED:
        proto.state = study_pb2.Trial.REQUESTED
    elif status == vz.TrialStatus.STOPPING:
        proto.state = study_pb2.Trial.STOPPING
    elif status == vz.TrialStatus.COMPLETED:
        proto.state = (
            study_pb2.Trial.INFEASIBLE if trial.infeasible else study_pb2.Trial.SUCCEEDED
        )
    else:
        proto.state = study_pb2.Trial.ACTIVE
    for pname, pvalue in trial.parameters.items():
        assignment = proto.parameters.add()
        assignment.name = pname
        assignment.value.CopyFrom(parameter_value_to_proto(pvalue.value))
    for m in trial.measurements:
        proto.measurements.add().CopyFrom(measurement_to_proto(m))
    if trial.final_measurement is not None:
        proto.final_measurement.CopyFrom(measurement_to_proto(trial.final_measurement))
    if trial.infeasibility_reason:
        proto.infeasibility_reason = trial.infeasibility_reason
    if trial.assigned_worker:
        proto.assigned_worker = trial.assigned_worker
    if trial.stopping_reason:
        proto.stopping_reason = trial.stopping_reason
    proto.metadata.extend(metadata_to_key_values(trial.metadata))
    if trial.creation_time is not None:
        proto.creation_time_secs = trial.creation_time.timestamp()
    if trial.completion_time is not None:
        proto.completion_time_secs = trial.completion_time.timestamp()
    return proto


def trial_from_proto(proto: study_pb2.Trial) -> vz.Trial:
    import datetime

    params = vz.ParameterDict()
    for assignment in proto.parameters:
        params[assignment.name] = parameter_value_from_proto(assignment.value)
    trial = vz.Trial(
        id=int(proto.id),
        parameters=params,
        metadata=metadata_from_key_values(proto.metadata),
        is_requested=proto.state == study_pb2.Trial.REQUESTED,
        assigned_worker=proto.assigned_worker or None,
        stopping_reason=proto.stopping_reason or None,
        measurements=[measurement_from_proto(m) for m in proto.measurements],
    )
    if proto.state == study_pb2.Trial.STOPPING:
        trial.stop(proto.stopping_reason or None)
    if proto.state == study_pb2.Trial.SUCCEEDED and proto.HasField("final_measurement"):
        trial.final_measurement = measurement_from_proto(proto.final_measurement)
    elif proto.state == study_pb2.Trial.INFEASIBLE:
        trial.infeasibility_reason = proto.infeasibility_reason or "infeasible"
        if proto.HasField("final_measurement"):
            trial.final_measurement = measurement_from_proto(proto.final_measurement)
    if proto.creation_time_secs:
        trial.creation_time = datetime.datetime.fromtimestamp(
            proto.creation_time_secs, datetime.timezone.utc
        )
    if proto.completion_time_secs:
        trial.completion_time = datetime.datetime.fromtimestamp(
            proto.completion_time_secs, datetime.timezone.utc
        )
    return trial


def trial_suggestion_to_proto(s: vz.TrialSuggestion) -> study_pb2.Trial:
    t = vz.Trial(id=0, parameters=s.parameters, metadata=s.metadata, is_requested=True)
    return trial_to_proto(t)


# ---------------------------------------------------------------------------
# Study config
# ---------------------------------------------------------------------------


def study_config_to_proto(config: vz.StudyConfig) -> study_pb2.StudySpec:
    proto = study_pb2.StudySpec(algorithm=str(config.algorithm))
    for p in config.search_space.parameters:
        proto.parameters.add().CopyFrom(parameter_config_to_proto(p))
    for m in config.metric_information:
        proto.metrics.add().CopyFrom(metric_information_to_proto(m))
    noise_map = {
        vz.ObservationNoise.OBSERVATION_NOISE_UNSPECIFIED: study_pb2.StudySpec.OBSERVATION_NOISE_UNSPECIFIED,
        vz.ObservationNoise.LOW: study_pb2.StudySpec.LOW,
        vz.ObservationNoise.HIGH: study_pb2.StudySpec.HIGH,
    }
    proto.observation_noise = noise_map[config.observation_noise]
    if config.automated_stopping_config is not None:
        proto.early_stopping.use_steps = config.automated_stopping_config.use_steps
        proto.early_stopping.min_num_trials = config.automated_stopping_config.min_num_trials
        proto.early_stopping.rule = config.automated_stopping_config.rule
    if config.pythia_endpoint:
        proto.pythia_endpoint = config.pythia_endpoint
    proto.metadata.extend(metadata_to_key_values(config.metadata))
    return proto


def study_config_from_proto(proto: study_pb2.StudySpec) -> vz.StudyConfig:
    space = vz.SearchSpace(
        [parameter_config_from_proto(p) for p in proto.parameters]
    )
    metrics = vz.MetricsConfig(
        [metric_information_from_proto(m) for m in proto.metrics]
    )
    noise_map = {
        study_pb2.StudySpec.OBSERVATION_NOISE_UNSPECIFIED: vz.ObservationNoise.OBSERVATION_NOISE_UNSPECIFIED,
        study_pb2.StudySpec.LOW: vz.ObservationNoise.LOW,
        study_pb2.StudySpec.HIGH: vz.ObservationNoise.HIGH,
    }
    stopping = None
    if proto.HasField("early_stopping"):
        stopping = vz.AutomatedStoppingConfig(
            use_steps=proto.early_stopping.use_steps,
            min_num_trials=proto.early_stopping.min_num_trials,
            rule=proto.early_stopping.rule or "median",
        )
    return vz.StudyConfig(
        search_space=space,
        metric_information=metrics,
        metadata=metadata_from_key_values(proto.metadata),
        algorithm=proto.algorithm or vz.Algorithm.DEFAULT.value,
        observation_noise=noise_map.get(
            proto.observation_noise, vz.ObservationNoise.OBSERVATION_NOISE_UNSPECIFIED
        ),
        automated_stopping_config=stopping,
        pythia_endpoint=proto.pythia_endpoint or None,
    )


def study_to_proto(
    config: vz.StudyConfig, name: str, display_name: str = "", state: Optional[int] = None
) -> study_pb2.Study:
    proto = study_pb2.Study(
        name=name,
        display_name=display_name,
        state=state if state is not None else study_pb2.Study.ACTIVE,
        creation_time_secs=time.time(),
    )
    proto.study_spec.CopyFrom(study_config_to_proto(config))
    return proto
