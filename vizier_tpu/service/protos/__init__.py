"""Generated protobuf stubs.

protoc generates flat-module imports (``import study_pb2``), so this package
puts its directory on ``sys.path`` before importing them — the same
mechanism the reference uses for its compiled stubs
(``/root/reference/vizier/__init__.py:18-25``). Regenerate with
``build_protos.sh`` at the repo root.
"""

import os
import sys

_HERE = os.path.dirname(__file__)
if _HERE not in sys.path:
    sys.path.append(_HERE)

import key_value_pb2  # noqa: E402
import pythia_service_pb2  # noqa: E402
import study_pb2  # noqa: E402
import vizier_service_pb2  # noqa: E402

__all__ = ["key_value_pb2", "pythia_service_pb2", "study_pb2", "vizier_service_pb2"]
